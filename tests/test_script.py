"""painless-lite tests: parser/interpreter semantics, device tracing parity,
and every script context (query, score, fields, sort, update, ingest).
Reference behaviors: `modules/lang-painless` + ScriptScoreQueryBuilder,
UpdateHelper.executeScriptedUpsert, ScriptProcessor."""

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient
from opensearch_tpu.script import ScriptError, execute
from opensearch_tpu.script import painless_lite
from opensearch_tpu.script.painless_lite import (parse, referenced_doc_fields,
                                                 validate_device_script)


# ---------------------------------------------------------------- interpreter

class TestInterpreter:
    def test_arithmetic_precedence(self):
        assert execute("1 + 2 * 3", {}) == 7
        assert execute("(1 + 2) * 3", {}) == 9
        assert execute("2 * 3 % 4", {}) == 2

    def test_java_integer_division(self):
        assert execute("7 / 2", {}) == 3
        assert execute("-7 / 2", {}) == -3  # truncates toward zero
        assert execute("7.0 / 2", {}) == 3.5
        assert execute("-7 % 3", {}) == -1  # Java remainder keeps sign

    def test_division_by_zero_raises(self):
        with pytest.raises(ScriptError):
            execute("1 / 0", {})

    def test_string_concat(self):
        assert execute("'a' + 'b' + 1", {}) == "ab1"

    def test_ternary_and_bool(self):
        assert execute("x > 3 ? 'big' : 'small'", {"x": 5}) == "big"
        assert execute("true && false || true", {})
        assert execute("!false", {})

    def test_locals_and_blocks(self):
        assert execute("def a = 2; def b = a * a; b + 1", {}) == 5

    def test_if_else_chain(self):
        src = "if (x < 0) { return 'neg' } else if (x == 0) { return 'zero' } else { return 'pos' }"
        assert execute(src, {"x": -2}) == "neg"
        assert execute(src, {"x": 0}) == "zero"
        assert execute(src, {"x": 9}) == "pos"

    def test_for_in_loop(self):
        assert execute("def t = 0; for (v in vals) { t += v } return t",
                       {"vals": [1, 2, 3]}) == 6

    def test_math(self):
        assert execute("Math.max(Math.abs(-3), 2)", {}) == 3
        assert abs(execute("Math.pow(2, 10)", {}) - 1024) < 1e-9
        assert abs(execute("Math.log(Math.E)", {}) - 1.0) < 1e-12

    def test_string_methods(self):
        assert execute("'Hello'.toLowerCase()", {}) == "hello"
        assert execute("'hello world'.contains('wor')", {})
        assert execute("'a,b,c'.split(',')", {}) == ["a", "b", "c"]
        assert execute("'abc'.substring(1)", {}) == "bc"

    def test_list_and_map_methods(self):
        assert execute("def l = [1, 2]; l.add(3); l.size()", {}) == 3
        assert execute("def m = ['a': 1]; m.put('b', 2); m.containsKey('b')", {})
        assert execute("def m = [:]; m.isEmpty()", {})
        assert execute("params.getOrDefault('missing', 42)", {"params": {}}) == 42

    def test_compound_assignment_on_map(self):
        ctx = {"_source": {"n": 10}}
        execute("ctx._source.n *= 3", {"ctx": ctx})
        assert ctx["_source"]["n"] == 30

    def test_loop_limit(self):
        with pytest.raises(ScriptError):
            execute("def t = 0; for (v in vals) { t += v }",
                    {"vals": list(range(200_001))})

    def test_parse_error(self):
        with pytest.raises(ScriptError):
            parse("def = 1")
        with pytest.raises(ScriptError):
            parse("1 +")

    def test_comments(self):
        assert execute("// note\n1 + 1 /* mid */ + 1", {}) == 3

    def test_referenced_doc_fields(self):
        ast = parse("doc['a'].value + doc['b'].value * doc['a'].value")
        assert referenced_doc_fields(ast) == ("a", "b")

    def test_device_validation_rejects_if(self):
        with pytest.raises(ScriptError):
            validate_device_script("if (x > 1) { return 1 }")


# ---------------------------------------------------------------- contexts

@pytest.fixture
def client():
    c = RestClient()
    c.indices.create("s", {"mappings": {"properties": {
        "price": {"type": "float"}, "qty": {"type": "integer"},
        "name": {"type": "text"}, "tag": {"type": "keyword"}}}})
    docs = [(10.0, 2, "red shirt", "a"), (20.0, 1, "blue shirt", "b"),
            (5.0, 7, "green hat", "a"), (40.0, 0, "red hat", "c")]
    for i, (p, q, n, t) in enumerate(docs):
        c.index("s", {"price": p, "qty": q, "name": n, "tag": t}, id=str(i))
    c.indices.refresh("s")
    return c


class TestScriptQuery:
    def test_filter_by_expression(self, client):
        r = client.search("s", {"query": {"script": {"script": {
            "source": "doc['price'].value * doc['qty'].value > params.t",
            "params": {"t": 19}}}}})
        assert sorted(h["_id"] for h in r["hits"]["hits"]) == ["0", "1", "2"]

    def test_missing_field_is_empty(self, client):
        r = client.search("s", {"query": {"script": {"script": {
            "source": "doc['nope'].empty"}}}})
        assert r["hits"]["total"]["value"] == 4

    def test_bad_script_is_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("s", {"query": {"script": {"script": {"source": "1 +"}}}})
        assert ei.value.status == 400

    def test_non_numeric_param_is_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("s", {"query": {"script": {"script": {
                "source": "doc['price'].value > 1", "params": {"s": "x"}}}}})
        assert ei.value.status == 400


class TestScriptScoreQuery:
    def test_replaces_score(self, client):
        r = client.search("s", {"query": {"script_score": {
            "query": {"match_all": {}},
            "script": {"source": "doc['price'].value + 1"}}}})
        got = [(h["_id"], h["_score"]) for h in r["hits"]["hits"]]
        assert got[0] == ("3", 41.0)
        assert got[-1] == ("2", 6.0)

    def test_score_variable_binds_child(self, client):
        r = client.search("s", {"query": {"script_score": {
            "query": {"match": {"name": "shirt"}},
            "script": {"source": "_score * 0 + doc['qty'].value"}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got == {"0": 2.0, "1": 1.0}

    def test_min_score_cuts(self, client):
        r = client.search("s", {"query": {"script_score": {
            "query": {"match_all": {}},
            "script": {"source": "doc['price'].value"},
            "min_score": 15.0}}})
        assert sorted(h["_id"] for h in r["hits"]["hits"]) == ["1", "3"]

    def test_params_reuse_compiled_program(self, client):
        a = client.search("s", {"query": {"script_score": {
            "query": {"match_all": {}},
            "script": {"source": "doc['price'].value * params.m",
                       "params": {"m": 2.0}}}}})
        b = client.search("s", {"query": {"script_score": {
            "query": {"match_all": {}},
            "script": {"source": "doc['price'].value * params.m",
                       "params": {"m": 3.0}}}}})
        sa = {h["_id"]: h["_score"] for h in a["hits"]["hits"]}
        sb = {h["_id"]: h["_score"] for h in b["hits"]["hits"]}
        assert sb["0"] == pytest.approx(sa["0"] * 1.5)

    def test_function_score_script_function(self, client):
        r = client.search("s", {"query": {"function_score": {
            "query": {"match": {"name": "hat"}},
            "functions": [{"script_score": {"script": {
                "source": "Math.sqrt(doc['price'].value)"}}}],
            "boost_mode": "replace"}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["3"] == pytest.approx(40 ** 0.5, rel=1e-5)
        assert got["2"] == pytest.approx(5 ** 0.5, rel=1e-5)


class TestScriptFieldsSortUpdate:
    def test_script_fields(self, client):
        r = client.search("s", {"query": {"ids": {"values": ["2"]}},
                                "script_fields": {
                                    "margin": {"script": {
                                        "source": "doc['price'].value * 0.5"}},
                                    "label": {"script": {
                                        "source": "doc['tag'].value + '!'"}}}})
        f = r["hits"]["hits"][0]["fields"]
        assert f["margin"] == [2.5]
        assert f["label"] == ["a!"]

    def test_script_sort(self, client):
        r = client.search("s", {"query": {"match_all": {}},
                                "sort": [{"_script": {
                                    "type": "number",
                                    "script": {"source": "doc['qty'].value * -1"},
                                    "order": "asc"}}]})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["2", "0", "1", "3"]

    def test_scripted_update_and_noop(self, client):
        client.update("s", "0", {"script": {
            "source": "ctx._source.qty += params.n", "params": {"n": 10}}})
        assert client.get("s", "0")["_source"]["qty"] == 12
        r = client.update("s", "0", {"script": {"source": "ctx.op = 'none'"}})
        assert r["result"] == "noop"

    def test_scripted_update_delete(self, client):
        client.update("s", "1", {"script": {
            "source": "if (ctx._source.qty < 5) { ctx.op = 'delete' }"}})
        assert not client.exists("s", "1")

    def test_scripted_upsert(self, client):
        client.update("s", "counter", {"scripted_upsert": True,
                                       "upsert": {"n": 0},
                                       "script": {"source": "ctx._source.n += 1"}})
        assert client.get("s", "counter")["_source"]["n"] == 1
        client.update("s", "counter", {"scripted_upsert": True,
                                       "upsert": {"n": 0},
                                       "script": {"source": "ctx._source.n += 1"}})
        assert client.get("s", "counter")["_source"]["n"] == 2

    def test_update_by_query_script(self, client):
        client.update_by_query("s", {"query": {"term": {"tag": "a"}},
                                     "script": {"source":
                                                "ctx._source.flagged = true"}},
                               refresh=True)
        assert client.get("s", "0")["_source"].get("flagged") is True
        assert client.get("s", "1")["_source"].get("flagged") is None

    def test_noop_script_does_not_corrupt_stored_source(self, client):
        # mutating nested state then op='none' must not leak into the segment
        client.index("s", {"tags": ["x"]}, id="nest", refresh=True)
        r = client.update("s", "nest", {"script": {
            "source": "ctx._source.tags.add('evil'); ctx.op = 'none'"}})
        assert r["result"] == "noop"
        assert client.get("s", "nest")["_source"]["tags"] == ["x"]

    def test_runtime_fault_maps_to_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.update("s", "0", {"script": {"source": "ctx._source.x = 1 % 0"}})
        assert ei.value.status == 400

    def test_device_trace_error_maps_to_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("s", {"query": {"script": {"script": {
                "source": "doc['price'].values"}}}})
        assert ei.value.status == 400

    def test_search_after_with_script_sort_is_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("s", {"query": {"match_all": {}},
                                "search_after": [1.0],
                                "sort": [{"_script": {
                                    "type": "number",
                                    "script": {"source": "doc['qty'].value"}}}]})
        assert ei.value.status == 400

    def test_backslash_escape_decoding(self):
        assert execute(r"'a\\nb'", {}) == "a\\nb"  # escaped backslash + n
        assert execute(r"'a\nb'", {}) == "a\nb"    # real newline escape

    def test_bad_ingest_script_rejected_at_put(self, client):
        from opensearch_tpu.ingest.pipeline import IngestProcessorException
        with pytest.raises((ApiError, IngestProcessorException)):
            client.ingest.put_pipeline("bad", {"processors": [
                {"script": {"source": "1 +"}}]})

    def test_ingest_script_processor(self, client):
        client.ingest.put_pipeline("calc", {"processors": [
            {"script": {"source": "ctx.total = ctx.price * ctx.qty"}}]})
        client.index("s", {"price": 3.0, "qty": 4}, id="x", pipeline="calc",
                     refresh=True)
        assert client.get("s", "x")["_source"]["total"] == 12.0


class TestReferencePainlessShapes:
    """r5 depth probe: the statement/collection shapes that dominate the
    reference's painless test corpus (`modules/lang-painless` tests) —
    C-style for, for-each with `:`, while, break/continue, ++/--, lambdas,
    streams, splitOnToken — must run on the host interpreter."""

    @pytest.mark.parametrize("src,want", [
        ("int total = 0; for (int i = 0; i < 10; ++i) { total += i } "
         "return total;", 45),
        ("def total = 0; for (def x : [1,2,3]) { total += x } "
         "return total;", 6),
        ("def i = 0; def s = 0; while (i < 5) { s += i; i += 1 } "
         "return s;", 10),
        ("def s = 0; for (int i = 0; i < 100; i++) { if (i > 4) break; "
         "s += i } return s;", 10),
        ("def s = 0; for (int i = 0; i < 6; i++) { if (i % 2 == 0) "
         "continue; s += i } return s;", 9),
        ("def s = 'a,b,c'; return s.splitOnToken(',').length;", 3),
        ("def vals = [3,1,2]; vals.sort((a,b) -> a - b); "
         "return vals[0];", 1),
        ("def vals = [3,1,2]; vals.sort((a,b) -> b - a); "
         "return vals[0];", 3),
        ("def l = [1,2,3,4]; return l.stream().filter(x -> x > 2)"
         ".count();", 2),
        ("def l = [1,2,3,4]; return l.stream().map(x -> x * 2).sum();", 20),
        ("def l = [4,1,3]; return l.stream().sorted().toList()[0];", 1),
        ("def l = [1,2,2,3]; return l.stream().distinct().count();", 3),
        ("def l = [1,5,2]; return l.stream().anyMatch(x -> x > 4);", True),
        ("def l = [1,5,2]; return l.stream().allMatch(x -> x > 0);", True),
        ("def l = [1,2,3]; l.removeIf(x -> x > 1); return l.size();", 1),
        ("def i = 3; def j = i++; return i * 10 + j;", 43),
        ("def i = 3; def j = ++i; return i * 10 + j;", 44),
        ("def m = [:]; for (int i = 0; i < 3; i++) { m[i] = i * i } "
         "return m[2];", 4),
        ("def f = x -> x * x; return f(5);", 25),
    ])
    def test_shape(self, src, want):
        assert painless_lite.execute(src, {}) == want

    def test_loop_limit_guards_while(self):
        with pytest.raises(painless_lite.ScriptError):
            painless_lite.execute("def i = 0; while (true) { i += 1 } "
                                  "return i;", {})

    def test_lambda_captures_and_restores_scope(self):
        src = ("def x = 7; def l = [1,2]; def s = l.stream()"
               ".map(v -> v + x).sum(); return s * 100 + x;")
        assert painless_lite.execute(src, {}) == 1707

    def test_break_outside_loop_is_script_error(self):
        with pytest.raises(painless_lite.ScriptError):
            painless_lite.execute(
                "def x = 1; if (x > 0) { break } return x;", {})

    def test_break_in_lambda_is_script_error(self):
        with pytest.raises(painless_lite.ScriptError):
            painless_lite.execute(
                "def f = x -> { break }; for (x in [1,2]) { f(x) }", {})

    def test_runaway_lambda_recursion_is_script_error(self):
        with pytest.raises(painless_lite.ScriptError):
            painless_lite.execute("def f = x -> f(x + 1); return f(0);", {})

    def test_split_on_token_java_limit_semantics(self):
        assert painless_lite.execute(
            "return 'a,b,c'.splitOnToken(',', 2).length;", {}) == 2
        assert painless_lite.execute(
            "def p = 'a,b,c'.splitOnToken(',', 2); return p[1];",
            {}) == "b,c"

    def test_stream_distinct_equals_semantics(self):
        assert painless_lite.execute(
            "return [[1,2],[1,2]].stream().distinct().count();", {}) == 1
