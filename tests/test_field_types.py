"""Field-type long tail: range family, scaled_float, unsigned_long,
match_only_text, constant_keyword, flat_object, binary, token_count,
search_as_you_type (reference RangeFieldMapper, mapper-extras
ScaledFloatFieldMapper, MatchOnlyTextFieldMapper,
ConstantKeywordFieldMapper, FlatObjectFieldMapper, BinaryFieldMapper,
TokenCountFieldMapper, SearchAsYouTypeFieldMapper)."""

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient


@pytest.fixture()
def client():
    return RestClient()


def _ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


class TestRangeFields:
    @pytest.fixture()
    def c(self, client):
        client.indices.create("r", {"mappings": {"properties": {
            "age": {"type": "integer_range"},
            "when": {"type": "date_range"},
            "temp": {"type": "float_range"},
            "net": {"type": "ip_range"},
        }}})
        client.index("r", {"age": {"gte": 10, "lte": 20}}, id="a")
        client.index("r", {"age": {"gt": 20, "lt": 30}}, id="b")
        client.index("r", {"age": {"gte": 5, "lte": 50}}, id="c")
        client.index("r", {"when": {"gte": "2024-01-01",
                                    "lt": "2024-02-01"}}, id="d")
        client.index("r", {"temp": {"gte": 1.5, "lt": 2.5}}, id="e")
        client.index("r", {"net": {"gte": "10.0.0.1",
                                   "lte": "10.0.0.200"}}, id="f")
        client.indices.refresh("r")
        return client

    def test_intersects_default(self, c):
        r = c.search("r", {"query": {"range": {"age": {"gte": 18,
                                                       "lte": 22}}}})
        assert _ids(r) == ["a", "b", "c"]

    def test_within(self, c):
        # b stores the open range (20, 30) = [21, 29]: 29 > 25 -> not within
        r = c.search("r", {"query": {"range": {"age": {
            "gte": 0, "lte": 25, "relation": "within"}}}})
        assert _ids(r) == ["a"]
        r2 = c.search("r", {"query": {"range": {"age": {
            "gte": 0, "lte": 30, "relation": "within"}}}})
        assert _ids(r2) == ["a", "b"]

    def test_contains(self, c):
        r = c.search("r", {"query": {"range": {"age": {
            "gte": 12, "lte": 18, "relation": "contains"}}}})
        assert _ids(r) == ["a", "c"]

    def test_open_bounds_exact(self, c):
        # b is (20, 30) exclusive: 20 itself must not match
        r = c.search("r", {"query": {"term": {"age": 20}}})
        assert _ids(r) == ["a", "c"]
        r2 = c.search("r", {"query": {"term": {"age": 21}}})
        assert _ids(r2) == ["b", "c"]

    def test_date_range(self, c):
        r = c.search("r", {"query": {"range": {"when": {
            "gte": "2024-01-15", "lte": "2024-01-20"}}}})
        assert _ids(r) == ["d"]
        r2 = c.search("r", {"query": {"term": {"when": "2024-02-01"}}})
        assert _ids(r2) == []    # lt bound is exclusive

    def test_float_range_ulp(self, c):
        r = c.search("r", {"query": {"term": {"temp": 2.5}}})
        assert _ids(r) == []
        r2 = c.search("r", {"query": {"term": {"temp": 2.4999}}})
        assert _ids(r2) == ["e"]

    def test_ip_range(self, c):
        r = c.search("r", {"query": {"term": {"net": "10.0.0.77"}}})
        assert _ids(r) == ["f"]
        r2 = c.search("r", {"query": {"term": {"net": "10.0.1.1"}}})
        assert _ids(r2) == []

    def test_exists(self, c):
        r = c.search("r", {"query": {"exists": {"field": "age"}}})
        assert _ids(r) == ["a", "b", "c"]

    def test_invalid_bounds_rejected(self, c):
        with pytest.raises(ApiError):
            c.index("r", {"age": {"gte": 30, "lte": 10}}, id="bad")


class TestScaledFloat:
    def test_quantization_and_queries(self, client):
        client.indices.create("sf", {"mappings": {"properties": {
            "price": {"type": "scaled_float", "scaling_factor": 100}}}})
        client.index("sf", {"price": 9.991}, id="a")   # -> 9.99
        client.index("sf", {"price": 10.004}, id="b")  # -> 10.00
        client.indices.refresh("sf")
        r = client.search("sf", {"query": {"range": {"price": {"gte": 10}}}})
        assert _ids(r) == ["b"]
        r2 = client.search("sf", {"query": {"term": {"price": 9.99}}})
        assert _ids(r2) == ["a"]
        agg = client.search("sf", {"size": 0, "aggs": {
            "s": {"sum": {"field": "price"}}}})
        assert abs(agg["aggregations"]["s"]["value"] - 19.99) < 0.01

    def test_missing_factor_rejected(self, client):
        with pytest.raises(Exception):
            client.indices.create("sf2", {"mappings": {"properties": {
                "x": {"type": "scaled_float"}}}})


class TestUnsignedLong:
    def test_order_and_render(self, client):
        client.indices.create("ul", {"mappings": {"properties": {
            "n": {"type": "unsigned_long"}}}})
        big = (1 << 64) - 2
        client.index("ul", {"n": big}, id="big")
        client.index("ul", {"n": 5}, id="small")
        client.index("ul", {"n": (1 << 63) + 7}, id="mid")
        client.indices.refresh("ul")
        r = client.search("ul", {"query": {"range": {"n": {
            "gte": 1 << 63}}}, "sort": [{"n": "desc"}]})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["big", "mid"]
        got = client.search("ul", {"query": {"term": {"n": big}},
                                   "docvalue_fields": ["n"]})
        assert got["hits"]["hits"][0]["fields"]["n"] == [big]

    def test_out_of_range(self, client):
        client.indices.create("ul2", {"mappings": {"properties": {
            "n": {"type": "unsigned_long"}}}})
        with pytest.raises(ApiError):
            client.index("ul2", {"n": -1}, id="neg")
        with pytest.raises(ApiError):
            client.index("ul2", {"n": 1 << 64}, id="over")


class TestMatchOnlyText:
    @pytest.fixture()
    def c(self, client):
        client.indices.create("mot", {"mappings": {"properties": {
            "body": {"type": "match_only_text"}}}})
        client.index("mot", {"body": "quick brown fox jumps"}, id="a")
        client.index("mot", {"body": "brown quick fox"}, id="b")
        client.index("mot", {"body": "quick quick quick dog"}, id="c")
        client.indices.refresh("mot")
        return client

    def test_match_constant_tf(self, c):
        r = c.search("mot", {"query": {"match": {"body": "quick"}}})
        assert len(r["hits"]["hits"]) == 3
        # tf clamps to 1: the triple-quick doc scores no higher
        scores = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert abs(scores["c"] - scores["b"]) < 1e-4

    def test_phrase_via_source(self, c):
        r = c.search("mot", {"query": {"match_phrase": {
            "body": "quick brown"}}})
        assert _ids(r) == ["a"]
        r2 = c.search("mot", {"query": {"match_phrase": {
            "body": {"query": "quick fox", "slop": 1}}}})
        assert _ids(r2) == ["a", "b"]


class TestConstantKeyword:
    def test_mapping_value(self, client):
        client.indices.create("ck", {"mappings": {"properties": {
            "env": {"type": "constant_keyword", "value": "prod"},
            "body": {"type": "text"}}}})
        client.index("ck", {"body": "one"}, id="a")          # no env given
        client.index("ck", {"body": "two", "env": "prod"}, id="b")
        client.indices.refresh("ck")
        r = client.search("ck", {"query": {"term": {"env": "prod"}}})
        assert _ids(r) == ["a", "b"]
        r2 = client.search("ck", {"query": {"term": {"env": "dev"}}})
        assert _ids(r2) == []
        with pytest.raises(ApiError):
            client.index("ck", {"env": "staging"}, id="bad")

    def test_first_value_fixes(self, client):
        client.indices.create("ck2", {"mappings": {"properties": {
            "env": {"type": "constant_keyword"}}}})
        client.index("ck2", {"env": "dev"}, id="a")
        with pytest.raises(ApiError):
            client.index("ck2", {"env": "prod"}, id="b")


class TestFlatObject:
    @pytest.fixture()
    def c(self, client):
        client.indices.create("fo", {"mappings": {"properties": {
            "attrs": {"type": "flat_object"}}}})
        client.index("fo", {"attrs": {"color": "red",
                                      "size": {"h": "10", "w": "20"}}},
                     id="a")
        client.index("fo", {"attrs": {"color": "blue", "tags": ["x", "y"]}},
                     id="b")
        client.indices.refresh("fo")
        return client

    def test_leaf_term(self, c):
        r = c.search("fo", {"query": {"term": {"attrs.color": "red"}}})
        assert _ids(r) == ["a"]
        r2 = c.search("fo", {"query": {"term": {"attrs.size.h": "10"}}})
        assert _ids(r2) == ["a"]

    def test_root_search(self, c):
        # the root field matches any leaf value
        r = c.search("fo", {"query": {"term": {"attrs": "red"}}})
        assert _ids(r) == ["a"]
        r2 = c.search("fo", {"query": {"terms": {"attrs": ["x", "red"]}}})
        assert _ids(r2) == ["a", "b"]

    def test_leaf_exists(self, c):
        r = c.search("fo", {"query": {"exists": {"field": "attrs.tags"}}})
        assert _ids(r) == ["b"]

    def test_same_value_different_paths_distinct(self, c):
        c.index("fo", {"attrs": {"size": {"w": "10"}}}, id="w10")
        c.indices.refresh("fo")
        r = c.search("fo", {"query": {"term": {"attrs.size.h": "10"}}})
        assert _ids(r) == ["a"]


class TestBinaryTokenCount:
    def test_binary_stored_not_searchable(self, client):
        client.indices.create("bin", {"mappings": {"properties": {
            "blob": {"type": "binary"}}}})
        client.index("bin", {"blob": "U29tZSBiaW5hcnkgYmxvYg=="}, id="a")
        client.indices.refresh("bin")
        got = client.get("bin", "a")
        assert got["_source"]["blob"].startswith("U29tZSB")

    def test_token_count(self, client):
        client.indices.create("tc", {"mappings": {"properties": {
            "name": {"type": "text", "fields": {
                "length": {"type": "token_count", "analyzer": "standard"}}}}}})
        client.index("tc", {"name": "John Smith"}, id="a")
        client.index("tc", {"name": "Rachel Alice Williams"}, id="b")
        client.indices.refresh("tc")
        r = client.search("tc", {"query": {"range": {"name.length": {
            "gte": 3}}}})
        assert _ids(r) == ["b"]
        agg = client.search("tc", {"size": 0, "aggs": {
            "m": {"max": {"field": "name.length"}}}})
        assert agg["aggregations"]["m"]["value"] == 3


class TestSearchAsYouType:
    def test_prefix_and_shingles(self, client):
        client.indices.create("sayt", {"mappings": {"properties": {
            "title": {"type": "search_as_you_type"}}}})
        client.index("sayt", {"title": "quick brown fox"}, id="a")
        client.index("sayt", {"title": "quick black cat"}, id="b")
        client.indices.refresh("sayt")
        # shingle subfield matches the 2gram
        r = client.search("sayt", {"query": {"match": {
            "title._2gram": "quick brown"}}})
        assert _ids(r) == ["a"]
        # prefix subfield matches partial last term
        r2 = client.search("sayt", {"query": {"match": {
            "title._index_prefix": "bro"}}})
        assert _ids(r2) == ["a"]
        # bool_prefix over the main field: should-clauses, so the full
        # prefix match ranks first and the quick-only doc still matches
        r3 = client.search("sayt", {"query": {"match_bool_prefix": {
            "title": "quick bl"}}})
        assert r3["hits"]["hits"][0]["_id"] == "b"
        assert _ids(r3) == ["a", "b"]
