"""Replicas, segment replication, allocation, failover (reference
`indices/replication/`, `cluster/routing/allocation/`). Runs on the 8-device
virtual CPU mesh from conftest, so replica copies land on real (virtual)
devices."""

import numpy as np
import pytest

import jax

from opensearch_tpu.parallel.placement import ShardAllocator
from opensearch_tpu.rest.client import RestClient

WORDS = ["alpha", "beta", "gamma", "delta", "eps", "zeta"]


class TestAllocator:
    def test_same_shard_never_shares_device(self):
        alloc = ShardAllocator(4)
        table = alloc.allocate(n_shards=3, n_replicas=2)
        for s in range(3):
            devs = [c.device for c in table.for_shard(s)]
            assert len(devs) == len(set(devs)) == 3
        # balanced: 9 copies over 4 devices -> max 3 per device
        by_dev = {}
        for c in table.copies:
            by_dev[c.device] = by_dev.get(c.device, 0) + 1
        assert max(by_dev.values()) <= 3

    def test_unassigned_when_devices_exhausted(self):
        alloc = ShardAllocator(1)
        table = alloc.allocate(n_shards=1, n_replicas=1)
        assert table.for_shard(0)[0].state == "STARTED"
        assert table.for_shard(0)[1].state == "UNASSIGNED"

    def test_fail_device_reallocates(self):
        alloc = ShardAllocator(3)
        table = alloc.allocate(n_shards=2, n_replicas=1)
        victim = table.for_shard(0)[1].device
        changed = alloc.fail_device(victim, table)
        assert changed
        for c in table.copies:
            assert c.device != victim
        for s in range(2):
            devs = [c.device for c in table.for_shard(s)
                    if c.device is not None]
            assert len(devs) == len(set(devs))


@pytest.fixture
def client():
    rng = np.random.default_rng(11)
    c = RestClient()
    c.indices.create("r", {"settings": {"number_of_shards": 2,
                                        "number_of_replicas": 1},
                           "mappings": {"properties": {
                               "body": {"type": "text"}}}})
    for i in range(120):
        c.index("r", {"body": " ".join(rng.choice(WORDS, size=5))}, id=str(i))
    c.indices.refresh("r")
    return c


class TestReplication:
    def test_replicas_allocated_and_synced(self, client):
        svc = client.node.indices["r"]
        assert len(svc.replicas) == 2       # 1 replica per shard
        for (sid, _rid), rep in svc.replicas.items():
            assert rep.segments == svc.shards[sid].segments
            assert rep.checkpoint == svc.shards[sid].seq_no
        health = client.cluster.health()
        assert health["status"] == "green"
        assert health["active_shards"] == 4

    def test_replica_serves_identical_results(self, client):
        svc = client.node.indices["r"]
        body = {"query": {"match": {"body": "alpha beta"}}, "size": 20}
        results = []
        for _ in range(4):  # round-robin cycles primary/replica copies
            r = client.search("r", dict(body, _probe=len(results)))
            results.append((r["hits"]["total"]["value"],
                            tuple((h["_id"], round(h["_score"], 5))
                                  for h in r["hits"]["hits"])))
        assert len({t for t, _ in results}) == 1
        assert len({h for _, h in results}) == 1

    def test_round_robin_uses_replicas(self, client):
        svc = client.node.indices["r"]
        picked = set()
        for _ in range(6):
            for s in svc.search_copies():
                picked.add(id(s))
        # 2 shards x 2 copies = 4 distinct searchers over the cycle
        assert len(picked) == 4

    def test_replica_lags_until_refresh(self, client):
        svc = client.node.indices["r"]
        client.index("r", {"body": "zeta omega"}, id="new1")
        # primary buffer has it; replica checkpoint does not
        for (sid, _), rep in svc.replicas.items():
            assert rep.checkpoint < svc.shards[sid].seq_no or \
                svc.shards[sid].seq_no == rep.checkpoint
        client.indices.refresh("r")
        for (sid, _), rep in svc.replicas.items():
            assert rep.checkpoint == svc.shards[sid].seq_no

    def test_cat_shards_shows_copies(self, client):
        rows = client.cat.shards("r")
        assert len(rows) == 4
        assert {r["prirep"] for r in rows} == {"p", "r"}
        assert all(r["state"] == "STARTED" for r in rows)
        # copies of one shard never share a device
        for sid in ("0", "1"):
            devs = [r["node"] for r in rows if r["shard"] == sid]
            assert len(set(devs)) == 2

    def test_failover_promotes_replica(self, client):
        svc = client.node.indices["r"]
        before = client.search("r", {"query": {"match": {"body": "alpha"}},
                                     "size": 30, "_probe": "pre"})
        docs0 = svc.shards[0].num_docs
        svc.fail_primary(0)
        after = client.search("r", {"query": {"match": {"body": "alpha"}},
                                    "size": 30, "_probe": "post"})
        assert after["hits"]["total"] == before["hits"]["total"]
        assert [h["_id"] for h in after["hits"]["hits"]] == \
            [h["_id"] for h in before["hits"]["hits"]]
        assert svc.shards[0].num_docs == docs0
        # the promoted primary accepts writes
        client.index("r", {"body": "alpha fresh"},
                     id="post-failover", refresh=True)
        got = client.get("r", "post-failover")
        assert got["found"]

    def test_fail_device_end_to_end(self, client):
        svc = client.node.indices["r"]
        before = client.search("r", {"query": {"match": {"body": "beta"}},
                                     "size": 30, "_probe": "dev-pre"})
        # kill the device holding shard 0's primary
        victim = next(c.device for c in svc.table.for_shard(0) if c.primary)
        svc.fail_device(victim)
        assert all(c.device != victim for c in svc.table.copies
                   if c.device is not None)
        # every started replica copy has a live ReplicaShard on its device
        for c in svc.table.copies:
            if not c.primary and c.state == "STARTED":
                assert (c.shard, c.replica) in svc.replicas
        after = client.search("r", {"query": {"match": {"body": "beta"}},
                                    "size": 30, "_probe": "dev-post"})
        assert after["hits"]["total"] == before["hits"]["total"]

    def test_zero_replicas_single_device_is_green(self):
        c = RestClient()
        c.indices.create("nr", {"settings": {"number_of_shards": 1,
                                             "number_of_replicas": 0}})
        assert c.node.indices["nr"].health_status() == "green"
