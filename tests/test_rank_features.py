"""rank_feature(s) / sparse_vector / distance_feature tests. Reference:
mapper-extras RankFeature(s)FieldMapper + RankFeatureQuery,
DistanceFeatureQueryBuilder, neural-search learned-sparse scoring. Ours:
feature-weight CSR postings scored by the gather->fn->scatter pass
(ops.feature_score)."""

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient


@pytest.fixture
def client():
    c = RestClient()
    c.indices.create("rf", {"mappings": {"properties": {
        "pagerank": {"type": "rank_feature"},
        "topics": {"type": "rank_features"},
        "embedding": {"type": "sparse_vector"},
        "title": {"type": "text"},
        "published": {"type": "date"},
        "location": {"type": "geo_point"}}}})
    c.index("rf", {"title": "jax on tpu", "pagerank": 10.0,
                   "topics": {"ml": 5.0, "hardware": 2.0},
                   "embedding": {"jax": 2.0, "tpu": 1.5},
                   "published": "2024-06-01", "location": {"lat": 0, "lon": 0}},
            id="1")
    c.index("rf", {"title": "cooking pasta", "pagerank": 2.0,
                   "topics": {"food": 8.0},
                   "embedding": {"pasta": 3.0},
                   "published": "2020-01-01", "location": {"lat": 10, "lon": 10}},
            id="2")
    c.index("rf", {"title": "tpu pods", "pagerank": 30.0,
                   "topics": {"ml": 1.0, "hardware": 9.0},
                   "embedding": {"tpu": 3.0, "pod": 1.0},
                   "published": "2024-05-01", "location": {"lat": 0.1, "lon": 0.1}},
            id="3")
    c.indices.refresh("rf")
    return c


class TestRankFeature:
    def test_saturation_on_numeric_field(self, client):
        r = client.search("rf", {"query": {"rank_feature": {
            "field": "pagerank", "saturation": {"pivot": 10}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["1"] == pytest.approx(10 / 20)
        assert got["2"] == pytest.approx(2 / 12)
        assert got["3"] == pytest.approx(30 / 40)

    def test_default_pivot_is_mean(self, client):
        r = client.search("rf", {"query": {"rank_feature": {"field": "pagerank"}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        mean = (10 + 2 + 30) / 3
        assert got["1"] == pytest.approx(10 / (10 + mean))

    def test_features_field(self, client):
        r = client.search("rf", {"query": {"rank_feature": {
            "field": "topics.ml", "saturation": {"pivot": 1}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert set(got) == {"1", "3"}  # doc 2 has no ml feature
        assert got["1"] == pytest.approx(5 / 6)
        assert got["3"] == pytest.approx(1 / 2)

    def test_log_and_sigmoid_and_linear(self, client):
        import math
        r = client.search("rf", {"query": {"rank_feature": {
            "field": "pagerank", "log": {"scaling_factor": 4}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["1"] == pytest.approx(math.log(14), rel=1e-5)
        r = client.search("rf", {"query": {"rank_feature": {
            "field": "pagerank", "sigmoid": {"pivot": 10, "exponent": 2}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["3"] == pytest.approx(900 / (900 + 100))
        r = client.search("rf", {"query": {"rank_feature": {
            "field": "pagerank", "linear": {}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["3"] == pytest.approx(30.0)

    def test_boost_and_bool_combination(self, client):
        r = client.search("rf", {"query": {"bool": {
            "must": [{"match": {"title": "tpu"}}],
            "should": [{"rank_feature": {"field": "pagerank",
                                         "saturation": {"pivot": 10},
                                         "boost": 2.0}}]}}})
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert set(ids) == {"1", "3"}
        assert ids[0] == "3"  # pagerank boost dominates

    def test_bad_function_spec_is_400(self, client):
        with pytest.raises(ApiError):
            client.search("rf", {"query": {"rank_feature": {
                "field": "pagerank", "log": {}}}})
        with pytest.raises(ApiError):
            client.search("rf", {"query": {"rank_feature": {
                "field": "title"}}})

    def test_positive_score_impact_false(self, client):
        c = RestClient()
        c.indices.create("neg", {"mappings": {"properties": {
            "url_length": {"type": "rank_feature",
                           "positive_score_impact": False}}}})
        c.index("neg", {"url_length": 10.0}, id="a")
        c.index("neg", {"url_length": 90.0}, id="b", refresh=True)
        r = c.search("neg", {"query": {"rank_feature": {
            "field": "url_length", "saturation": {"pivot": 10}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["a"] > got["b"]  # shorter URL scores higher
        assert got["a"] == pytest.approx(10 / 20)


class TestNeuralSparse:
    def test_dot_product(self, client):
        r = client.search("rf", {"query": {"neural_sparse": {"embedding": {
            "query_tokens": {"tpu": 2.0, "jax": 1.0}}}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["1"] == pytest.approx(2 * 1.5 + 1 * 2.0)
        assert got["3"] == pytest.approx(2 * 3.0)
        assert "2" not in got

    def test_unknown_field_is_400(self, client):
        with pytest.raises(ApiError):
            client.search("rf", {"query": {"neural_sparse": {"title": {
                "query_tokens": {"x": 1.0}}}}})


class TestDistanceFeature:
    def test_date(self, client):
        r = client.search("rf", {"query": {"distance_feature": {
            "field": "published", "origin": "2024-06-01", "pivot": "7d"}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["1"] == pytest.approx(1.0, abs=1e-3)   # zero distance
        assert got["3"] == pytest.approx(7 / (7 + 31), rel=1e-2)
        assert got["1"] > got["3"] > got["2"]

    def test_geo(self, client):
        r = client.search("rf", {"query": {"distance_feature": {
            "field": "location", "origin": [0, 0], "pivot": "100km"}}})
        got = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert got["1"] == pytest.approx(1.0, abs=1e-3)
        assert got["1"] > got["3"] > got["2"]

    def test_combined_with_match(self, client):
        r = client.search("rf", {"query": {"bool": {
            "must": [{"match": {"title": "tpu"}}],
            "should": [{"distance_feature": {"field": "published",
                                             "origin": "2024-06-01",
                                             "pivot": "1d", "boost": 5.0}}]}}})
        assert [h["_id"] for h in r["hits"]["hits"]][0] == "1"


class TestFeaturePersistence:
    def test_flush_and_reload(self, client, tmp_path):
        import tempfile
        p = str(tmp_path / "data")
        c = RestClient(data_path=p)
        c.indices.create("rfp", {"mappings": {"properties": {
            "topics": {"type": "rank_features"}}}})
        c.index("rfp", {"topics": {"a": 4.0}}, id="1", refresh=True)
        c.indices.flush("rfp")
        c2 = RestClient(data_path=p)
        r = c2.search("rfp", {"query": {"rank_feature": {
            "field": "topics.a", "saturation": {"pivot": 4}}}})
        assert r["hits"]["hits"][0]["_score"] == pytest.approx(0.5)

    def test_negative_weight_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("rf", {"topics": {"bad": -1.0}}, id="x")

    def test_negative_scalar_rank_feature_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("rf", {"pagerank": -5.0}, id="x")
        with pytest.raises((ApiError, ValueError)):
            client.index("rf", {"pagerank": 0.0}, id="x")

    def test_array_of_feature_objects_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("rf", {"topics": [{"ml": 2.0}]}, id="x")

    def test_log_on_negative_impact_field_is_400(self):
        c = RestClient()
        c.indices.create("neg2", {"mappings": {"properties": {
            "len": {"type": "rank_feature", "positive_score_impact": False}}}})
        c.index("neg2", {"len": 5.0}, id="a", refresh=True)
        with pytest.raises(ApiError):
            c.search("neg2", {"query": {"rank_feature": {
                "field": "len", "log": {"scaling_factor": 2}}}})
