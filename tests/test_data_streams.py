"""Data streams: template-gated creation, backing-index naming/rollover,
@timestamp enforcement, create-only writes, search expansion
(reference cluster/metadata/DataStream.java +
action/admin/indices/datastream/)."""

import tempfile

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient


@pytest.fixture()
def client():
    c = RestClient()
    c.indices.put_index_template("logs-template", {
        "index_patterns": ["logs-*"],
        "data_stream": {},
        "template": {"mappings": {"properties": {
            "msg": {"type": "text"}, "level": {"type": "keyword"}}}},
    })
    return c


def _put(c, stream, docs):
    for i, d in enumerate(docs):
        c.index(stream, d, id=f"d{i}", op_type="create")
    c.indices.refresh(stream)


class TestDataStreamCRUD:
    def test_requires_template(self, client):
        with pytest.raises(ApiError) as e:
            client.indices.create_data_stream("metrics-app")
        assert "template" in e.value.reason

    def test_create_get_delete(self, client):
        client.indices.create_data_stream("logs-app")
        got = client.indices.get_data_stream("logs-app")["data_streams"]
        assert len(got) == 1
        ds = got[0]
        assert ds["generation"] == 1
        assert ds["indices"] == [{"index_name": ".ds-logs-app-000001"}]
        assert ds["timestamp_field"] == {"name": "@timestamp"}
        client.indices.delete_data_stream("logs-app")
        assert client.indices.get_data_stream("*")["data_streams"] == []
        assert not client.indices.exists(".ds-logs-app-000001")

    def test_name_conflicts(self, client):
        client.indices.create("logs-taken")
        with pytest.raises(ApiError):
            client.indices.create_data_stream("logs-taken")
        client.indices.create_data_stream("logs-app")
        with pytest.raises(ApiError):
            client.indices.create_data_stream("logs-app")

    def test_backing_index_delete_guarded(self, client):
        client.indices.create_data_stream("logs-app")
        with pytest.raises(ApiError) as e:
            client.indices.delete(".ds-logs-app-000001")
        assert "backing index" in e.value.reason
        # the index delete API rejects the stream name itself too
        with pytest.raises(ApiError) as e2:
            client.indices.delete("logs-app")
        assert "data stream" in e2.value.reason

    def test_wildcard_delete_skips_backing(self, client):
        client.indices.create_data_stream("logs-app")
        client.indices.create("plain")
        client.indices.delete("*")
        assert not client.indices.exists("plain")
        assert client.indices.exists(".ds-logs-app-000001")

    def test_template_mappings_applied(self, client):
        client.indices.create_data_stream("logs-app")
        svc = client.node.indices[".ds-logs-app-000001"]
        ft = svc.mappings.resolve_field("level")
        assert ft is not None and ft.type == "keyword"


class TestDataStreamWrites:
    def test_create_only_and_timestamp(self, client):
        client.indices.create_data_stream("logs-app")
        with pytest.raises(ApiError) as e:
            client.index("logs-app", {"@timestamp": "2025-01-01",
                                      "msg": "x"})  # default op_type=index
        assert "op_type of create" in e.value.reason
        with pytest.raises(ApiError) as e2:
            client.index("logs-app", {"msg": "no ts"}, op_type="create")
        assert "@timestamp" in e2.value.reason
        r = client.index("logs-app", {"@timestamp": "2025-01-01T10:00:00Z",
                                      "msg": "hello"}, op_type="create")
        assert r["result"] == "created"
        # responses name the concrete backing index (reference behavior)
        assert r["_index"] == ".ds-logs-app-000001"

    def test_bulk_create(self, client):
        client.indices.create_data_stream("logs-app")
        r = client.bulk([
            {"create": {"_index": "logs-app"}},
            {"@timestamp": "2025-01-01", "msg": "a"},
            {"index": {"_index": "logs-app"}},          # rejected
            {"@timestamp": "2025-01-01", "msg": "b"},
        ])
        assert r["errors"]
        ok = [it for it in r["items"] if "create" in it]
        bad = [it for it in r["items"] if "index" in it]
        assert ok[0]["create"]["status"] == 201
        assert bad[0]["index"]["status"] == 400

    def test_search_expands_backing_indices(self, client):
        client.indices.create_data_stream("logs-app")
        _put(client, "logs-app", [
            {"@timestamp": "2025-01-01", "msg": "alpha", "level": "info"}])
        client.rollover("logs-app")
        _put(client, "logs-app", [
            {"@timestamp": "2025-01-02", "msg": "beta", "level": "warn"}])
        r = client.search("logs-app", {"query": {"match_all": {}}})
        assert r["hits"]["total"]["value"] == 2
        idxs = {h["_index"] for h in r["hits"]["hits"]}
        assert idxs == {".ds-logs-app-000001", ".ds-logs-app-000002"}


class TestDataStreamRollover:
    def test_rollover_generations(self, client):
        client.indices.create_data_stream("logs-app")
        r = client.rollover("logs-app")
        assert r["rolled_over"]
        assert r["old_index"] == ".ds-logs-app-000001"
        assert r["new_index"] == ".ds-logs-app-000002"
        ds = client.indices.get_data_stream("logs-app")["data_streams"][0]
        assert ds["generation"] == 2
        # writes land in the new write index
        client.index("logs-app", {"@timestamp": "2025-01-03", "msg": "x"},
                     id="w", op_type="create")
        client.indices.refresh("logs-app")
        got = client.search("logs-app", {"query": {"ids": {
            "values": ["w"]}}})
        assert got["hits"]["hits"][0]["_index"] == ".ds-logs-app-000002"

    def test_conditional_rollover(self, client):
        client.indices.create_data_stream("logs-app")
        r = client.rollover("logs-app", {"conditions": {"max_docs": 5}})
        assert not r["rolled_over"]
        _put(client, "logs-app",
             [{"@timestamp": "2025-01-01", "msg": f"m{i}"} for i in range(6)])
        r2 = client.rollover("logs-app", {"conditions": {"max_docs": 5}})
        assert r2["rolled_over"]

    def test_persistence(self):
        path = tempfile.mkdtemp()
        c = RestClient(data_path=path)
        c.indices.put_index_template("t", {"index_patterns": ["s-*"],
                                           "data_stream": {}})
        c.indices.create_data_stream("s-1")
        c.index("s-1", {"@timestamp": "2025-01-01"}, op_type="create")
        c.rollover("s-1")
        c.indices.flush("s-1")
        c2 = RestClient(data_path=path)
        ds = c2.indices.get_data_stream("s-1")["data_streams"][0]
        assert ds["generation"] == 2
        assert len(ds["indices"]) == 2
