"""Percolator tests. Reference semantics: modules/percolator
(PercolatorFieldMapper term extraction, PercolateQueryBuilder, matched
document slots). Ours: candidate mini-segment + host plan evaluator with
keyword-column term pre-filtering (search/percolate.py)."""

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient


@pytest.fixture
def client():
    c = RestClient()
    c.indices.create("alerts", {"mappings": {"properties": {
        "query": {"type": "percolator"},
        "message": {"type": "text"},
        "severity": {"type": "integer"},
        "tag": {"type": "keyword"}}}})
    c.index("alerts", {"query": {"match": {"message": "error"}}}, id="q_err")
    c.index("alerts", {"query": {"bool": {"must": [
        {"match": {"message": "disk"}},
        {"range": {"severity": {"gte": 5}}}]}}}, id="q_disk")
    c.index("alerts", {"query": {"term": {"tag": "network"}}}, id="q_net")
    c.index("alerts", {"query": {"range": {"severity": {"gte": 9}}}}, id="q_crit")
    c.index("alerts", {"query": {"match_phrase": {"message": "out of memory"}}},
            id="q_oom")
    c.indices.refresh("alerts")
    return c


class TestPercolate:
    def test_basic_match(self, client):
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query",
            "document": {"message": "a disk error occurred", "severity": 7}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_err", "q_disk"}

    def test_range_only_query_always_evaluated(self, client):
        # q_crit has no extractable terms -> must still be tried
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query", "document": {"severity": 10}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_crit"}

    def test_phrase(self, client):
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query",
            "document": {"message": "process killed: out of memory"}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_oom"}
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query",
            "document": {"message": "memory of out"}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == set()

    def test_keyword_term(self, client):
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query", "document": {"tag": "network"}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_net"}

    def test_no_match(self, client):
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query", "document": {"message": "all quiet"}}}})
        assert r["hits"]["hits"] == []

    def test_multiple_documents_with_slots(self, client):
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query",
            "documents": [{"message": "error one"},
                          {"message": "quiet"},
                          {"message": "disk error", "severity": 6}]}}})
        by_id = {h["_id"]: h for h in r["hits"]["hits"]}
        assert set(by_id) == {"q_err", "q_disk"}
        assert by_id["q_err"]["fields"]["_percolator_document_slot"] == [0, 2]
        assert by_id["q_disk"]["fields"]["_percolator_document_slot"] == [2]

    def test_document_reference(self, client):
        client.indices.create("docs", {})
        client.index("docs", {"message": "error in prod"}, id="d1", refresh=True)
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query", "index": "docs", "id": "d1"}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_err"}

    def test_invalid_stored_query_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("alerts", {"query": {"bogus_kind": {}}}, id="bad")

    def test_updates_and_deletes(self, client):
        client.delete("alerts", "q_err")
        client.index("alerts", {"query": {"match": {"message": "warning"}}},
                     id="q_warn", refresh=True)
        r = client.search("alerts", {"query": {"percolate": {
            "field": "query", "document": {"message": "error warning"}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_warn"}

    def test_bool_of_percolate_and_term(self, client):
        # percolate composes with ordinary queries on the percolator index
        client.index("alerts", {"query": {"match": {"message": "error"}},
                                "tag": "paging"}, id="q_page", refresh=True)
        r = client.search("alerts", {"query": {"bool": {
            "must": [{"percolate": {"field": "query",
                                    "document": {"message": "error"}}}],
            "filter": [{"term": {"tag": "paging"}}]}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_page"}

    def test_document_containing_percolate_key_not_resolved(self, client):
        # candidate doc content must never be treated as DSL
        body = {"query": {"percolate": {"field": "query", "document": {
            "message": "error", "percolate": {"index": "nope", "id": "1"}}}}}
        r = client.search("alerts", body)
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q_err"}
        # the caller's body was not mutated
        assert "document" not in body["query"]["percolate"].get("percolate", {})

    def test_two_named_percolate_queries_keep_separate_slots(self, client):
        r = client.search("alerts", {"query": {"bool": {"should": [
            {"percolate": {"field": "query", "_name": "p1",
                           "documents": [{"message": "error"}, {"message": "x"}]}},
            {"percolate": {"field": "query", "_name": "p2",
                           "documents": [{"message": "y"}, {"message": "error"}]}},
        ]}}})
        h = next(x for x in r["hits"]["hits"] if x["_id"] == "q_err")
        assert h["fields"]["_percolator_document_slot_p1"] == [0]
        assert h["fields"]["_percolator_document_slot_p2"] == [1]

    def test_count_with_doc_reference(self, client):
        client.indices.create("docs2", {})
        client.index("docs2", {"message": "error here"}, id="d1", refresh=True)
        r = client.count("alerts", {"query": {"percolate": {
            "field": "query", "index": "docs2", "id": "d1"}}})
        assert r["count"] == 1

    def test_unknown_percolator_field_is_400(self, client):
        with pytest.raises(ApiError):
            client.search("alerts", {"query": {"percolate": {
                "field": "message", "document": {"message": "x"}}}})

    def test_missing_document_is_400(self, client):
        with pytest.raises(ApiError):
            client.search("alerts", {"query": {"percolate": {"field": "query"}}})

    def test_nested_query_percolation(self, client):
        c = RestClient()
        c.indices.create("np", {"mappings": {"properties": {
            "query": {"type": "percolator"},
            "comments": {"type": "nested", "properties": {
                "text": {"type": "text"}}}}}})
        c.index("np", {"query": {"nested": {
            "path": "comments",
            "query": {"match": {"comments.text": "spam"}}}}}, id="q1",
            refresh=True)
        r = c.search("np", {"query": {"percolate": {
            "field": "query",
            "document": {"comments": [{"text": "this is spam"}]}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q1"}
