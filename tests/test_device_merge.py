"""Device-side multiway sorted-run merge (ops/device_merge.py): the postings
lexsort of segment merging runs as a 2-key lax.sort; results must be
bit-identical to the numpy path, including positional regathers."""

import numpy as np
import pytest

from opensearch_tpu.index.engine import Engine
from opensearch_tpu.index.mappings import Mappings
from opensearch_tpu.ops import device_merge
from opensearch_tpu.rest.client import RestClient

WORDS = [f"w{i}" for i in range(50)]


def _build_engine():
    rng = np.random.default_rng(3)
    m = Mappings({"properties": {"body": {"type": "text"},
                                 "tag": {"type": "keyword"}}})
    eng = Engine(m)
    for i in range(400):
        eng.index_doc(str(i), {"body": " ".join(rng.choice(WORDS, size=8)),
                               "tag": f"t{i % 7}"})
        if i % 100 == 99:
            eng.refresh()          # 4 segments
    # delete some docs so the merge compacts
    for i in range(0, 50, 5):
        eng.delete_doc(str(i))
    eng.refresh()
    return eng


class TestDeviceMerge:
    def test_sorted_runs_match_lexsort(self):
        rng = np.random.default_rng(0)
        n, n_rows = 5000, 64
        rows = rng.integers(0, n_rows, n).astype(np.int64)
        docs = rng.permutation(n).astype(np.int64)  # unique (row, doc) pairs
        tfs = rng.random(n).astype(np.float32)
        r, d, t, order, counts = device_merge.merge_sorted_runs(
            rows.astype(np.int32), docs.astype(np.int32), tfs, n_rows)
        ref = np.lexsort((docs, rows))
        np.testing.assert_array_equal(r, rows[ref])
        np.testing.assert_array_equal(d, docs[ref])
        np.testing.assert_array_equal(t, tfs[ref])
        np.testing.assert_array_equal(order, ref)
        np.testing.assert_array_equal(counts,
                                      np.bincount(rows, minlength=n_rows))

    def test_force_merge_bit_identical(self, monkeypatch):
        eng_dev = _build_engine()
        monkeypatch.setattr(device_merge, "DEVICE_MERGE_MIN", 1)
        eng_dev.force_merge(1)
        monkeypatch.setattr(device_merge, "DEVICE_MERGE_MIN", 1 << 62)
        eng_np = _build_engine()
        eng_np.force_merge(1)
        sd, sn = eng_dev.segments[0], eng_np.segments[0]
        assert sd.ndocs == sn.ndocs
        assert sd.ids[:] == sn.ids[:]
        for f in ("body", "tag"):
            pd, pn = sd.postings.get(f), sn.postings.get(f)
            if pn is None:
                assert pd is None
                continue
            assert pd.vocab == pn.vocab
            np.testing.assert_array_equal(pd.starts, pn.starts)
            np.testing.assert_array_equal(pd.doc_ids, pn.doc_ids)
            np.testing.assert_array_equal(pd.tfs, pn.tfs)
            if pn.pos_starts is not None:
                np.testing.assert_array_equal(pd.pos_starts, pn.pos_starts)
                np.testing.assert_array_equal(pd.positions, pn.positions)

    def test_phrases_survive_device_merge(self, monkeypatch):
        monkeypatch.setattr(device_merge, "DEVICE_MERGE_MIN", 1)
        c = RestClient()
        c.indices.create("dm")
        for i in range(120):
            c.index("dm", {"body": f"alpha beta doc{i}"}, id=str(i))
            if i % 40 == 39:
                c.indices.refresh("dm")
        c.indices.refresh("dm")
        c.indices.forcemerge("dm")
        eng = c.node.indices["dm"].shards[0]
        assert len(eng.segments) == 1
        r = c.search("dm", {"query": {"match_phrase": {"body": "alpha beta"}}})
        assert r["hits"]["total"]["value"] == 120
