"""HBM ledger + per-query device cost accounting (ISSUE 7).

Covers: exact concurrent charge/release balance, weakref-finalize release
exactness under forced GC, the partial→full residency promotion dedupe
(the `pruned_arrays` double-charge bugfix), breaker-trip behavior,
residency events on flight-recorder timelines, the `_cat/segments` and
`_nodes/stats` "hbm" surfaces, the profile `cost` block against a
hand-computed oracle, the `explain=device_plan` view, and the
`scripts/hbm_report.py` smoke. The standing ledger↔breaker invariant
(`sum(live charged bytes) == breaker.used`) is asserted after EVERY
tier-1 test by the conftest autouse fixture."""

import gc
import json
import threading

import numpy as np
import pytest

from opensearch_tpu.cluster.node import Node
from opensearch_tpu.obs import query_cost
from opensearch_tpu.obs.flight_recorder import RECORDER
from opensearch_tpu.obs.hbm_ledger import LEDGER, HBMLedger
from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.utils.breaker import (CircuitBreaker,
                                          CircuitBreakingException)


@pytest.fixture
def scratch_breaker():
    """Fresh breaker installed as the ledger's charge target; restores
    the previous target afterwards (the LEDGER is a process singleton)."""
    old = LEDGER.breaker
    b = CircuitBreaker("scratch", 1 << 40)
    LEDGER.set_breaker(b)
    try:
        yield b
    finally:
        LEDGER.set_breaker(old)


def make_client():
    c = RestClient(node=Node(mesh_service=False))
    c.indices.create("hbmt", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 0},
        "mappings": {"properties": {"body": {"type": "text"},
                                    "status": {"type": "keyword"}}}})
    return c


# ---------------------------------------------------------------------
# core ledger mechanics
# ---------------------------------------------------------------------

class TestLedgerCore:
    def test_register_release_exact_balance(self, scratch_breaker):
        a = LEDGER.register("aligned_postings", 1000, label="t1")
        b = LEDGER.register("filter_list", 24, label="t2")
        assert scratch_breaker.used == 1024
        assert not LEDGER.verify_breakers()
        LEDGER.release(a)
        assert scratch_breaker.used == 24
        LEDGER.release(b)
        LEDGER.release(b)          # idempotent: double release is a no-op
        assert scratch_breaker.used == 0
        assert not LEDGER.verify_breakers()

    def test_concurrent_hammer_exact_final_balance(self, scratch_breaker):
        """32 threads register/release concurrently; the final balance is
        exactly zero on both the ledger side and the derived breaker."""
        NT, PER = 32, 100
        # other test modules may legitimately keep segments (and their
        # cached filtered-postings tenants) alive in module globals —
        # assert this hammer's own balance, not a global absolute zero,
        # so the test doesn't depend on file execution order
        base = LEDGER.snapshot()["tenants"].get("filtered_postings",
                                                {}).get("bytes", 0)
        errs = []

        def worker(tid):
            try:
                held = []
                for i in range(PER):
                    alloc = LEDGER.register(
                        "filtered_postings", 64 + (tid * PER + i) % 512,
                        label=f"h{tid}-{i}")
                    if i % 3 == 0:
                        LEDGER.release(alloc)
                    else:
                        held.append(alloc)
                for alloc in held:
                    LEDGER.release(alloc)
            except Exception as e:            # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(NT)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert scratch_breaker.used == 0
        snap = LEDGER.snapshot()
        assert snap["tenants"].get("filtered_postings",
                                   {}).get("bytes", 0) == base
        assert not LEDGER.verify_breakers()

    def test_weakref_finalize_releases_exactly_once(self, scratch_breaker):
        class Owner:
            pass

        o = Owner()
        alloc = LEDGER.register("quality_tier", 4096, owner=o, label="gc")
        assert scratch_breaker.used == 4096
        del o
        gc.collect()
        assert scratch_breaker.used == 0
        # the finalizer already fired; an explicit release stays a no-op
        LEDGER.release(alloc)
        assert scratch_breaker.used == 0

    def test_explicit_release_then_owner_gc_no_double_credit(
            self, scratch_breaker):
        class Owner:
            pass

        o = Owner()
        pad = LEDGER.register("filter_list", 500, label="pad")
        alloc = LEDGER.register("quality_tier", 100, owner=o)
        LEDGER.release(alloc)
        assert scratch_breaker.used == 500
        del o
        gc.collect()               # finalizer fires; must not re-credit
        assert scratch_breaker.used == 500
        LEDGER.release(pad)

    def test_breaker_trip_records_nothing(self, scratch_breaker):
        tiny = CircuitBreaker("tiny", 100)
        LEDGER.set_breaker(tiny)
        before = LEDGER.snapshot()["total_bytes"]
        with pytest.raises(CircuitBreakingException):
            LEDGER.register("segment_columns", 1 << 20, label="boom")
        assert tiny.used == 0
        assert LEDGER.snapshot()["total_bytes"] == before
        assert not LEDGER.verify_breakers()

    def test_peak_tracking_survives_release(self, scratch_breaker):
        led = HBMLedger()          # isolated instance: deterministic peaks
        led.set_breaker(scratch_breaker)
        a = led.register("aligned_postings", 1 << 20)
        b = led.register("aligned_postings", 1 << 20)
        led.release(a)
        led.release(b)
        snap = led.snapshot()
        assert snap["total_bytes"] == 0
        assert snap["peak_bytes"] == 2 << 20
        assert snap["tenants"]["aligned_postings"]["peak_bytes"] == 2 << 20

    def test_uncharged_advisory_tenant(self, scratch_breaker):
        alloc = LEDGER.register("program", 0, charge=False, label="adv")
        assert scratch_breaker.used == 0
        snap = LEDGER.snapshot()
        assert snap["tenants"]["program"]["count"] >= 1
        LEDGER.release(alloc)


# ---------------------------------------------------------------------
# partial→full promotion dedupe (the satellite bugfix)
# ---------------------------------------------------------------------

class TestPartialPromotion:
    def test_partial_charges_released_on_full_build(self):
        c = make_client()
        for i in range(40):
            c.index("hbmt", {"body": f"alpha w{i}", "status": "draft"},
                    id=str(i))
        c.indices.refresh("hbmt")
        seg = c.node.indices["hbmt"].shards[0].segments[0]
        breaker = c.node.breakers.breaker("fielddata")
        used0 = breaker.used

        # partial residency first (the filter-mask path's entry point)
        seg.pruned_arrays(None, {"postings": {"status"},
                                 "keyword": {"status"}})
        partial_allocs = dict(seg.__dict__.get("_field_device_allocs", {}))
        assert partial_allocs, "partial build registered nothing"
        partial_bytes = sum(a.nbytes for a in partial_allocs.values())
        assert partial_bytes > 0
        assert breaker.used == used0 + partial_bytes

        # full-residency promotion: the partial charges must be released,
        # NOT stacked on top of the full pytree's charge (the
        # "later full device_arrays() reuses nothing" double-charge)
        seg.device_arrays(None)
        # codec v2 splits the full build across per-kind allocations
        # (segment_columns + impact_postings + advisory block_max)
        full_bytes = sum(a.nbytes for a in
                         seg.__dict__["_hbm_allocs"][None] if a.charged)
        assert breaker.used == used0 + full_bytes
        assert not any(k[0] is None for k in
                       seg.__dict__.get("_field_device_allocs", {}))
        assert all(not a.live for a in partial_allocs.values())
        # and pruned_arrays now serves from the full pytree, charging
        # nothing new
        seg.pruned_arrays(None, {"postings": {"status"}})
        assert breaker.used == used0 + full_bytes
        assert not LEDGER.verify_breakers()

    def test_drop_device_releases_eagerly(self):
        c = make_client()
        for i in range(10):
            c.index("hbmt", {"body": f"beta w{i}"}, id=str(i))
        c.indices.refresh("hbmt")
        seg = c.node.indices["hbmt"].shards[0].segments[0]
        breaker = c.node.breakers.breaker("fielddata")
        used0 = breaker.used
        seg.device_arrays(None)
        assert breaker.used > used0
        seg.drop_device()
        assert breaker.used == used0


# ---------------------------------------------------------------------
# end-to-end surfaces
# ---------------------------------------------------------------------

class TestSurfaces:
    def test_residency_events_on_timeline(self):
        c = make_client()
        for i in range(12):
            c.index("hbmt", {"body": f"gamma delta w{i}"}, id=str(i))
        c.indices.refresh("hbmt")
        enabled0 = RECORDER.enabled
        RECORDER.enabled = True
        try:
            # fresh segment: the search triggers the device_arrays build
            # inside the request timeline -> hbm.build lands on it
            c.search("hbmt", {"query": {"match": {"body": "gamma"}}})
            dump = c.flight_recorder_dump(note="hbm-test")["dump"]
        finally:
            RECORDER.enabled = enabled0
        kinds = [ev.get("kind")
                 for tl in dump["timelines"].values()
                 for ev in tl["events"]]
        assert "hbm.build" in kinds
        builds = [ev for tl in dump["timelines"].values()
                  for ev in tl["events"] if ev.get("kind") == "hbm.build"]
        assert any(ev.get("tenant") == "segment_columns"
                   and ev.get("bytes", 0) > 0 for ev in builds)

    def test_nodes_stats_hbm_block_and_cat_segments(self):
        c = make_client()
        for i in range(15):
            c.index("hbmt", {"body": f"epsilon w{i}"}, id=str(i))
        c.indices.refresh("hbmt")
        c.search("hbmt", {"query": {"match": {"body": "epsilon"}}})
        hbm = c.nodes_stats()["nodes"]["node-0"]["hbm"]
        assert hbm["total_bytes"] > 0
        assert hbm["charged_bytes"] <= hbm["total_bytes"] or \
            hbm["charged_bytes"] == hbm["total_bytes"]
        assert "segment_columns" in hbm["tenants"]
        rows = c.cat.segments("hbmt")
        assert rows
        row = rows[0]
        assert int(row["memory.device"]) > 0
        assert "segment_columns=" in row["memory.device.tenants"]

    def test_ledger_matches_breaker_stats(self):
        c = make_client()
        for i in range(8):
            c.index("hbmt", {"body": f"zeta w{i}"}, id=str(i))
        c.indices.refresh("hbmt")
        c.search("hbmt", {"query": {"match": {"body": "zeta"}}})
        assert not LEDGER.verify_breakers()


# ---------------------------------------------------------------------
# per-query cost accounting
# ---------------------------------------------------------------------

class TestQueryCost:
    def _fixed_corpus(self):
        """Known synthetic segment: hand-computable document frequencies
        for the 3-term oracle — df(alpha)=3, df(beta)=3, df(gamma)=2."""
        c = make_client()
        docs = ["alpha beta gamma", "alpha beta", "beta gamma delta",
                "alpha", "delta epsilon"]
        for i, d in enumerate(docs):
            c.index("hbmt", {"body": d}, id=str(i))
        c.indices.refresh("hbmt")
        return c

    def test_profile_cost_matches_hand_computed_oracle(self):
        c = self._fixed_corpus()
        r = c.search("hbmt", {"query": {"match": {
            "body": "alpha beta gamma"}}, "profile": True})
        cost = r["profile"]["cost"]
        # predicted, from CSR stats alone: (3 + 3 + 2) true postings,
        # 6 bytes per codec-v2 slot (doc_id i32 + u16 quantized impact)
        assert cost["predicted_bytes_gathered"] == 8 * 6
        assert cost["predicted_scatter_adds"] == 8
        # actual, from the launched program shape: the eager impact pass
        # (search/impactpath.py) flattens the kept blocks into
        # pick_bucket(8) = 256 slots (pow2 floor 256) of 6 bytes; the
        # scatter count is the TRUE kept posting count
        assert cost["actual_bytes_gathered"] == 256 * 6
        assert cost["actual_scatter_adds"] == 8
        assert cost["launches"] == 1
        assert cost["predicted_vs_actual_pct"] == pytest.approx(
            100.0 * 48 / 1536, abs=0.01)

    def test_profile_cost_v1_oracle(self, monkeypatch):
        """The legacy codec keeps the 8-byte slot model and the XLA
        bucket-gather actuals."""
        monkeypatch.setenv("OPENSEARCH_TPU_CODEC", "1")
        c = self._fixed_corpus()
        r = c.search("hbmt", {"query": {"match": {
            "body": "alpha beta gamma"}}, "profile": True})
        cost = r["profile"]["cost"]
        assert cost["predicted_bytes_gathered"] == 8 * 8
        assert cost["predicted_scatter_adds"] == 8
        assert cost["actual_bytes_gathered"] == 256 * 8
        assert cost["actual_scatter_adds"] == 256
        assert cost["launches"] == 1
        assert cost["predicted_vs_actual_pct"] == pytest.approx(
            100.0 * 64 / 2048, abs=0.01)

    def test_device_plan_explain_view(self):
        c = self._fixed_corpus()
        r = c.search("hbmt", {"query": {"match": {"body": "alpha beta"}},
                              "explain": "device_plan"})
        plan = r["device_plan"]
        # 6 postings x 6-byte codec-v2 slots
        assert plan["cost"]["predicted_bytes_gathered"] == 6 * 6
        segs = plan["segments"]
        assert any("predicted_bytes_gathered" in e for e in segs)
        assert any(e.get("path") in ("xla", "impact") for e in segs)
        # device_plan must not attach per-hit _explanation trees
        assert all("_explanation" not in h for h in r["hits"]["hits"])

    def test_cost_histograms_recorded(self):
        from opensearch_tpu.utils.metrics import METRICS
        c = self._fixed_corpus()
        c.search("hbmt", {"query": {"match": {"body": "alpha"}}})
        hists = METRICS.snapshot()["histograms"]
        assert hists.get("cost.bytes_per_query", {}).get("count", 0) >= 1
        assert hists.get("cost.predicted_bytes_per_query",
                         {}).get("count", 0) >= 1

    def test_cost_disabled_env(self, monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_COST", "0")
        c = self._fixed_corpus()
        r = c.search("hbmt", {"query": {"match": {"body": "alpha"}},
                              "profile": True})
        assert "cost" not in r["profile"]

    def test_spec_gather_shape_walker(self):
        # query spec: nid int in slot 1, bucket in slot 4
        spec = ("bool", 0,
                (("terms", 1, "body", 8, 512, 0, 1.2, 0.75, "score"),),
                (), (), ())
        b, s = query_cost.spec_gather_shape(spec)
        assert (b, s) == (512 * 8, 512)
        # agg-shaped "terms" spec (string prefix in slot 1) is NOT counted
        agg = ("terms", "a0", "status", 64, ())
        assert query_cost.spec_gather_shape(agg) == (0, 0)


# ---------------------------------------------------------------------
# hbm_report smoke (CI/tooling satellite)
# ---------------------------------------------------------------------

class TestHbmReport:
    def test_report_smoke(self, capsys):
        import importlib
        H = importlib.import_module("scripts.hbm_report")
        rc = H.main(["--ndocs", "120"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "HBM ledger:" in out
        assert "segment_columns" in out
        assert "bytes/query" in out

    def test_report_json_shape(self, tmp_path):
        import importlib
        H = importlib.import_module("scripts.hbm_report")
        qf = tmp_path / "q.jsonl"
        qf.write_text(json.dumps(
            {"query": {"match": {"body": "w00000"}}, "size": 5}) + "\n")
        rep = H.build_report(100, queries_path=str(qf))
        assert rep["queries_replayed"] == 1
        assert rep["ledger"]["total_bytes"] > 0
        assert rep["per_query_costs"] and \
            rep["per_query_costs"][0]["actual_bytes_gathered"] > 0


class TestPressureEviction:
    """ROADMAP item 2: loading past the HBM budget must EVICT the
    least-recently-used segment planes and succeed, not fail — a 1M+ doc
    index's residency is budget-bounded, not load-bounded."""

    def _mk(self, name, n=300):
        from opensearch_tpu.index.mappings import Mappings
        from opensearch_tpu.index.segment import build_segment
        m = Mappings({"properties": {"body": {"type": "text"}}})
        docs = [m.parse(f"{name}{i}", {"body": "alpha beta gamma delta"})
                for i in range(n)]
        return build_segment(name, docs, m)

    @staticmethod
    def _one_bytes(s):
        """One segment's full device footprint, measured as a ledger
        DELTA: earlier tests' segments may still be resident (charged to
        their own nodes' breakers), so the absolute total would inflate
        the eviction budget and the breaker would never trip."""
        gc.collect()               # flush pending weakref releases first
        before = LEDGER.total_bytes()
        s.device_arrays()
        one = LEDGER.total_bytes() - before
        s.drop_device()
        return one

    def test_load_past_budget_evicts_lru_and_succeeds(self):
        s1, s2, s3 = self._mk("ev_a"), self._mk("ev_b"), self._mk("ev_c")
        one = self._one_bytes(s1)
        old = LEDGER.breaker
        br = CircuitBreaker("evict-test", int(one * 2.5))
        LEDGER.set_breaker(br)
        try:
            base_ev = LEDGER.pressure_evictions
            s1.device_arrays()
            s2.device_arrays()          # both fit
            # regression: this used to raise CircuitBreakingException —
            # now the LRU plane group (s1: loaded first, never re-used)
            # is evicted and the load proceeds
            s3.device_arrays()
            assert LEDGER.pressure_evictions == base_ev + 1
            assert not s1._device_cache          # the LRU victim
            assert s2._device_cache and s3._device_cache
            # the evicted segment transparently rebuilds on next use
            # (evicting the new LRU, s2)
            s1.device_arrays()
            assert LEDGER.pressure_evictions == base_ev + 2
            assert not s2._device_cache
            assert not LEDGER.verify_breakers()
        finally:
            LEDGER.set_breaker(old)
            for s in (s1, s2, s3):
                s.drop_device()

    def test_recency_touch_orders_victims(self):
        s1, s2, s3 = self._mk("tr_a"), self._mk("tr_b"), self._mk("tr_c")
        one = self._one_bytes(s1)
        old = LEDGER.breaker
        br = CircuitBreaker("touch-test", int(one * 2.5))
        LEDGER.set_breaker(br)
        try:
            s1.device_arrays()
            s2.device_arrays()
            s1.device_arrays()          # touch s1: s2 becomes LRU
            s3.device_arrays()
            assert s1._device_cache and not s2._device_cache
        finally:
            LEDGER.set_breaker(old)
            for s in (s1, s2, s3):
                s.drop_device()

    def test_eviction_skips_segment_mid_build(self):
        s1, s2 = self._mk("mb_a"), self._mk("mb_b")
        one = self._one_bytes(s1)
        old = LEDGER.breaker
        br = CircuitBreaker("busy-test", int(one * 1.5))
        LEDGER.set_breaker(br)
        try:
            s1.device_arrays()
            # hold s1's build lock: the evictor must refuse it and, with
            # nothing else evictable, the breaker exception propagates
            lock = s1.__dict__["_device_build_lock"]
            assert lock.acquire(blocking=False)
            try:
                with pytest.raises(CircuitBreakingException):
                    s2.device_arrays()
            finally:
                lock.release()
            # lock released: the same load now evicts s1 and succeeds
            s2.device_arrays()
            assert not s1._device_cache and s2._device_cache
            assert not LEDGER.verify_breakers()
        finally:
            LEDGER.set_breaker(old)
            for s in (s1, s2):
                s.drop_device()

    def test_evict_pressure_event_on_recorder_timeline(self):
        from opensearch_tpu.obs import flight_recorder as fr
        s1, s2 = self._mk("rc_a"), self._mk("rc_b")
        one = self._one_bytes(s1)
        old = LEDGER.breaker
        br = CircuitBreaker("rec-test", int(one * 1.5))
        LEDGER.set_breaker(br)
        was_enabled = fr.RECORDER.enabled
        fr.RECORDER.enabled = True
        tl = fr.RECORDER.start("search", test="evict")
        tok = fr.set_current(tl)
        try:
            s1.device_arrays()
            s2.device_arrays()
            events = [e for e in fr.RECORDER.timeline_events(tl)
                      if e.get("kind") == "hbm.evict_pressure"]
            assert events and events[0]["segment"] == "rc_a"
            assert events[0]["bytes"] > 0
        finally:
            fr.reset_current(tok)
            fr.RECORDER.enabled = was_enabled
            LEDGER.set_breaker(old)
            for s in (s1, s2):
                s.drop_device()


class TestTouchCleanup:
    """Code-review regression: `_touch` recency keys must not outlive
    their (segment, device) plane group — merge/refresh churn mints a new
    uid per merge, so retained keys leak in the process singleton."""

    def _mk(self, name, n=120):
        from opensearch_tpu.index.mappings import Mappings
        from opensearch_tpu.index.segment import build_segment
        m = Mappings({"properties": {"body": {"type": "text"}}})
        docs = [m.parse(f"{name}{i}", {"body": "alpha beta gamma"})
                for i in range(n)]
        return build_segment(name, docs, m)

    def test_drop_device_removes_touch_key(self):
        s = self._mk("tk_a")
        s.device_arrays()
        key = (s.uid, "default")
        assert any(k[0] == s.uid for k in LEDGER._touch)
        s.drop_device()
        gc.collect()        # flush any weakref finalizer releases
        assert not any(k[0] == s.uid for k in LEDGER._touch), key

    def test_gc_of_segment_removes_touch_key(self):
        s = self._mk("tk_b")
        s.device_arrays()
        uid = s.uid
        del s
        gc.collect()
        assert not any(k[0] == uid for k in LEDGER._touch)

    def test_failed_build_cleans_touch_key(self, scratch_breaker):
        """A build that trips the breaker with nothing evictable never
        registered an allocation, so the release-side cleanup can't fire
        — the register failure path must drop the pre-build touch key or
        sustained pressure leaks one entry per failed build (code-review
        regression)."""
        from opensearch_tpu.utils.breaker import (CircuitBreaker,
                                                  CircuitBreakingException)
        tiny = CircuitBreaker("tiny", 1)       # nothing fits, nothing to evict
        old = LEDGER.breaker
        LEDGER.set_breaker(tiny)
        try:
            s = self._mk("tk_fail")
            with pytest.raises(CircuitBreakingException):
                s.device_arrays()
            assert not any(k[0] == s.uid for k in LEDGER._touch)
        finally:
            LEDGER.set_breaker(old)
