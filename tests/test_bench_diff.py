"""bench_diff (ISSUE 12 satellite): the perf-trajectory differ over the
committed BENCH ladder — both artifact shapes load, direction-aware
regression classification works, --gate exits nonzero past threshold,
and the committed ladder itself parses end to end."""

import importlib.util
import json
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_diff", os.path.join(REPO_ROOT, "scripts", "bench_diff.py"))
bench_diff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_diff)


def _direct_doc(qps, p99, bytes_p50, skip):
    return {"metric": "bm25_rest_qps_per_chip", "value": qps,
            "unit": "queries/sec", "vs_baseline": None,
            "extra": {
                "bytes_per_query": {"actual": {"count": 10,
                                               "p50": bytes_p50,
                                               "p95": bytes_p50 * 4}},
                "latency_percentiles": {
                    "search.total": {"count": 10, "p50_ms": p99 / 3,
                                     "p99_ms": p99}},
                "impacts": {"v2": {"qps_32t": qps,
                                   "block_skip_rate": skip,
                                   "mean_bytes_per_query": bytes_p50}},
            }}


class TestLoad:
    def test_direct_doc(self, tmp_path):
        p = tmp_path / "a.json"
        p.write_text(json.dumps(_direct_doc(100.0, 200.0, 4096, 0.5)))
        doc = bench_diff.load_bench(str(p))
        assert doc["value"] == 100.0

    def test_wrapper_doc_parses_tail(self, tmp_path):
        inner = _direct_doc(50.0, 100.0, 2048, 0.4)
        p = tmp_path / "w.json"
        p.write_text(json.dumps({
            "n": 3, "cmd": "python bench.py", "rc": 0,
            "tail": "WARNING: some log line\n" + json.dumps(inner) + "\n"}))
        doc = bench_diff.load_bench(str(p))
        assert doc["value"] == 50.0 and doc["_round"] == 3

    def test_wrapper_doc_unparsed_tail(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"n": 4, "cmd": "x", "rc": 124,
                                 "tail": "timed out\n"}))
        doc = bench_diff.load_bench(str(p))
        assert doc["extra"]["status"] == "unparsed"
        assert bench_diff.metrics_of(doc) == {}

    def test_garbage_raises(self, tmp_path):
        p = tmp_path / "g.json"
        p.write_text(json.dumps({"something": "else"}))
        with pytest.raises(ValueError):
            bench_diff.load_bench(str(p))


class TestDiff:
    def test_direction_classification(self):
        assert bench_diff.direction("qps") == "up"
        assert bench_diff.direction("reorder.bp.multi_eq.qps") == "up"
        assert bench_diff.direction("reorder.bp.multi_eq.lat_ms_p99") \
            == "down"
        assert bench_diff.direction("impacts.v2.block_skip_rate") == "up"
        assert bench_diff.direction(
            "bytes_per_query.actual.p50_bytes") == "down"

    def test_improvement_is_not_regression(self):
        old = bench_diff.metrics_of(_direct_doc(100.0, 400.0, 8192, 0.2))
        new = bench_diff.metrics_of(_direct_doc(150.0, 200.0, 2048, 0.7))
        rep = bench_diff.diff(old, new, 0.10)
        assert rep["compared"] > 0
        assert rep["regressions"] == []

    def test_regression_detected_and_gated(self, tmp_path):
        a = tmp_path / "old.json"
        b = tmp_path / "new.json"
        a.write_text(json.dumps(_direct_doc(100.0, 200.0, 2048, 0.6)))
        # qps down 30%, p99 up 2x, bytes up 4x: all three directions bad
        b.write_text(json.dumps(_direct_doc(70.0, 400.0, 8192, 0.6)))
        rep = bench_diff.diff_files(str(a), str(b), 0.10)
        bad = {r["metric"] for r in rep["regressions"]}
        assert "qps" in bad
        assert "latency.search.total.p99_ms" in bad
        assert "bytes_per_query.actual.p50_bytes" in bad
        # --gate exits 1; without it, informational exit 0
        assert bench_diff.main([str(a), str(b), "--gate"]) == 1
        assert bench_diff.main([str(a), str(b)]) == 0

    def test_threshold_suppresses_noise(self, tmp_path):
        a = tmp_path / "old.json"
        b = tmp_path / "new.json"
        a.write_text(json.dumps(_direct_doc(100.0, 200.0, 2048, 0.6)))
        b.write_text(json.dumps(_direct_doc(95.0, 210.0, 2100, 0.58)))
        rep = bench_diff.diff_files(str(a), str(b), 0.10)
        assert rep["regressions"] == []
        # a tighter threshold catches the same drift
        rep2 = bench_diff.diff_files(str(a), str(b), 0.03)
        assert any(r["metric"] == "qps" for r in rep2["regressions"])

    def test_usage_errors(self):
        assert bench_diff.main([]) == 2
        assert bench_diff.main(["nope.json", "also_nope.json"]) == 2


def _traffic_doc(t2g, shed, green, p95):
    return {"metric": "bm25_rest_qps_per_chip", "value": None,
            "unit": "queries/sec", "vs_baseline": None,
            "extra": {"traffic": {"scenarios": [
                {"scenario": "overload", "time_to_green_s": t2g,
                 "time_to_detect_s": 2.0, "shed_fraction": shed,
                 "green_within_window": green, "byte_stable": True,
                 "released_all": True,
                 "load": {"lat_ms_p50": p95 / 3, "lat_ms_p95": p95}},
                {"scenario": "baseline", "byte_stable": True,
                 "load": {"lat_ms_p50": 5.0, "lat_ms_p95": 20.0}},
            ]}}}


class TestTrafficShape:
    """The traffic-harness emission (scripts/traffic_harness.py): the
    differ extracts per-scenario time-to-green / shed fraction /
    green-under-load booleans and gates them like BENCH rounds."""

    def test_extraction(self):
        m = bench_diff.metrics_of(_traffic_doc(1.5, 0.8, True, 300.0))
        assert m["traffic.overload.time_to_green_s"] == 1.5
        assert m["traffic.overload.shed_fraction"] == 0.8
        assert m["traffic.overload.green_ok"] == 1.0
        assert m["traffic.overload.released_ok"] == 1.0
        assert m["traffic.overload.byte_stable"] == 1.0
        assert m["traffic.overload.lat_ms_p95"] == 300.0
        assert m["traffic.baseline.byte_stable"] == 1.0

    def test_directions(self):
        assert bench_diff.direction(
            "traffic.overload.time_to_green_s") == "down"
        assert bench_diff.direction(
            "traffic.overload.green_ok") == "up"
        assert bench_diff.direction(
            "traffic.overload.shed_fraction") == "up"
        assert bench_diff.direction(
            "traffic.overload.lat_ms_p95") == "down"

    def test_green_flip_is_a_gated_regression(self, tmp_path):
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(_traffic_doc(1.5, 0.8, True, 300.0)))
        # recovery stops fitting the window AND slows 3x: both gate
        b.write_text(json.dumps(_traffic_doc(4.5, 0.8, False, 300.0)))
        rep = bench_diff.diff_files(str(a), str(b), 0.10)
        bad = {r["metric"] for r in rep["regressions"]}
        assert "traffic.overload.green_ok" in bad
        assert "traffic.overload.time_to_green_s" in bad
        assert bench_diff.main([str(a), str(b), "--gate"]) == 1


def _ingest_doc(docs_per_s, rtv_p50, rtv_p95, q_p99_idle, q_p99_busy):
    return {"metric": "ingest_docs_per_s", "value": docs_per_s,
            "unit": "docs/sec",
            "extra": {"ingest": {
                "docs_per_s": docs_per_s,
                "refresh_to_visible": {"count": 500, "p50_ms": rtv_p50,
                                       "p95_ms": rtv_p95},
                "query_p99_ms_baseline": q_p99_idle,
                "query_p99_ms_while_indexing": q_p99_busy,
                "query_p99_degradation_ratio":
                    round(q_p99_busy / q_p99_idle, 4)}}}


class TestIngestShape:
    """The ingest bench emission (scripts/measure_ingest.py): docs/s,
    refresh-to-visible percentiles, and query-p99-while-indexing are
    direction-aware gated metrics (ISSUE 18 satellite)."""

    def test_extraction(self):
        m = bench_diff.metrics_of(
            _ingest_doc(5000.0, 40.0, 120.0, 20.0, 30.0))
        assert m["ingest.docs_per_s"] == 5000.0
        assert m["ingest.refresh_to_visible.p50_ms"] == 40.0
        assert m["ingest.refresh_to_visible.p95_ms"] == 120.0
        assert m["ingest.query_p99_ms_while_indexing"] == 30.0
        assert m["ingest.query_p99_degradation_ratio"] == 1.5

    def test_directions(self):
        assert bench_diff.direction("ingest.docs_per_s") == "up"
        assert bench_diff.direction(
            "ingest.refresh_to_visible.p95_ms") == "down"
        assert bench_diff.direction(
            "ingest.query_p99_ms_while_indexing") == "down"
        assert bench_diff.direction(
            "ingest.query_p99_degradation_ratio") == "down"
        assert bench_diff.direction(
            "concurrency.ingest_obs_overhead_32t.qps_ratio") == "up"

    def test_throughput_drop_and_lag_spike_gate(self, tmp_path):
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(
            _ingest_doc(5000.0, 40.0, 120.0, 20.0, 30.0)))
        # docs/s halves AND refresh lag triples: both must gate
        b.write_text(json.dumps(
            _ingest_doc(2500.0, 40.0, 360.0, 20.0, 30.0)))
        rep = bench_diff.diff_files(str(a), str(b), 0.10)
        bad = {r["metric"] for r in rep["regressions"]}
        assert "ingest.docs_per_s" in bad
        assert "ingest.refresh_to_visible.p95_ms" in bad
        assert bench_diff.main([str(a), str(b), "--gate"]) == 1

    def test_obs_overhead_pair_extracted(self):
        doc = {"metric": "x", "value": 1.0, "extra": {"concurrency": {
            "ingest_obs_overhead_32t": {"qps_ratio": 0.995}}}}
        m = bench_diff.metrics_of(doc)
        assert m["concurrency.ingest_obs_overhead_32t.qps_ratio"] \
            == 0.995


class TestCommittedLadder:
    def test_every_committed_round_loads(self):
        import glob
        paths = sorted(glob.glob(os.path.join(REPO_ROOT,
                                              "BENCH_r*.json")))
        assert len(paths) >= 2, "the committed ladder exists"
        for p in paths:
            doc = bench_diff.load_bench(p)
            assert isinstance(bench_diff.metrics_of(doc), dict)

    def test_ladder_walk(self):
        reports = bench_diff.ladder(0.10)
        assert reports, "adjacent pairs compared"
        for rep in reports:
            assert rep["compared"] >= 0
            assert "regressions" in rep
