"""terms_set, match_bool_prefix, combined_fields (BM25F), wrapper, pinned
queries + geo_distance aggregation.

References: TermsSetQueryBuilder.java, MatchBoolPrefixQueryBuilder.java,
CombinedFieldsQueryBuilder.java, WrapperQueryBuilder.java,
PinnedQueryBuilder.java, bucket/range/GeoDistanceAggregationBuilder.java."""

import base64
import json

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient


@pytest.fixture
def client():
    c = RestClient()
    c.indices.create("nq", body={"mappings": {"properties": {
        "codes": {"type": "keyword"},
        "required": {"type": "integer"},
        "title": {"type": "text"},
        "body": {"type": "text"},
        "loc": {"type": "geo_point"}}}})
    docs = [
        {"codes": ["a", "b", "c"], "required": 2,
         "title": "quick brown fox", "body": "lazy dog",
         "loc": {"lat": 0.0, "lon": 0.0}},
        {"codes": ["a"], "required": 2,
         "title": "quick start guide", "body": "install quick tools",
         "loc": {"lat": 0.0, "lon": 1.0}},
        {"codes": ["b", "c"], "required": 1,
         "title": "slow cooker", "body": "brown stew fox",
         "loc": {"lat": 0.0, "lon": 3.0}},
        {"codes": ["a", "b"], "required": 3,
         "title": "fox hunting", "body": "quick quick quick",
         "loc": {"lat": 45.0, "lon": 90.0}},
    ]
    for i, d in enumerate(docs):
        c.index("nq", d, id=str(i))
    c.indices.refresh("nq")
    return c


def _ids(r):
    return {h["_id"] for h in r["hits"]["hits"]}


class TestTermsSet:
    def test_msm_field(self, client):
        r = client.search("nq", {"query": {"terms_set": {"codes": {
            "terms": ["a", "b", "c"],
            "minimum_should_match_field": "required"}}}})
        # doc0: 3 matches >= 2 OK; doc1: 1 >= 2 no; doc2: 2 >= 1 OK;
        # doc3: 2 >= 3 no
        assert _ids(r) == {"0", "2"}

    def test_msm_constant_script(self, client):
        r = client.search("nq", {"query": {"terms_set": {"codes": {
            "terms": ["a", "b", "c"],
            "minimum_should_match_script": {
                "source": "params.num_terms - 1"}}}}})
        # need >= 2 matches: doc0 (3), doc2 (2), doc3 (2)
        assert _ids(r) == {"0", "2", "3"}

    def test_msm_doc_script(self, client):
        r = client.search("nq", {"query": {"terms_set": {"codes": {
            "terms": ["a", "b", "c"],
            "minimum_should_match_script": {
                "source": "doc['required'].value"}}}}})
        assert _ids(r) == {"0", "2"}

    def test_validation_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("nq", {"query": {"terms_set": {"codes": {
                "terms": ["a"]}}}})
        assert ei.value.status == 400


class TestMatchBoolPrefix:
    def test_last_term_prefix(self, client):
        r = client.search("nq", {"query": {"match_bool_prefix": {
            "title": "quick br"}}})
        # "quick" OR prefix "br": doc0 (quick+brown), doc1 (quick)
        assert "0" in _ids(r) and "1" in _ids(r)

    def test_operator_and(self, client):
        r = client.search("nq", {"query": {"match_bool_prefix": {
            "title": {"query": "quick br", "operator": "and"}}}})
        assert _ids(r) == {"0"}


class TestCombinedFields:
    def test_union_semantics(self, client):
        r = client.search("nq", {"query": {"combined_fields": {
            "query": "quick", "fields": ["title", "body"]}}})
        # quick in title (0,1) or body (1,3)
        assert _ids(r) == {"0", "1", "3"}

    def test_weighted_field_changes_ranking(self, client):
        r1 = client.search("nq", {"query": {"combined_fields": {
            "query": "quick", "fields": ["title^5", "body"]}}})
        r2 = client.search("nq", {"query": {"combined_fields": {
            "query": "quick", "fields": ["title", "body^5"]}}})
        # body-heavy weighting favors doc3 (3x quick in body)
        assert r2["hits"]["hits"][0]["_id"] == "3"
        assert r1["hits"]["hits"][0]["_id"] != "3"

    def test_operator_and(self, client):
        r = client.search("nq", {"query": {"combined_fields": {
            "query": "quick fox", "fields": ["title", "body"],
            "operator": "and"}}})
        # needs both terms across the combined field: doc0 (t+t),
        # doc3 (title fox + body quick)
        assert _ids(r) == {"0", "3"}

    def test_requires_fields(self, client):
        with pytest.raises(ApiError):
            client.search("nq", {"query": {"combined_fields": {
                "query": "x"}}})


class TestWrapperAndPinned:
    def test_wrapper(self, client):
        inner = base64.b64encode(
            json.dumps({"term": {"codes": "a"}}).encode()).decode()
        r = client.search("nq", {"query": {"wrapper": {"query": inner}}})
        assert _ids(r) == {"0", "1", "3"}

    def test_wrapper_bad_payload_400(self, client):
        with pytest.raises(ApiError):
            client.search("nq", {"query": {"wrapper": {"query": "!!!"}}})

    def test_pinned(self, client):
        r = client.search("nq", {"query": {"pinned": {
            "ids": ["2", "1"],
            "organic": {"match": {"title": "quick"}}}}})
        got = [h["_id"] for h in r["hits"]["hits"]]
        assert got[:2] == ["2", "1"]          # pinned order wins
        assert set(got[2:]) == {"0"}           # organic follows (doc1 pinned)

    def test_pinned_no_organic(self, client):
        r = client.search("nq", {"query": {"pinned": {"ids": ["3"]}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["3"]


class TestGeoDistanceAgg:
    def test_rings(self, client):
        r = client.search("nq", {"size": 0, "aggs": {"rings": {
            "geo_distance": {"field": "loc",
                             "origin": {"lat": 0, "lon": 0},
                             "unit": "km",
                             "ranges": [{"to": 200},
                                        {"from": 200, "to": 1000},
                                        {"from": 1000}]}}}})
        buckets = r["aggregations"]["rings"]["buckets"]
        assert [b["doc_count"] for b in buckets] == [2, 1, 1]
        assert buckets[1]["from"] == 200 and buckets[1]["to"] == 1000

    def test_sub_metric(self, client):
        r = client.search("nq", {"size": 0, "aggs": {"rings": {
            "geo_distance": {"field": "loc",
                             "origin": "0,0", "unit": "km",
                             "ranges": [{"to": 500}]},
            "aggs": {"mx": {"max": {"field": "required"}}}}}})
        b = r["aggregations"]["rings"]["buckets"][0]
        assert b["doc_count"] == 3     # docs at 0, ~111km, ~333km
        assert b["mx"]["value"] == 2.0


class TestReviewRegressions:
    def test_combined_fields_bad_boost_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("nq", {"query": {"combined_fields": {
                "query": "fox", "fields": ["title^bad"]}}})
        assert ei.value.status == 400

    def test_geo_distance_agg_missing_origin_400(self, client):
        with pytest.raises(ApiError) as ei:
            client.search("nq", {"size": 0, "aggs": {"x": {
                "geo_distance": {"field": "loc",
                                 "ranges": [{"to": 100}]}}}})
        assert ei.value.status == 400

    def test_pinned_profile_shows_organic(self, client):
        r = client.search("nq", {"profile": True, "query": {"pinned": {
            "ids": ["1"], "organic": {"match": {"title": "quick"}}}}})
        q = r["profile"]["shards"][0]["searches"][0]["query"][0]
        assert q["type"] == "Pinned"
        assert any(c["type"] == "Terms" for c in q.get("children", []))
