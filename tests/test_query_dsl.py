import pytest

from opensearch_tpu.search.query_dsl import (BoolQuery, MatchQuery, QueryParseError,
                                             TermQuery, parse_minimum_should_match,
                                             parse_query)


def test_parse_shorthand_and_full_forms():
    q = parse_query({"term": {"f": "v"}})
    assert isinstance(q, TermQuery) and q.value == "v" and q.boost == 1.0
    q = parse_query({"term": {"f": {"value": "v", "boost": 2.0}}})
    assert q.boost == 2.0
    q = parse_query({"match": {"f": {"query": "a b", "operator": "AND"}}})
    assert isinstance(q, MatchQuery) and q.operator == "and"


def test_parse_bool_nested():
    q = parse_query({"bool": {"must": {"term": {"a": 1}},
                              "should": [{"match": {"b": "x"}}],
                              "filter": [{"range": {"c": {"gte": 0}}}]}})
    assert isinstance(q, BoolQuery)
    assert len(q.must) == 1 and len(q.should) == 1 and len(q.filter) == 1


def test_parse_errors():
    with pytest.raises(QueryParseError):
        parse_query({"unknown_query": {}})
    with pytest.raises(QueryParseError):
        parse_query({"terms": {"a": [1], "b": [2]}})


def test_minimum_should_match_grammar():
    assert parse_minimum_should_match("2", 5) == 2
    assert parse_minimum_should_match("-1", 5) == 4
    assert parse_minimum_should_match("60%", 5) == 3
    assert parse_minimum_should_match("-25%", 4) == 3
    assert parse_minimum_should_match(None, 5) == 0
    assert parse_minimum_should_match("10", 3) == 3


def test_geo_distance_units():
    q = parse_query({"geo_distance": {"distance": "2km",
                                      "loc": {"lat": 1.0, "lon": 2.0}}})
    assert q.distance_m == 2000.0
    q = parse_query({"geo_distance": {"distance": "1mi", "loc": "1,2"}})
    assert abs(q.distance_m - 1609.344) < 1e-6


def test_match_none_and_all():
    from opensearch_tpu.search.query_dsl import MatchAllQuery, MatchNoneQuery
    assert isinstance(parse_query(None), MatchAllQuery)
    assert isinstance(parse_query({"match_none": {}}), MatchNoneQuery)
