"""Analysis filter additions (word_delimiter, pattern_capture, elision,
ngram filters, keyword_marker+stemmer fusion, stemmer_override, limit,
decimal_digit, apostrophe), _cat endpoint completion, failure-detector
heartbeat, fvh highlight type.

References: modules/analysis-common factories, rest/action/cat/,
cluster/coordination/FollowersChecker.java."""

import pytest

from opensearch_tpu.analysis.analyzers import AnalysisRegistry
from opensearch_tpu.rest.client import RestClient


def _texts(reg, analyzer, s):
    return [t.text for t in reg.get(analyzer).analyze(s)]


def _registry(filters: dict, analyzer_filters: list):
    return AnalysisRegistry({
        "filter": filters,
        "analyzer": {"t": {"type": "custom", "tokenizer": "whitespace",
                           "filter": analyzer_filters}}})


class TestNewFilters:
    def test_word_delimiter(self):
        reg = _registry({}, ["word_delimiter"])
        assert _texts(reg, "t", "Wi-Fi PowerShot500") == \
            ["Wi", "Fi", "Power", "Shot", "500"]

    def test_word_delimiter_catenate(self):
        reg = _registry({"wd": {"type": "word_delimiter",
                                "catenate_words": True}}, ["wd"])
        out = _texts(reg, "t", "wi-fi")
        assert "wifi" in out and "wi" in out and "fi" in out

    def test_pattern_capture(self):
        reg = _registry({"pc": {"type": "pattern_capture",
                                "patterns": [r"(\d+)"],
                                "preserve_original": True}}, ["pc"])
        assert set(_texts(reg, "t", "abc123def")) == {"abc123def", "123"}

    def test_elision(self):
        reg = _registry({}, ["elision"])
        assert _texts(reg, "t", "l'avion d'art") == ["avion", "d'art"]

    def test_edge_ngram_filter(self):
        reg = _registry({"eg": {"type": "edge_ngram", "min_gram": 1,
                                "max_gram": 3}}, ["eg"])
        assert _texts(reg, "t", "fox") == ["f", "fo", "fox"]

    def test_keyword_marker_protects_stemming(self):
        reg = _registry({"km": {"type": "keyword_marker",
                                "keywords": ["running"]}},
                        ["km", "stemmer"])
        assert _texts(reg, "t", "running jumping") == ["running", "jump"]

    def test_stemmer_override(self):
        reg = _registry({"so": {"type": "stemmer_override",
                                "rules": ["running => sprint"]}},
                        ["so"])
        assert _texts(reg, "t", "running") == ["sprint"]

    def test_limit_decimal_apostrophe(self):
        reg = _registry({"lim": {"type": "limit", "max_token_count": 2}},
                        ["lim"])
        assert _texts(reg, "t", "a b c d") == ["a", "b"]
        reg = _registry({}, ["apostrophe"])
        assert _texts(reg, "t", "o'brien turkish'i") == ["o", "turkish"]


class TestCatEndpoints:
    @pytest.fixture
    def client(self):
        c = RestClient()
        c.indices.create("c1", body={"aliases": {"al": {}}})
        c.index("c1", {"x": 1}, id="1", refresh=True)
        c.indices.put_index_template("tpl", {"index_patterns": ["z*"]})
        return c

    def test_cat_nodes_health_segments(self, client):
        assert client.cat.nodes()[0]["docs.count"] == "1"
        h = client.cat.health()[0]
        assert h["status"] in ("green", "yellow", "red")
        segs = client.cat.segments()
        assert segs and segs[0]["index"] == "c1"
        assert segs[0]["docs.count"] == "1"

    def test_cat_aliases_templates_allocation(self, client):
        al = client.cat.aliases()
        assert al and al[0]["alias"] == "al" and al[0]["index"] == "c1"
        t = client.cat.templates()
        assert any(row["name"] == "tpl" for row in t)
        assert int(client.cat.allocation()[0]["shards"]) >= 1


class TestFailureDetector:
    def test_threshold_and_failover(self):
        c = RestClient()
        c.indices.create("fd", body={"settings": {"number_of_shards": 1,
                                                  "number_of_replicas": 1}})
        c.index("fd", {"v": 1}, id="1", refresh=True)
        fd = c.node.failure_detector
        # probe that fails only device 0
        calls = []

        def prober(dev):
            calls.append(dev)
            import jax
            return dev is not jax.devices()[0]
        fd.prober = prober
        fd.failure_threshold = 2
        ev1 = fd.tick()
        assert any(e["event"] == "probe_failed" for e in ev1)
        assert not fd.dead
        ev2 = fd.tick()
        assert any(e["event"] == "failed" and e["device"] == 0 for e in ev2)
        assert 0 in fd.dead
        # search still works after failover handling
        r = c.search("fd", {"query": {"match_all": {}}})
        assert r["hits"]["total"]["value"] == 1
        st = c.node.stats()["failure_detection"]
        assert st["dead_devices"] == [0] and st["rounds"] == 2

    def test_recovery_event(self):
        c = RestClient()
        fd = c.node.failure_detector
        flaky = {"fail": True}
        fd.prober = lambda dev: not flaky["fail"]
        fd.failure_threshold = 5
        fd.tick()
        flaky["fail"] = False
        ev = fd.tick()
        assert any(e["event"] == "recovered" for e in ev)


class TestFvhType:
    def test_fvh_highlight(self):
        c = RestClient()
        c.indices.create("hv", body={"mappings": {"properties": {
            "t": {"type": "text"}}}})
        c.index("hv", {"t": "the quick brown fox jumps over the dog"},
                id="1", refresh=True)
        r = c.search("hv", {"query": {"match": {"t": "fox"}},
                            "highlight": {"fields": {"t": {"type": "fvh"}}}})
        frags = r["hits"]["hits"][0]["highlight"]["t"]
        assert any("<em>fox</em>" in f for f in frags)


class TestReviewRegressions:
    def test_stemmer_override_not_restemmed(self):
        reg = _registry({"so": {"type": "stemmer_override",
                                "rules": ["mice => mouse"]}},
                        ["so", "stemmer"])
        assert _texts(reg, "t", "mice running") == ["mouse", "run"]

    def test_combined_fields_commensurate_with_match(self):
        c = RestClient()
        c.indices.create("cfm", body={"mappings": {"properties": {
            "a": {"type": "text"}}}})
        c.index("cfm", {"a": "zebra"}, id="1", refresh=True)
        r1 = c.search("cfm", {"query": {"combined_fields": {
            "query": "zebra", "fields": ["a"]}}})
        r2 = c.search("cfm", {"query": {"match": {"a": "zebra"}}})
        s1 = r1["hits"]["hits"][0]["_score"]
        s2 = r2["hits"]["hits"][0]["_score"]
        # single field, weight 1 -> identical BM25 (no (k1+1) inflation)
        assert s1 == pytest.approx(s2, rel=1e-5)

    def test_geo_ring_boundary_refinement(self):
        c = RestClient()
        c.indices.create("gb", body={"mappings": {"properties": {
            "loc": {"type": "geo_point"}, "k": {"type": "keyword"}}}})
        c.index("gb", {"loc": "0,0", "k": "x"}, id="origin", refresh=True)
        # doc at distance exactly 0; ring [0, 10km): strict-< refinement
        # keeps it in the same bucket the device counted it in
        r = c.search("gb", {"size": 0, "aggs": {"rings": {
            "geo_distance": {"field": "loc", "origin": "0,0", "unit": "km",
                             "ranges": [{"from": 0, "to": 10}]},
            "aggs": {"kt": {"terms": {"field": "k"},
                            "aggs": {"c": {"cardinality": {
                                "field": "k"}}}}}}}})
        b = r["aggregations"]["rings"]["buckets"][0]
        assert b["doc_count"] == 1
        assert b["kt"]["buckets"][0]["doc_count"] == 1


class TestPositionalFusion:
    def test_marker_after_lowercase_protects_lowercased_form(self):
        # keyword set matches the text AS IT IS at the stemmer's position
        reg = _registry({"km": {"type": "keyword_marker",
                                "keywords": ["running"]}},
                        ["lowercase", "km", "stemmer"])
        assert _texts(reg, "t", "Running jumping") == ["running", "jump"]

    def test_override_before_intervening_filter_stays_positional(self):
        # override applies at its declared position, before lowercase
        reg = _registry({"so": {"type": "stemmer_override",
                                "rules": ["FOO => Bar"]}},
                        ["so", "lowercase"])
        assert _texts(reg, "t", "FOO") == ["bar"]

    def test_probe_timeout_counts_as_failure(self):
        c = RestClient()
        fd = c.node.failure_detector
        fd.probe_timeout_s = 0.2
        fd.failure_threshold = 1
        fd.prober = lambda dev: __import__("time").sleep(5) or True
        ev = fd.tick()
        assert any(e["event"] == "failed" for e in ev)


class TestKeywordAttribute:
    def test_marker_survives_intervening_filters(self):
        # the keyword FLAG persists across intervening filters, like the
        # reference KeywordAttribute
        reg = _registry({"km": {"type": "keyword_marker",
                                "keywords": ["running"]}},
                        ["km", "trim", "stemmer"])
        assert _texts(reg, "t", "running jumping") == ["running", "jump"]

    def test_marker_ignore_case(self):
        reg = _registry({"km": {"type": "keyword_marker",
                                "keywords": ["running"],
                                "ignore_case": True}},
                        ["km", "lowercase", "stemmer"])
        assert _texts(reg, "t", "Running") == ["running"]
