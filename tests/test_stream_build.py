"""Streaming segment builder (index/segment.py StreamingSegmentBuilder):
chunked/spill build must be BIT-IDENTICAL to the in-memory build — same
CSR arrays, same doc values, same impact planes — because refresh picks
the path by buffer size alone (index/engine.py stream_refresh_min_docs)
and replicas/oracles assume one canonical segment per doc set."""

import os

import numpy as np
import pytest

from opensearch_tpu.index.engine import Engine
from opensearch_tpu.index.mappings import Mappings
from opensearch_tpu.index.segment import (StreamingSegmentBuilder,
                                          build_segment,
                                          build_segment_streaming,
                                          stream_eligible)

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "title": {"type": "text"},
        "status": {"type": "keyword"},
        "price": {"type": "integer"},
        "rating": {"type": "float"},
        "loc": {"type": "geo_point"},
        "vec": {"type": "knn_vector", "dimension": 4},
    }
}


def _corpus(n, seed=0, vocab=400):
    rng = np.random.default_rng(seed)
    m = Mappings(MAPPINGS)
    words = [f"w{i:04d}" for i in range(vocab)]
    docs = []
    for i in range(n):
        src = {"body": " ".join(
            words[int(t)] for t in rng.zipf(1.3, rng.integers(2, 9)) % vocab)}
        if i % 3 == 0:
            src["title"] = f"{words[i % vocab]} {words[(i * 7) % vocab]}"
        if i % 2 == 0:
            src["status"] = ["a", "b", "c"][i % 3]
        if i % 5 == 0:
            src["price"] = int(rng.integers(0, 500))
        if i % 7 == 0:
            src["rating"] = float(rng.random())
        if i % 11 == 0:
            src["loc"] = {"lat": float(rng.uniform(-80, 80)),
                          "lon": float(rng.uniform(-170, 170))}
        if i % 13 == 0:
            src["vec"] = [float(x) for x in rng.random(4)]
        docs.append(m.parse(f"d{i}", src))
    return m, docs


def assert_segments_identical(a, b):
    assert a.ndocs == b.ndocs
    assert a.codec_version == b.codec_version
    assert set(a.postings) == set(b.postings)
    for f, pa in a.postings.items():
        pb = b.postings[f]
        assert pa.vocab == pb.vocab
        for attr in ("starts", "doc_ids", "tfs"):
            xa, xb = getattr(pa, attr), getattr(pb, attr)
            assert xa.dtype == xb.dtype, (f, attr)
            assert np.array_equal(xa, xb), (f, attr)
        assert (pa.pos_starts is None) == (pb.pos_starts is None)
        if pa.pos_starts is not None:
            assert np.array_equal(pa.pos_starts, pb.pos_starts)
            assert np.array_equal(pa.positions, pb.positions)
        assert (pa.impact is None) == (pb.impact is None)
        if pa.impact is not None:
            ia, ib = pa.impact, pb.impact
            assert np.array_equal(ia.q, ib.q)
            assert ia.scale == ib.scale and ia.bits == ib.bits
            assert ia.avgdl == ib.avgdl and ia.dl_max == ib.dl_max
            assert np.array_equal(ia.block_starts, ib.block_starts)
            assert np.array_equal(ia.block_off, ib.block_off)
            assert np.array_equal(ia.block_max, ib.block_max)
    assert set(a.numeric_cols) == set(b.numeric_cols)
    for f, ca in a.numeric_cols.items():
        cb = b.numeric_cols[f]
        assert ca.kind == cb.kind
        assert ca.values.dtype == cb.values.dtype
        assert np.array_equal(ca.values, cb.values)
        assert np.array_equal(ca.present, cb.present)
    assert set(a.keyword_cols) == set(b.keyword_cols)
    for f, ca in a.keyword_cols.items():
        cb = b.keyword_cols[f]
        assert ca.vocab == cb.vocab
        for attr in ("starts", "ords", "doc_of_value", "min_ord"):
            assert np.array_equal(getattr(ca, attr), getattr(cb, attr)), \
                (f, attr)
    for f, ca in a.geo_cols.items():
        cb = b.geo_cols[f]
        assert np.array_equal(ca.lat, cb.lat)
        assert np.array_equal(ca.lon, cb.lon)
        assert np.array_equal(ca.present, cb.present)
    for f, ca in a.vector_cols.items():
        cb = b.vector_cols[f]
        assert np.array_equal(ca.values, cb.values)
        assert np.array_equal(ca.present, cb.present)
        assert ca.similarity == cb.similarity
    assert set(a.doc_lens) == set(b.doc_lens)
    for f in a.doc_lens:
        assert np.array_equal(a.doc_lens[f], b.doc_lens[f])
    assert {f: (s.doc_count, s.sum_dl) for f, s in a.text_stats.items()} \
        == {f: (s.doc_count, s.sum_dl) for f, s in b.text_stats.items()}
    assert list(a.ids) == list(b.ids)
    assert list(a.sources) == list(b.sources)
    assert np.array_equal(a.seq_nos, b.seq_nos)
    assert (a.stored_vals is None) == (b.stored_vals is None)


class TestStreamingEquivalence:
    def test_50k_doc_chunked_spill_build_bit_identical(self, tmp_path):
        """The ISSUE-11 satellite gate: a 50k-doc corpus through the
        chunked/spill path is array-for-array identical to the in-memory
        build (impact planes included)."""
        m, docs = _corpus(50_000, seed=3)
        seqs = list(range(len(docs)))
        mem = build_segment("s", docs, m, seq_nos=seqs)
        stream = build_segment_streaming("s", docs, m, seq_nos=seqs,
                                         chunk_docs=4096,
                                         spill_dir=str(tmp_path))
        assert_segments_identical(mem, stream)
        # the spill dir is cleaned up after finish
        assert not any(f.endswith(".npz") for f in os.listdir(tmp_path))

    def test_chunk_size_does_not_change_output(self):
        m, docs = _corpus(700, seed=5)
        a = build_segment_streaming("s", docs, m, chunk_docs=64)
        b = build_segment_streaming("s", docs, m, chunk_docs=701)
        mem = build_segment("s", docs, m)
        assert_segments_identical(mem, a)
        assert_segments_identical(mem, b)

    def test_positions_survive_chunk_boundaries(self):
        m = Mappings({"properties": {"body": {"type": "text"}}})
        docs = [m.parse(str(i), {"body": f"x y x z w{i % 7} x"})
                for i in range(300)]
        mem = build_segment("s", docs, m)
        st = build_segment_streaming("s", docs, m, chunk_docs=37)
        assert_segments_identical(mem, st)
        # sanity: a mid-corpus doc's positions for the tripled term
        pb = st.postings["body"]
        r = pb.row("x")
        a, b = pb.row_slice(r)
        k = a + int(np.searchsorted(pb.doc_ids[a:b], 153))
        assert pb.doc_ids[k] == 153
        ps, pe = pb.pos_starts[k], pb.pos_starts[k + 1]
        assert list(pb.positions[ps:pe]) == [0, 2, 5]

    def test_ineligible_docs_raise_and_gate_reports(self):
        m = Mappings({"properties": {
            "n": {"type": "nested", "properties": {
                "a": {"type": "keyword"}}}}})
        pd = m.parse("1", {"n": [{"a": "x"}]})
        assert not stream_eligible([pd])
        b = StreamingSegmentBuilder("s", m)
        with pytest.raises(ValueError):
            b.add(pd)
        b._cleanup()

    def test_empty_and_single_chunk(self):
        m = Mappings({"properties": {"body": {"type": "text"}}})
        docs = [m.parse("only", {"body": "solo token"})]
        mem = build_segment("s", docs, m)
        st = build_segment_streaming("s", docs, m, chunk_docs=10)
        assert_segments_identical(mem, st)


class TestEngineStreamingRefresh:
    def test_refresh_routes_large_buffers_through_streaming(self,
                                                            monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_STREAM_REFRESH_DOCS", "100")
        m = Mappings({"properties": {"body": {"type": "text"}}})
        eng = Engine(m)
        for i in range(250):
            eng.index_doc(str(i), {"body": f"alpha w{i % 17} beta"})
        eng.refresh()
        assert eng.stats.get("stream_refreshes", 0) == 1
        assert eng.num_docs == 250
        # realtime get still resolves through the streamed segment
        got = eng.get("137")
        assert got is not None and got["found"]

    def test_streamed_and_buffered_refresh_segments_identical(
            self, monkeypatch):
        m = Mappings({"properties": {"body": {"type": "text"},
                                     "status": {"type": "keyword"}}})

        def fill(e):
            for i in range(180):
                e.index_doc(str(i), {"body": f"tok{i % 23} common",
                                     "status": ["x", "y"][i % 2]})
            e.refresh()

        monkeypatch.setenv("OPENSEARCH_TPU_STREAM_REFRESH_DOCS", "50")
        eng_s = Engine(m)
        fill(eng_s)
        monkeypatch.setenv("OPENSEARCH_TPU_STREAM_REFRESH_DOCS", "100000")
        eng_m = Engine(m)
        fill(eng_m)
        assert eng_s.stats.get("stream_refreshes", 0) == 1
        assert eng_m.stats.get("stream_refreshes", 0) == 0
        assert_segments_identical(eng_m.segments[0], eng_s.segments[0])

    def test_nested_docs_fall_back_to_in_memory_build(self, monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_STREAM_REFRESH_DOCS", "10")
        m = Mappings({"properties": {
            "body": {"type": "text"},
            "n": {"type": "nested", "properties": {
                "a": {"type": "keyword"}}}}})
        eng = Engine(m)
        for i in range(40):
            eng.index_doc(str(i), {"body": "alpha",
                                   "n": [{"a": f"v{i % 3}"}]})
        eng.refresh()
        assert eng.stats.get("stream_refreshes", 0) == 0
        assert eng.num_docs == 40
        assert "n" in eng.segments[0].nested
