"""Deadline-aware parallel legs (ISSUE 17, utils/legs.py) and the two
hot loops refactored onto them.

The tentpole invariants, asserted here:

- `LegSet.join()` returns outcomes in ADD order with per-leg exception
  capture; ambient context (Deadline, contextvars) travels with every
  leg; nested fan-outs spill to dedicated threads and cannot starve
  the bounded pool; a wedged leg is abandoned after the ambient budget
  plus grace, never waited on forever.
- The serial arm (`OPENSEARCH_TPU_LEGS=0`) is the SAME primitive minus
  the scheduling: identical leg paths, identical outcome objects — so
  every downstream merge (hybrid fusion, scatter reduce) is
  byte-identical across arms, under 32-thread load, under seeded chaos
  (kill / flaky / blackhole), on both distnode coordinators.
- `ChaosSchedule` keys per-rule call counters and probability draws by
  the call's stable identity (op, member, leg path): seeded journals
  replay byte-identically no matter how threads interleave, and the
  serial and parallel arms produce the SAME canonical journal.
- A single slow leg no longer stalls its siblings: hybrid latency is
  the MAX of the sub-retrievals, not the SUM.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from opensearch_tpu.cluster import faults
from opensearch_tpu.cluster.distnode import DistClusterNode, RetryPolicy
from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.search import fusion
from opensearch_tpu.utils import deadline as dl
from opensearch_tpu.utils import legs
from opensearch_tpu.utils.metrics import METRICS


@pytest.fixture()
def serial_arm(monkeypatch):
    monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")


@pytest.fixture(autouse=True)
def _no_leftover_chaos():
    yield
    faults.uninstall()


# ---------------------------------------------------------------------
# the primitive
# ---------------------------------------------------------------------

class TestLegSetPrimitive:
    def test_results_in_add_order_and_overlap(self):
        ls = legs.LegSet("t")
        for i in range(6):
            ls.add_leg(lambda i=i: (time.sleep(0.08), i)[1], name=str(i))
        t0 = time.monotonic()
        out = ls.join()
        wall = time.monotonic() - t0
        assert [leg.value for leg in out] == list(range(6))
        assert all(leg.ok for leg in out)
        # 6 x 80ms overlapped: max-shaped, not sum-shaped
        assert wall < 0.35

    def test_serial_arm_same_outcomes(self, serial_arm):
        ls = legs.LegSet("t")
        for i in range(3):
            ls.add_leg(lambda i=i: (i, legs.current_path()), name=str(i))
        out = ls.join()
        assert [leg.value for leg in out] == [
            (0, "t:0"), (1, "t:1"), (2, "t:2")]

    def test_leg_paths_identical_across_arms(self, monkeypatch):
        def run():
            def sub(i):
                inner = legs.LegSet("inner")
                for j in range(2):
                    inner.add_leg(lambda: legs.current_path(),
                                  name=str(j))
                return [leg.value for leg in inner.join()]
            ls = legs.LegSet("outer")
            for i in range(2):
                ls.add_leg(lambda i=i: sub(i), name=str(i))
            return [leg.value for leg in ls.join()]

        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        par = run()
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")
        ser = run()
        assert par == ser == [
            ["outer:0/inner:0", "outer:0/inner:1"],
            ["outer:1/inner:0", "outer:1/inner:1"]]
        assert legs.current_path() == ""   # restored outside legs

    def test_context_travels_with_leg(self):
        d = dl.Deadline(30.0)
        with dl.scope(d):
            ls = legs.LegSet("ctx")
            ls.add_leg(lambda: dl.current() is d)
            out = ls.join()
        assert out[0].value is True

    def test_exception_capture_and_result_raises(self):
        ls = legs.LegSet("e")
        ls.add_leg(lambda: 1 / 0, name="boom")
        ls.add_leg(lambda: 42, name="fine")
        out = ls.join()
        assert isinstance(out[0].error, ZeroDivisionError)
        assert out[1].value == 42 and out[1].ok
        with pytest.raises(ZeroDivisionError):
            out[0].result()

    def test_wedged_leg_abandoned_within_budget(self):
        release = threading.Event()
        with dl.scope(dl.Deadline(0.05)):
            ls = legs.LegSet("w")
            ls.add_leg(lambda: release.wait(10.0), name="wedge")
            ls.add_leg(lambda: "fast", name="ok")
            t0 = time.monotonic()
            out = ls.join()
            wall = time.monotonic() - t0
        release.set()
        assert out[0].wedged and isinstance(out[0].error, legs.LegWedged)
        assert out[1].value == "fast"
        # deadline (50ms) + grace, never the 10 s wedge
        assert wall < legs.JOIN_GRACE_S + 1.0

    def test_nested_fanout_wider_than_pool_completes(self):
        """Parents blocked in join() must never starve their children
        of pool slots: a two-level fan-out wider than the shared pool
        completes because nested LegSets spill to dedicated threads."""
        width = legs.pool_stats()["max_workers"] + 4

        def parent(i):
            inner = legs.LegSet("inner")
            for j in range(2):
                inner.add_leg(lambda j=j: (time.sleep(0.01), j)[1])
            return sum(leg.value for leg in inner.join())

        ls = legs.LegSet("outer")
        for i in range(width):
            ls.add_leg(lambda i=i: parent(i))
        out = ls.join()
        assert [leg.value for leg in out] == [1] * width

    def test_join_metrics_account(self):
        before = METRICS.counter("legs.launched").value
        ls = legs.LegSet("m")
        for i in range(3):
            ls.add_leg(lambda: None)
        ls.join()
        assert METRICS.counter("legs.launched").value == before + 3

    def test_single_shot(self):
        ls = legs.LegSet("s")
        ls.add_leg(lambda: 1)
        ls.join()
        with pytest.raises(RuntimeError):
            ls.join()
        with pytest.raises(RuntimeError):
            ls.add_leg(lambda: 2)


# ---------------------------------------------------------------------
# chaos determinism under concurrent legs (the keyed-draw contract)
# ---------------------------------------------------------------------

class TestChaosKeyedDeterminism:
    def _storm(self, sched, nthreads=8, ncalls=25):
        """Fire the same keyed call pattern from many threads at once:
        every thread is one 'leg' with a distinct stable path, arrival
        order fully scrambled."""
        barrier = threading.Barrier(nthreads)

        def worker(t):
            ls = legs.LegSet("storm")

            def leg():
                try:                     # serial arm: barrier can't fill
                    barrier.wait(timeout=0.5)
                except threading.BrokenBarrierError:
                    pass
                for c in range(ncalls):
                    try:
                        sched.fire("rpc.send", op="query_phase",
                                   member=f"m{t % 3}")
                    except Exception:
                        pass
            ls.add_leg(leg, name=str(t))
            return ls.join()

        outer = legs.LegSet("outer")
        for t in range(nthreads):
            outer.add_leg(lambda t=t: worker(t), name=str(t))
        outer.join()

    def test_concurrent_replay_byte_stable(self):
        """Same seed + same call set -> byte-identical canonical
        journal, regardless of thread interleaving (the satellite's
        regression oracle)."""
        journals = []
        for _ in range(2):
            s = (faults.ChaosSchedule(seed=9)
                 .add("rpc.send", "delay", member="m1", p=0.5,
                      delay_s=0.0)
                 .add("rpc.send", "delay", op="query_phase", at=[3, 7],
                      delay_s=0.0))
            self._storm(s)
            journals.append(json.dumps(s.journal, sort_keys=True))
        assert journals[0] == journals[1]
        assert json.loads(journals[0])    # non-vacuous: faults fired

    def test_serial_and_parallel_arms_same_journal(self, monkeypatch):
        def run():
            s = (faults.ChaosSchedule(seed=5)
                 .add("rpc.send", "delay", member="m0", p=0.4,
                      delay_s=0.0))
            self._storm(s, nthreads=6, ncalls=10)
            return json.dumps(s.journal, sort_keys=True)

        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        par = run()
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")
        ser = run()
        assert par == ser

    def test_at_counts_per_identity(self):
        """at=[2] means 'the 2nd call of EACH identity', so a sibling
        leg's calls can never shift which call a rule fires on."""
        s = faults.ChaosSchedule(seed=0).add(
            "rpc.send", "delay", at=[2], delay_s=0.0)
        assert s.fire("rpc.send", op="q", member="a") is None
        assert s.fire("rpc.send", op="q", member="b") is None  # own count
        assert s.fire("rpc.send", op="q", member="a")["member"] == "a"
        assert s.fire("rpc.send", op="q", member="b")["member"] == "b"

    def test_journal_canonical_not_arrival(self):
        s = faults.ChaosSchedule(seed=0) \
            .add("rpc.send", "delay", after=1, delay_s=0.0)
        s.fire("rpc.send", op="q", member="b")
        s.fire("rpc.send", op="q", member="a")
        j = s.journal
        assert [e["member"] for e in j] == ["a", "b"]   # canonical order
        assert [e["seq"] for e in j] == [1, 2]          # recomputed
        assert [e["member"] for e in s.journal_arrivals()] == ["b", "a"]


# ---------------------------------------------------------------------
# hybrid: serial-vs-parallel byte parity + aggs over fusion
# ---------------------------------------------------------------------

MAPPING = {"mappings": {"properties": {
    "body": {"type": "text"},
    "emb": {"type": "rank_features", "index_impacts": True},
    "vec": {"type": "dense_vector", "dims": 8, "similarity": "cosine"},
    "cat": {"type": "keyword"},
    "num": {"type": "integer"}}}}

SUBS = [
    {"match": {"body": "w1 w2 w3"}},
    {"neural_sparse": {"emb": {"query_tokens": {"t1": 2.0, "t2": 1.0,
                                                "t7": 0.4}}}},
    {"knn": {"vec": {"vector": [0.9, 0.1, 0.5, 0.3, 0.7, 0.2, 0.6, 0.4],
                     "k": 20}}},
]


def _mk_docs(n=200, seed=7):
    rng = np.random.default_rng(seed)
    vocab = [f"w{i}" for i in range(25)]
    feats = [f"t{i}" for i in range(20)]
    docs = {}
    for i in range(n):
        toks = rng.choice(vocab, size=int(rng.integers(2, 6)))
        fsel = rng.choice(feats, size=int(rng.integers(2, 5)),
                          replace=False)
        docs[str(i)] = {
            "body": " ".join(toks),
            "emb": {f: round(float(rng.exponential(1.0) + 0.05), 3)
                    for f in fsel},
            "vec": [float(x) for x in rng.random(8)],
            "cat": "odd" if i % 2 else "even",
            "num": int(rng.integers(0, 100))}
    return docs


def _hybrid_body(size=10, frm=0, window=50, method="rrf", aggs=None):
    fusion_spec = {"method": method, "window_size": window}
    if method == "linear":
        fusion_spec["normalization"] = "min_max"
    body = {"query": {"hybrid": {"queries": SUBS,
                                 "fusion": fusion_spec}},
            "from": frm, "size": size}
    if aggs:
        body["aggs"] = aggs
    return body


def _page_bytes(resp):
    return json.dumps(
        {"hits": [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]],
         "total": resp["hits"]["total"],
         "max": resp["hits"]["max_score"],
         "aggs": resp.get("aggregations"),
         "shards": {k: v for k, v in resp.get("_shards", {}).items()}},
        sort_keys=True)


@pytest.fixture(scope="module")
def hybrid_client():
    docs = _mk_docs()
    c = RestClient()
    c.indices.create("lhx", {**MAPPING, "settings": {
        "index": {"number_of_shards": 2}}})
    for did, d in docs.items():
        c.index("lhx", d, id=did)
    c.indices.refresh("lhx")
    return c


class TestHybridParity:
    AGGS = {"cats": {"terms": {"field": "cat"}},
            "n": {"value_count": {"field": "cat"}}}

    def _pages(self, c, bodies):
        out = []
        for b in bodies:
            c.node.request_cache._store.clear()
            out.append(_page_bytes(c.search("lhx", dict(b))))
        return out

    def test_legs_on_off_byte_identical(self, hybrid_client,
                                        monkeypatch):
        bodies = [_hybrid_body(),
                  _hybrid_body(method="linear"),
                  _hybrid_body(size=4, frm=3, window=30),
                  _hybrid_body(aggs=self.AGGS)]
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        on = self._pages(hybrid_client, bodies)
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")
        off = self._pages(hybrid_client, bodies)
        assert on == off

    def test_aggs_over_fused_window_oracle(self, hybrid_client):
        """Hybrid aggs == the same aggs over an explicit ids query on
        the fused candidate window — and present on the fused page."""
        c = hybrid_client
        body = _hybrid_body(aggs=self.AGGS)
        r = c.search("lhx", dict(body))
        q = fusion.parse_hybrid(body)
        subs = [c.search("lhx", sb) for sb in fusion.sub_bodies(body, q)]
        lists = [[((h["_index"], h["_id"]), h["_score"])
                  for h in s["hits"]["hits"]] for s in subs]
        fused = fusion.fuse_ranked_lists(lists, q.fusion)
        oracle = c.search("lhx", {
            "query": {"ids": {"values": sorted({k[1] for k, _ in fused})}},
            "size": 0, "aggs": self.AGGS})
        assert r["aggregations"] == oracle["aggregations"]
        assert r["aggregations"]["cats"]["buckets"]

    def test_parity_under_32_thread_load(self, hybrid_client,
                                         monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")
        c = hybrid_client
        body = _hybrid_body(size=8, aggs=self.AGGS)
        expect = _page_bytes(c.search("lhx", dict(body)))
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        got = [None] * 32
        errors = []

        def worker(i):
            try:
                got[i] = _page_bytes(c.search("lhx", dict(body)))
            except Exception as e:       # surfaced after join
                errors.append(e)

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(32)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errors == []
        assert all(g == expect for g in got)

    def test_slow_leg_does_not_stall_siblings(self):
        """The ISSUE pin: blackhole ONE sub-retrieval -> total wall is
        ≈ that leg's own latency, while the sibling legs complete
        during its window (serial would be the SUM)."""
        calls = []

        def run_sub(sb):
            i = len(calls)
            calls.append(sb)
            time.sleep(0.5 if i == 1 else 0.2)
            return {"hits": {"total": {"value": 1, "relation": "eq"},
                             "max_score": 1.0,
                             "hits": [{"_index": "x", "_id": f"d{i}",
                                       "_score": 1.0}]},
                    "_shards": {"total": 1, "successful": 1,
                                "skipped": 0, "failed": 0},
                    "timed_out": False}

        body = {"query": {"hybrid": {"queries": SUBS,
                                     "fusion": {"method": "rrf",
                                                "window_size": 10}}},
                "size": 5}
        t0 = time.monotonic()
        resp = fusion.run_hybrid(body, run_sub)
        wall = time.monotonic() - t0
        assert len(resp["hits"]["hits"]) == 3     # every sibling landed
        # MAX-shaped (~0.5 s slow leg), nowhere near the 0.9 s SUM
        assert wall < 0.8, wall


# ---------------------------------------------------------------------
# distributed: both coordinators, chaos, serial-vs-parallel parity
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster3():
    policy = RetryPolicy(same_member_retries=1, budget=6,
                         base_backoff_s=0.001, max_backoff_s=0.005)
    a = DistClusterNode("la", retry_policy=policy)
    b = DistClusterNode("lb", seed=a.addr)
    c = DistClusterNode("lc", seed=a.addr)
    docs = _mk_docs(n=120, seed=3)
    a.create_index("ldx", {
        **MAPPING,
        "settings": {"number_of_shards": 4,
                     "number_of_node_replicas": 1}})
    for did, d in docs.items():
        a.index_doc("ldx", d, id=did)
    a.refresh("ldx")
    yield a, b, c, docs
    for n in (a, b, c):
        n.stop()


def _reset_fd(*nodes):
    for n in nodes:
        for m in sorted(n.members):
            n.member_fd.note_success(m)


class TestDistributedLegsParity:
    BODIES = [
        {"query": {"match": {"body": "w1 w2"}}, "size": 10},
        {"query": {"match": {"body": "w3"}}, "size": 5,
         "aggs": {"c": {"terms": {"field": "cat"}}}},
        _hybrid_body(size=6, window=30),
    ]

    def _arm_pages(self, coord, chaos_seed=None, chaos=None):
        """One arm's pages for every probe body (fresh chaos schedule
        per arm so both arms see identical injection plans)."""
        pages = []
        journal = None
        if chaos is not None:
            sched = chaos(faults.ChaosSchedule(seed=chaos_seed))
            faults.install(sched)
        try:
            for body in self.BODIES:
                pages.append(_page_bytes(coord.search("ldx",
                                                      dict(body))))
            if chaos is not None:
                journal = json.dumps(faults.installed().journal,
                                     sort_keys=True)
        finally:
            faults.uninstall()
        return pages, journal

    def _parity(self, coord, monkeypatch, chaos=None, seed=0,
                expect_fired=False):
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        _reset_fd(coord)
        on, jon = self._arm_pages(coord, seed, chaos)
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")
        _reset_fd(coord)
        off, joff = self._arm_pages(coord, seed, chaos)
        _reset_fd(coord)
        assert on == off
        if chaos is not None:
            assert jon == joff          # canonical journals byte-equal
            if expect_fired:
                assert json.loads(jon)
        return on

    def test_clean_parity_both_coordinators(self, cluster3,
                                            monkeypatch):
        a, b, *_ = cluster3
        pa = self._parity(a, monkeypatch)
        pb = self._parity(b, monkeypatch)
        assert pa == pb                 # coordinator-invariant too

    def test_kill_chaos_parity(self, cluster3, monkeypatch):
        a, b, *_ = cluster3
        # replicas present: kill -> failover -> same bytes as clean
        clean = self._parity(a, monkeypatch)
        killed = self._parity(a, monkeypatch,
                              chaos=lambda s: s.kill_node("lb"),
                              seed=4, expect_fired=True)
        assert killed == clean
        self._parity(b, monkeypatch,
                     chaos=lambda s: s.kill_node("lc"), seed=4,
                     expect_fired=True)

    def test_flaky_chaos_parity(self, cluster3, monkeypatch):
        a, *_ = cluster3
        self._parity(
            a, monkeypatch,
            chaos=lambda s: s.add("rpc.send", "drop", member="lb",
                                  p=0.4),
            seed=11, expect_fired=True)

    def test_blackhole_chaos_parity(self, cluster3, monkeypatch):
        a, *_ = cluster3
        # short blackhole: FaultTimeout -> retry/failover (no request
        # deadline, so both arms take the same keyed failover path)
        self._parity(
            a, monkeypatch,
            chaos=lambda s: s.add("rpc.send", "blackhole", member="lc",
                                  op="query_phase", after=1,
                                  delay_s=0.05),
            seed=12, expect_fired=True)

    def test_blackholed_member_bounds_wall_not_sum(self, cluster3,
                                                   monkeypatch):
        """Parallel legs under a blackholed member: the round's wall is
        ONE blackhole hold (all member legs overlap), and the other
        members' shards still serve."""
        a, *_ = cluster3
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        _reset_fd(a)
        faults.install(faults.ChaosSchedule(seed=13).add(
            "rpc.send", "blackhole", member="lb", after=1,
            delay_s=0.4))
        try:
            t0 = time.monotonic()
            r = a.search("ldx", {"query": {"match": {"body": "w1"}},
                                 "size": 5})
            wall = time.monotonic() - t0
        finally:
            faults.uninstall()
        _reset_fd(a)
        assert r["_shards"]["failed"] == 0      # replicas absorbed it
        # dfs+query+fetch each see at most one 0.4 s hold + retries;
        # the serial arm pays the hold PER MEMBER GROUP in sequence
        assert wall < 4.0

    def test_federation_scrape_parity(self, cluster3, monkeypatch):
        a, *_ = cluster3
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "1")
        on = a.cluster_stats()
        monkeypatch.setenv("OPENSEARCH_TPU_LEGS", "0")
        off = a.cluster_stats()
        assert on["_nodes"] == off["_nodes"]
        assert sorted(on["nodes"]) == sorted(off["nodes"]) \
            == sorted(a.members)
        assert all(v["status"] == "ok" for v in on["nodes"].values())
        stats = a.nodes_stats_federated()
        assert stats["_nodes"]["successful"] == len(a.members)
