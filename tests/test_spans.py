"""Span query algebra + intervals sources/filters (reference
`index/query/Span*QueryBuilder.java`, `IntervalsSourceProvider.java`),
evaluated by the host span engine (search/spans.py)."""

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient


@pytest.fixture(scope="module")
def client():
    c = RestClient()
    c.indices.create("txt", {"mappings": {"properties": {
        "body": {"type": "text"}, "alt": {"type": "text"}}}})
    docs = [
        ("1", "the quick brown fox jumps over the lazy dog"),
        ("2", "quick fox"),
        ("3", "the fox is quick and brown"),
        ("4", "brown dog sleeps"),
        ("5", "quick quick brown"),
        ("6", "a very quick red fox"),
    ]
    for did, body in docs:
        c.index("txt", {"body": body, "alt": body}, id=did)
    c.indices.refresh("txt")
    return c


def _ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


class TestSpanAlgebra:
    def test_span_or(self, client):
        r = client.search("txt", {"query": {"span_or": {"clauses": [
            {"span_term": {"body": "lazy"}},
            {"span_term": {"body": "sleeps"}}]}}, "size": 10})
        assert _ids(r) == ["1", "4"]

    def test_span_not(self, client):
        # quick not immediately followed by brown
        r = client.search("txt", {"query": {"span_not": {
            "include": {"span_term": {"body": "quick"}},
            "exclude": {"span_near": {"clauses": [
                {"span_term": {"body": "quick"}},
                {"span_term": {"body": "brown"}}],
                "slop": 0, "in_order": True}}}}, "size": 10})
        # doc1 "quick brown" excluded; doc5 has standalone quick too
        ids = _ids(r)
        assert "2" in ids and "3" in ids and "6" in ids
        assert "1" not in ids

    def test_span_first(self, client):
        r = client.search("txt", {"query": {"span_first": {
            "match": {"span_term": {"body": "quick"}}, "end": 1}},
            "size": 10})
        assert _ids(r) == ["2", "5"]   # quick at position 0

    def test_span_containing_and_within(self, client):
        big = {"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"span_term": {"body": "fox"}}], "slop": 3, "in_order": True}}
        little = {"span_term": {"body": "red"}}
        r = client.search("txt", {"query": {"span_containing": {
            "big": big, "little": little}}, "size": 10})
        assert _ids(r) == ["6"]        # quick red fox contains red
        r = client.search("txt", {"query": {"span_within": {
            "big": big, "little": little}}, "size": 10})
        assert _ids(r) == ["6"]

    def test_span_multi_prefix(self, client):
        r = client.search("txt", {"query": {"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"span_multi": {"match": {"prefix": {"body": "bro"}}}}],
            "slop": 0, "in_order": True}}, "size": 10})
        assert _ids(r) == ["1", "5"]   # quick brown adjacency

    def test_field_masking_span(self, client):
        r = client.search("txt", {"query": {"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"field_masking_span": {
                "query": {"span_term": {"alt": "brown"}},
                "field": "body"}}],
            "slop": 0, "in_order": True}}, "size": 10})
        assert _ids(r) == ["1", "5"]

    def test_mismatched_fields_400(self, client):
        with pytest.raises(ApiError):
            client.search("txt", {"query": {"span_or": {"clauses": [
                {"span_term": {"body": "quick"}},
                {"span_term": {"alt": "fox"}}]}}})


class TestIntervals:
    def test_all_of_ordered(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "all_of": {"ordered": True, "max_gaps": 0, "intervals": [
                {"match": {"query": "quick"}},
                {"match": {"query": "brown"}}]}}}}, "size": 10})
        assert _ids(r) == ["1", "5"]

    def test_any_of(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "any_of": {"intervals": [
                {"match": {"query": "lazy"}},
                {"match": {"query": "sleeps"}}]}}}}, "size": 10})
        assert _ids(r) == ["1", "4"]

    def test_prefix_rule(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "prefix": {"prefix": "jum"}}}}, "size": 10})
        assert _ids(r) == ["1"]

    def test_fuzzy_rule(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "fuzzy": {"term": "quikc"}}}}, "size": 10})
        assert "1" in _ids(r)

    def test_filter_containing(self, client):
        # quick..fox spans that contain "red"
        r = client.search("txt", {"query": {"intervals": {"body": {
            "all_of": {"ordered": True, "max_gaps": 2, "intervals": [
                {"match": {"query": "quick"}},
                {"match": {"query": "fox"}}],
                "filter": {"containing": {"match": {"query": "red"}}}}}}},
            "size": 10})
        assert _ids(r) == ["6"]

    def test_filter_not_overlapping(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "match": {"query": "quick",
                      "filter": {"not_overlapping": {
                          "match": {"query": "quick brown",
                                    "ordered": True, "max_gaps": 0}}}}}}},
            "size": 10})
        ids = _ids(r)
        assert "2" in ids and "1" not in ids

    def test_before_after(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "match": {"query": "fox",
                      "filter": {"before": {"match": {"query": "jumps"}}}}}}},
            "size": 10})
        assert _ids(r) == ["1"]
        r = client.search("txt", {"query": {"intervals": {"body": {
            "match": {"query": "fox",
                      "filter": {"after": {"match": {"query": "the"}}}}}}},
            "size": 10})
        assert "1" in _ids(r) and "3" in _ids(r)

    def test_plain_match_rule_still_device(self, client):
        r = client.search("txt", {"query": {"intervals": {"body": {
            "match": {"query": "quick brown", "max_gaps": 0,
                      "ordered": True}}}}, "size": 10})
        assert _ids(r) == ["1", "5"]

    def test_scores_positive_and_explainable(self, client):
        r = client.search("txt", {"query": {"span_or": {"clauses": [
            {"span_term": {"body": "lazy"}}]}}, "size": 10})
        assert all(h["_score"] > 0 for h in r["hits"]["hits"])


class TestReviewRegressions:
    def test_span_not_huge_post_still_excludes(self, client):
        r = client.search("txt", {"query": {"span_not": {
            "include": {"span_term": {"body": "quick"}},
            "exclude": {"span_term": {"body": "brown"}},
            "post": 8589934592}}, "size": 10})
        # every doc containing both quick and brown is excluded
        assert "1" not in _ids(r) and "5" not in _ids(r)
        assert "2" in _ids(r)

    def test_invalid_span_rejected_on_empty_index(self):
        c = RestClient()
        c.indices.create("empty-span")
        with pytest.raises(ApiError):
            c.search("empty-span", {"query": {"span_not": {
                "include": {"span_term": {"a": "x"}},
                "exclude": {"span_term": {"b": "y"}}}}})

    def test_span_first_requires_end(self, client):
        with pytest.raises(ApiError):
            client.search("txt", {"query": {"span_first": {
                "match": {"span_term": {"body": "quick"}}}}})
