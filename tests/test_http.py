"""HTTP wire layer (rest/http_server.py): real sockets, JSON + NDJSON
dialects, status-code mapping, and the concurrent-client story. Reference:
`http/HttpServerTransport.java:1`, `rest/RestController.java:1`."""

import http.client
import json
import threading

import pytest

from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.rest.http_server import HttpServer


@pytest.fixture(scope="module")
def srv():
    server = HttpServer(RestClient())
    port = server.start()
    yield server, port
    server.stop()


def req(port, method, path, body=None, ndjson=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    payload = None
    headers = {}
    if ndjson is not None:
        payload = "\n".join(json.dumps(x) for x in ndjson) + "\n"
        headers["Content-Type"] = "application/x-ndjson"
    elif body is not None:
        payload = json.dumps(body)
        headers["Content-Type"] = "application/json"
    conn.request(method, path, body=payload, headers=headers)
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    try:
        return resp.status, json.loads(raw)
    except json.JSONDecodeError:
        return resp.status, raw


class TestHttpBasics:
    def test_root_info(self, srv):
        _, port = srv
        status, body = req(port, "GET", "/")
        assert status == 200
        assert body["version"]["distribution"] == "opensearch-tpu"

    def test_index_lifecycle_and_docs(self, srv):
        _, port = srv
        status, body = req(port, "PUT", "/books", {
            "mappings": {"properties": {"title": {"type": "text"},
                                        "year": {"type": "integer"}}}})
        assert status == 200 and body["acknowledged"]
        # HEAD exists
        assert req(port, "HEAD", "/books")[0] == 200
        assert req(port, "HEAD", "/missing")[0] == 404
        # index + get
        status, body = req(port, "PUT", "/books/_doc/1?refresh=true",
                           {"title": "dune", "year": 1965})
        assert status == 201 and body["result"] in ("created", "updated")
        status, body = req(port, "GET", "/books/_doc/1")
        assert status == 200 and body["_source"]["year"] == 1965
        # 404 doc
        assert req(port, "GET", "/books/_doc/zzz")[0] == 404
        # search
        status, body = req(port, "POST", "/books/_search",
                           {"query": {"match": {"title": "dune"}}})
        assert status == 200
        assert body["hits"]["total"]["value"] == 1
        # delete doc
        assert req(port, "DELETE", "/books/_doc/1")[0] == 200

    def test_bulk_and_msearch_ndjson(self, srv):
        _, port = srv
        req(port, "PUT", "/bulkidx")
        lines = []
        for i in range(20):
            lines.append({"index": {"_index": "bulkidx", "_id": str(i)}})
            lines.append({"n": i, "tag": "even" if i % 2 == 0 else "odd"})
        status, body = req(port, "POST", "/_bulk?refresh=true", ndjson=lines)
        assert status == 200 and not body["errors"]
        status, body = req(port, "POST", "/_msearch", ndjson=[
            {"index": "bulkidx"}, {"query": {"term": {"tag": "even"}}},
            {"index": "bulkidx"}, {"query": {"match_all": {}}, "size": 3},
        ])
        assert status == 200
        assert body["responses"][0]["hits"]["total"]["value"] == 10
        assert body["responses"][1]["hits"]["total"]["value"] == 20

    def test_error_mapping(self, srv):
        _, port = srv
        status, body = req(port, "POST", "/nosuch/_search",
                           {"query": {"match_all": {}}})
        assert status == 404
        assert body["error"]["type"] == "index_not_found_exception"
        req(port, "PUT", "/errs")
        status, body = req(port, "POST", "/errs/_search",
                           {"query": {"bogus_kind": {}}})
        assert status == 400
        # malformed JSON
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/errs/_search", body="{not json",
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        raw = json.loads(r.read().decode())
        conn.close()
        assert r.status == 400 and raw["error"]["type"] == "parsing_exception"
        # unknown route
        status, body = req(port, "POST", "/errs/_frobnicate")
        assert status == 400

    def test_mutating_routes_reject_get(self, srv):
        """GET on mutating routes must 405, never mutate (a probe or
        browser must not be able to close an index)."""
        _, port = srv
        req(port, "PUT", "/mget405")
        status, body = req(port, "GET", "/mget405/_close")
        assert status == 405
        # index still open
        assert req(port, "POST", "/mget405/_search",
                   {"query": {"match_all": {}}})[0] == 200
        assert req(port, "GET", "/mget405/_forcemerge")[0] == 405
        assert req(port, "GET", "/_remotestore/_restore")[0] == 405
        # the reference registers GET for _refresh/_flush — they stay open
        assert req(port, "GET", "/mget405/_refresh")[0] == 200

    def test_cat_and_cluster(self, srv):
        _, port = srv
        status, body = req(port, "GET", "/_cluster/health")
        assert status == 200 and "status" in body
        status, rows = req(port, "GET", "/_cat/indices?format=json")
        assert status == 200 and isinstance(rows, list)
        status, text = req(port, "GET", "/_cat/indices")
        assert status == 200 and isinstance(text, str)

    def test_scroll_and_tasks_over_http(self, srv):
        _, port = srv
        req(port, "PUT", "/scr")
        lines = []
        for i in range(25):
            lines.append({"index": {"_index": "scr", "_id": str(i)}})
            lines.append({"n": i})
        req(port, "POST", "/_bulk?refresh=true", ndjson=lines)
        status, first = req(port, "POST", "/scr/_search?scroll=1m",
                            {"query": {"match_all": {}}, "size": 10,
                             "sort": [{"n": "asc"}]})
        assert status == 200 and "_scroll_id" in first
        seen = [h["_source"]["n"] for h in first["hits"]["hits"]]
        sid = first["_scroll_id"]
        while True:
            status, page = req(port, "POST", "/_search/scroll",
                               {"scroll_id": sid, "scroll": "1m"})
            assert status == 200
            if not page["hits"]["hits"]:
                break
            seen.extend(h["_source"]["n"] for h in page["hits"]["hits"])
            sid = page["_scroll_id"]
        assert seen == list(range(25))
        status, body = req(port, "DELETE", "/_search/scroll",
                           {"scroll_id": sid})
        assert status == 200
        status, body = req(port, "GET", "/_tasks")
        assert status == 200
        assert "nodes" in body
        # cancel-all form routes correctly (nothing running -> empty list)
        status, body = req(port, "POST", "/_tasks/_cancel")
        assert status == 200 and body["cancelled"] == []
        # all-indices scroll opens a context too
        status, allscroll = req(port, "POST", "/_search?scroll=1m",
                                {"query": {"match_all": {}}, "size": 3})
        assert status == 200 and "_scroll_id" in allscroll
        # scroll id in the URL path form
        status, nxt = req(port, "POST",
                          f"/_search/scroll/{allscroll['_scroll_id']}")
        assert status == 200

    def test_mapping_settings_roundtrip(self, srv):
        _, port = srv
        req(port, "PUT", "/maps", {"mappings": {"properties": {
            "a": {"type": "keyword"}}}})
        status, body = req(port, "GET", "/maps/_mapping")
        assert status == 200
        assert body["maps"]["mappings"]["properties"]["a"]["type"] == \
            "keyword"
        status, body = req(port, "PUT", "/maps/_mapping",
                           {"properties": {"b": {"type": "integer"}}})
        assert status == 200


class TestHttpConcurrency:
    def test_concurrent_searches_and_writes(self, srv):
        """The concurrent-client story: parallel searches over HTTP all
        succeed with consistent results while writes interleave."""
        _, port = srv
        req(port, "PUT", "/conc")
        lines = []
        for i in range(50):
            lines.append({"index": {"_index": "conc", "_id": str(i)}})
            lines.append({"body": f"word{i % 5} shared"})
        req(port, "POST", "/_bulk?refresh=true", ndjson=lines)

        results = []
        errors = []

        def reader(k):
            try:
                for _ in range(10):
                    s, b = req(port, "POST", "/conc/_search",
                               {"query": {"match": {"body": "shared"}},
                                "size": 5, "_c": k})
                    assert s == 200
                    results.append(b["hits"]["total"]["value"])
            except Exception as e:                     # noqa: BLE001
                errors.append(e)

        def writer(k):
            try:
                for j in range(5):
                    s, _ = req(port, "PUT",
                               f"/conc/_doc/w{k}-{j}?refresh=true",
                               {"body": "extra doc"})
                    assert s == 201
            except Exception as e:                     # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(k,))
                   for k in range(6)] + \
                  [threading.Thread(target=writer, args=(k,))
                   for k in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:3]
        assert len(results) == 60
        assert all(v >= 50 for v in results)


class TestPerIndexWriteLocks:
    def test_parallel_writes_to_distinct_indices(self, srv):
        """Per-index write locks (r5): writers on different indices make
        progress in parallel and both datasets land intact; a concurrent
        same-index writer pair stays serialized and loses no docs."""
        _, port = srv
        for name in ("wa", "wb"):
            req(port, "PUT", f"/{name}")
        errors = []
        marks = {"wa": [], "wb": []}

        def writer(index, n):
            try:
                for j in range(n):
                    s, _ = req(port, "PUT", f"/{index}/_doc/d{j}",
                               {"n": j, "tag": index})
                    assert s in (200, 201)
                    marks[index].append(j)
            except Exception as e:                     # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=("wa", 40)),
                   threading.Thread(target=writer, args=("wb", 40)),
                   threading.Thread(target=writer, args=("wa", 40))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:3]
        for name in ("wa", "wb"):
            req(port, "POST", f"/{name}/_refresh")
            s, b = req(port, "POST", f"/{name}/_search",
                       {"query": {"match_all": {}}, "size": 0})
            assert s == 200
            assert b["hits"]["total"]["value"] == 40

    def test_dynamic_create_during_concurrent_bulks(self, srv):
        """Bulks that dynamically create DIFFERENT indices run
        concurrently without corrupting cluster metadata."""
        _, port = srv
        errors = []

        def bulker(k):
            try:
                lines = []
                for j in range(20):
                    lines.append({"index": {"_index": f"dyn{k}",
                                            "_id": str(j)}})
                    lines.append({"v": j})
                s, b = req(port, "POST", "/_bulk?refresh=true",
                           ndjson=lines)
                assert s == 200 and not b.get("errors"), b
            except Exception as e:                     # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=bulker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:3]
        for k in range(4):
            s, b = req(port, "POST", f"/dyn{k}/_search",
                       {"size": 0, "query": {"match_all": {}}})
            assert s == 200 and b["hits"]["total"]["value"] == 20

    def test_delete_index_never_races_doc_write(self, srv):
        """Metadata ops take the target's index lock too: deleting an
        index concurrently with writes yields clean outcomes only (every
        write either lands before the delete or 404s after it — no 500s)."""
        _, port = srv
        req(port, "PUT", "/ephemeral")
        outcomes = []

        def writer():
            for j in range(30):
                s, _ = req(port, "PUT", f"/ephemeral/_doc/x{j}",
                           {"v": j})
                outcomes.append(s)

        def deleter():
            req(port, "DELETE", "/ephemeral")

        t1 = threading.Thread(target=writer)
        t2 = threading.Thread(target=deleter)
        t1.start()
        t2.start()
        t1.join(timeout=120)
        t2.join(timeout=120)
        # writes after the delete dynamically recreate (like upstream
        # auto-create) or 404 depending on timing; what must NEVER
        # appear is a 500 from racing the engine teardown
        assert all(s in (200, 201, 404) for s in outcomes), outcomes
