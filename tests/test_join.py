"""Parent-child join tests. Reference semantics: modules/parent-join
(ParentJoinFieldMapper, HasChildQueryBuilder, HasParentQueryBuilder,
ParentIdQueryBuilder, inner hits). Ours: shard-global slot space + two-pass
device scatter/gather (search/join.py, compiler LHasChild/LHasParent)."""

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient

MAPPING = {"mappings": {"properties": {
    "my_join": {"type": "join", "relations": {"question": ["answer", "comment"]}},
    "title": {"type": "text"},
    "body": {"type": "text"},
    "votes": {"type": "integer"}}}}


@pytest.fixture
def client():
    c = RestClient()
    c.indices.create("j", MAPPING)
    c.index("j", {"title": "how to jit", "my_join": "question"}, id="q1")
    c.index("j", {"title": "sharding question", "my_join": "question"}, id="q2")
    c.index("j", {"title": "lonely question", "my_join": "question"}, id="q3")
    # children must route to the parent's shard
    c.index("j", {"body": "use jax.jit decorator", "votes": 5,
                  "my_join": {"name": "answer", "parent": "q1"}},
            id="a1", routing="q1")
    c.index("j", {"body": "trace once compile once", "votes": 2,
                  "my_join": {"name": "answer", "parent": "q1"}},
            id="a2", routing="q1")
    c.index("j", {"body": "use a mesh", "votes": 7,
                  "my_join": {"name": "answer", "parent": "q2"}},
            id="a3", routing="q2")
    c.index("j", {"body": "nice question", "votes": 1,
                  "my_join": {"name": "comment", "parent": "q2"}},
            id="c1", routing="q2")
    c.indices.refresh("j")
    return c


class TestJoinMapping:
    def test_child_without_routing_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("j", {"my_join": {"name": "answer", "parent": "q1"}},
                         id="bad1")

    def test_child_without_parent_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("j", {"my_join": {"name": "answer"}}, id="bad2",
                         routing="q1")

    def test_unknown_relation_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.index("j", {"my_join": "reply"}, id="bad3", routing="q1")

    def test_mapping_roundtrip(self, client):
        m = client.indices.get_mapping("j")["j"]["mappings"]
        assert m["properties"]["my_join"]["relations"] == {
            "question": ["answer", "comment"]}

    def test_term_query_on_join_field(self, client):
        r = client.search("j", {"query": {"term": {"my_join": "answer"}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a1", "a2", "a3"}


class TestHasChild:
    def test_basic_filter(self, client):
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match": {"body": "jit"}}}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["q1"]
        assert r["hits"]["hits"][0]["_score"] == 1.0  # score_mode none

    def test_match_all_children(self, client):
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q1", "q2"}

    def test_child_type_isolation(self, client):
        # c1 is a comment, not an answer
        r = client.search("j", {"query": {"has_child": {
            "type": "comment", "query": {"match_all": {}}}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["q2"]

    def test_score_modes(self, client):
        def scores(mode):
            r = client.search("j", {"query": {"has_child": {
                "type": "answer", "score_mode": mode,
                "query": {"function_score": {
                    "query": {"match_all": {}},
                    "functions": [{"script_score": {"script": {
                        "source": "doc['votes'].value"}}}],
                    "boost_mode": "replace"}}}}})
            return {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert scores("sum") == {"q1": 7.0, "q2": 7.0}
        assert scores("max") == {"q1": 5.0, "q2": 7.0}
        assert scores("min") == {"q1": 2.0, "q2": 7.0}
        assert scores("avg") == {"q1": 3.5, "q2": 7.0}

    def test_min_max_children(self, client):
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}, "min_children": 2}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["q1"]
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}, "max_children": 1}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["q2"]

    def test_min_children_zero_still_requires_a_match(self, client):
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}, "min_children": 0}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q1", "q2"}  # not q3

    def test_bad_score_mode_is_400(self, client):
        with pytest.raises(ApiError):
            client.search("j", {"query": {"has_child": {
                "type": "answer", "query": {"match_all": {}},
                "score_mode": "total"}}})

    def test_second_join_field_rejected(self, client):
        with pytest.raises((ApiError, ValueError)):
            client.indices.create("j2", {"mappings": {"properties": {
                "join_a": {"type": "join", "relations": {"p": ["c"]}},
                "join_b": {"type": "join", "relations": {"x": ["y"]}}}}})

    def test_cross_segment_join(self, client):
        # the new child lands in a different segment than its parent
        client.index("j", {"body": "late jit answer", "votes": 9,
                           "my_join": {"name": "answer", "parent": "q3"}},
                     id="a4", routing="q3")
        client.indices.refresh("j")
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match": {"body": "late"}}}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["q3"]

    def test_deleted_child_stops_matching(self, client):
        client.delete("j", "a3", routing="q2")
        client.indices.refresh("j")
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q1"}

    def test_in_bool_with_parent_fields(self, client):
        r = client.search("j", {"query": {"bool": {
            "must": [{"match": {"title": "question"}}],
            "filter": [{"has_child": {"type": "answer",
                                      "query": {"match_all": {}}}}]}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["q2"]

    def test_ignore_unmapped(self, client):
        c = RestClient()
        c.indices.create("plain", {})
        c.index("plain", {"x": 1}, id="1", refresh=True)
        r = c.search("plain", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}},
            "ignore_unmapped": True}}})
        assert r["hits"]["hits"] == []
        with pytest.raises(ApiError):
            c.search("plain", {"query": {"has_child": {
                "type": "answer", "query": {"match_all": {}}}}})

    def test_inner_hits(self, client):
        r = client.search("j", {"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}},
            "score_mode": "sum", "inner_hits": {}}}})
        by_id = {h["_id"]: h for h in r["hits"]["hits"]}
        ih = by_id["q1"]["inner_hits"]["answer"]["hits"]
        assert ih["total"]["value"] == 2
        assert {hh["_id"] for hh in ih["hits"]} == {"a1", "a2"}

    def test_explain_matches_score(self, client):
        r = client.search("j", {"explain": True,
                                "query": {"has_child": {
                                    "type": "answer", "score_mode": "sum",
                                    "query": {"function_score": {
                                        "query": {"match_all": {}},
                                        "functions": [{"script_score": {"script": {
                                            "source": "doc['votes'].value"}}}],
                                        "boost_mode": "replace"}}}}})
        for h in r["hits"]["hits"]:
            assert h["_explanation"]["value"] == pytest.approx(h["_score"], rel=1e-5)


class TestHasParent:
    def test_basic(self, client):
        r = client.search("j", {"query": {"has_parent": {
            "parent_type": "question", "query": {"match": {"title": "jit"}}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a1", "a2"}
        assert all(h["_score"] == 1.0 for h in r["hits"]["hits"])

    def test_all_child_types_match(self, client):
        r = client.search("j", {"query": {"has_parent": {
            "parent_type": "question",
            "query": {"match": {"title": "sharding"}}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a3", "c1"}

    def test_score_true(self, client):
        r = client.search("j", {"query": {"has_parent": {
            "parent_type": "question", "score": True,
            "query": {"function_score": {
                "query": {"match_all": {}},
                "functions": [{"weight": 3.0}],
                "boost_mode": "replace"}}}}})
        assert all(h["_score"] == 3.0 for h in r["hits"]["hits"])
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a1", "a2", "a3", "c1"}

    def test_inner_hits(self, client):
        r = client.search("j", {"query": {"has_parent": {
            "parent_type": "question", "query": {"match": {"title": "jit"}},
            "inner_hits": {}}}})
        h = next(x for x in r["hits"]["hits"] if x["_id"] == "a1")
        ih = h["inner_hits"]["question"]["hits"]
        assert ih["total"]["value"] == 1
        assert ih["hits"][0]["_id"] == "q1"


class TestParentId:
    def test_basic(self, client):
        r = client.search("j", {"query": {"parent_id": {
            "type": "answer", "id": "q1"}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a1", "a2"}

    def test_type_filtering(self, client):
        r = client.search("j", {"query": {"parent_id": {
            "type": "comment", "id": "q2"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["c1"]


class TestJoinMultiShard:
    def test_routing_keeps_family_together(self):
        c = RestClient()
        c.indices.create("jm", {**MAPPING, "settings": {"number_of_shards": 4}})
        for i in range(6):
            c.index("jm", {"title": f"question {i}", "my_join": "question"},
                    id=f"q{i}")
            c.index("jm", {"body": f"answer {i}", "votes": i,
                           "my_join": {"name": "answer", "parent": f"q{i}"}},
                    id=f"a{i}", routing=f"q{i}")
        c.indices.refresh("jm")
        r = c.search("jm", {"query": {"has_child": {
            "type": "answer", "query": {"range": {"votes": {"gte": 4}}}}},
            "size": 20})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"q4", "q5"}
        r = c.search("jm", {"query": {"has_parent": {
            "parent_type": "question", "query": {"match": {"title": "3"}}}},
            "size": 20})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a3"}
