"""Fault-tolerant distributed serving (docs/RESILIENCE.md).

Deadline propagation (one `timeout` honored end-to-end), per-shard retry
with replica failover, the hardened partial-results contract
(`_shards.failed` reasons, `timed_out`/`terminated_early`,
`allow_partial_search_results=false`), and the seeded chaos harness
(`cluster/faults.py`) that makes every failure interleaving replayable.

The headline invariants, asserted here with seeded injection:

- kill one node mid-query with replicas present -> the served page is
  BYTE-IDENTICAL to the no-fault run and `_shards.failed == 0`;
- kill without replicas -> honest per-shard failures, and
  `allow_partial_search_results=false` fails the whole request;
- an injected RPC delay past the coordinator `timeout` yields
  `timed_out: true` WITHIN the budget (no transport-cap stall);
- a retry storm freezes a flight-recorder dump;
- the same chaos seed replays the same injection journal.
"""

import json
import time

import numpy as np
import pytest

from opensearch_tpu.cluster import faults
from opensearch_tpu.cluster.distnode import DistClusterNode, RetryPolicy
from opensearch_tpu.cluster.failure import MemberFailureDetector
from opensearch_tpu.cluster.routing import (assign_copies, order_copies,
                                            shard_for)
from opensearch_tpu.obs.flight_recorder import RECORDER
from opensearch_tpu.rest.client import ApiError, RestClient
from opensearch_tpu.utils import deadline as dl
from opensearch_tpu.utils.metrics import METRICS

WORDS = ["alpha", "beta", "gamma", "delta", "eps", "zeta", "kappa"]
NDOCS = 90


def _norm(resp: dict) -> str:
    return json.dumps({k: v for k, v in resp.items() if k != "took"},
                      sort_keys=True)


@pytest.fixture(autouse=True)
def _no_leftover_chaos():
    yield
    faults.uninstall()


# ---------------------------------------------------------------------
# deadline unit surface
# ---------------------------------------------------------------------

class TestDeadline:
    def test_parse_units(self):
        assert dl.parse_timeout_s("500ms") == pytest.approx(0.5)
        assert dl.parse_timeout_s("2s") == pytest.approx(2.0)
        assert dl.parse_timeout_s("1m") == pytest.approx(60.0)
        assert dl.parse_timeout_s("250micros") == pytest.approx(2.5e-4)
        assert dl.parse_timeout_s(1500) == pytest.approx(1.5)
        assert dl.parse_timeout_s(None) is None
        # reference sentinel: -1 (and any negative) = NO timeout;
        # explicit zero = degenerate instantly-exhausted budget
        assert dl.parse_timeout_s(-1) is None
        assert dl.parse_timeout_s("-1") is None
        assert dl.parse_timeout_s("0ms") == 0.0
        with pytest.raises(ValueError):
            dl.parse_timeout_s("junk")

    def test_budget_and_rpc_derivation(self):
        d = dl.Deadline(10.0)
        assert 9.0 < d.remaining_s() <= 10.0
        assert not d.exhausted()
        # the hop timeout is min(remaining, cap)
        assert d.rpc_timeout_s(30.0) <= 10.0
        assert d.rpc_timeout_s(0.5) == pytest.approx(0.5, abs=0.01)
        spent = dl.Deadline(0.0)
        assert spent.exhausted()
        # floored, never zero/negative (urllib treats 0 as unbounded)
        assert spent.rpc_timeout_s(30.0) == dl.MIN_RPC_TIMEOUT_S

    def test_wire_roundtrip_reanchors(self):
        d = dl.Deadline(5.0)
        w = d.to_wire()
        assert 4000.0 < w["remaining_ms"] <= 5000.0
        d2 = dl.Deadline.from_wire(w)
        assert 4.0 < d2.remaining_s() <= 5.0
        assert dl.Deadline.from_wire(None) is None
        assert dl.Deadline.from_wire({"remaining_ms": "x"}) is None

    def test_scope_contextvar(self):
        assert dl.current() is None
        with dl.scope(dl.Deadline(1.0)) as d:
            assert dl.current() is d
        assert dl.current() is None
        with dl.scope(None):
            assert dl.current() is None


# ---------------------------------------------------------------------
# chaos schedule mechanics (no cluster needed)
# ---------------------------------------------------------------------

class TestChaosSchedule:
    def _drive(self, sched):
        fired = []
        for i in range(12):
            rec = sched.fire("rpc.send", op="dfs",
                             member="b" if i % 2 else "a")
            if rec:
                fired.append((rec["rule"], rec["site"], rec["member"],
                              rec["call"], rec["action"]))
        return fired

    def test_seeded_replay_determinism(self):
        mk = lambda: (faults.ChaosSchedule(seed=7)
                      .add("rpc.send", "drop", member="b", p=0.5)
                      .add("rpc.send", "delay", op="dfs", at=[3],
                           delay_s=0.0))
        j1 = self._drive(mk())
        j2 = self._drive(mk())
        assert j1 == j2 and j1   # identical AND non-empty

    def test_positional_rules(self):
        s = faults.ChaosSchedule(seed=0).add(
            "rpc.send", "drop", member="b", at=[2], times=1)
        assert s.fire("rpc.send", op="q", member="b") is None
        assert s.fire("rpc.send", op="q", member="b")["action"] == "drop"
        assert s.fire("rpc.send", op="q", member="b") is None  # times=1

    def test_kill_node_drops_every_send(self):
        s = faults.ChaosSchedule(seed=0).kill_node("b")
        faults.install(s)
        with pytest.raises(faults.FaultInjected):
            faults.on_rpc_send("b", "dfs", 1.0)
        faults.on_rpc_send("a", "dfs", 1.0)        # other members fine
        with pytest.raises(faults.FaultInjected):
            faults.on_rpc_send("b", "fetch_phase", 1.0)

    def test_blackhole_holds_callers_timeout_not_cap(self):
        s = faults.ChaosSchedule(seed=0).add(
            "rpc.send", "blackhole", member="b", after=1, delay_s=30.0)
        faults.install(s)
        t0 = time.monotonic()
        with pytest.raises(faults.FaultTimeout):
            faults.on_rpc_send("b", "query_phase", 0.05)
        assert time.monotonic() - t0 < 1.0

    def test_sched_complete_site(self):
        s = faults.ChaosSchedule(seed=0).add(
            "sched.complete", "delay", delay_s=0.0, after=1, times=2)
        faults.install(s)
        faults.on_sched_complete("n1")
        faults.on_sched_complete("n1")
        faults.on_sched_complete("n1")          # times exhausted
        assert [r["site"] for r in s.journal] == ["sched.complete"] * 2
        assert faults.stats()["installed"] is True


# ---------------------------------------------------------------------
# single-node deadline + terminate_after + track_scores
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def single():
    c = RestClient()
    c.indices.create("res1", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {"body": {"type": "text"},
                                    "num": {"type": "integer"}}}})
    rng = np.random.default_rng(11)
    for i in range(60):
        c.index("res1", {"body": " ".join(
            rng.choice(WORDS, size=int(rng.integers(3, 7)))),
            "num": int(i)}, id=str(i))
        if i % 20 == 19:
            c.indices.refresh("res1")    # several segments per shard
    c.indices.refresh("res1")
    return c


class TestSingleNodePartialContract:
    def test_exhausted_timeout_is_immediate_partial(self, single):
        t0 = time.monotonic()
        r = single.search(index="res1", body={
            "query": {"match": {"body": "alpha"}}, "timeout": "0ms"})
        assert time.monotonic() - t0 < 5.0
        assert r["timed_out"] is True
        assert r["hits"]["hits"] == []
        assert r["hits"]["total"]["relation"] == "gte"

    def test_timed_out_page_never_cached(self, single):
        body = {"query": {"match": {"body": "beta"}}, "timeout": "0ms"}
        r1 = single.search(index="res1", body=dict(body))
        assert r1["timed_out"] is True
        # same body with a generous budget must NOT see a cached stub
        body2 = {"query": {"match": {"body": "beta"}}, "timeout": "30s"}
        r2 = single.search(index="res1", body=dict(body2))
        assert r2["timed_out"] is False
        assert r2["hits"]["total"]["value"] > 0

    def test_allow_partial_false_fails_request(self, single):
        with pytest.raises(ApiError) as ei:
            single.search(index="res1", body={
                "query": {"match_all": {}}, "timeout": "0ms",
                "allow_partial_search_results": False})
        assert ei.value.status == 503

    def test_bad_timeout_is_400(self, single):
        with pytest.raises(ApiError) as ei:
            single.search(index="res1", body={
                "query": {"match_all": {}}, "timeout": "nonsense"})
        assert ei.value.status == 400

    def test_terminate_after_flags_and_totals(self, single):
        full = single.search(index="res1", body={
            "query": {"match_all": {}}})
        total = full["hits"]["total"]["value"]
        r = single.search(index="res1", body={
            "query": {"match_all": {}}, "terminate_after": 1})
        assert r.get("terminated_early") is True
        assert r["hits"]["total"]["relation"] == "gte"
        assert 1 <= r["hits"]["total"]["value"] < total
        # a budget the collection never crosses leaves no flag
        r2 = single.search(index="res1", body={
            "query": {"match_all": {}}, "terminate_after": total + 10})
        assert "terminated_early" not in r2
        assert r2["hits"]["total"] == full["hits"]["total"]

    def test_no_timeout_sentinel_and_mesh_decline(self, single):
        """`timeout: -1` is the reference no-deadline sentinel (full
        run, eligible everywhere); a LIVE budget on a mesh-eligible
        multi-shard body must land on the deadline-aware host loop —
        the mesh cannot stop mid-launch — so an exhausted budget still
        yields an honest timed_out partial."""
        single.indices.create("res2", {
            "settings": {"number_of_shards": 2},
            "mappings": {"properties": {"body": {"type": "text"}}}})
        for i in range(24):
            single.index("res2", {"body": "alpha beta"}, id=str(i))
        single.indices.refresh("res2")
        r = single.search(index="res2", body={
            "query": {"match": {"body": "alpha"}}, "timeout": -1})
        assert r["timed_out"] is False
        assert r["hits"]["total"]["value"] == 24
        r = single.search(index="res2", body={
            "query": {"match": {"body": "alpha"}}, "timeout": "0ms"})
        assert r["timed_out"] is True
        assert r["hits"]["hits"] == []

    def test_track_scores_under_field_sort(self, single):
        base = {"query": {"match": {"body": "alpha"}},
                "sort": [{"num": "asc"}], "size": 5}
        off = single.search(index="res1",
                            body=dict(base, track_scores=False))
        assert all(h["_score"] is None for h in off["hits"]["hits"])
        assert off["hits"]["max_score"] is None
        on = single.search(index="res1",
                           body=dict(base, track_scores=True))
        assert all(h["_score"] is not None for h in on["hits"]["hits"])
        assert on["hits"]["max_score"] is not None
        # the sort order itself is identical either way
        assert [h["_id"] for h in off["hits"]["hits"]] == \
            [h["_id"] for h in on["hits"]["hits"]]


class TestSchedulerDeadline:
    def test_queue_wait_derives_from_request_budget(self):
        """With a wedged dispatcher, a queued entry degrades after the
        REQUEST's remaining budget (~0.2 s here), not the scheduler's
        30 s request_timeout — and without a wedge dump (the dispatcher
        isn't wedged; the budget just ran out)."""
        from opensearch_tpu.cluster.node import Node
        from opensearch_tpu.serving import (SchedulerConfig,
                                            ServingScheduler)
        node = Node()
        client = RestClient(node=node)
        client.indices.create("sdl", {
            "settings": {"number_of_shards": 1},
            "mappings": {"properties": {"b": {"type": "text"}}}})
        client.index("sdl", {"b": "x"}, id="1", refresh=True)
        svc = node.indices["sdl"]
        sched = ServingScheduler(node, SchedulerConfig(), enabled=True)
        sched._dispatcher_alive = lambda: True    # nobody will flush
        before = RECORDER.trigger_counts.get("deadline_miss", 0)
        try:
            with dl.scope(dl.Deadline(0.2)):
                t0 = time.monotonic()
                resp = sched.execute("sdl", svc,
                                     {"query": {"match_all": {}}})
                elapsed = time.monotonic() - t0
            # degraded to direct execution (mesh may serve it or decline
            # to the caller's host loop; either way within budget)
            assert resp is None or isinstance(resp, dict)
            assert 0.1 < elapsed < 5.0   # budget-bounded, not 30 s
            assert sched.stats()["direct_fallbacks"] == 1
            assert RECORDER.trigger_counts.get("deadline_miss", 0) \
                == before
        finally:
            sched.close(drain=False)

    def test_scheduler_budget_body_eligibility(self):
        from opensearch_tpu.cluster.node import Node
        from opensearch_tpu.serving import (SchedulerConfig,
                                            ServingScheduler)
        sched = ServingScheduler(Node(), SchedulerConfig(), enabled=True)
        try:
            # budgeted bodies stay on the deadline-aware host loop: the
            # batched mesh/kernel launches cannot stop mid-launch, so
            # both budget kinds bypass the queue (ambient hop-propagated
            # deadlines still derive the queue wait — covered above)
            assert not sched.accepts({"query": {}, "terminate_after": 5})
            assert not sched.accepts({"query": {}, "timeout": "1s"})
            assert sched.accepts({"query": {}})
        finally:
            sched.close(drain=False)


# ---------------------------------------------------------------------
# three-node cluster: failover, deadlines, storms, replay
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster3():
    policy = RetryPolicy(same_member_retries=1, budget=4,
                         base_backoff_s=0.001, max_backoff_s=0.005,
                         storm_n=6)
    a = DistClusterNode("ra", retry_policy=policy)
    b = DistClusterNode("rb", seed=a.addr)
    c = DistClusterNode("rc", seed=a.addr)
    rng = np.random.default_rng(17)
    docs = {str(i): {"body": " ".join(
        rng.choice(WORDS, size=int(rng.integers(3, 8)))),
        "num": int(rng.integers(0, 100))} for i in range(NDOCS)}
    # replicated index: every shard has a second copy on another member
    a.create_index("ridx", {
        "settings": {"number_of_shards": 4,
                     "number_of_node_replicas": 1},
        "mappings": {"properties": {"body": {"type": "text"},
                                    "num": {"type": "integer"}}}})
    # primaries-only index: honest failure surface
    a.create_index("pidx", {
        "settings": {"number_of_shards": 3},
        "mappings": {"properties": {"body": {"type": "text"}}}})
    for i, d in docs.items():
        a.index_doc("ridx", d, id=i)
        a.index_doc("pidx", {"body": d["body"]}, id=i)
    a.refresh("ridx")
    a.refresh("pidx")
    yield a, b, c, docs
    for n in (a, b, c):
        n.stop()


class TestReplicaFailover:
    BODY = {"query": {"match": {"body": "alpha beta"}}, "size": 10}

    def test_copies_assigned_distinct_members(self, cluster3):
        a, *_ = cluster3
        for s, copy_list in a.copies["ridx"].items():
            assert len(copy_list) == 2
            assert len(set(copy_list)) == 2
            assert a.routing["ridx"][s] == copy_list[0]
        # primaries-only index keeps single-copy lists
        assert all(len(cl) == 1 for cl in a.copies["pidx"].values())

    def test_kill_node_with_replicas_byte_identical(self, cluster3):
        a, b, c, _ = cluster3
        baseline = a.search("ridx", dict(self.BODY))
        assert baseline["_shards"]["failed"] == 0
        fo_before = METRICS.counter("dist.rpc.failover").value
        faults.install(faults.ChaosSchedule(seed=4).kill_node("rb"))
        try:
            r = a.search("ridx", dict(self.BODY))
        finally:
            faults.uninstall()
        assert r["_shards"]["failed"] == 0
        assert _norm(r) == _norm(baseline)
        assert METRICS.counter("dist.rpc.failover").value > fo_before
        # detector learned; clear so later tests see the default order
        a.member_fd.note_success("rb")

    def test_kill_without_replicas_honest_failures(self, cluster3):
        a, *_ = cluster3
        owners = a.routing["pidx"]
        rc_shards = [s for s, n in owners.items() if n == "rc"]
        assert rc_shards
        faults.install(faults.ChaosSchedule(seed=5).kill_node("rc"))
        try:
            r = a.search("pidx", {"query": {"match": {"body": "alpha"}},
                                  "size": 10})
            assert r["_shards"]["failed"] == len(rc_shards)
            reasons = {f["shard"]: f["reason"]["type"]
                       for f in r["_shards"]["failures"]}
            assert set(reasons) == set(rc_shards)
            assert all(t == "node_unreachable" for t in reasons.values())
            # reference parity: partiality refused -> whole-request error
            with pytest.raises(ApiError) as ei:
                a.search("pidx", {"query": {"match": {"body": "alpha"}},
                                  "allow_partial_search_results": False})
            assert ei.value.status == 503
        finally:
            faults.uninstall()
        a.member_fd.note_success("rc")

    def test_rpc_delay_past_deadline_no_stall(self, cluster3):
        a, *_ = cluster3
        faults.install(faults.ChaosSchedule(seed=6).add(
            "rpc.send", "blackhole", member="rb", after=1, delay_s=30.0))
        t0 = time.monotonic()
        try:
            r = a.search("pidx", {"query": {"match": {"body": "alpha"}},
                                  "size": 5, "timeout": "300ms"})
        finally:
            faults.uninstall()
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0            # never the 30 s transport cap
        assert r["timed_out"] is True
        assert r["_shards"]["failed"] >= 1
        assert any(f["reason"]["type"] == "timeout_exception"
                   for f in r["_shards"]["failures"])
        a.member_fd.note_success("rb")

    def test_retry_storm_freezes_dump(self, cluster3):
        a, *_ = cluster3
        p = a.retry_policy
        saved = (p.same_member_retries, p.budget, p.storm_n)
        p.same_member_retries, p.budget, p.storm_n = 3, 8, 2
        before = RECORDER.trigger_counts.get("retry_storm", 0)
        faults.install(faults.ChaosSchedule(seed=8).kill_node("rb"))
        try:
            a.search("pidx", {"query": {"match": {"body": "beta"}}})
        finally:
            faults.uninstall()
            p.same_member_retries, p.budget, p.storm_n = saved
        assert RECORDER.trigger_counts.get("retry_storm", 0) > before
        storm = [d for d in RECORDER.dumps()
                 if d["reason"] == "retry_storm"]
        assert storm
        kinds = {e["kind"] for tl in storm[-1]["timelines"].values()
                 for e in tl["events"]}
        assert "rpc.retry" in kinds
        assert "dist.accept" in kinds
        a.member_fd.note_success("rb")

    def test_cluster_replay_same_seed_same_journal(self, cluster3):
        a, *_ = cluster3
        body = {"query": {"match": {"body": "gamma"}}, "size": 5}
        journals = []
        for _ in range(2):
            sched = faults.ChaosSchedule(seed=9).add(
                "rpc.send", "drop", member="rb", p=0.5)
            faults.install(sched)
            try:
                r = a.search("ridx", dict(body))
            finally:
                faults.uninstall()
            assert r["_shards"]["failed"] == 0   # replicas absorb drops
            journals.append([(e["rule"], e["site"], e["op"], e["member"],
                              e["call"], e["action"])
                             for e in sched.journal])
            a.member_fd.note_success("rb")
        assert journals[0] == journals[1]

    def test_deadline_rides_the_wire(self, cluster3):
        """A remote hop sees a smaller remaining budget than the
        coordinator started with (the stamp spends transit + local
        time), and an exhausted arrival 408s: both via the immediate
        shard-failure path."""
        a, *_ = cluster3
        # directly exercise the serving side: an exhausted deadline_ctx
        status, resp = a.handle_internal("POST", ["_internal", "dfs"], {
            "index": "ridx", "body": {"query": {"match_all": {}}},
            "shards": [0], "deadline_ctx": {"remaining_ms": 0.0}})
        assert status == 408
        assert resp["error"]["type"] == "request_timeout_exception"

    def test_member_detector_feeds_copy_order(self, cluster3):
        a, *_ = cluster3
        fd = a.member_fd
        for _ in range(fd.failure_threshold):
            fd.note_failure("rb")
        assert "rb" in fd.deprioritized()
        assert order_copies(["rb", "rc"], fd.deprioritized()) == \
            ["rc", "rb"]
        # a deprioritized member is not selected while a healthy copy
        # exists: the killed-node page still serves failover-first
        r = a.search("ridx", dict(self.BODY))
        assert r["_shards"]["failed"] == 0
        # recovery: a successful probe round restores the order
        events = fd.tick(a.members)
        assert {"member": "rb", "event": "recovered",
                "after_failures": fd.failure_threshold} in events
        assert "rb" not in fd.deprioritized()
        assert order_copies(["rb", "rc"], fd.deprioritized()) == \
            ["rb", "rc"]

    def test_detector_tick_probes_down_member(self, cluster3):
        a, *_ = cluster3
        fd = MemberFailureDetector(failure_threshold=2)
        fd.note_failure("ghost")
        events = fd.tick({"ghost": "127.0.0.1:1"})   # nothing listens
        assert events[0]["event"] == "probe_failed"
        assert events[0]["deprioritized"] is True
        assert "ghost" in fd.deprioritized()

    def test_resilience_surfaces(self, cluster3):
        a, *_ = cluster3
        block = a.client.nodes_stats()["nodes"][
            a.node.node_name]["resilience"]
        assert {"rpc", "deadline", "shards_failed", "chaos"} <= set(block)
        assert block["rpc"]["retries"] >= 1
        assert block["rpc"]["failovers"] >= 1
        assert block["deadline"]["exhausted"] >= 1
        assert block["chaos"]["installed"] is False
        rstats = a.resilience_stats()
        assert rstats["retry_policy"]["budget"] == a.retry_policy.budget
        assert "member_detector" in rstats

    def test_zz_dist_terminate_after_rides_wire(self, cluster3):
        """`terminate_after` crosses the RPC inside the body and every
        shard's leg honors the per-shard budget."""
        a, *_ = cluster3
        r = a.search("ridx", {"query": {"match_all": {}},
                              "terminate_after": 1, "size": 5})
        assert r.get("terminated_early") is True
        assert r["_shards"]["failed"] == 0
