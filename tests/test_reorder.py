"""BP-style impact-clustered doc-id reordering (index/reorder.py).

The standing contract: reordering is INVISIBLE to every consumer — the
same corpus indexed with and without the permutation serves identical
top-k pages (scores AND `_id`s), across refresh and across replica
failover; only the internal doc-id layout (and therefore the block-max
sidecar skew) changes. Plus unit coverage for the permutation itself:
valid permutation, every per-doc plane threads through, impacts carried
with recomputed sidecars, determinism."""

import numpy as np
import pytest

from opensearch_tpu.index import reorder as R
from opensearch_tpu.index.engine import Engine
from opensearch_tpu.index.mappings import Mappings
from opensearch_tpu.rest.client import RestClient

WORDS = [f"w{i:03d}" for i in range(120)]


def _docs(n, seed=0):
    """Corpus with dl spread wide enough that window-boundary scores are
    distinct — the parity assertion compares pages byte-for-byte, and a
    boundary TIE breaks by internal doc id, which is exactly what the
    permutation changes."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(rng.integers(3, 40))
        toks = [WORDS[int(t) % 120] for t in rng.zipf(1.25, k)]
        out.append({"body": " ".join(toks),
                    "status": ["a", "b", "c"][i % 3],
                    "price": int(rng.integers(0, 1000))})
    return out


MAP = {"properties": {"body": {"type": "text"},
                      "status": {"type": "keyword"},
                      "price": {"type": "integer"}}}


def _page(client, index, body, probe):
    r = client.search(index, dict(body, _probe=probe))
    return (r["hits"]["total"]["value"],
            [(h["_id"], h["_score"]) for h in r["hits"]["hits"]])


QUERIES = [
    {"query": {"match": {"body": "w001 w004"}}, "size": 10},
    {"query": {"match": {"body": "w000"}}, "size": 10},
    {"query": {"bool": {"must": [{"match": {"body": "w002 w005 w009"}}],
                        "filter": [{"term": {"status": "a"}}]}},
     "size": 10},
    {"query": {"range": {"price": {"gte": 100, "lt": 700}}},
     "sort": [{"price": "asc"}, {"_id": "asc"}], "size": 10},
]


class TestPermutationUnit:
    @pytest.fixture(scope="class")
    def seg(self):
        m = Mappings(MAP)
        eng = Engine(m)
        for i, src in enumerate(_docs(3000, seed=2)):
            eng.index_doc(f"d{i}", src)
        eng.refresh()
        eng.force_merge(1)
        return eng.segments[0]

    def test_permutation_is_valid_and_deterministic(self, seg):
        p1 = R.compute_permutation(seg, leaf=64)
        p2 = R.compute_permutation(seg, leaf=64)
        assert p1 is not None
        assert np.array_equal(np.sort(p1), np.arange(seg.ndocs))
        assert np.array_equal(p1, p2)
        # a permutation that actually moves docs (not identity)
        assert not np.array_equal(p1, np.arange(seg.ndocs))

    def test_apply_threads_every_plane(self, seg):
        perm = R.compute_permutation(seg, leaf=64)
        out = R.apply_permutation(seg, perm)
        old2new = np.empty(seg.ndocs, np.int64)
        old2new[perm] = np.arange(seg.ndocs)
        # ids / sources / seq_nos / doc values follow the permutation
        for new in (0, 7, 1234, seg.ndocs - 1):
            old = int(perm[new])
            assert out.ids[new] == seg.ids[old]
            assert out.sources[new] == seg.sources[old]
            assert out.seq_nos[new] == seg.seq_nos[old]
            assert out.numeric_cols["price"].values[new] \
                == seg.numeric_cols["price"].values[old]
            assert out.keyword_cols["status"].min_ord[new] \
                == seg.keyword_cols["status"].min_ord[old]
            assert out.doc_lens["body"][new] == seg.doc_lens["body"][old]
        # postings: every row stays doc-ascending, same (term -> doc set)
        pa, pb = seg.postings["body"], out.postings["body"]
        assert np.array_equal(pa.starts, pb.starts)
        for r in range(0, pb.nterms, 17):
            a, b = pb.row_slice(r)
            row = pb.doc_ids[a:b]
            assert np.all(np.diff(row) > 0)
            assert np.array_equal(np.sort(old2new[pa.doc_ids[a:b]]), row)
        # impacts: same quantized multiset per row, sidecar recomputed
        ia, ib = pa.impact, pb.impact
        assert ia.scale == ib.scale and ia.bits == ib.bits
        assert np.array_equal(np.sort(ia.q), np.sort(ib.q))
        if len(ib.block_off):
            assert np.array_equal(
                ib.block_max, np.maximum.reduceat(ib.q, ib.block_off))

    def test_skip_gates(self, seg, monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER", "0")
        assert R.maybe_reorder(seg) is seg
        monkeypatch.delenv("OPENSEARCH_TPU_REORDER")
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "100000")
        assert R.maybe_reorder(seg) is seg
        # v1 segments never reorder
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "16")
        import copy
        v1 = copy.copy(seg)
        v1.codec_version = 1
        assert R.maybe_reorder(v1) is v1

    @staticmethod
    def _reorder_degenerate_seq_nos(seg):
        """Reorder a copy of `seg` whose seq_nos carry no order (the
        direct-CSR corpora default — bench make_index)."""
        import copy
        z = copy.copy(seg)
        z.__dict__ = dict(seg.__dict__)
        z.__dict__.pop("_tie_rank", None)
        z.seq_nos = np.zeros(seg.ndocs, np.int64)
        assert z.tie_ranks() is None         # heuristic alone is blind
        perm = R.compute_permutation(z, leaf=64)
        return R.apply_permutation(z, perm), perm

    def test_tie_plane_pinned_without_seq_nos(self, seg):
        """Zero seq_nos blind Segment.tie_ranks's monotonicity heuristic
        — apply_permutation must pin the arrival-rank plane explicitly
        or the reordered arm silently loses the whole tie-parity
        machinery (code-review regression)."""
        out, perm = self._reorder_degenerate_seq_nos(seg)
        tr = out.tie_ranks()
        assert tr is not None
        # source doc order WAS arrival order, so the permuted plane is
        # exactly the permutation (arrival rank of new doc = its old id)
        assert np.array_equal(tr, np.asarray(perm, np.int64))

    def test_pinned_tie_plane_survives_save_load(self, seg, tmp_path):
        """Degenerate seq_nos can't recover the pinned plane after a
        reload — save() must persist it (code-review regression)."""
        out, _ = self._reorder_degenerate_seq_nos(seg)
        from opensearch_tpu.index.segment import Segment
        d = str(tmp_path / "zseg")
        out.save(d)
        back = Segment.load(d)
        tr2 = back.tie_ranks()
        assert tr2 is not None and np.array_equal(tr2, out.tie_ranks())

    def test_noop_pass_marks_reordered(self, seg, monkeypatch):
        """An applicable segment whose signature band is empty must still
        be marked: engine.force_merge's lone-segment gate would otherwise
        re-run a full single-segment merge on every call (code-review
        regression)."""
        import copy
        s = copy.copy(seg)
        s.__dict__ = dict(seg.__dict__)
        s.__dict__.pop("_reordered", None)
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "16")
        monkeypatch.setattr(R, "compute_permutation", lambda *a, **k: None)
        assert R.maybe_reorder(s) is s
        assert s.__dict__.get("_reordered")

    def test_reordered_marker_survives_save_load(self, seg, tmp_path):
        """After flush/restart the first force_merge must not re-merge an
        already-clustered segment: the marker rides the codec meta."""
        from opensearch_tpu.index.segment import Segment
        perm = R.compute_permutation(seg, leaf=64)
        out = R.apply_permutation(seg, perm)
        out.__dict__["_reordered"] = True
        d = str(tmp_path / "seg")
        out.save(d)
        back = Segment.load(d)
        assert back.__dict__.get("_reordered")
        # the reloaded permuted seq_nos keep the tie plane armed too
        assert back.tie_ranks() is not None

    def test_merge_drives_reorder(self, monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "256")
        m = Mappings(MAP)
        eng = Engine(m)
        for i, src in enumerate(_docs(900, seed=4)):
            eng.index_doc(f"d{i}", src)
            if i % 300 == 299:
                eng.refresh()
        eng.refresh()
        eng.force_merge(1)
        merged = eng.segments[0]
        assert merged.__dict__.get("_reordered")
        # version map re-anchored: realtime get serves the right doc
        got = eng.get("d123")
        assert got["found"] and got["_source"] == _docs(900, seed=4)[123]


class TestServingParityOracle:
    """Same corpus, two indices: reorder ON vs OFF. Every served page —
    scores and _ids — must be byte-identical, across refresh rounds."""

    @pytest.fixture()
    def pair(self, monkeypatch):
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "256")
        client = RestClient()
        docs = _docs(1200, seed=9)
        for name, flag in (("ron", "1"), ("roff", "0")):
            monkeypatch.setenv("OPENSEARCH_TPU_REORDER", flag)
            client.indices.create(name, {
                "settings": {"number_of_replicas": 0},
                "mappings": MAP})
            for i, src in enumerate(docs[:900]):
                client.index(name, src, id=f"d{i}")
            client.indices.refresh(name)
            client.indices.forcemerge(name)
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER", "1")
        return client, docs

    def test_pages_identical_and_reorder_engaged(self, pair, monkeypatch):
        client, docs = pair
        ron = client.node.indices["ron"].shards[0].segments
        assert any(s.__dict__.get("_reordered") for s in ron)
        for qi, q in enumerate(QUERIES):
            a = _page(client, "ron", q, f"p{qi}a")
            b = _page(client, "roff", q, f"p{qi}b")
            assert a == b, (qi, a, b)

    def test_parity_across_second_merge_with_ties(self, monkeypatch):
        """A merge that CONSUMES a reordered segment places it in the
        concatenation in permuted order — merge_segments must thread the
        inputs' arrival planes through (code-review regression) or
        exact-score ties in the merged segment break differently from
        the unreordered arm's merge of the same corpus."""
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "256")
        client = RestClient()
        rng = np.random.default_rng(13)
        docs = []
        for i in range(1200):
            if i % 3 == 0:
                docs.append({"body": "tie alpha beta"})  # big tie class
            else:
                k = int(rng.integers(3, 30))
                docs.append({"body": " ".join(WORDS[int(t) % 120]
                                              for t in rng.zipf(1.3, k))})
        for name, flag in (("m2on", "1"), ("m2off", "0")):
            monkeypatch.setenv("OPENSEARCH_TPU_REORDER", flag)
            client.indices.create(name, {
                "settings": {"number_of_replicas": 0}, "mappings": MAP})
            for i, src in enumerate(docs[:800]):
                client.index(name, src, id=f"d{i}")
            client.indices.refresh(name)
            client.indices.forcemerge(name)       # reorder applies (on arm)
            for i, src in enumerate(docs[800:]):
                client.index(name, src, id=f"d{800 + i}")
            client.indices.refresh(name)
            client.indices.forcemerge(name)       # merge CONSUMES it
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER", "1")
        segs = client.node.indices["m2on"].shards[0].segments
        assert len(segs) == 1 and segs[0].tie_ranks() is not None
        for qi, q in enumerate(["tie", "tie alpha", "alpha beta"]):
            body = {"query": {"match": {"body": q}}, "size": 10}
            a = _page(client, "m2on", body, f"m2{qi}a")
            b = _page(client, "m2off", body, f"m2{qi}b")
            assert a == b, (q, a, b)

    def test_boundary_tie_class_parity_general_path(self, monkeypatch):
        """A bigger-than-k_pad exact-score tie class straddling the page
        boundary, served by the GENERAL (XLA) path: device top-k breaks
        ties by permuted internal id on the reordered arm, so the
        executor must widen its extraction window until the class is
        whole (code-review regression — the fastpath DECLINES boundary
        ties to this path assuming it resolves them exactly)."""
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "256")
        client = RestClient()
        rng = np.random.default_rng(11)
        docs = []
        for i in range(1200):
            if i % 4 == 0:
                # ~300 docs with identical body: one exact-score tie
                # class far wider than the k_pad=16 device window
                docs.append({"body": "tie alpha beta"})
            else:
                k = int(rng.integers(3, 30))
                docs.append({"body": " ".join(WORDS[int(t) % 120]
                                              for t in rng.zipf(1.3, k))})
        for name, flag in (("tron", "1"), ("troff", "0")):
            monkeypatch.setenv("OPENSEARCH_TPU_REORDER", flag)
            client.indices.create(name, {
                "settings": {"number_of_replicas": 0}, "mappings": MAP})
            for i, src in enumerate(docs):
                client.index(name, src, id=f"d{i}")
            client.indices.refresh(name)
            client.indices.forcemerge(name)
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER", "1")
        assert any(s.__dict__.get("_reordered")
                   for s in client.node.indices["tron"].shards[0].segments)
        for qi, q in enumerate(["tie", "tie alpha", "alpha beta"]):
            body = {"query": {"match": {"body": q}}, "size": 10}
            a = _page(client, "tron", body, f"bt{qi}a")
            b = _page(client, "troff", body, f"bt{qi}b")
            assert a == b, (q, a, b)

    def test_parity_across_refresh(self, pair, monkeypatch):
        client, docs = pair
        # a second indexing round + refresh on both arms (reorder state
        # per-arm preserved via the env the fixture leaves at "1": the
        # roff arm is re-pinned off per write round)
        for name, flag in (("ron", "1"), ("roff", "0")):
            monkeypatch.setenv("OPENSEARCH_TPU_REORDER", flag)
            for i, src in enumerate(docs[900:]):
                client.index(name, src, id=f"d{900 + i}")
            client.indices.refresh(name)
        for qi, q in enumerate(QUERIES):
            a = _page(client, "ron", q, f"r{qi}a")
            b = _page(client, "roff", q, f"r{qi}b")
            assert a == b, (qi, a, b)


class TestReplicaFailoverParity:
    def test_failover_serves_identical_pages_on_reordered_index(
            self, monkeypatch):
        """Replica copies of a reordered index stay byte-identical: after
        primary failover the promoted replica serves the same pages."""
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER_MIN_DOCS", "256")
        monkeypatch.setenv("OPENSEARCH_TPU_REORDER", "1")
        client = RestClient()
        client.indices.create("rf", {
            "settings": {"number_of_shards": 1, "number_of_replicas": 1},
            "mappings": MAP})
        for i, src in enumerate(_docs(800, seed=6)):
            client.index("rf", src, id=f"d{i}")
        client.indices.refresh("rf")
        client.indices.forcemerge("rf")
        svc = client.node.indices["rf"]
        assert any(s.__dict__.get("_reordered")
                   for s in svc.shards[0].segments)
        before = [_page(client, "rf", q, f"f{qi}a")
                  for qi, q in enumerate(QUERIES)]
        svc.fail_primary(0)
        after = [_page(client, "rf", q, f"f{qi}b")
                 for qi, q in enumerate(QUERIES)]
        assert before == after
