"""Search templates (reference `modules/lang-mustache/`) and the _rank_eval
API (reference `modules/rank-eval/`)."""

import math

import pytest

from opensearch_tpu.rest.client import ApiError, RestClient
from opensearch_tpu.rest.templates import render_template


class TestMustacheLite:
    def test_scalars_and_paths(self):
        out = render_template(
            {"query": {"match": {"{{fld}}": "{{q.text}}"}}, "size": "{{sz}}"},
            {"fld": "title", "q": {"text": "hello"}, "sz": 5})
        # quoted placeholders render as strings; the API coerces numerics
        assert out == {"query": {"match": {"title": "hello"}}, "size": "5"}

    def test_to_json_and_sections(self):
        src = ('{"query": {"terms": {"tag": {{#toJson}}tags{{/toJson}} }}'
               '{{#paged}}, "from": {{from}}{{/paged}} }')
        out = render_template(src, {"tags": ["a", "b"],
                                    "paged": {"from": 20}})
        assert out == {"query": {"terms": {"tag": ["a", "b"]}}, "from": 20}

    def test_inverted_and_loop(self):
        src = ('{"v": [{{#xs}}"{{.}}",{{/xs}}{{^xs}}"none",{{/xs}} "end"]}')
        assert render_template(src, {"xs": ["p", "q"]}) == \
            {"v": ["p", "q", "end"]}
        assert render_template(src, {}) == {"v": ["none", "end"]}

    def test_join(self):
        src = '{"q": "{{#join}}words{{/join}}"}'
        assert render_template(src, {"words": ["a", "b", "c"]}) == \
            {"q": "a,b,c"}

    def test_string_escaping(self):
        out = render_template({"query": {"match": {"t": "{{v}}"}}},
                              {"v": 'he said "hi"\n'})
        assert out["query"]["match"]["t"] == 'he said "hi"\n'


@pytest.fixture(scope="module")
def client():
    c = RestClient()
    c.indices.create("lib", {"mappings": {"properties": {
        "title": {"type": "text"}, "year": {"type": "long"}}}})
    books = [("1", "the art of search", 2001), ("2", "searching at scale", 2015),
             ("3", "cooking for two", 2019), ("4", "search engines deep dive", 2020)]
    for did, title, year in books:
        c.index("lib", {"title": title, "year": year}, id=did)
    c.indices.refresh("lib")
    return c


class TestSearchTemplateEndpoints:
    def test_inline_source(self, client):
        r = client.search_template("lib", {
            "source": {"query": {"match": {"title": "{{q}}"}},
                       "size": "{{size}}"},
            "params": {"q": "search", "size": 2}})
        assert len(r["hits"]["hits"]) == 2

    def test_stored_template_roundtrip(self, client):
        client.put_script("findbook", {"script": {
            "lang": "mustache",
            "source": {"query": {"match": {"title": "{{q}}"}}}}})
        got = client.get_script("findbook")
        assert got["found"]
        r = client.search_template("lib", {"id": "findbook",
                                           "params": {"q": "cooking"}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["3"]
        client.delete_script("findbook")
        with pytest.raises(ApiError):
            client.get_script("findbook")

    def test_render_endpoint(self, client):
        r = client.render_search_template({
            "source": '{"query": {"range": {"year": {"gte": {{y}}}}}}',
            "params": {"y": 2015}})
        assert r["template_output"] == \
            {"query": {"range": {"year": {"gte": 2015}}}}

    def test_msearch_template(self, client):
        r = client.msearch_template([
            {"index": "lib"},
            {"source": {"query": {"match": {"title": "{{q}}"}}},
             "params": {"q": "search"}},
            {"index": "lib"},
            {"id": "missing-template", "params": {}},
        ])
        assert r["responses"][0]["hits"]["total"]["value"] == 2
        assert "error" in r["responses"][1]


class TestRankEval:
    def test_precision_and_recall(self, client):
        body = {
            "requests": [{
                "id": "q1",
                "request": {"query": {"match": {"title": "search"}}},
                "ratings": [{"_index": "lib", "_id": "1", "rating": 1},
                            {"_index": "lib", "_id": "2", "rating": 1},
                            {"_index": "lib", "_id": "3", "rating": 0}],
            }],
            "metric": {"precision": {"k": 3,
                                     "relevant_rating_threshold": 1}},
        }
        r = client.rank_eval("lib", body)
        d = r["details"]["q1"]
        # hits are 1,4 (no stemming: "searching" != "search"); 4 unrated
        # counts as non-relevant
        assert d["metric_score"] == pytest.approx(1 / 2)
        assert {u["_id"] for u in d["unrated_docs"]} == {"4"}
        body["metric"] = {"recall": {"k": 3}}
        r = client.rank_eval("lib", body)
        assert r["metric_score"] == pytest.approx(0.5)  # 1 of 2 relevant found

    def test_mrr_and_ndcg_and_err(self, client):
        reqs = [{
            "id": "q",
            "request": {"query": {"match": {"title": "search"}}},
            "ratings": [{"_index": "lib", "_id": "4", "rating": 3},
                        {"_index": "lib", "_id": "2", "rating": 1}],
        }]
        r = client.rank_eval("lib", {"requests": reqs, "metric": {
            "mean_reciprocal_rank": {"k": 5}}})
        assert 0 < r["metric_score"] <= 1.0
        r = client.rank_eval("lib", {"requests": reqs, "metric": {
            "dcg": {"k": 5, "normalize": True}}})
        assert 0 < r["metric_score"] <= 1.0
        r = client.rank_eval("lib", {"requests": reqs, "metric": {
            "expected_reciprocal_rank": {"k": 5, "maximum_relevance": 3}}})
        assert 0 < r["metric_score"] <= 1.0

    def test_bad_metric_400(self, client):
        with pytest.raises(ApiError):
            client.rank_eval("lib", {"requests": [],
                                     "metric": {"nope": {}}})

    def test_failures_collected(self, client):
        r = client.rank_eval("lib", {
            "requests": [{"id": "bad",
                          "request": {"query": {"zap": {}}},
                          "ratings": []}],
            "metric": {"precision": {"k": 2}}})
        assert "bad" in r["failures"]
