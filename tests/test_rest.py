import pytest

from opensearch_tpu import RestClient
from opensearch_tpu.rest.client import ApiError


@pytest.fixture
def client(tmp_data_path):
    return RestClient(data_path=tmp_data_path)


def seed(c, index="items", shards=2):
    c.indices.create(index, {"settings": {"number_of_shards": shards},
                             "mappings": {"properties": {
                                 "name": {"type": "text"},
                                 "price": {"type": "double"},
                                 "cat": {"type": "keyword"}}}})
    c.bulk([
        {"index": {"_index": index, "_id": "1"}}, {"name": "red sweater", "price": 40.0, "cat": "clothing"},
        {"index": {"_index": index, "_id": "2"}}, {"name": "blue sweater", "price": 30.0, "cat": "clothing"},
        {"index": {"_index": index, "_id": "3"}}, {"name": "espresso machine", "price": 250.0, "cat": "kitchen"},
    ], refresh=True)


def test_doc_crud(client):
    client.index("i", {"a": 1}, id="x", refresh=True)
    assert client.get("i", "x")["_source"] == {"a": 1}
    assert client.exists("i", "x")
    client.delete("i", "x", refresh=True)
    assert not client.exists("i", "x")
    with pytest.raises(ApiError) as e:
        client.get("i", "x")
    assert e.value.status == 404


def test_auto_id_and_op_type(client):
    r = client.index("i", {"a": 1})
    assert r["_id"]
    client.create("i", "fixed", {"b": 2})
    with pytest.raises(ApiError) as e:
        client.create("i", "fixed", {"b": 3})
    assert e.value.status == 409


def test_bulk_mixed_and_errors(client):
    r = client.bulk([
        {"index": {"_index": "b", "_id": "1"}}, {"v": 1},
        {"create": {"_index": "b", "_id": "1"}}, {"v": 2},   # conflict
        {"delete": {"_index": "b", "_id": "zz"}},             # not found
        {"update": {"_index": "b", "_id": "1"}}, {"doc": {"v": 9}},
    ], refresh=True)
    assert r["errors"] is True
    stats = [list(i.values())[0]["status"] for i in r["items"]]
    assert stats == [201, 409, 404, 200]
    assert client.get("b", "1")["_source"]["v"] == 9


def test_update_upsert_noop(client):
    r = client.update("u", "1", {"doc": {"x": 1}, "doc_as_upsert": True})
    assert r["result"] in ("created", "updated")
    r = client.update("u", "1", {"doc": {"x": 1}})
    assert r["result"] == "noop"
    client.update("u", "2", {"upsert": {"y": 5}, "doc": {"y": 6}})
    assert client.get("u", "2")["_source"]["y"] == 5


def test_search_and_count(client):
    seed(client)
    r = client.search("items", {"query": {"match": {"name": "sweater"}}})
    assert r["hits"]["total"]["value"] == 2
    assert client.count("items", {"query": {"term": {"cat": "kitchen"}}})["count"] == 1


def test_msearch(client):
    seed(client)
    r = client.msearch([{"index": "items"}, {"query": {"match_all": {}}},
                        {"index": "items"}, {"query": {"term": {"cat": "kitchen"}}}])
    assert r["responses"][0]["hits"]["total"]["value"] == 3
    assert r["responses"][1]["hits"]["total"]["value"] == 1


def test_mget(client):
    seed(client)
    r = client.mget({"docs": [{"_index": "items", "_id": "1"},
                              {"_index": "items", "_id": "nope"}]})
    assert r["docs"][0]["_source"]["price"] == 40.0
    assert r["docs"][1]["found"] is False


def test_aliases_and_wildcards(client):
    seed(client, "logs-2024-01")
    seed(client, "logs-2024-02")
    client.indices.update_aliases({"actions": [
        {"add": {"index": "logs-2024-01", "alias": "logs"}},
        {"add": {"index": "logs-2024-02", "alias": "logs"}}]})
    assert client.count("logs")["count"] == 6
    assert client.count("logs-2024-*")["count"] == 6
    al = client.indices.get_alias(name="logs")
    assert set(al) == {"logs-2024-01", "logs-2024-02"}


def test_index_templates(client):
    client.indices.put_index_template("tmpl", {
        "index_patterns": ["tmp-*"],
        "template": {"settings": {"number_of_shards": 3},
                     "mappings": {"properties": {"f": {"type": "keyword"}}}}})
    client.index("tmp-1", {"f": "v"}, id="1", refresh=True)
    svc = client.node.indices["tmp-1"]
    assert svc.meta.num_shards == 3
    assert svc.mappings.fields["f"].type == "keyword"


def test_mapping_apis(client):
    seed(client)
    m = client.indices.get_mapping("items")
    assert m["items"]["mappings"]["properties"]["name"]["type"] == "text"
    client.indices.put_mapping("items", {"properties": {"extra": {"type": "long"}}})
    assert client.node.indices["items"].mappings.fields["extra"].type == "long"


def test_analyze_api(client):
    seed(client)
    toks = client.indices.analyze("items", {"text": "Red Sweaters",
                                            "analyzer": "english"})["tokens"]
    assert [t["token"] for t in toks] == ["red", "sweater"]
    toks = client.indices.analyze("items", {"field": "cat", "text": "As-Is"})["tokens"]
    assert [t["token"] for t in toks] == ["As-Is"]


def test_field_caps(client):
    seed(client)
    r = client.field_caps("items", "*")
    assert r["fields"]["price"]["double"]["aggregatable"]
    assert r["fields"]["name"]["text"]["searchable"]


def test_reindex_and_delete_by_query(client):
    seed(client)
    client.reindex({"source": {"index": "items"}, "dest": {"index": "copy"}},
                   refresh=True)
    assert client.count("copy")["count"] == 3
    client.delete_by_query("copy", {"query": {"term": {"cat": "clothing"}}},
                           refresh=True)
    assert client.count("copy")["count"] == 1


def test_scroll(client):
    seed(client)
    r = client.search("items", {"query": {"match_all": {}}, "size": 2,
                                "sort": [{"price": "asc"}]}, scroll="1m")
    page1 = [h["_id"] for h in r["hits"]["hits"]]
    r2 = client.scroll(r["_scroll_id"])
    page2 = [h["_id"] for h in r2["hits"]["hits"]]
    assert page1 == ["2", "1"] and page2 == ["3"]
    client.clear_scroll(r["_scroll_id"])
    with pytest.raises(ApiError):
        client.scroll(r["_scroll_id"])


def test_pit_isolation(client):
    seed(client)
    pit = client.create_pit("items")
    client.index("items", {"name": "new thing", "price": 5.0}, id="9", refresh=True)
    live = client.search("items", {"query": {"match_all": {}}})
    pinned = client.search("items", {"query": {"match_all": {}},
                                     "pit": {"id": pit["pit_id"]}})
    assert live["hits"]["total"]["value"] == 4
    assert pinned["hits"]["total"]["value"] == 3
    client.delete_pit({"pit_id": pit["pit_id"]})


def test_ingest_pipeline(client):
    client.ingest.put_pipeline("p1", {"processors": [
        {"set": {"field": "tagged", "value": True}},
        {"uppercase": {"field": "name"}},
        {"convert": {"field": "num", "type": "integer", "ignore_missing": True}},
    ]})
    client.index("pi", {"name": "abc", "num": "42"}, id="1", pipeline="p1",
                 refresh=True)
    src = client.get("pi", "1")["_source"]
    assert src == {"name": "ABC", "num": 42, "tagged": True}
    sim = client.ingest.simulate({"pipeline": {"processors": [
        {"fail": {"message": "boom"}}]}, "docs": [{"_source": {}}]})
    assert "error" in sim["docs"][0]


def test_default_pipeline(client):
    client.ingest.put_pipeline("dp", {"processors": [
        {"set": {"field": "via", "value": "pipeline"}}]})
    client.indices.create("auto", {"settings": {"default_pipeline": "dp"}})
    client.index("auto", {"x": 1}, id="1", refresh=True)
    assert client.get("auto", "1")["_source"]["via"] == "pipeline"


def test_snapshot_restore(client, tmp_path):
    seed(client)
    client.snapshot.create_repository("repo", {"settings": {"location": str(tmp_path / "snaps")}})
    client.snapshot.create("repo", "snap1", {"indices": "items"})
    client.indices.delete("items")
    assert not client.indices.exists("items")
    client.snapshot.restore("repo", "snap1")
    assert client.count("items")["count"] == 3
    assert client.snapshot.get("repo")["snapshots"][0]["snapshot"] == "snap1"


def test_explain_api(client):
    seed(client)
    r = client.explain("items", "1", {"query": {"match": {"name": "red"}}})
    assert r["matched"] is True
    r = client.explain("items", "3", {"query": {"match": {"name": "red"}}})
    assert r["matched"] is False


def test_termvectors(client):
    seed(client)
    r = client.termvectors("items", "1", fields=["name"])
    assert r["term_vectors"]["name"]["terms"]["red"]["term_freq"] == 1


def test_cluster_and_cat(client):
    seed(client)
    assert client.cluster.health()["status"] == "green"
    assert client.cluster.state()["metadata"]["indices"]["items"]["state"] == "open"
    cats = client.cat.indices()
    assert any(row["index"] == "items" and row["docs.count"] == "3" for row in cats)
    assert client.cat.count("items")[0]["count"] == "3"


def test_request_cache(client):
    seed(client)
    body = {"query": {"match": {"name": "sweater"}}}
    client.search("items", body)
    m0 = client.node.request_cache.hits
    client.search("items", body)
    assert client.node.request_cache.hits == m0 + 1
    # a write invalidates via generation
    client.index("items", {"name": "green sweater", "price": 10.0}, id="9",
                 refresh=True)
    r = client.search("items", body)
    assert r["hits"]["total"]["value"] == 3


def test_node_recovery(tmp_data_path):
    c = RestClient(data_path=tmp_data_path)
    seed(c)
    c.indices.flush("items")
    c2 = RestClient(data_path=tmp_data_path)
    assert c2.count("items")["count"] == 3
    assert c2.get("items", "1")["_source"]["name"] == "red sweater"


def test_routing_param(client):
    client.indices.create("r", {"settings": {"number_of_shards": 4}})
    client.index("r", {"v": 1}, id="a", routing="user1", refresh=True)
    assert client.get("r", "a", routing="user1")["_source"]["v"] == 1


def test_index_not_found(client):
    from opensearch_tpu.cluster.state import IndexNotFoundError
    with pytest.raises(IndexNotFoundError):
        client.search("missing_index", {"query": {"match_all": {}}})


def test_bad_query_is_400(client):
    seed(client)
    with pytest.raises(ApiError) as e:
        client.search("items", {"query": {"frobnicate": {}}})
    assert e.value.status == 400


def test_explain_matches_score_across_shards(client):
    seed(client)
    r = client.search("items", {"query": {"match": {"name": "sweater"}},
                                "explain": True})
    for h in r["hits"]["hits"]:
        assert h["_explanation"]["value"] == pytest.approx(h["_score"], rel=1e-4)
