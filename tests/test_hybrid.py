"""Hybrid retrieval engine (ISSUE 15): fusion algebra vs a brute-force
host oracle, distributed-merge commutativity (fused pages identical on
every serving arm), pagination stability, the learned-sparse impact
plane (parity vs the exact sparse_dot path + hostile-margin forced
escalation), and the first-class batched-knn serving route."""

import json
import random
import threading

import numpy as np
import pytest

from opensearch_tpu.cluster.node import Node
from opensearch_tpu.index.segment import CODEC_V2
from opensearch_tpu.obs.insights import fingerprint
from opensearch_tpu.rest.client import ApiError, RestClient
from opensearch_tpu.search import fusion, impactpath
from opensearch_tpu.search import query_dsl as dsl
from opensearch_tpu.search.executor import msearch_batched, search_shards
from opensearch_tpu.serving import SchedulerConfig, ServingScheduler

MAPPING = {"mappings": {"properties": {
    "body": {"type": "text"},
    "emb": {"type": "rank_features", "index_impacts": True},
    "vec": {"type": "dense_vector", "dims": 8, "similarity": "cosine"},
    "cat": {"type": "keyword"}}}}

VOCAB = [f"w{i}" for i in range(30)]
FEATS = [f"t{i}" for i in range(25)]


def _mk_docs(n=300, seed=7):
    rng = random.Random(seed)
    docs = {}
    for i in range(n):
        toks = rng.sample(VOCAB, rng.randint(2, 6))
        feats = {f: round(rng.expovariate(1.0) + 0.05, 3)
                 for f in rng.sample(FEATS, rng.randint(2, 5))}
        docs[str(i)] = {
            "body": " ".join(toks),
            "emb": feats,
            "vec": [rng.random() for _ in range(8)],
            "cat": "odd" if i % 2 else "even"}
    return docs


def _client(docs, shards=1):
    c = RestClient(node=Node())
    body = dict(MAPPING)
    if shards > 1:
        body = {**MAPPING,
                "settings": {"index": {"number_of_shards": shards}}}
    c.indices.create("hx", body)
    for did, d in docs.items():
        c.index("hx", d, id=did)
    c.indices.refresh("hx")
    return c


def _hits(resp):
    return [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]


def _page_bytes(resp):
    """The byte-comparable identity of a served page."""
    return json.dumps({"hits": _hits(resp),
                       "total": resp["hits"]["total"],
                       "max": resp["hits"]["max_score"]},
                      sort_keys=True)


SUBS = [
    {"match": {"body": "w1 w2 w3"}},
    {"neural_sparse": {"emb": {"query_tokens": {"t1": 2.0, "t2": 1.0,
                                                "t7": 0.4}}}},
    {"knn": {"vec": {"vector": [0.9, 0.1, 0.5, 0.3, 0.7, 0.2, 0.6, 0.4],
                     "k": 20}}},
]


def _hybrid_body(method="rrf", size=10, frm=0, window=50, weights=None,
                 norm=None, subs=None):
    f = {"method": method, "rank_constant": 20, "window_size": window}
    if weights is not None:
        f["weights"] = weights
    if norm is not None:
        f["normalization"] = norm
    return {"query": {"hybrid": {"queries": list(subs or SUBS),
                                 "fusion": f}},
            "from": frm, "size": size}


# ----------------------------------------------------------------------
# fusion algebra vs the brute-force oracle
# ----------------------------------------------------------------------

class TestFusionAlgebra:
    def test_minmax_normalize(self):
        assert fusion.minmax_normalize([4.0, 2.0, 3.0]) == [1.0, 0.0, 0.5]
        # degenerate constant list: presence is the only signal
        assert fusion.minmax_normalize([2.0, 2.0]) == [1.0, 1.0]
        assert fusion.minmax_normalize([]) == []

    def test_l2_normalize(self):
        out = fusion.l2_normalize([3.0, 4.0])
        assert out == pytest.approx([0.6, 0.8])
        assert fusion.l2_normalize([0.0, 0.0]) == [0.0, 0.0]

    def test_rrf_matches_hand_oracle(self):
        lists = [[("a", 9.0), ("b", 5.0), ("c", 1.0)],
                 [("b", 0.9), ("d", 0.7)]]
        spec = {"method": "rrf", "rank_constant": 10.0,
                "weights": [1.0, 2.0], "normalization": "min_max"}
        got = fusion.fuse_ranked_lists(lists, spec)
        want = {"a": 1 / 11, "b": 1 / 12 + 2 / 11, "c": 1 / 13,
                "d": 2 / 12}
        assert {k: pytest.approx(v) for k, v in dict(got).items()} == want
        assert [k for k, _ in got] == sorted(
            want, key=lambda k: -want[k])

    def test_linear_matches_hand_oracle(self):
        lists = [[("a", 10.0), ("b", 6.0), ("c", 2.0)],
                 [("c", 0.8), ("a", 0.4)]]
        spec = {"method": "linear", "rank_constant": 60.0,
                "weights": [1.0, 1.0], "normalization": "min_max"}
        got = dict(fusion.fuse_ranked_lists(lists, spec))
        assert got["a"] == pytest.approx(1.0 + 0.0)
        assert got["b"] == pytest.approx(0.5)
        assert got["c"] == pytest.approx(0.0 + 1.0)

    def test_tie_break_is_deterministic_and_arrival_free(self):
        # two docs with identical fused scores break on the best
        # (sub-query index, rank) coordinate, then the key
        lists = [[("b", 5.0), ("x", 4.0)], [("a", 5.0), ("y", 4.0)]]
        spec = {"method": "rrf", "rank_constant": 60.0,
                "weights": [1.0, 1.0], "normalization": "min_max"}
        got = [k for k, _ in fusion.fuse_ranked_lists(lists, spec)]
        # b and a tie by score; b holds (0, 0) < a's (1, 0)
        assert got == ["b", "a", "x", "y"]

    def test_fusion_is_commutative_over_key_insertion_order(self):
        rng = random.Random(3)
        lists = [[(f"d{rng.randrange(40)}", rng.random() * 10)
                  for _ in range(20)] for _ in range(3)]
        # dedupe keys within a list, keep first occurrence (rank order)
        lists = [list(dict(lst).items()) for lst in lists]
        spec = {"method": "linear", "rank_constant": 60.0,
                "weights": [1.0, 0.5, 2.0], "normalization": "l2"}
        a = fusion.fuse_ranked_lists(lists, spec)
        b = fusion.fuse_ranked_lists(list(lists), spec)
        assert a == b


# ----------------------------------------------------------------------
# end-to-end single node: oracle parity + pagination + validation
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def docs():
    return _mk_docs()


@pytest.fixture(scope="module")
def client(docs):
    return _client(docs)


class TestHybridSearch:
    def _oracle_page(self, c, method, norm="min_max", weights=None,
                     window=50, frm=0, size=10):
        """Brute-force oracle: run each sub-query alone at the fusion
        window, fuse with an independent implementation, page."""
        w = weights or [1.0] * len(SUBS)
        lists = []
        for sub in SUBS:
            r = c.search("hx", {"query": sub, "size": window})
            lists.append([(h["_id"], h["_score"])
                          for h in r["hits"]["hits"]])
        fused = {}
        coord = {}
        for li, lst in enumerate(lists):
            if method == "rrf":
                contribs = [w[li] / (20.0 + r) for r in
                            range(1, len(lst) + 1)]
            else:
                scores = [s for _, s in lst]
                if norm == "l2":
                    nrm = sum(s * s for s in scores) ** 0.5 or 1.0
                    ns = [s / nrm for s in scores]
                else:
                    lo, hi = (min(scores), max(scores)) if scores \
                        else (0, 0)
                    ns = [1.0] * len(scores) if hi <= lo else \
                        [(s - lo) / (hi - lo) for s in scores]
                contribs = [w[li] * n for n in ns]
            for r0, ((k, _), cb) in enumerate(zip(lst, contribs)):
                fused[k] = fused.get(k, 0.0) + cb
                coord.setdefault(k, (li, r0))
                if (li, r0) < coord[k]:
                    coord[k] = (li, r0)
        order = sorted(fused, key=lambda k: (-fused[k], coord[k],
                                             ("hx", k)))
        return [(k, round(fused[k], 7))
                for k in order[frm: frm + size]]

    @pytest.mark.parametrize("method,norm", [("rrf", "min_max"),
                                             ("linear", "min_max"),
                                             ("linear", "l2")])
    def test_engine_matches_oracle(self, client, method, norm):
        r = client.search("hx", _hybrid_body(method=method, norm=norm))
        assert _hits(r) == self._oracle_page(client, method, norm)

    def test_weights_shift_the_page(self, client):
        r = client.search("hx", _hybrid_body(
            method="linear", weights=[0.0, 0.0, 5.0]))
        knn_only = client.search(
            "hx", {"query": SUBS[2], "size": 10})
        assert [h for h, _ in _hits(r)] == [h for h, _ in
                                            _hits(knn_only)]

    def test_pagination_is_stable(self, client):
        whole = client.search("hx", _hybrid_body(size=12))
        p1 = client.search("hx", _hybrid_body(size=6))
        p2 = client.search("hx", _hybrid_body(size=6, frm=6))
        assert _hits(p1) + _hits(p2) == _hits(whole)

    def test_from_size_beyond_window_is_400(self, client):
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", _hybrid_body(size=10, frm=45,
                                             window=50))

    def test_validation_400s(self, client):
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", {"query": {"hybrid": {
                "queries": SUBS, "fusion": {"method": "magic"}}}})
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", {"query": {"hybrid": {
                "queries": SUBS, "fusion": {"weights": [1.0]}}}})
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", {"query": {"hybrid": {"queries": []}}})
        # nested hybrid is structural 400
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", {"query": {"hybrid": {"queries": [
                {"hybrid": {"queries": [SUBS[0]]}}]}}})
        # sort cannot ride a hybrid body (aggs CAN, since PR 17 — they
        # run over the fused candidate window, see tests/test_legs.py::TestHybridParity)
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", {**_hybrid_body(),
                                 "sort": [{"cat": "asc"}]})
        # hybrid nested inside bool is a structural 400 too
        with pytest.raises((ApiError, dsl.QueryParseError)):
            client.search("hx", {"query": {"bool": {"must": [
                {"hybrid": {"queries": [SUBS[0]]}}]}}})

    def test_total_is_honest_union_bound(self, client, docs):
        r = client.search("hx", _hybrid_body())
        subs_totals = [client.search("hx", {"query": s, "size": 0})
                       ["hits"]["total"]["value"] for s in SUBS]
        assert r["hits"]["total"]["value"] == max(subs_totals)
        assert r["hits"]["total"]["relation"] == "gte"

    def test_profile_carries_sub_query_attribution(self, client):
        r = client.search("hx", {**_hybrid_body(), "profile": True})
        hp = r["profile"]["hybrid"]
        assert hp["fusion"]["method"] == "rrf"
        assert len(hp["sub_queries"]) == len(SUBS)
        for sq in hp["sub_queries"]:
            assert sq["candidates"] > 0
            assert sq["total"]["value"] > 0

    def test_hybridpath_stats_move(self, client):
        before = fusion.stats()["searches"]
        client.search("hx", _hybrid_body(size=3, window=20))
        assert fusion.stats()["searches"] == before + 1

    def test_single_sub_query_passthrough_ranks(self, client):
        r = client.search("hx", _hybrid_body(subs=[SUBS[0]], size=5))
        alone = client.search("hx", {"query": SUBS[0], "size": 5})
        assert [h for h, _ in _hits(r)] == [h for h, _ in _hits(alone)]
        # single sub: totals keep the sub's exact relation
        assert r["hits"]["total"] == alone["hits"]["total"]


# ----------------------------------------------------------------------
# distributed merge commutativity + serving-arm byte-parity
# ----------------------------------------------------------------------

class TestDistributedParity:
    def test_fused_page_identical_on_every_arm(self, docs):
        from opensearch_tpu.cluster.distnode import DistClusterNode
        a = DistClusterNode("ha")
        b = DistClusterNode("hb", seed=a.addr)
        try:
            a.create_index("hx", {**MAPPING, "settings": {
                "index": {"number_of_shards": 2}}})
            for did, d in docs.items():
                a.index_doc("hx", d, id=did)
            a.refresh("hx")
            oracle = _client(docs, shards=2)
            bodies = [_hybrid_body(),
                      _hybrid_body(method="linear", norm="l2"),
                      _hybrid_body(size=4, frm=3, window=30)]
            for body in bodies:
                pages = [a.search("hx", dict(body)),
                         b.search("hx", dict(body)),
                         oracle.search("hx", dict(body))]
                # coordinator A == coordinator B == single node: the
                # distributed merge is commutative over shard/node
                # arrival order and the fusion is a pure function
                assert (_page_bytes(pages[0]) == _page_bytes(pages[1])
                        == _page_bytes(pages[2]))
        finally:
            b.stop()
            a.stop()

    def test_pure_knn_serves_distributed(self, docs):
        from opensearch_tpu.cluster.distnode import DistClusterNode
        a = DistClusterNode("ka")
        try:
            a.create_index("hx", MAPPING)
            for did, d in docs.items():
                a.index_doc("hx", d, id=did)
            a.refresh("hx")
            oracle = _client(docs)
            body = {"query": SUBS[2], "size": 8}
            assert _page_bytes(a.search("hx", dict(body))) \
                == _page_bytes(oracle.search("hx", dict(body)))
        finally:
            a.stop()


# ----------------------------------------------------------------------
# scheduler arm: hybrid + knn coalesce and stay byte-identical
# ----------------------------------------------------------------------

class TestSchedulerParity:
    def test_knn_is_no_longer_a_bypass_key(self, docs):
        c = _client(docs)
        node = c.node
        sched = ServingScheduler(node, SchedulerConfig(
            max_batch=8, max_wait_us=50_000))
        assert sched.accepts({"query": SUBS[2], "size": 5})
        assert sched.accepts({"knn": {"field": "vec",
                                      "query_vector": [0.0] * 8,
                                      "k": 5}})
        assert sched.accepts(_hybrid_body())
        sched.close()

    def test_scheduler_on_off_pages_byte_identical(self, docs):
        c = _client(docs)
        node = c.node
        rng = random.Random(11)
        bodies = []
        for i in range(12):
            kind = i % 3
            if kind == 0:
                bodies.append(_hybrid_body(size=5, window=20))
            elif kind == 1:
                bodies.append({"query": {"knn": {"vec": {
                    "vector": [rng.random() for _ in range(8)],
                    "k": 8}}}, "size": 8})
            else:
                bodies.append({"query": SUBS[1], "size": 6})
        off = [c.search("hx", dict(b)) for b in bodies]
        node.request_cache._store.clear()
        node.serving = ServingScheduler(node, SchedulerConfig(
            max_batch=16, max_wait_us=200_000))
        try:
            on = [None] * len(bodies)

            def run(i):
                on[i] = c.search("hx", dict(bodies[i]))

            ts = [threading.Thread(target=run, args=(i,))
                  for i in range(len(bodies))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(len(bodies)):
                assert _page_bytes(on[i]) == _page_bytes(off[i]), i
        finally:
            node.serving.close()
            node.serving = None

    def test_batched_knn_route_serves_and_matches_direct(self, docs):
        c = _client(docs)
        searchers = c.node.indices["hx"].searchers
        rng = random.Random(2)
        bodies = [{"query": {"knn": {"vec": {
            "vector": [rng.random() for _ in range(8)], "k": 6}}},
            "size": 6} for _ in range(4)]
        bodies.append({"knn": {"field": "vec",
                               "query_vector": [rng.random()
                                                for _ in range(8)],
                               "k": 4}, "size": 4})
        bodies.append({"query": {"knn": {"vec": {
            "vector": [rng.random() for _ in range(8)], "k": 5,
            "filter": {"term": {"cat": "odd"}}}}}, "size": 5})
        before = fusion.stats()
        rs = msearch_batched(searchers, bodies, "hx")
        after = fusion.stats()
        assert all(r is not None for r in rs)
        assert after["knn_batched"] - before["knn_batched"] \
            == len(bodies)
        assert after["knn_batch_launches"] > before["knn_batch_launches"]
        direct = [search_shards(searchers, dict(b), "hx")
                  for b in bodies]
        for got, want in zip(rs, direct):
            assert _page_bytes(got) == _page_bytes(want)


# ----------------------------------------------------------------------
# learned-sparse on the impact ladder
# ----------------------------------------------------------------------

def _sparse_corpus(n=4000, seed=0, opt_in=True):
    rng = random.Random(seed)
    # mesh-less node: the impact ladder only engages on single-domain
    # serving (search/impactpath.py _MESH_ATTACHED) — the conftest's
    # virtual 8-device CPU mesh would otherwise stand it down
    c = RestClient(node=Node(mesh_service=False))
    mapping = {"settings": {"number_of_shards": 1,
                            "number_of_replicas": 0},
               "mappings": {"properties": {
                   "emb": {"type": "rank_features",
                           **({"index_impacts": True}
                              if opt_in else {})}}}}
    c.indices.create("sx", mapping)
    docs = {}
    for i in range(n):
        toks = {f"t{rng.randrange(30)}": round(rng.expovariate(1.0)
                                               + 0.05, 3)
                for _ in range(6)}
        docs[str(i)] = toks
        c.index("sx", {"emb": toks}, id=str(i))
    c.indices.refresh("sx")
    return c, docs


QTOKENS = {"t1": 3.0, "t2": 1.5, "t7": 0.3, "t9": 0.15, "t11": 0.1}


def _sparse_body(size=10):
    return {"query": {"neural_sparse": {"emb": {
        "query_tokens": dict(QTOKENS)}}}, "size": size}


class TestSparseImpactLadder:
    def test_opt_in_builds_feature_plane(self):
        c, _ = _sparse_corpus(n=300)
        seg = c.node.indices["sx"].shards[0].segments[0]
        plane = seg.postings["emb"].impact
        assert plane is not None and plane.kind == "feature"
        assert seg.codec_version == CODEC_V2

    def test_no_opt_in_no_plane(self):
        c, _ = _sparse_corpus(n=200, opt_in=False)
        seg = c.node.indices["sx"].shards[0].segments[0]
        assert seg.postings["emb"].impact is None

    def test_ladder_serves_with_block_skip(self):
        c, _ = _sparse_corpus()
        before = dict(impactpath.STATS)
        r = c.search("sx", _sparse_body())
        after = dict(impactpath.STATS)
        assert after["served"] == before["served"] + 1
        assert after["blocks_skipped"] > before["blocks_skipped"]
        assert len(r["hits"]["hits"]) == 10

    def test_parity_vs_exact_sparse_dot(self, monkeypatch):
        c, docs = _sparse_corpus(seed=5)
        got = c.search("sx", _sparse_body())
        # the exact arm: impact ladder disabled -> generic sparse_dot
        # XLA program (fresh node so no request cache aliasing)
        monkeypatch.setenv("OPENSEARCH_TPU_NO_IMPACT", "1")
        c2 = RestClient(node=Node(mesh_service=False))
        c2.indices.create("sx", {"mappings": {"properties": {
            "emb": {"type": "rank_features", "index_impacts": True}}}})
        for did, d in docs.items():
            c2.index("sx", {"emb": d}, id=did)
        c2.indices.refresh("sx")
        want = c2.search("sx", _sparse_body())
        assert [h for h, _ in _hits(got)] == [h for h, _ in _hits(want)]
        for (_, a), (_, b) in zip(_hits(got), _hits(want)):
            assert a == pytest.approx(b, rel=1e-5)

    def test_parity_vs_host_oracle(self):
        c, docs = _sparse_corpus(seed=9)
        r = c.search("sx", _sparse_body())
        scores = {}
        for did, toks in docs.items():
            s = np.float32(0.0)
            hitn = 0
            for t in sorted(QTOKENS):
                if t in toks:
                    s = np.float32(s + np.float32(
                        np.float32(QTOKENS[t]) * np.float32(toks[t])))
                    hitn += 1
            if hitn:
                scores[did] = float(s)
        want = sorted(scores.items(),
                      key=lambda kv: (-kv[1], int(kv[0])))[:10]
        assert [h for h, _ in _hits(r)] == [d for d, _ in want]
        for (_, a), (_, b) in zip(_hits(r), want):
            assert a == pytest.approx(b, abs=1e-5)

    def test_hostile_margin_forces_escalation_and_stays_exact(
            self, monkeypatch):
        monkeypatch.setattr(impactpath, "PRUNE_MARGIN", 1e9)
        monkeypatch.setattr(impactpath, "KEEP_MIN", 32)
        monkeypatch.setattr(impactpath, "KEEP_FACTOR", 1)
        c, docs = _sparse_corpus(seed=13)
        before = dict(impactpath.STATS)
        r = c.search("sx", _sparse_body())
        after = dict(impactpath.STATS)
        # the hostile margin prunes past certification: the ladder must
        # escalate (phase-2 or dense) — never serve an uncertified page
        assert (after["escalated"] > before["escalated"]
                or after["phase2_served"] > before["phase2_served"])
        scores = {}
        for did, toks in docs.items():
            s = np.float32(0.0)
            for t in sorted(QTOKENS):
                if t in toks:
                    s = np.float32(s + np.float32(
                        np.float32(QTOKENS[t]) * np.float32(toks[t])))
            if s > 0:
                scores[did] = float(s)
        want = [d for d, _ in sorted(
            scores.items(), key=lambda kv: (-kv[1], int(kv[0])))[:10]]
        assert [h for h, _ in _hits(r)] == want

    def test_boosted_sparse_serves_the_generic_score_domain(self):
        # one score domain per query: the certified ladder must serve
        # (Σ w·tf) · boost — the generic sparse_dot ordering — so
        # certified and escalated segments never mix domains
        c, docs = _sparse_corpus(seed=31)
        before = dict(impactpath.STATS)
        r = c.search("sx", {"query": {"neural_sparse": {"emb": {
            "query_tokens": dict(QTOKENS), "boost": 2.0}}}, "size": 10})
        assert impactpath.STATS["served"] == before["served"] + 1
        scores = {}
        for did, toks in docs.items():
            s = np.float32(0.0)
            for t in sorted(QTOKENS):
                if t in toks:
                    s = np.float32(s + np.float32(
                        np.float32(QTOKENS[t]) * np.float32(toks[t])))
            if s > 0:
                scores[did] = float(np.float32(s * np.float32(2.0)))
        want = sorted(scores.items(),
                      key=lambda kv: (-kv[1], int(kv[0])))[:10]
        assert _hits(r) == [(d, pytest.approx(sc, abs=1e-6))
                            for d, sc in want]

    def test_track_total_hits_rides_unpruned(self):
        c, _ = _sparse_corpus(seed=3)
        before = dict(impactpath.STATS)
        r = c.search("sx", {**_sparse_body(),
                            "track_total_hits": True})
        after = dict(impactpath.STATS)
        assert after["pruned_served"] == before["pruned_served"]
        assert r["hits"]["total"]["relation"] == "eq"

    def test_merge_preserves_feature_plane(self):
        c, _ = _sparse_corpus(n=600, seed=21)
        # force a second segment then merge
        rng = random.Random(99)
        for i in range(600, 900):
            c.index("sx", {"emb": {f"t{rng.randrange(30)}": 1.0}},
                    id=str(i))
        c.indices.refresh("sx")
        svc = c.node.indices["sx"]
        assert len(svc.shards[0].segments) == 2
        svc.force_merge(1)
        seg = svc.shards[0].segments[0]
        assert seg.postings["emb"].impact is not None
        assert seg.postings["emb"].impact.kind == "feature"

    def test_bool_embedded_neural_sparse_still_serves(self):
        # non-pure shapes decline the ladder and run the generic
        # sparse_dot program — which lazily promotes the f32 weights
        c, _ = _sparse_corpus(n=500, seed=4)
        r = c.search("sx", {"query": {"bool": {
            "must": [{"neural_sparse": {"emb": {
                "query_tokens": {"t1": 1.0}}}},
                {"neural_sparse": {"emb": {
                    "query_tokens": {"t2": 0.5}}}}]}}, "size": 5})
        assert len(r["hits"]["hits"]) == 5


# ----------------------------------------------------------------------
# insights: vector/hybrid workload identity
# ----------------------------------------------------------------------

class TestInsightsFeatures:
    def test_hybrid_fingerprint_carries_sub_query_features(self):
        k, shape, feats = fingerprint(_hybrid_body())
        assert feats["hybrid"] and feats["sub_queries"] == 3
        assert "knn" in feats["sub_kinds"]
        assert feats["knn"] is True
        assert shape.startswith("hybrid([")

    def test_distinct_sub_families_are_distinct_shapes(self):
        a = fingerprint(_hybrid_body(subs=[SUBS[0], SUBS[2]]))[0]
        b = fingerprint(_hybrid_body(subs=[SUBS[0], SUBS[1]]))[0]
        assert a != b

    def test_query_knn_counts_as_vector_workload(self):
        _, _, feats = fingerprint({"query": SUBS[2], "size": 5})
        assert feats["knn"] is True and not feats["hybrid"]
