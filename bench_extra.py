"""Extended benchmark configs 4 and 5 (BASELINE.json):

  4. BEIR-shaped small/large corpora, BM25 doc-len-norm ablation
     (b=0.75 vs b=0) — qps + recall@10 vs the C++ MaxScore baseline at
     BOTH settings (the baseline recomputes with the matching b).
  5. ClueWeb-scale 50M-doc MULTI-SEGMENT index: 8 segments in one shard,
     cross-segment top-k through the product msearch path, plus a timed
     device merge of two segments (ops/device_merge path).

Run manually (these are heavy; the driver's budgeted bench.py covers
configs 1-3): `python bench_extra.py`. Results merge into
BASELINE.json's `published` section under config4/config5 keys and are
also written to BENCH_extra_out.json incrementally. Env:
BENCH5_NDOCS (default 50_000_000), BENCH5_SEGMENTS (8), BENCH45 to
select ("4", "5", or "45" default).
"""

import json
import os
import signal
import sys
import time

import numpy as np

import bench as B

TOPK = 10
_REPO = os.path.dirname(os.path.abspath(__file__))
_OUT = {"config4_beir_ablation": None, "config5_multisegment": None,
        "status": "started"}


def _emit(status):
    _OUT["status"] = status
    try:
        with open(os.path.join(_REPO, "BENCH_extra_out.json"), "w") as f:
            json.dump(_OUT, f, indent=2)
    except OSError:
        pass


def _on_term(signum, frame):
    _emit(f"interrupted(sig{signum})")
    print(json.dumps(_OUT), flush=True)
    os._exit(0)


signal.signal(signal.SIGTERM, _on_term)
signal.signal(signal.SIGINT, _on_term)


def _merge_published(key, value):
    # same contract as bench.py: local/smoke runs must never rewrite the
    # checked-in baseline; opt in with BENCH_WRITE_BASELINE=1
    if os.environ.get("BENCH_WRITE_BASELINE") != "1":
        return
    try:
        with open(os.path.join(_REPO, "BASELINE.json"), "r+") as f:
            bl = json.load(f)
            bl.setdefault("published", {})[key] = value
            f.seek(0)
            json.dump(bl, f, indent=2)
            f.truncate()
    except OSError:
        pass


# ---------------------------------------------------------------------
# config 4: BEIR-shaped doc-len-norm ablation
# ---------------------------------------------------------------------

def config4():
    from opensearch_tpu import native
    from opensearch_tpu.rest.client import RestClient
    from opensearch_tpu.search import fastpath

    assert native.available()
    out = {}
    for name, ndocs, avg_dl, vocab in (("nfcorpus_like", 4_000, 220, 30_000),
                                       ("trec_covid_like", 171_000, 160,
                                        80_000)):
        starts, doc_ids, tfs, dl, df = B._cached(
            f"beir_{name}", lambda: B.build_corpus(ndocs, vocab=vocab,
                                                   avg_dl=avg_dl, seed=7),
            True)
        order = np.argsort(-df)
        pool = order[20: max(len(order) // 10, 200)]
        pool = pool[df[pool] > 0]
        rng = np.random.default_rng(8)
        queries = rng.choice(pool, size=(256, 2), replace=True)
        avgdl = float(dl.sum()) / ndocs
        idf = np.log1p((float(ndocs) - df + 0.5) / (df + 0.5)).astype(
            np.float32)
        entry = {}
        for b_val in (0.75, 0.0):
            # CPU baseline with the SAME norm setting
            kdoc = (1.2 * (1.0 - b_val + b_val * dl.astype(np.float32)
                           / np.float32(avgdl))).astype(np.float32)
            ub = native.term_upper_bounds(starts, doc_ids, tfs, kdoc, idf)
            t0 = time.time()
            cpu = [native.maxscore_topk(starts, doc_ids, tfs, kdoc, idf, ub,
                                        np.asarray(q, np.int32), 1, TOPK,
                                        None)
                   for q in queries]
            cpu_qps = len(queries) / (time.time() - t0)

            client = RestClient()
            vocab_strs = [f"t{i:07d}" for i in range(len(df))]
            tcsr = B.build_title_corpus(min(ndocs, 10_000))
            tvocab_strs = [f"p{i:04d}" for i in range(len(tcsr[0]) - 1)]
            client.indices.create("bench", {
                "settings": {"similarity": {"default": {
                    "type": "BM25", "b": b_val, "k1": 1.2}}},
                "mappings": {"properties": {"body": {"type": "text"}}}})
            B.make_index(client, (starts, doc_ids, tfs, vocab_strs), dl,
                         (tcsr[0], tcsr[1], tcsr[2], tcsr[3], tcsr[4],
                          tvocab_strs),
                         np.zeros(ndocs, np.int32),
                         np.zeros(ndocs, np.int64), create=False)
            lines = []
            for qi, q in enumerate(queries):
                lines.append({"index": "bench"})
                lines.append({"query": {"match": {"body":
                              f"{vocab_strs[q[0]]} {vocab_strs[q[1]]}"}},
                              "size": TOPK, "_b": f"{name}{b_val}{qi}"})
            resp = client.msearch(lines)       # warmup + compile
            t0 = time.time()
            reps = 3
            for rep in range(reps):
                for j, ln in enumerate(lines):
                    if j % 2:
                        ln["_b"] = f"{name}{b_val}r{rep}-{j}"
                resp = client.msearch(lines)
            qps = reps * len(queries) / (time.time() - t0)
            # recall@10 vs the matching-b CPU baseline; tie-aware like
            # bench.py (b=0 scores are tf-only, so exact ties are the norm
            # and set membership at the boundary is tie-break dependent)
            def cpu_score(d, q):
                s = 0.0
                for t in q:
                    a, e = starts[t], starts[t + 1]
                    j = np.searchsorted(doc_ids[a:e], d)
                    if j < e - a and doc_ids[a + j] == d:
                        tf = tfs[a + j]
                        s += idf[t] * tf / (tf + kdoc[d])
                return s

            tie_ok, strict, denom = 0, 0, 0
            for qi in range(len(queries)):
                got = [int(h["_id"]) for h in
                       resp["responses"][qi]["hits"]["hits"]]
                cdocs, cscores, _ = cpu[qi]
                cset = set(int(d) for d in cdocs if d >= 0)
                if not cset:
                    continue
                kth = min(cscores[j] for j in range(len(cdocs))
                          if cdocs[j] >= 0)
                head = got[:len(cset)]
                denom += len(cset)
                strict += sum(1 for d in head if d in cset)
                tie_ok += sum(
                    1 for d in head
                    if d in cset or cpu_score(d, queries[qi])
                    >= kth - 1e-5 * max(abs(kth), 1.0))
            entry[f"b{b_val}"] = {
                "qps": round(qps, 1), "cpu_qps": round(cpu_qps, 1),
                "vs_cpu": round(qps / cpu_qps, 2),
                "recall_at_10_tie_aware": round(tie_ok / max(denom, 1), 4),
                "recall_at_10_strict": round(strict / max(denom, 1), 4)}
        out[name] = entry
        B.log(f"config4 {name}: {entry}")
        _OUT["config4_beir_ablation"] = out
        _emit("config4_partial")
    return out


# ---------------------------------------------------------------------
# config 5: 50M docs, 8 segments, cross-segment top-k + device merge
# ---------------------------------------------------------------------

def config5():
    from opensearch_tpu.rest.client import RestClient
    from opensearch_tpu.search import fastpath
    from opensearch_tpu import native

    ndocs = int(os.environ.get("BENCH5_NDOCS", 50_000_000))
    nseg = int(os.environ.get("BENCH5_SEGMENTS", 8))
    per = ndocs // nseg
    client = RestClient()
    client.indices.create("bench5", {"mappings": {"properties": {
        "body": {"type": "text"}}}})
    eng = client.node.indices["bench5"].shards[0]
    eng.segments = []
    vocab = 200_000
    df_total = np.zeros(vocab, np.int64)
    seg_datas = []
    for si in range(nseg):
        starts, doc_ids, tfs, dl, df = B._cached(
            f"cw_{per}_{si}",
            lambda si=si: B.build_corpus(per, vocab=vocab, avg_dl=20,
                                         seed=100 + si), True)
        df_total += df
        seg_datas.append((starts, doc_ids, tfs, dl))
        B.log(f"config5: segment {si} corpus ready ({len(doc_ids)} postings)")
    vocab_strs = [f"t{i:07d}" for i in range(vocab)]
    from opensearch_tpu.index.segment import (PostingsBlock, Segment,
                                              TextFieldStats)
    for si, (starts, doc_ids, tfs, dl) in enumerate(seg_datas):
        pb = PostingsBlock(field="body", vocab=list(vocab_strs),
                           terms={t: i for i, t in enumerate(vocab_strs)},
                           starts=starts, doc_ids=doc_ids, tfs=tfs)
        seg = Segment(name=f"bench5_{si}", ndocs=per,
                      postings={"body": pb}, numeric_cols={},
                      keyword_cols={}, geo_cols={},
                      doc_lens={"body": dl},
                      text_stats={"body": TextFieldStats(
                          doc_count=per, sum_dl=int(dl.sum()))},
                      ids=[], sources=[])
        seg.ids = B._LazyIds(per)
        seg.sources = B._LazySources(per)
        seg.id2doc = {}
        seg.live = np.ones(per, dtype=bool)
        eng.segments.append(seg)
    client.node.indices["bench5"].generation += 1

    rng = np.random.default_rng(11)
    order = np.argsort(-df_total)
    pool = order[100:20_000]
    pool = pool[df_total[pool] > 0]
    queries = rng.choice(pool, size=(512, 2), replace=True)

    lines = []
    for qi, q in enumerate(queries):
        lines.append({"index": "bench5"})
        lines.append({"query": {"match": {"body":
                      f"{vocab_strs[q[0]]} {vocab_strs[q[1]]}"}},
                      "size": TOPK, "_b": f"c5-{qi}"})
    B.log("config5: warmup (compiles + per-segment residency builds)")
    t0 = time.time()
    resp = client.msearch(lines)
    B.log(f"config5: warmup done in {time.time()-t0:.1f}s")
    t0 = time.time()
    reps = 3
    for rep in range(reps):
        for j, ln in enumerate(lines):
            if j % 2:
                ln["_b"] = f"c5r{rep}-{j}"
        resp = client.msearch(lines)
    qps = reps * len(queries) / (time.time() - t0)
    total0 = resp["responses"][0]["hits"]["total"]

    # cross-segment correctness probe: every hit doc id in range, scores
    # monotonically non-increasing
    h0 = resp["responses"][0]["hits"]["hits"]
    scores = [h["_score"] for h in h0]
    assert all(scores[i] >= scores[i + 1] - 1e-6
               for i in range(len(scores) - 1))

    # device merge: merge the two smallest segments, re-run a query slice
    t0 = time.time()
    eng.force_merge_group(eng.segments[:2])
    merge_s = time.time() - t0
    client.node.indices["bench5"].generation += 1
    sl = lines[:64]
    for j, ln in enumerate(sl):
        if j % 2:
            ln["_b"] = f"c5m-{j}"
    resp2 = client.msearch(sl)
    out = {"ndocs": ndocs, "segments_before_merge": nseg,
           "qps": round(qps, 1),
           "sample_total": total0,
           "device_merge_s": round(merge_s, 1),
           "device_merge_docs": 2 * per,
           "post_merge_ok": all("hits" in r for r in resp2["responses"])}
    _OUT["config5_multisegment"] = out
    _emit("config5_done")
    B.log(f"config5: {out}")
    return out


def config6():
    """North-star-scale reorder A/B (ISSUE 11 / ROADMAP item 2): a 1M+
    doc single-segment corpus served through the codec-v2 impact ladder,
    arrival order vs BP impact-clustered order (index/reorder.py), on
    single-term and equal-idf multi-term query mixes. Produces the
    BENCH_r08 `reorder` stamp: p50/p99 latency, qps, block-skip rate,
    escalations, bytes/query per (arm, mix)."""
    from opensearch_tpu.rest.client import RestClient

    ndocs = int(os.environ.get("BENCH6_NDOCS", 1_000_000))
    t0 = time.time()
    # topical corpus (build_corpus_topical): real passages share topic
    # vocabulary, which is the co-occurrence signal BP clusters on — on
    # the iid-token synthetic, reordering measurably cannot concentrate
    # anything (zero per-term range concentration) and the A/B would
    # test nothing
    starts, doc_ids, tfs, dl, df, _topic = B._cached(
        f"reorder_top_{ndocs}",
        lambda: B.build_corpus_topical(ndocs, seed=0), True)
    corpus_s = time.time() - t0
    B.log(f"config6: topical corpus {ndocs} docs / {len(doc_ids)} "
          f"postings in {corpus_s:.1f}s")
    tstarts, tdoc_ids, ttfs, tpos_starts, tpositions, first, second, _pc = \
        B._cached(f"reorder_title_{ndocs}",
                  lambda: B.build_title_corpus(ndocs), True)
    rng = np.random.default_rng(3)
    status_ord = rng.integers(0, 3, ndocs).astype(np.int32)
    price = rng.integers(0, 10_000, ndocs).astype(np.int64)
    vocab_strs = [f"t{i:07d}" for i in range(len(df))]
    tvocab_strs = [f"p{i:04d}" for i in range(len(tstarts) - 1)]
    client = RestClient()
    t0 = time.time()
    seg = B.make_index(client, (starts, doc_ids, tfs, vocab_strs), dl,
                       (tstarts, tdoc_ids, ttfs, tpos_starts, tpositions,
                        tvocab_strs), status_ord, price)
    B.log(f"config6: segment + impact planes in {time.time()-t0:.1f}s")
    # query pools from the TOPICAL band (vocab upper half): df high
    # enough to span many 128-posting blocks, low enough to be
    # selective — the gap shape the reorder pass exists for
    topical = np.arange(len(df) // 2, len(df))
    pool = topical[(df[topical] >= 1024) & (df[topical] <= (1 << 17))]
    out = B.measure_reorder(client, seg, df, vocab_strs, B.log,
                            nq=int(os.environ.get("BENCH6_NQ", 256)),
                            single_pool=pool, multi_pool=pool)
    out["postings"] = int(len(doc_ids))
    out["corpus_build_s"] = round(corpus_s, 1)
    _OUT["config6_reorder"] = out
    _emit("config6_done")
    B.log(f"config6: {out.get('gates')}")
    return out


def main():
    which = os.environ.get("BENCH45", "45")
    if "4" in which:
        out4 = config4()
        _merge_published("config4_beir_ablation", out4)
    if "5" in which:
        out5 = config5()
        _merge_published("config5_multisegment", out5)
    if "6" in which:
        out6 = config6()
        _merge_published("config6_reorder", out6)
    _emit("complete")
    print(json.dumps(_OUT))


if __name__ == "__main__":
    main()
