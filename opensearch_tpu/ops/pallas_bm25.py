"""Fused BM25 top-k Pallas kernel — the flagship device kernel, and the
PRODUCTION scorer for term/match queries (see search/fastpath.py).

Replaces Lucene's per-doc BulkScorer loop (reference
`search/query/QueryPhase.java` + BM25Similarity) with one fused TPU program
per query:

    HBM CSR postings ──async DMA──▶ VMEM [T, L] (docs, packed tf·dl)
      ─▶ decode + BM25 (VPU) ─▶ bitonic MERGE of T doc-sorted runs
      ─▶ shift-add dedup (runs ≤ T) ─▶ iterative top-k extraction
      ─▶ [K] (scores, doc_ids) per query

Why not XLA: on TPU, XLA `gather`, `scatter-add` and `sort` on this access
pattern each cost ~100ms for a 512-query batch (measured on v5e) — they
serialize or relayout. Everything here is DMA + dense VPU ops:

- The CSR gather is contiguous per term -> plain async DMA (posting rows are
  1024-element-aligned at build time so DMA slices are tile-aligned).
- Each term's DMA covers only ITS OWN pow2 bucket (static-size branches on a
  prefetched row count), not the batch-wide max — rare terms don't pay the
  frequent term's bandwidth.
- Postings carry (doc_id, tf·dl packed in one i32); BM25 is computed on the
  VPU with the SAME f32 expression the XLA path uses, so both paths are
  bit-identical per posting (no pre-rounded "eager impact" drift) and the
  avgdl collection statistic stays a query-time scalar.
- The per-term posting lists are ALREADY doc-sorted, so we need a merge
  network, not a sort: log2(n) compare-exchange stages, each a pair of
  `pltpu.roll`s + selects (strides >= 128 roll sublanes, < 128 roll lanes).
- Duplicate docs across terms form runs of length <= T in the merged order,
  so per-doc score sums are T-1 shifted adds — no segment scatter.
- top-k for k<=K_MAX is k rounds of (max-reduce, arg-select, mask), each a
  full-array VPU reduction.

All shapes are static per (T, L, K) bucket; the host picks L = pow2 of the
longest posting list among the query's terms (from host row pointers — no
device sync) so one compiled kernel serves all queries in that bucket.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INT_SENTINEL = np.int32(2**31 - 1)
NEG_SENTINEL = np.int32(-2**31)
LANES = 128
# 1D HBM memrefs are tiled at 1024 elements (i32/f32): DMA slice starts and
# sizes must be 1024-aligned, so CSR rows are packed to this alignment
HBM_ALIGN = 1024
NEG_INF = float("-inf")


# ---------------------------------------------------------------------
# flattened [R, 128] helpers: rolls that emulate ops on the flat [R*128] order
# ---------------------------------------------------------------------

def _ids(shape):
    rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    lanes = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return rows, lanes


def _roll(x, shift: int, axis: int):
    """pltpu.roll with negative shifts normalized (it requires shift >= 0)."""
    n = x.shape[axis]
    return pltpu.roll(x, shift % n, axis)


def _cx(keys, payload, s: int):
    """One ascending compare-exchange stage at element stride `s` (partner =
    index XOR s) over the flattened [R,128] array. Moves `payload` with keys
    (a single array or a tuple of arrays, all selected by the same mask)."""
    single = not isinstance(payload, tuple)
    ps = (payload,) if single else payload
    shape = keys.shape
    rows, lanes = _ids(shape)
    if s >= LANES:
        r = s // LANES
        kf = _roll(keys, -r, 0)
        kb = _roll(keys, r, 0)
        pf = [_roll(p, -r, 0) for p in ps]
        pb = [_roll(p, r, 0) for p in ps]
        first = ((rows // r) % 2) == 0
    else:
        kf = _roll(keys, -s, 1)
        kb = _roll(keys, s, 1)
        pf = [_roll(p, -s, 1) for p in ps]
        pb = [_roll(p, s, 1) for p in ps]
        first = ((lanes // s) % 2) == 0
    nk = jnp.where(first, jnp.minimum(keys, kf), jnp.maximum(keys, kb))
    # NB: selecting between bool arrays with jnp.where trips a Mosaic i8->i1
    # truncation bug; keep predicates in pure i1 logic
    take_self = (first & (keys <= kf)) | ((~first) & (keys >= kb))
    nps = tuple(jnp.where(take_self, p, jnp.where(first, f, b))
                for p, f, b in zip(ps, pf, pb))
    return nk, (nps[0] if single else nps)


def _swap(x, s: int):
    """Unconditional exchange at element stride s (index XOR s)."""
    shape = x.shape
    rows, lanes = _ids(shape)
    if s >= LANES:
        r = s // LANES
        xf = _roll(x, -r, 0)
        xb = _roll(x, r, 0)
        first = ((rows // r) % 2) == 0
    else:
        xf = _roll(x, -s, 1)
        xb = _roll(x, s, 1)
        first = ((lanes // s) % 2) == 0
    return jnp.where(first, xf, xb)


def _block_flip(x, block: int):
    """Reverse every `block`-length run of the flattened order (index XOR
    (block-1)) by composing unconditional stride swaps over all bits."""
    s = 1
    while s < block:
        x = _swap(x, s)
        s *= 2
    return x


def _merge_pairs(keys, payload, half: int):
    """Merge adjacent sorted runs of length `half` into sorted runs of
    2*half (Batcher bitonic merge, ascending). `payload` may be one array
    or a tuple of arrays that all ride the same permutation."""
    single = not isinstance(payload, tuple)
    ps = (payload,) if single else payload
    kf = _block_flip(keys, 2 * half)
    pf = [_block_flip(p, 2 * half) for p in ps]
    rows, lanes = _ids(keys.shape)
    idx = rows * LANES + lanes
    first = (idx % (2 * half)) < half
    take_self = (first & (keys <= kf)) | ((~first) & (keys >= kf))
    nk = jnp.where(take_self, keys, kf)
    npay = tuple(jnp.where(take_self, p, f) for p, f in zip(ps, pf))
    s = half // 2
    while s >= 1:
        nk, npay = _cx(nk, npay, s)
        s //= 2
    return nk, (npay[0] if single else npay)


def _flat_shift_down(x, fill):
    """y[i] = x[i-1] over the flattened order (y[0] = fill)."""
    rows, lanes = _ids(x.shape)
    a = _roll(x, 1, 1)                      # lane l <- l-1 (lane0 wraps)
    b = _roll(_roll(x, 1, 0), 1, 1)         # row r-1, lane 127 at lane 0
    y = jnp.where(lanes == 0, b, a)
    return jnp.where((rows == 0) & (lanes == 0), fill, y)


def _flat_shift_up(x, fill):
    """y[i] = x[i+1] (y[last] = fill)."""
    rows, lanes = _ids(x.shape)
    nrows = x.shape[0]
    a = _roll(x, -1, 1)
    b = _roll(_roll(x, -1, 0), -1, 1)
    y = jnp.where(lanes == LANES - 1, b, a)
    return jnp.where((rows == nrows - 1) & (lanes == LANES - 1), fill, y)


# ---------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------

def _bm25_kernel(T: int, L: int, K: int,
                 starts_ref, lens_ref, weights_ref, msm_ref,
                 docs_hbm, norms_hbm, out_scores, out_docs, out_totals,
                 docs_v, norms_v, sems):
    q = pl.program_id(0)

    # ---- DMA all term posting ranges HBM -> VMEM ----
    # HBM arrays are [P/128, 128]; starts are element offsets aligned to
    # HBM_ALIGN so row starts/extents satisfy the (8, 128) tiling
    rows_per_term = L // LANES
    dmas = []
    for t in range(T):
        row_start = pl.multiple_of(starts_ref[t, q] // LANES, HBM_ALIGN // LANES)
        d1 = pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, rows_per_term)],
                                   docs_v.at[t], sems.at[2 * t])
        d2 = pltpu.make_async_copy(norms_hbm.at[pl.ds(row_start, rows_per_term)],
                                   norms_v.at[t], sems.at[2 * t + 1])
        d1.start()
        d2.start()
        dmas.extend((d1, d2))
    for d in dmas:
        d.wait()

    # ---- mask tails, apply per-term weights ----
    R = (T * L) // LANES
    docs2 = docs_v[:].reshape(R, LANES)
    norms2 = norms_v[:].reshape(R, LANES)
    rows, lanes = _ids((R, LANES))
    term_of_row = rows // rows_per_term
    pos_in_term = (rows % rows_per_term) * LANES + lanes

    # per-row scalars from SMEM (loop over T is static & tiny)
    w_row = jnp.zeros((R, LANES), jnp.float32)
    len_row = jnp.zeros((R, LANES), jnp.int32)
    for t in range(T):
        sel = term_of_row == t
        w_row = jnp.where(sel, weights_ref[t, q], w_row)
        len_row = jnp.where(sel, lens_ref[t, q], len_row)
    valid = pos_in_term < len_row
    keys = jnp.where(valid, docs2, INT_SENTINEL)
    contrib = jnp.where(valid, w_row * norms2, 0.0)

    # ---- merge the T doc-sorted runs (each of length L) ----
    half = L
    while half < T * L:
        keys, contrib = _merge_pairs(keys, contrib, half)
        half *= 2

    # ---- dedup: runs of equal doc have length <= T ----
    score = contrib
    kk = keys
    cc = contrib
    count = jnp.ones((R, LANES), jnp.float32)
    for _ in range(T - 1):
        kk = _flat_shift_down(kk, INT_SENTINEL)
        cc = _flat_shift_down(cc, 0.0)
        eq = (kk == keys) & (keys < INT_SENTINEL)
        score = score + jnp.where(eq, cc, 0.0)
        count = count + jnp.where(eq, 1.0, 0.0)
    knext = _flat_shift_up(keys, INT_SENTINEL)
    is_last = (knext != keys) & (keys < INT_SENTINEL)
    msm = msm_ref[0, q]
    final = jnp.where(is_last & (count >= msm), score, NEG_INF)

    # exact total hits (track_total_hits): one doc survives per dedup run
    total = jnp.sum((final > NEG_INF).astype(jnp.int32))
    out_totals[q, :] = jnp.full((LANES,), total, jnp.int32)

    # ---- iterative top-K extraction ----
    acc_s = jnp.full((1, LANES), NEG_INF, jnp.float32)
    acc_d = jnp.full((1, LANES), -1, jnp.int32)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    for j in range(K):
        best = jnp.max(final)
        sel = final == best
        bdoc = jnp.min(jnp.where(sel, keys, INT_SENTINEL))
        # scalar selects first: scalar-bool & vector-bool hits a Mosaic
        # truncation bug, so fold `got` into scalars
        got = best > NEG_INF
        best_or = jnp.where(got, best, NEG_INF)
        bdoc_or = jnp.where(got, bdoc, -1)
        hit = out_lane == j
        acc_s = jnp.where(hit, best_or, acc_s)
        acc_d = jnp.where(hit, bdoc_or, acc_d)
        final = jnp.where(sel & (keys == bdoc), NEG_INF, final)
    out_scores[q, :] = acc_s[0]
    out_docs[q, :] = acc_d[0]


@functools.partial(jax.jit, static_argnames=("T", "L", "K"))
def fused_bm25_topk(docs_hbm: jnp.ndarray, norms_hbm: jnp.ndarray,
                    starts: jnp.ndarray, lens: jnp.ndarray,
                    weights: jnp.ndarray, msm: jnp.ndarray,
                    T: int, L: int, K: int):
    """Batched fused BM25 top-k.

    docs_hbm  i32[P] — doc ids, CSR-flat, rows 128-aligned, >= L tail margin
    norms_hbm f32[P] — per-posting eager impacts tf/(tf+K_d) (BM25S-style)
    starts    i32[QB, T] — 128-aligned row starts (absent term: any aligned
              offset with lens=0)
    lens      i32[QB, T]
    weights   f32[QB, T] — query-time idf * boost (collection-wide stats)
    msm       f32[QB, 1] — minimum matching terms (1=OR, T=AND)
    Returns (scores f32[QB, 128], doc_ids i32[QB, 128], totals i32[QB, 128])
    — first K lanes of scores/doc_ids valid; totals[q, 0] is the exact hit
    count (docs matching >= msm terms).
    """
    QB = starts.shape[0]
    # SMEM operands are lane-padded to 128 in their last dim: keep QB (large)
    # last and T (tiny) first so prefetch stays a few KB
    starts = starts.T
    lens = lens.T
    weights = weights.T
    msm = msm.T
    assert docs_hbm.shape[0] % LANES == 0
    docs_hbm = docs_hbm.reshape(-1, LANES)
    norms_hbm = norms_hbm.reshape(-1, LANES)
    kernel = functools.partial(_bm25_kernel, T, L, K)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(QB,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            # whole-array blocks: each program writes its own row q (TPU grid
            # steps are sequential; (1, 128) blocks violate the (8, 128)
            # min-tile rule)
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.VMEM((T, L // LANES, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2 * T,)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((QB, LANES), jnp.float32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
    ]
    scores, doc_ids, totals = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(starts, lens, weights, msm, docs_hbm, norms_hbm)
    return scores, doc_ids, totals


# ---------------------------------------------------------------------
# production variant: packed (tf, dl) postings + per-term DMA buckets
# ---------------------------------------------------------------------

# tf and doc length packed losslessly into one i32 per posting:
#   packed = tf << DL_BITS | dl    (tf < 2^TF_BITS, dl < 2^DL_BITS)
# Segments violating the bounds (tf >= 2048 or a 2M-token doc) fall back to
# the XLA path — see search/fastpath.py.
TF_BITS = 11
DL_BITS = 21
DL_MASK = (1 << DL_BITS) - 1
TF_MAX = (1 << TF_BITS) - 1
DL_MAX = DL_MASK


def _bm25_tfdl_kernel(T: int, L: int, K: int, k1: float, b: float,
                      sizes: tuple,
                      rowstart_ref, nrows_ref, lens_ref, skips_ref,
                      weights_ref, msm_ref, avgdl_ref, dlo_ref, dhi_ref,
                      docs_hbm, tfdl_hbm, out_scores, out_docs, out_totals,
                      docs_v, tfdl_v, sems):
    q = pl.program_id(0)
    rows_per_term = L // LANES

    # ---- per-term DMA at the term's own pow2 bucket ----
    # `nrows_ref[t, q]` is the pow2 number of 128-lane rows this term needs
    # (0 = absent term, no DMA). DMA sizes must be static, so each size in
    # `sizes` is its own predicated start; rare terms move KBs while a
    # frequent term in the same query moves its full row — no shared max-L.
    for t in range(T):
        nr = nrows_ref[t, q]
        row_start = pl.multiple_of(rowstart_ref[t, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(t=t, s=s, row_start=row_start):
                pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t]).start()
                pltpu.make_async_copy(tfdl_hbm.at[pl.ds(row_start, s)],
                                      tfdl_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t + 1]).start()
    for t in range(T):
        nr = nrows_ref[t, q]
        row_start = pl.multiple_of(rowstart_ref[t, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(t=t, s=s, row_start=row_start):
                pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t]).wait()
                pltpu.make_async_copy(tfdl_hbm.at[pl.ds(row_start, s)],
                                      tfdl_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t + 1]).wait()

    # ---- decode + BM25 on the VPU (tails beyond each term's true length are
    # masked by position, so un-DMA'd scratch garbage never contributes) ----
    R = (T * L) // LANES
    docs2 = docs_v[:].reshape(R, LANES)
    tfdl2 = tfdl_v[:].reshape(R, LANES)
    rows, lanes = _ids((R, LANES))
    term_of_row = rows // rows_per_term
    pos_in_term = (rows % rows_per_term) * LANES + lanes

    w_row = jnp.zeros((R, LANES), jnp.float32)
    len_row = jnp.zeros((R, LANES), jnp.int32)
    skip_row = jnp.zeros((R, LANES), jnp.int32)
    for t in range(T):
        sel = term_of_row == t
        w_row = jnp.where(sel, weights_ref[t, q], w_row)
        len_row = jnp.where(sel, lens_ref[t, q], len_row)
        skip_row = jnp.where(sel, skips_ref[t, q], skip_row)
    # posting rows are 128-lane aligned; each DMA starts at the 1024-aligned
    # HBM block below the window, so `skip` masks the spilled-in prefix
    # (which may belong to the PREVIOUS row) positionally. Oversized rows
    # additionally split into [dlo, dhi) doc ranges. The merge network needs
    # each slot ASCENDING, so excluded-but-in-window docs below range map to
    # a NEGATIVE sentinel (front of the run, excluded at the end) — mapping
    # them to +sentinel would break sortedness and split dedup runs.
    dlo = dlo_ref[0, q]
    dhi = dhi_ref[0, q]
    in_pos = (pos_in_term >= skip_row) & (pos_in_term < skip_row + len_row)
    valid = in_pos & (docs2 >= dlo) & (docs2 < dhi)
    # the skip prefix must sort to the FRONT of the slot (NEG_SENTINEL):
    # +sentinel there would break the merge network's ascending-run
    # invariant, exactly like below-range docs in chunked windows
    is_prefix = pos_in_term < skip_row
    keys = jnp.where(is_prefix | (in_pos & (docs2 < dlo)), NEG_SENTINEL,
                     jnp.where(valid, docs2, INT_SENTINEL))

    # mask after the shift: tf >= 1024 sets the i32 sign bit and >> is
    # arithmetic (sign-extending)
    tf = ((tfdl2 >> DL_BITS) & TF_MAX).astype(jnp.float32)
    dl = (tfdl2 & DL_MASK).astype(jnp.float32)
    avgdl = avgdl_ref[0, q]
    # EXACTLY the XLA path's expression (ops/scoring.py posting_contrib,
    # SIM_BM25) so both paths agree bit-for-bit per posting
    k = k1 * (1.0 - b + b * dl / avgdl)
    contrib = jnp.where(valid, w_row * tf / (tf + k), 0.0)

    # ---- merge the T doc-sorted runs (each of length L) ----
    half = L
    while half < T * L:
        keys, contrib = _merge_pairs(keys, contrib, half)
        half *= 2

    # ---- dedup: runs of equal doc have length <= T ----
    score = contrib
    kk = keys
    cc = contrib
    count = jnp.ones((R, LANES), jnp.float32)
    for _ in range(T - 1):
        kk = _flat_shift_down(kk, INT_SENTINEL)
        cc = _flat_shift_down(cc, 0.0)
        eq = (kk == keys) & (keys < INT_SENTINEL)
        score = score + jnp.where(eq, cc, 0.0)
        count = count + jnp.where(eq, 1.0, 0.0)
    knext = _flat_shift_up(keys, INT_SENTINEL)
    is_last = (knext != keys) & (keys < INT_SENTINEL) & (keys > NEG_SENTINEL)
    msm = msm_ref[0, q]
    final = jnp.where(is_last & (count >= msm), score, NEG_INF)

    total = jnp.sum((final > NEG_INF).astype(jnp.int32))
    out_totals[q, :] = jnp.full((LANES,), total, jnp.int32)

    # ---- iterative top-K extraction ----
    acc_s = jnp.full((1, LANES), NEG_INF, jnp.float32)
    acc_d = jnp.full((1, LANES), -1, jnp.int32)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    for j in range(K):
        best = jnp.max(final)
        sel = final == best
        bdoc = jnp.min(jnp.where(sel, keys, INT_SENTINEL))
        got = best > NEG_INF
        best_or = jnp.where(got, best, NEG_INF)
        bdoc_or = jnp.where(got, bdoc, -1)
        hit = out_lane == j
        acc_s = jnp.where(hit, best_or, acc_s)
        acc_d = jnp.where(hit, bdoc_or, acc_d)
        final = jnp.where(sel & (keys == bdoc), NEG_INF, final)
    out_scores[q, :] = acc_s[0]
    out_docs[q, :] = acc_d[0]


@functools.partial(jax.jit, static_argnames=("T", "L", "K", "k1", "b"))
def fused_bm25_topk_tfdl(docs_hbm: jnp.ndarray, tfdl_hbm: jnp.ndarray,
                         rowstarts: jnp.ndarray, nrows: jnp.ndarray,
                         lens: jnp.ndarray, skips: jnp.ndarray,
                         weights: jnp.ndarray,
                         msm: jnp.ndarray, avgdl: jnp.ndarray,
                         dlo: jnp.ndarray, dhi: jnp.ndarray,
                         T: int, L: int, K: int, k1: float, b: float):
    """Batched fused BM25 top-k over packed (tf, dl) postings.

    docs_hbm  i32[P] — doc ids, CSR-flat, rows 128-lane aligned
    tfdl_hbm  i32[P] — tf << DL_BITS | dl per posting (lossless)
    rowstarts i32[QB, T] — DMA starts in 128-lane ROW units, 1024-element
              aligned (host aligns the window start DOWN to the HBM tile)
    nrows     i32[QB, T] — pow2 rows to DMA per term (0 = absent)
    lens      i32[QB, T] — true window posting counts (element units)
    skips     i32[QB, T] — spilled-in prefix length before the window
    weights   f32[QB, T] — query-time idf * boost
    msm       f32[QB, 1] — minimum matching terms
    avgdl     f32[QB, 1] — query-time average doc length scalar
    dlo/dhi   i32[QB, 1] — doc-id window [dlo, dhi) (0, INT_MAX = whole)
    k1, b     static similarity params (b already zeroed when norms are off)
    Returns (scores f32[QB, 128], doc_ids i32[QB, 128], totals i32[QB, 128]).
    """
    QB = rowstarts.shape[0]
    rowstarts = rowstarts.T
    nrows = nrows.T
    lens = lens.T
    skips = skips.T
    weights = weights.T
    msm = msm.T
    avgdl = avgdl.T
    dlo = dlo.T
    dhi = dhi.T
    assert docs_hbm.shape[0] % LANES == 0
    docs_hbm = docs_hbm.reshape(-1, LANES)
    tfdl_hbm = tfdl_hbm.reshape(-1, LANES)
    min_rows = HBM_ALIGN // LANES
    sizes = []
    s = min_rows
    while s <= L // LANES:
        sizes.append(s)
        s *= 2
    kernel = functools.partial(_bm25_tfdl_kernel, T, L, K, float(k1), float(b),
                               tuple(sizes))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=9,
        grid=(QB,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA((2 * T,)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((QB, LANES), jnp.float32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
    ]
    scores, doc_ids, totals = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(rowstarts, nrows, lens, skips, weights, msm, avgdl, dlo, dhi,
      docs_hbm, tfdl_hbm)
    return scores, doc_ids, totals


# ---------------------------------------------------------------------
# bool/filtered variant: weighted-threshold clause semantics
# ---------------------------------------------------------------------
#
# Generalizes the tfdl kernel to Lucene BooleanQuery shapes (reference
# `search/BooleanScorer` / `ConjunctionDISI`): each slot carries a COUNT
# WEIGHT `cw` alongside its score weight, and a doc passes iff the summed
# count weight of its matching slots reaches `thresh`. With required slots
# (must / filter) at cw=REQ_W and optional slots (should, or the terms of
# one multi-term group) at cw=1, `thresh = REQ_W*n_required + msm` encodes
# "ALL required AND >= msm optional" exactly (REQ_W > max optional count,
# so optionals can never substitute for a missing required slot).
#
# Filters ride as one extra slot whose doc list comes from a SEPARATE HBM
# buffer (`filt_hbm`, built host-side from the cached dense filter mask of
# the XLA path — reference IndicesQueryCache bitsets) with score weight 0
# and cw=REQ_W: the same merge network that dedups scoring terms performs
# the filter intersection, so no per-doc gather is ever needed.
REQ_W = 1024.0


def _bm25_bool_kernel(TS: int, L: int, K: int, k1: float, b: float,
                      sizes: tuple, filtered: bool,
                      rowstart_ref, nrows_ref, lens_ref, skips_ref,
                      weights_ref,
                      cw_ref, thresh_ref, avgdl_ref, dlo_ref, dhi_ref,
                      docs_hbm, tfdl_hbm, filt_hbm,
                      out_scores, out_docs, out_totals,
                      docs_v, tfdl_v, sems):
    q = pl.program_id(0)
    T = 2 * TS if filtered else TS
    rows_per_term = L // LANES

    # ---- per-slot DMA at the slot's own pow2 bucket ----
    # term slots [0, TS) move (docs, tfdl) from the postings buffers; the
    # filter slot TS (when present) moves docs only, from filt_hbm. Slots
    # with nrows=0 (absent term / dead padding) match no size branch -> no
    # DMA, and their VMEM garbage is masked below by len_row=0.
    for t in range(TS):
        nr = nrows_ref[t, q]
        row_start = pl.multiple_of(rowstart_ref[t, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(t=t, s=s, row_start=row_start):
                pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t]).start()
                pltpu.make_async_copy(tfdl_hbm.at[pl.ds(row_start, s)],
                                      tfdl_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t + 1]).start()
    if filtered:
        nr = nrows_ref[TS, q]
        row_start = pl.multiple_of(rowstart_ref[TS, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(s=s, row_start=row_start):
                pltpu.make_async_copy(filt_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[TS, pl.ds(0, s)],
                                      sems.at[2 * TS]).start()
    for t in range(TS):
        nr = nrows_ref[t, q]
        row_start = pl.multiple_of(rowstart_ref[t, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(t=t, s=s, row_start=row_start):
                pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t]).wait()
                pltpu.make_async_copy(tfdl_hbm.at[pl.ds(row_start, s)],
                                      tfdl_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t + 1]).wait()
    if filtered:
        nr = nrows_ref[TS, q]
        row_start = pl.multiple_of(rowstart_ref[TS, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(s=s, row_start=row_start):
                pltpu.make_async_copy(filt_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[TS, pl.ds(0, s)],
                                      sems.at[2 * TS]).wait()

    # ---- decode + BM25 + per-slot count weights ----
    R = (T * L) // LANES
    docs2 = docs_v[:].reshape(R, LANES)
    tfdl2 = tfdl_v[:].reshape(R, LANES)
    rows, lanes = _ids((R, LANES))
    term_of_row = rows // rows_per_term
    pos_in_term = (rows % rows_per_term) * LANES + lanes

    w_row = jnp.zeros((R, LANES), jnp.float32)
    len_row = jnp.zeros((R, LANES), jnp.int32)
    skip_row = jnp.zeros((R, LANES), jnp.int32)
    cw_row = jnp.zeros((R, LANES), jnp.float32)
    for t in range(T):
        sel = term_of_row == t
        len_row = jnp.where(sel, lens_ref[t, q], len_row)
        skip_row = jnp.where(sel, skips_ref[t, q], skip_row)
        cw_row = jnp.where(sel, cw_ref[t, q], cw_row)
        if t < TS:
            w_row = jnp.where(sel, weights_ref[t, q], w_row)
    dlo = dlo_ref[0, q]
    dhi = dhi_ref[0, q]
    in_pos = (pos_in_term >= skip_row) & (pos_in_term < skip_row + len_row)
    valid = in_pos & (docs2 >= dlo) & (docs2 < dhi)
    # the skip prefix must sort to the FRONT of the slot (NEG_SENTINEL):
    # +sentinel there would break the merge network's ascending-run
    # invariant, exactly like below-range docs in chunked windows
    is_prefix = pos_in_term < skip_row
    keys = jnp.where(is_prefix | (in_pos & (docs2 < dlo)), NEG_SENTINEL,
                     jnp.where(valid, docs2, INT_SENTINEL))

    tf = ((tfdl2 >> DL_BITS) & TF_MAX).astype(jnp.float32)
    dl = (tfdl2 & DL_MASK).astype(jnp.float32)
    avgdl = avgdl_ref[0, q]
    kd = k1 * (1.0 - b + b * dl / avgdl)
    # filter-slot rows score 0 (their tfdl scratch is never DMA'd garbage)
    is_term = term_of_row < TS
    contrib = jnp.where(valid & is_term, w_row * tf / (tf + kd), 0.0)
    cw = jnp.where(valid, cw_row, 0.0)

    # ---- merge the T doc-sorted runs, carrying (score, count-weight) ----
    half = L
    payload = (contrib, cw)
    while half < T * L:
        keys, payload = _merge_pairs(keys, payload, half)
        half *= 2
    contrib, cw = payload

    # ---- dedup: runs of equal doc have length <= T ----
    score = contrib
    cnt = cw
    kk = keys
    cc = contrib
    aa = cw
    for _ in range(T - 1):
        kk = _flat_shift_down(kk, INT_SENTINEL)
        cc = _flat_shift_down(cc, 0.0)
        aa = _flat_shift_down(aa, 0.0)
        eq = (kk == keys) & (keys < INT_SENTINEL)
        score = score + jnp.where(eq, cc, 0.0)
        cnt = cnt + jnp.where(eq, aa, 0.0)
    knext = _flat_shift_up(keys, INT_SENTINEL)
    is_last = (knext != keys) & (keys < INT_SENTINEL) & (keys > NEG_SENTINEL)
    final = jnp.where(is_last & (cnt >= thresh_ref[0, q]), score, NEG_INF)

    total = jnp.sum((final > NEG_INF).astype(jnp.int32))
    out_totals[q, :] = jnp.full((LANES,), total, jnp.int32)

    # ---- iterative top-K extraction ----
    acc_s = jnp.full((1, LANES), NEG_INF, jnp.float32)
    acc_d = jnp.full((1, LANES), -1, jnp.int32)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    for j in range(K):
        best = jnp.max(final)
        sel = final == best
        bdoc = jnp.min(jnp.where(sel, keys, INT_SENTINEL))
        got = best > NEG_INF
        best_or = jnp.where(got, best, NEG_INF)
        bdoc_or = jnp.where(got, bdoc, -1)
        hit = out_lane == j
        acc_s = jnp.where(hit, best_or, acc_s)
        acc_d = jnp.where(hit, bdoc_or, acc_d)
        final = jnp.where(sel & (keys == bdoc), NEG_INF, final)
    out_scores[q, :] = acc_s[0]
    out_docs[q, :] = acc_d[0]


@functools.partial(jax.jit,
                   static_argnames=("TS", "L", "K", "k1", "b", "filtered"))
def fused_bm25_bool_topk(docs_hbm: jnp.ndarray, tfdl_hbm: jnp.ndarray,
                         filt_hbm: jnp.ndarray,
                         rowstarts: jnp.ndarray, nrows: jnp.ndarray,
                         lens: jnp.ndarray, skips: jnp.ndarray,
                         weights: jnp.ndarray,
                         cw: jnp.ndarray, thresh: jnp.ndarray,
                         avgdl: jnp.ndarray, dlo: jnp.ndarray,
                         dhi: jnp.ndarray,
                         TS: int, L: int, K: int, k1: float, b: float,
                         filtered: bool):
    """Batched fused bool/filtered BM25 top-k.

    Slots [0, TS) are scoring terms over (docs_hbm, tfdl_hbm); when
    `filtered`, slot TS is the filter doc list in filt_hbm (i32[Pf], rows
    1024-aligned, INT_SENTINEL padded) and slots (TS, 2*TS) are dead
    padding (nrows=0). Per-query arrays are [QB, T] (T = 2*TS when
    filtered else TS) except weights [QB, TS] and thresh/avgdl/dlo/dhi
    [QB, 1]. `cw` carries per-slot count weights (REQ_W required / 1.0
    optional / 0 dead); a doc passes when its summed cw >= thresh.
    Returns (scores f32[QB, 128], doc_ids i32[QB, 128], totals i32[QB, 128]).
    """
    QB = rowstarts.shape[0]
    rowstarts = rowstarts.T
    nrows = nrows.T
    lens = lens.T
    skips = skips.T
    weights = weights.T
    cw = cw.T
    thresh = thresh.T
    avgdl = avgdl.T
    dlo = dlo.T
    dhi = dhi.T
    T = 2 * TS if filtered else TS
    assert docs_hbm.shape[0] % LANES == 0
    assert filt_hbm.shape[0] % LANES == 0
    docs_hbm = docs_hbm.reshape(-1, LANES)
    tfdl_hbm = tfdl_hbm.reshape(-1, LANES)
    filt_hbm = filt_hbm.reshape(-1, LANES)
    min_rows = HBM_ALIGN // LANES
    sizes = []
    s = min_rows
    while s <= L // LANES:
        sizes.append(s)
        s *= 2
    kernel = functools.partial(_bm25_bool_kernel, TS, L, K, float(k1),
                               float(b), tuple(sizes), bool(filtered))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=10,
        grid=(QB,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA((2 * T,)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((QB, LANES), jnp.float32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
    ]
    scores, doc_ids, totals = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(rowstarts, nrows, lens, skips, weights, cw, thresh, avgdl, dlo, dhi,
      docs_hbm, tfdl_hbm, filt_hbm)
    return scores, doc_ids, totals


# ---------------------------------------------------------------------
# codec-v2 variant: quantized eager impacts (BM25S), no per-posting math
# ---------------------------------------------------------------------
#
# The tfdl kernel spends VPU work per posting on the BM25 saturation
# (shift/mask decode + div) and needs avgdl/k1/b per query. With codec v2
# (index/segment.py ImpactPlane) the saturation was evaluated at index
# time: the posting payload is the quantized impact held in an i32 lane
# (the HBM 1D tiling is i32-granular; the u8/u16 density win belongs to
# the XLA path's resident planes), and the per-posting math collapses to
# ONE multiply by a weight that folds idf·boost·scale. Block-max skipping
# happens where the DMA windows are planned: the HOST prices each
# IMPACT_BLOCK run off the plane's block-max sidecar (exact in the
# quantized domain) and passes only the kept, compacted windows through
# rowstarts/nrows/lens/skips — a skipped block never leaves HBM, the same
# contract as the impact-ordered head regions. Exactness of served pages
# stays with the fastpath verify ladder: results of this kernel are
# candidate partials whose certification must add the caller's
# quantization-error margin (ImpactPlane.quant_err/drift_bound) to the
# unseen-doc bound.


def _bm25_impact_kernel(T: int, L: int, K: int, sizes: tuple,
                        rowstart_ref, nrows_ref, lens_ref, skips_ref,
                        weights_ref, msm_ref, dlo_ref, dhi_ref,
                        docs_hbm, imp_hbm, out_scores, out_docs, out_totals,
                        docs_v, imp_v, sems):
    q = pl.program_id(0)
    rows_per_term = L // LANES

    for t in range(T):
        nr = nrows_ref[t, q]
        row_start = pl.multiple_of(rowstart_ref[t, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(t=t, s=s, row_start=row_start):
                pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t]).start()
                pltpu.make_async_copy(imp_hbm.at[pl.ds(row_start, s)],
                                      imp_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t + 1]).start()
    for t in range(T):
        nr = nrows_ref[t, q]
        row_start = pl.multiple_of(rowstart_ref[t, q], HBM_ALIGN // LANES)
        for s in sizes:
            @pl.when(nr == s)
            def _(t=t, s=s, row_start=row_start):
                pltpu.make_async_copy(docs_hbm.at[pl.ds(row_start, s)],
                                      docs_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t]).wait()
                pltpu.make_async_copy(imp_hbm.at[pl.ds(row_start, s)],
                                      imp_v.at[t, pl.ds(0, s)],
                                      sems.at[2 * t + 1]).wait()

    R = (T * L) // LANES
    docs2 = docs_v[:].reshape(R, LANES)
    imp2 = imp_v[:].reshape(R, LANES)
    rows, lanes = _ids((R, LANES))
    term_of_row = rows // rows_per_term
    pos_in_term = (rows % rows_per_term) * LANES + lanes

    w_row = jnp.zeros((R, LANES), jnp.float32)
    len_row = jnp.zeros((R, LANES), jnp.int32)
    skip_row = jnp.zeros((R, LANES), jnp.int32)
    for t in range(T):
        sel = term_of_row == t
        w_row = jnp.where(sel, weights_ref[t, q], w_row)
        len_row = jnp.where(sel, lens_ref[t, q], len_row)
        skip_row = jnp.where(sel, skips_ref[t, q], skip_row)
    dlo = dlo_ref[0, q]
    dhi = dhi_ref[0, q]
    in_pos = (pos_in_term >= skip_row) & (pos_in_term < skip_row + len_row)
    valid = in_pos & (docs2 >= dlo) & (docs2 < dhi)
    is_prefix = pos_in_term < skip_row
    keys = jnp.where(is_prefix | (in_pos & (docs2 < dlo)), NEG_SENTINEL,
                     jnp.where(valid, docs2, INT_SENTINEL))

    # the WHOLE per-posting score: one multiply (weights fold
    # idf·boost·scale — the designated dequant shape, oslint OSL507)
    contrib = jnp.where(valid, w_row * imp2.astype(jnp.float32), 0.0)

    half = L
    while half < T * L:
        keys, contrib = _merge_pairs(keys, contrib, half)
        half *= 2

    score = contrib
    kk = keys
    cc = contrib
    count = jnp.ones((R, LANES), jnp.float32)
    for _ in range(T - 1):
        kk = _flat_shift_down(kk, INT_SENTINEL)
        cc = _flat_shift_down(cc, 0.0)
        eq = (kk == keys) & (keys < INT_SENTINEL)
        score = score + jnp.where(eq, cc, 0.0)
        count = count + jnp.where(eq, 1.0, 0.0)
    knext = _flat_shift_up(keys, INT_SENTINEL)
    is_last = (knext != keys) & (keys < INT_SENTINEL) & (keys > NEG_SENTINEL)
    msm = msm_ref[0, q]
    final = jnp.where(is_last & (count >= msm), score, NEG_INF)

    total = jnp.sum((final > NEG_INF).astype(jnp.int32))
    out_totals[q, :] = jnp.full((LANES,), total, jnp.int32)

    acc_s = jnp.full((1, LANES), NEG_INF, jnp.float32)
    acc_d = jnp.full((1, LANES), -1, jnp.int32)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    for j in range(K):
        best = jnp.max(final)
        sel = final == best
        bdoc = jnp.min(jnp.where(sel, keys, INT_SENTINEL))
        got = best > NEG_INF
        best_or = jnp.where(got, best, NEG_INF)
        bdoc_or = jnp.where(got, bdoc, -1)
        hit = out_lane == j
        acc_s = jnp.where(hit, best_or, acc_s)
        acc_d = jnp.where(hit, bdoc_or, acc_d)
        final = jnp.where(sel & (keys == bdoc), NEG_INF, final)
    out_scores[q, :] = acc_s[0]
    out_docs[q, :] = acc_d[0]


@functools.partial(jax.jit, static_argnames=("T", "L", "K"))
def fused_bm25_topk_impact(docs_hbm: jnp.ndarray, imp_hbm: jnp.ndarray,
                           rowstarts: jnp.ndarray, nrows: jnp.ndarray,
                           lens: jnp.ndarray, skips: jnp.ndarray,
                           weights: jnp.ndarray, msm: jnp.ndarray,
                           dlo: jnp.ndarray, dhi: jnp.ndarray,
                           T: int, L: int, K: int):
    """Batched fused top-k over codec-v2 quantized impacts.

    docs_hbm  i32[P] — doc ids, CSR-flat, rows 128-lane aligned
    imp_hbm   i32[P] — quantized impact per posting (u8/u16 widened to
              the i32 HBM lane granularity)
    weights   f32[QB, T] — idf · boost · plane scale, folded on host
    (rowstarts/nrows/lens/skips/msm/dlo/dhi as in fused_bm25_topk_tfdl;
    the host's block-max prune compacts skipped blocks OUT of these
    windows.) No similarity statics: the kernel is one multiply per
    posting, and one compiled (T, L, K) variant serves every similarity
    the plane was built under.
    Returns (scores f32[QB, 128], doc_ids i32[QB, 128], totals)."""
    QB = rowstarts.shape[0]
    rowstarts = rowstarts.T
    nrows = nrows.T
    lens = lens.T
    skips = skips.T
    weights = weights.T
    msm = msm.T
    dlo = dlo.T
    dhi = dhi.T
    assert docs_hbm.shape[0] % LANES == 0
    docs_hbm = docs_hbm.reshape(-1, LANES)
    imp_hbm = imp_hbm.reshape(-1, LANES)
    min_rows = HBM_ALIGN // LANES
    sizes = []
    s = min_rows
    while s <= L // LANES:
        sizes.append(s)
        s *= 2
    kernel = functools.partial(_bm25_impact_kernel, T, L, K, tuple(sizes))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(QB,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.VMEM((T, L // LANES, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA((2 * T,)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((QB, LANES), jnp.float32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
        jax.ShapeDtypeStruct((QB, LANES), jnp.int32),
    ]
    scores, doc_ids, totals = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(rowstarts, nrows, lens, skips, weights, msm, dlo, dhi,
      docs_hbm, imp_hbm)
    return scores, doc_ids, totals


def align_csr_rows(starts: np.ndarray, doc_ids: np.ndarray, *vals: np.ndarray,
                   margin: int, alignment: int = HBM_ALIGN):
    """Re-pack CSR postings so every row begins at a 128-aligned offset
    (sentinel-padded gaps), with `margin` sentinel slack at the end so a
    fixed-size DMA window never runs off the buffer. Returns
    (new_starts i64[nrows+1 -> aligned row starts], docs, *aligned vals) —
    each extra `vals` array (tfs, impacts, per-posting dl, ...) is scattered
    to the same aligned layout with zero fill."""
    nrows = len(starts) - 1
    lens = np.diff(starts)
    aligned_lens = ((lens + alignment - 1) // alignment) * alignment
    new_starts = np.zeros(nrows + 1, dtype=np.int64)
    np.cumsum(aligned_lens, out=new_starts[1:])
    total = int(new_starts[-1]) + margin
    total = ((total + LANES - 1) // LANES) * LANES
    new_docs = np.full(total, INT_SENTINEL, dtype=np.int32)
    # vectorized row scatter
    src_idx = np.arange(len(doc_ids), dtype=np.int64)
    row_of = np.searchsorted(starts, src_idx, side="right") - 1
    offset_in_row = src_idx - starts[row_of]
    dst = new_starts[row_of] + offset_in_row
    new_docs[dst] = doc_ids
    out_vals = []
    for v in vals:
        nv = np.zeros(total, dtype=v.dtype)
        nv[dst] = v
        out_vals.append(nv)
    return (new_starts, new_docs, *out_vals)
