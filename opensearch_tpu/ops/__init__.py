from . import aggs, scoring

__all__ = ["scoring", "aggs"]
