"""Device-side scoring primitives: the TPU replacement for Lucene's per-doc
scoring loop (reference: `search/query/QueryPhase.java` driving Lucene's
BulkScorer + BM25Similarity).

The shape of the computation, per (segment, query term group):

    rows ──starts──▶ (row_start, row_len) ──flat iota + searchsorted──▶
    flat gather of (doc_id, tf) ──VPU: sim formula──▶ contrib ──scatter-add──▶
    dense scores[ndocs_pad] ──▶ combinators (masks) ──▶ fused top-k

All shapes are static: the flat gather width `bucket` is a power-of-two chosen
on the host from the *host* row pointers (no device sync), and segment arrays
are pow2-padded (see segment.py), so XLA compiles a handful of kernels that
get reused across queries and segments.

Scatter-adds here are the analog of Lucene accumulating scores doc-at-a-time;
on TPU they run at HBM bandwidth over the whole posting block at once.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = np.float32(-np.inf)  # numpy, not jnp: a module-level jax.Array
# becomes a device-resident trace constant that the jit fast path can hoist
# into an extra executable parameter (buffer-count mismatch on cache hits)

# similarity ids (static switch inside traced code)
SIM_BM25 = 0
SIM_CLASSIC = 1      # Lucene ClassicSimilarity (TF-IDF)
SIM_BOOLEAN = 2
SIM_LM_DIRICHLET = 3


class ScoredMask(NamedTuple):
    """Dense per-doc (scores, match_count) pair — every query node evaluates
    to one of these; `count` is the number of matching leaf terms (drives
    minimum_should_match and must semantics)."""

    scores: jnp.ndarray   # f32[ndocs_pad]
    count: jnp.ndarray    # f32[ndocs_pad]

    @property
    def matched(self) -> jnp.ndarray:
        return self.count > 0


def gather_postings(starts: jnp.ndarray, doc_ids: jnp.ndarray, tfs: jnp.ndarray,
                    rows: jnp.ndarray, bucket: int):
    """Flatten the postings of `rows` (i32[T], -1 = term absent) into static
    width `bucket`. Returns (docs i32[B], tf f32[B], term_idx i32[B],
    valid bool[B])."""
    nrows_pad = starts.shape[0]
    # absent terms -> the guaranteed-empty padding row (start == end == P)
    rows = jnp.where(rows < 0, nrows_pad - 2, rows)
    row_start = starts[rows]
    row_end = starts[rows + 1]
    lens = row_end - row_start
    cum = jnp.cumsum(lens)
    total = cum[-1]
    i = jnp.arange(bucket, dtype=jnp.int32)
    term_idx = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    term_idx = jnp.minimum(term_idx, rows.shape[0] - 1)
    prev = jnp.where(term_idx > 0, cum[jnp.maximum(term_idx - 1, 0)], 0)
    src = row_start[term_idx] + (i - prev)
    valid = i < total
    src = jnp.clip(src, 0, doc_ids.shape[0] - 1)
    docs = jnp.where(valid, doc_ids[src], jnp.int32(2**31 - 1))
    tf = jnp.where(valid, tfs[src], 0.0)
    return docs, tf, term_idx, valid


def posting_contrib(sim_id: int, tf, dl, weight, aux, k1: float, b: float, avgdl):
    """Per-posting score contribution under similarity `sim_id` (static).

    BM25 follows modern Lucene BM25Similarity (no (k1+1) factor, LUCENE-8563):
        idf * tf / (tf + k1*(1 - b + b*dl/avgdl))
    classic follows ClassicSimilarity: idf^2 * sqrt(tf) * 1/sqrt(dl) * boost
    (idf^2 because weight already folds one idf and queryNorm is gone).
    lm_dirichlet: log(1 + tf/(mu*p_c)) + log(mu/(dl+mu)), aux = p_c, k1 = mu.
    """
    if sim_id == SIM_BM25:
        k = k1 * (1.0 - b + b * dl / avgdl)
        return weight * tf / (tf + k)
    if sim_id == SIM_CLASSIC:
        inv_sqrt_dl = jnp.where(dl > 0, jax.lax.rsqrt(jnp.maximum(dl, 1.0)), 1.0)
        return weight * jnp.sqrt(tf) * inv_sqrt_dl
    if sim_id == SIM_BOOLEAN:
        return weight * jnp.ones_like(tf)
    if sim_id == SIM_LM_DIRICHLET:
        mu = k1
        core = jnp.log1p(tf / (mu * jnp.maximum(aux, 1e-12)))
        norm = jnp.log(mu / (dl + mu))
        return weight * (core + norm)
    raise ValueError(f"unknown sim_id {sim_id}")


def score_term_group(field_arrays: dict, dl: jnp.ndarray, live: jnp.ndarray,
                     rows: jnp.ndarray, weights: jnp.ndarray, aux: jnp.ndarray,
                     bucket: int, ndocs_pad: int, sim_id: int,
                     k1: float, b: float, avgdl) -> ScoredMask:
    """Score one group of weighted terms over a segment field: the fused
    gather→VPU→scatter pass. Returns dense (scores, term-match counts)."""
    docs, tf, term_idx, valid = gather_postings(
        field_arrays["starts"], field_arrays["doc_ids"], field_arrays["tfs"], rows, bucket)
    dsafe = jnp.minimum(docs, ndocs_pad - 1)
    dl_g = dl[dsafe]
    w = weights[term_idx]
    a = aux[term_idx]
    contrib = posting_contrib(sim_id, tf, dl_g, w, a, k1, b, avgdl)
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(contrib, mode="drop")
    counts = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
        jnp.where(valid & (tf > 0), 1.0, 0.0), mode="drop")
    live_ok = live > 0
    return ScoredMask(jnp.where(live_ok, scores, 0.0), jnp.where(live_ok, counts, 0.0))


# ---------------- codec v2: quantized-impact domain ----------------
#
# u8/u16 impact planes may only enter f32 score math through these two
# designated dequant helpers (oslint OSL507): the quantized domain is
# where block-max prune compares stay exact, and every implicit
# int->float promotion outside the helpers is a bound the serve
# certificates don't know about.


def dequant_impact(q: jnp.ndarray, scale) -> jnp.ndarray:
    """THE device-side dequantizer: quantized impact plane -> f32 score
    contributions. `scale` may be a scalar (the plane's global scale) or
    a broadcastable array with weights pre-folded in."""
    return q.astype(jnp.float32) * scale


def dequant_impact_np(q, scale):
    """Host mirror of `dequant_impact` (planning bounds, head selection,
    bench stamps)."""
    return np.asarray(q).astype(np.float32) * np.float32(scale)


def gather_impact_blocks(doc_ids: jnp.ndarray, impacts: jnp.ndarray,
                         bstart: jnp.ndarray, blen: jnp.ndarray,
                         bucket: int):
    """Flatten explicit posting-block windows [bstart_i, bstart_i+blen_i)
    into static width `bucket` — the block-granular analog of
    `gather_postings` for the codec-v2 impact path, where the host's
    block-max prune selects WHICH blocks are gathered at all (skipped
    blocks never move bytes). Returns (docs i32[B], iq uint[B],
    block_idx i32[B], valid bool[B]); iq stays in the quantized integer
    domain — callers dequantize via `dequant_impact`."""
    nblk = bstart.shape[0]
    cum = jnp.cumsum(blen)
    total = cum[-1]
    i = jnp.arange(bucket, dtype=jnp.int32)
    b_idx = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    b_idx = jnp.minimum(b_idx, nblk - 1)
    prev = jnp.where(b_idx > 0, cum[jnp.maximum(b_idx - 1, 0)], 0)
    src = bstart[b_idx] + (i - prev)
    valid = i < total
    src = jnp.clip(src, 0, doc_ids.shape[0] - 1)
    docs = jnp.where(valid, doc_ids[src], jnp.int32(2**31 - 1))
    iq = jnp.where(valid, impacts[src], 0)
    return docs, iq, b_idx, valid


def impact_score_blocks(doc_ids: jnp.ndarray, impacts: jnp.ndarray,
                        live: jnp.ndarray, bstart: jnp.ndarray,
                        blen: jnp.ndarray, bweight: jnp.ndarray,
                        bucket: int, ndocs_pad: int) -> ScoredMask:
    """The codec-v2 eager hot loop: gather quantized impacts over the
    kept blocks, one dequant multiply (weight·scale pre-folded per block
    on the host), scatter-add. NO per-posting tf/doclen math — the BM25
    saturation was evaluated at index time (BM25S eager scoring). Counts
    are exact for the gathered blocks: postings partition (term, doc)
    pairs, so counting postings counts matching terms."""
    docs, iq, b_idx, valid = gather_impact_blocks(doc_ids, impacts,
                                                  bstart, blen, bucket)
    contrib = jnp.where(valid, dequant_impact(iq, bweight[b_idx]), 0.0)
    scores = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(contrib,
                                                            mode="drop")
    counts = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
        jnp.where(valid, 1.0, 0.0), mode="drop")
    live_ok = live > 0
    return ScoredMask(jnp.where(live_ok, scores, 0.0),
                      jnp.where(live_ok, counts, 0.0))


def gather_docs_only(starts: jnp.ndarray, doc_ids: jnp.ndarray,
                     rows: jnp.ndarray, bucket: int):
    """`gather_postings` without the tf plane: (docs, valid) only. The
    codec-v2 layout has no resident f32 tfs, and non-scoring consumers
    (filter masks) never needed them — a real posting always has tf>0."""
    nrows_pad = starts.shape[0]
    rows = jnp.where(rows < 0, nrows_pad - 2, rows)
    row_start = starts[rows]
    row_end = starts[rows + 1]
    lens = row_end - row_start
    cum = jnp.cumsum(lens)
    total = cum[-1]
    i = jnp.arange(bucket, dtype=jnp.int32)
    term_idx = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    term_idx = jnp.minimum(term_idx, rows.shape[0] - 1)
    prev = jnp.where(term_idx > 0, cum[jnp.maximum(term_idx - 1, 0)], 0)
    src = row_start[term_idx] + (i - prev)
    valid = i < total
    src = jnp.clip(src, 0, doc_ids.shape[0] - 1)
    docs = jnp.where(valid, doc_ids[src], jnp.int32(2**31 - 1))
    return docs, valid


def term_match_mask(field_arrays: dict, live: jnp.ndarray,
                    rows: jnp.ndarray, bucket: int,
                    ndocs_pad: int) -> jnp.ndarray:
    """Non-scoring terms filter over the codec-v2 layout: identical
    semantics to `term_filter_mask` (every real posting has tf > 0) with
    no tf plane touched — 4 bytes gathered per slot instead of 8."""
    docs, valid = gather_docs_only(field_arrays["starts"],
                                   field_arrays["doc_ids"], rows, bucket)
    hits = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
        jnp.where(valid, 1.0, 0.0), mode="drop")
    return (hits > 0) & (live > 0)


def gather_tf_dense(field_arrays: dict, rows: jnp.ndarray, bucket: int,
                    ndocs_pad: int, t_pad: int) -> jnp.ndarray:
    """Per-term dense raw term frequencies: f32[t_pad, ndocs_pad].
    combined_fields (BM25F) needs tf BEFORE saturation so fields can be
    weighted and summed; one flat scatter builds all T rows at once."""
    docs, tf, term_idx, valid = gather_postings(
        field_arrays["starts"], field_arrays["doc_ids"], field_arrays["tfs"],
        rows, bucket)
    # clamp BEFORE the flat-index multiply: sentinel doc ids would overflow
    dsafe = jnp.clip(docs, 0, ndocs_pad - 1)
    flat = jnp.where(valid, term_idx * ndocs_pad + dsafe,
                     t_pad * ndocs_pad)   # OOB -> dropped
    out = jnp.zeros(t_pad * ndocs_pad, jnp.float32).at[flat].add(
        jnp.where(valid, tf, 0.0), mode="drop")
    return out.reshape(t_pad, ndocs_pad)


def term_filter_mask(field_arrays: dict, live: jnp.ndarray, rows: jnp.ndarray,
                     bucket: int, ndocs_pad: int) -> jnp.ndarray:
    """Non-scoring terms filter -> bool[ndocs_pad] (reference: filter clauses
    skip scoring entirely, BooleanWeight with needsScores=false)."""
    docs, tf, _, valid = gather_postings(
        field_arrays["starts"], field_arrays["doc_ids"], field_arrays["tfs"], rows, bucket)
    hits = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
        jnp.where(valid & (tf > 0), 1.0, 0.0), mode="drop")
    return (hits > 0) & (live > 0)


def feature_score(field_arrays: dict, live: jnp.ndarray, rows: jnp.ndarray,
                  bucket: int, ndocs_pad: int, contrib_fn) -> ScoredMask:
    """Score a feature-postings row group (rank_feature / sparse dot):
    gather (doc, weight) postings, apply `contrib_fn(weight, term_idx)` on the
    VPU, scatter-add. Matches only docs carrying the feature(s) (reference
    RankFeatureQuery / learned-sparse dot product)."""
    docs, w, term_idx, valid = gather_postings(
        field_arrays["starts"], field_arrays["doc_ids"], field_arrays["tfs"],
        rows, bucket)
    contrib = jnp.where(valid, contrib_fn(w, term_idx), 0.0)
    scores = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(contrib, mode="drop")
    counts = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
        jnp.where(valid, 1.0, 0.0), mode="drop")
    live_ok = live > 0
    return ScoredMask(jnp.where(live_ok, scores, 0.0),
                      jnp.where(live_ok, counts, 0.0))


def rank_feature_value(w, fn_id: str, p1, p2, positive: bool):
    """The four reference rank_feature scoring functions (RankFeatureQuery):
    saturation w/(w+pivot), log ln(scaling+w), sigmoid w^e/(w^e+p^e), linear.
    `positive=False` flips saturation/sigmoid (p/(p+w) style) like
    positive_score_impact=false."""
    if fn_id == "linear":
        return w
    if fn_id == "saturation":
        return p1 / (p1 + w) if not positive else w / (w + p1)
    if fn_id == "log":
        return jnp.log(p1 + w)
    if fn_id == "sigmoid":
        we = jnp.power(jnp.maximum(w, 0.0), p2)
        pe = jnp.power(p1, p2)
        return pe / (pe + we) if not positive else we / (we + pe)
    raise ValueError(f"unknown rank_feature function [{fn_id}]")


# ---------------- dense column predicates ----------------

def int64_range_mask(col: dict, lo_hi: jnp.ndarray, lo_lo: jnp.ndarray,
                     hi_hi: jnp.ndarray, hi_lo: jnp.ndarray,
                     include_lo: bool, include_hi: bool) -> jnp.ndarray:
    """Exact 64-bit range predicate over a (hi, lo)-split int column
    (reference: LongPoint range query). Bounds arrive as traced i32 scalars."""
    vhi, vlo = col["hi"], col["lo"]

    def ge(ahi, alo, bhi, blo, strict):
        gt = (ahi > bhi) | ((ahi == bhi) & (alo > blo))
        if strict:
            return gt
        return gt | ((ahi == bhi) & (alo == blo))

    lower_ok = ge(vhi, vlo, lo_hi, lo_lo, strict=not include_lo)
    upper_ok = ge(hi_hi, hi_lo, vhi, vlo, strict=not include_hi)
    return lower_ok & upper_ok & col["present"]


def float_range_mask(col: dict, lo: jnp.ndarray, hi: jnp.ndarray,
                     include_lo: bool, include_hi: bool) -> jnp.ndarray:
    v = col["f32"]
    lower = (v >= lo) if include_lo else (v > lo)
    upper = (v <= hi) if include_hi else (v < hi)
    return lower & upper & col["present"]


def exists_mask(present: jnp.ndarray, live: jnp.ndarray) -> jnp.ndarray:
    return present & (live > 0)


def docs_mask(doc_list: jnp.ndarray, ndocs_pad: int) -> jnp.ndarray:
    """ids query: a padded i32 doc-id list -> mask (sentinel-padded)."""
    hits = jnp.zeros(ndocs_pad, jnp.float32).at[doc_list].add(1.0, mode="drop")
    return hits > 0


def point_in_polygon_mask(geo: dict, plat: jnp.ndarray,
                          plon: jnp.ndarray) -> jnp.ndarray:
    """geo_polygon: ray-cast on the VPU. plat/plon are the query's closed
    ring padded by repeating the last vertex (degenerate edges cross
    nothing), so the [ndocs, V] crossing matrix is static-shape.
    Reference analog GeoPolygonQueryBuilder (deprecated there, still
    served)."""
    x = geo["lon"][:, None]
    y = geo["lat"][:, None]
    x1, y1 = plon[None, :-1], plat[None, :-1]
    x2, y2 = plon[None, 1:], plat[None, 1:]
    spans = ((y1 <= y) & (y < y2)) | ((y2 <= y) & (y < y1))
    denom = jnp.where(y2 == y1, 1e-30, y2 - y1)
    xin = x1 + (y - y1) / denom * (x2 - x1)
    crossings = jnp.sum((spans & (x < xin)).astype(jnp.int32), axis=1)
    return (crossings % 2 == 1) & geo["present"]


def geo_distance_vec(geo: dict, lat: jnp.ndarray,
                     lon: jnp.ndarray) -> jnp.ndarray:
    """Haversine distance in meters to (lat, lon), f32[ndocs] on the VPU."""
    r = 6371008.8
    p1 = jnp.deg2rad(geo["lat"])
    p2 = jnp.deg2rad(lat)
    dphi = p2 - p1
    dlmb = jnp.deg2rad(lon - geo["lon"])
    a = (jnp.sin(dphi / 2.0) ** 2
         + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2.0) ** 2)
    return 2.0 * r * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def geo_distance_mask(geo: dict, lat: jnp.ndarray, lon: jnp.ndarray,
                      radius_m: jnp.ndarray,
                      inclusive: bool = True) -> jnp.ndarray:
    """Haversine distance filter on the VPU (reference GeoDistanceQuery)."""
    r = 6371008.8
    p1 = jnp.deg2rad(geo["lat"])
    p2 = jnp.deg2rad(lat)
    dphi = p2 - p1
    dlmb = jnp.deg2rad(lon - geo["lon"])
    a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2
    d = 2 * r * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
    return ((d <= radius_m) if inclusive else (d < radius_m)) & geo["present"]


# ---------------- scatter-free sort-merge scoring ----------------

def sortmerge_topk(docs: jnp.ndarray, contribs: jnp.ndarray, k: int,
                   msm=None):
    """Top-k doc scores from flat (doc, contribution) postings WITHOUT a
    dense scatter (XLA scatter serializes on TPU — the dense path costs ~ms;
    this path is sort + cumsum + gathers, all MXU/VPU-friendly).

    Sort postings by doc id, then per-doc totals fall out of a cumulative-sum
    difference between run boundaries; the run start index comes from a
    prefix-max scan, so the whole reduction is dense ops. Returns
    (scores f32[k], doc_ids i32[k]) with -inf/-1 padding. `msm` (traced
    scalar) keeps only docs matched by >= msm distinct terms — each term
    contributes at most one posting per doc, so run length == match count.

    This is the TAAT->sort-merge reformulation of Lucene's BulkScorer loop:
    work is O(B log B) in the number of query postings B, independent of
    corpus size (the dense path is O(ndocs) + serialized scatter).
    """
    B = docs.shape[0]
    order = jnp.argsort(docs)
    d = docs[order]
    c = contribs[order]
    idx = jnp.arange(B, dtype=jnp.int32)
    is_first = jnp.concatenate([jnp.array([True]), d[1:] != d[:-1]])
    is_last = jnp.concatenate([d[:-1] != d[1:], jnp.array([True])])
    csum = jnp.cumsum(c)
    # index of the start of each position's run, via prefix max
    run_start = jax.lax.associative_scan(jnp.maximum,
                                         jnp.where(is_first, idx, -1))
    pre = jnp.where(run_start > 0, csum[jnp.maximum(run_start - 1, 0)], 0.0)
    run_total = csum - pre
    run_len = (idx - run_start + 1).astype(jnp.float32)
    valid = is_last & (d < jnp.int32(2**31 - 1))
    if msm is not None:
        valid = valid & (run_len >= msm)
    masked = jnp.where(valid, run_total, NEG_INF)
    k = min(k, B)
    vals, pos = jax.lax.top_k(masked, k)
    out_docs = jnp.where(vals > NEG_INF, d[pos], -1)
    return vals, out_docs


def count_matches_sortmerge(docs: jnp.ndarray, msm=None) -> jnp.ndarray:
    """Total distinct matching docs from flat postings, scatter-free."""
    d = jnp.sort(docs)
    is_last = jnp.concatenate([d[:-1] != d[1:], jnp.array([True])])
    valid = is_last & (d < jnp.int32(2**31 - 1))
    if msm is not None:
        idx = jnp.arange(d.shape[0], dtype=jnp.int32)
        is_first = jnp.concatenate([jnp.array([True]), d[1:] != d[:-1]])
        run_start = jax.lax.associative_scan(jnp.maximum,
                                             jnp.where(is_first, idx, -1))
        run_len = (idx - run_start + 1).astype(jnp.float32)
        valid = valid & (run_len >= msm)
    return jnp.sum(valid.astype(jnp.int32))


# ---------------- top-k ----------------

def collapse_topk(key: jnp.ndarray, matched: jnp.ndarray, live: jnp.ndarray,
                  ords: jnp.ndarray, n_ord_pad: int, k: int):
    """Field-collapsed top-k: one best doc per group ordinal (reference
    `search/collapse/CollapseBuilder.java` + CollapsingTopDocsCollector).

    Three dense passes, no sorting: scatter-max of the ranking key into group
    space, top-k over groups, then scatter-min of doc ids restricted to each
    group's best key (ties -> lowest doc id, like the plain collector).
    Docs with ord < 0 (missing field) share one null group (last slot)."""
    ndocs_pad = key.shape[0]
    masked = jnp.where(matched & (live > 0), key, NEG_INF)
    g = jnp.where(ords >= 0, ords, n_ord_pad - 1).astype(jnp.int32)
    g = jnp.clip(g, 0, n_ord_pad - 1)
    gbest = jnp.full(n_ord_pad, NEG_INF, jnp.float32).at[g].max(masked)
    doc_iota = jnp.arange(ndocs_pad, dtype=jnp.int32)
    valid = masked > NEG_INF
    cand = jnp.where(valid & (masked == gbest[g]), doc_iota,
                     jnp.int32(2**31 - 1))
    gdoc = jnp.full(n_ord_pad, 2**31 - 1, jnp.int32).at[g].min(cand)
    kk = min(k, n_ord_pad)
    vals, gsel = jax.lax.top_k(gbest, kk)
    docs = jnp.minimum(gdoc[gsel], ndocs_pad - 1)
    return vals, docs


def topk_docs(scores: jnp.ndarray, matched: jnp.ndarray, live: jnp.ndarray, k: int):
    """Masked fused top-k. Ties broken by ascending doc id like Lucene's
    TopScoreDocCollector (implemented by a tiny monotone doc-id epsilon that
    cannot reorder distinct f32 scores)."""
    masked = jnp.where(matched & (live > 0), scores, NEG_INF)
    k = min(k, scores.shape[0])
    vals, idx = jax.lax.top_k(masked, k)
    return vals, idx


def total_hits(matched: jnp.ndarray, live: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(jnp.where(matched & (live > 0), 1, 0))


# ---------------- host-side helpers ----------------

def bm25_idf(n_docs: int, df: int) -> float:
    """Lucene BM25Similarity.idfExplain: ln(1 + (N - df + 0.5)/(df + 0.5))."""
    return math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))


def classic_idf(n_docs: int, df: int) -> float:
    """Lucene ClassicSimilarity: 1 + ln((N+1)/(df+1))."""
    return 1.0 + math.log((n_docs + 1.0) / (df + 1.0))


def pick_bucket(total_postings: int, floor: int = 256) -> int:
    n = max(int(total_postings), floor)
    return 1 << (n - 1).bit_length()
