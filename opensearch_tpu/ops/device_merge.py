"""Device-side multiway sorted-run merge: the compute core of segment
merging (reference: Lucene SegmentMerger's doc-id remap + postings merge).

The merge pipeline (index/merge.py) is: remap each input segment's postings
to (union_row, new_doc, tf) triples, sort them lexicographically, and slice
CSR runs. The sort is the O(P log P) hot part — this module runs it on the
TPU as a two-key `lax.sort` over the concatenated runs, carrying the tf and
a source-index payload so the host can regather ragged position runs with
the SAME order (bit-identical output to the numpy path).

Shapes are pow2-padded; invalid padding sorts to the end via row = n_rows.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

# below this many postings the device round trip costs more than numpy
DEVICE_MERGE_MIN = 1 << 16


@partial(jax.jit, static_argnames=("n_rows",))
def _sort_runs(rows, docs, tfs, src, n_rows: int):
    r, d, t, s = jax.lax.sort((rows, docs, tfs, src), num_keys=2,
                              is_stable=True)
    counts = jnp.zeros(n_rows + 1, jnp.int32).at[jnp.minimum(r, n_rows)].add(
        jnp.where(r < n_rows, 1, 0))
    return r, d, t, s, counts


def merge_sorted_runs(rows: np.ndarray, docs: np.ndarray, tfs: np.ndarray,
                      n_rows: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray, np.ndarray]:
    """-> (rows, docs, tfs, order, per-row counts), sorted by (row, doc).

    `order` is the permutation applied (positions regather uses it).
    Equivalent to np.lexsort((docs, rows)) + bincount, executed on device.
    """
    n = len(rows)
    pad = 1 << int(np.ceil(np.log2(max(n, 2))))
    # bucket the static row count too, or every new vocab-union size would
    # recompile _sort_runs; padding rows sort as n_rows_pad (past all valid)
    n_rows_pad = 1 << int(np.ceil(np.log2(max(n_rows, 2))))
    rows_p = np.full(pad, n_rows_pad, np.int32)
    rows_p[:n] = rows           # the assignment casts int64 -> int32
    docs_p = np.zeros(pad, np.int32)
    docs_p[:n] = docs
    tfs_p = np.zeros(pad, np.float32)
    tfs_p[:n] = tfs
    src_p = np.arange(pad, dtype=np.int32)
    r, d, t, s, counts = _sort_runs(rows_p, docs_p, tfs_p, src_p, n_rows_pad)
    r = np.asarray(r)[:n]
    d = np.asarray(d)[:n]
    t = np.asarray(t)[:n]
    s = np.asarray(s)[:n]
    counts = np.asarray(counts)[:n_rows]
    return r, d, t, s, counts


def use_device_merge(total_postings: int) -> bool:
    import os
    if os.environ.get("OPENSEARCH_TPU_NO_DEVICE_MERGE"):
        return False
    return total_postings >= DEVICE_MERGE_MIN


# ---------------------------------------------------------------------
# codec v2: device-side impact quantization (index/refresh/merge time)
# ---------------------------------------------------------------------
#
# The eager-impact build (index/segment.py build_impact_plane) is an O(P)
# dense map — exactly the shape the device does at HBM bandwidth while the
# host packer is busy. The f32 expression mirrors the host oracle
# (fastpath._exact_rescore) so the quantization-error bound measured
# against the exact serve domain holds for either build path; the plane
# only steers candidate selection and prune bounds, so host/device build
# parity is a quality property, not a correctness requirement (the
# impact ladder's certify-or-escalate rungs keep served pages oracle-
# exact regardless — see docs/INDEX_FORMAT.md).

DEVICE_IMPACT_MIN = 1 << 16


@partial(jax.jit, static_argnames=("k1", "b", "qmax"))
def _quantize_impacts(tfs, dl_of, avgdl, k1: float, b: float, qmax: int):
    kfac = k1 * (1.0 - b + b * dl_of / avgdl)
    imp = tfs / (tfs + kfac)
    m = jnp.max(imp, initial=jnp.float32(0.0))
    scale = jnp.where(m > 0, m / qmax, 1.0).astype(jnp.float32)
    q = jnp.minimum(jnp.round(imp / scale), qmax).astype(jnp.int32)
    return q, scale


def quantize_impacts(tfs: np.ndarray, dl_of: np.ndarray, k1: float,
                     b: float, avgdl: float, qmax: int
                     ) -> Tuple[np.ndarray, float]:
    """-> (q i32[P], scale): quantized eager impacts computed on device.
    Shapes are pow2-padded (tf=0 padding quantizes to 0) so segment sizes
    don't storm the jit cache."""
    n = len(tfs)
    pad = 1 << int(np.ceil(np.log2(max(n, 2))))
    tfs_p = np.zeros(pad, np.float32)
    tfs_p[:n] = tfs
    dl_p = np.zeros(pad, np.float32)
    dl_p[:n] = dl_of
    q, scale = _quantize_impacts(tfs_p, dl_p,
                                 np.float32(max(avgdl, 1e-9)),
                                 float(k1), float(b), int(qmax))
    return np.asarray(q)[:n], float(np.asarray(scale))


def use_device_impacts(total_postings: int) -> bool:
    import os
    if os.environ.get("OPENSEARCH_TPU_NO_DEVICE_MERGE"):
        return False
    return total_postings >= DEVICE_IMPACT_MIN
