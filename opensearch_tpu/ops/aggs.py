"""Device aggregation kernels. Analog of reference
`search/aggregations/bucket/*` and `metrics/*` aggregators, which walk
matching docs one at a time; here each aggregation is a masked columnar
reduction (bincount / segment reduce / scatter-max) over the whole segment.

All kernels take `match` — the query's dense f32 0/1 match vector (already
live-masked) — so aggregations run in the same jitted program as scoring and
XLA fuses the mask with the reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

F32_MAX = np.float32(3.4e38)  # numpy, not jnp (see ops/scoring.NEG_INF note)


def _gather_match(match: jnp.ndarray, docs: jnp.ndarray) -> jnp.ndarray:
    safe = jnp.minimum(docs, match.shape[0] - 1)
    return jnp.where(docs < match.shape[0], match[safe], 0.0)


def terms_counts(kw: dict, match: jnp.ndarray, nvocab_pad: int) -> jnp.ndarray:
    """Keyword terms agg: per-ordinal doc counts (reference
    GlobalOrdinalsStringTermsAggregator). Returns f32[nvocab_pad]."""
    w = _gather_match(match, kw["doc_of_value"])
    return jnp.zeros(nvocab_pad, jnp.float32).at[kw["ords"]].add(w, mode="drop")


def terms_sub_metric(kw: dict, match: jnp.ndarray, values_f32: jnp.ndarray,
                     present: jnp.ndarray, nvocab_pad: int):
    """Per-ordinal (sum, count, min, max) of a numeric column — powers metric
    sub-aggregations under a terms bucket in a single fused pass."""
    docs = kw["doc_of_value"]
    safe = jnp.minimum(docs, values_f32.shape[0] - 1)
    w = _gather_match(match, docs) * jnp.where(present[safe], 1.0, 0.0)
    v = values_f32[safe]
    ords = kw["ords"]
    sums = jnp.zeros(nvocab_pad, jnp.float32).at[ords].add(w * v, mode="drop")
    cnts = jnp.zeros(nvocab_pad, jnp.float32).at[ords].add(w, mode="drop")
    mins = jnp.full(nvocab_pad, F32_MAX).at[ords].min(
        jnp.where(w > 0, v, F32_MAX), mode="drop")
    maxs = jnp.full(nvocab_pad, -F32_MAX).at[ords].max(
        jnp.where(w > 0, v, -F32_MAX), mode="drop")
    sumsq = jnp.zeros(nvocab_pad, jnp.float32).at[ords].add(w * v * v, mode="drop")
    return sums, cnts, mins, maxs, sumsq


def histogram_counts(values_f32: jnp.ndarray, present: jnp.ndarray, match: jnp.ndarray,
                     interval: float, offset: float, min_bucket: int, nbuckets: int):
    """Fixed-interval histogram (reference HistogramAggregator). The bucket
    window [min_bucket, min_bucket+nbuckets) is static, derived on the host
    from segment column stats."""
    b = jnp.floor((values_f32 - offset) / interval).astype(jnp.int32) - min_bucket
    w = match * jnp.where(present, 1.0, 0.0)
    b = jnp.where((b >= 0) & (b < nbuckets), b, nbuckets)  # OOB -> dropped
    return jnp.zeros(nbuckets, jnp.float32).at[b].add(w, mode="drop")


def range_counts(values_f32: jnp.ndarray, present: jnp.ndarray, match: jnp.ndarray,
                 lows: jnp.ndarray, highs: jnp.ndarray):
    """range agg: [low, high) per reference RangeAggregator. lows/highs are
    f32[nranges] traced arrays; returns f32[nranges] counts."""
    v = values_f32[None, :]
    in_range = (v >= lows[:, None]) & (v < highs[:, None])
    w = (match * jnp.where(present, 1.0, 0.0))[None, :]
    return jnp.sum(jnp.where(in_range, w, 0.0), axis=1)


def stats_agg(values_f32: jnp.ndarray, present: jnp.ndarray, match: jnp.ndarray):
    """count/sum/min/max/sumsq in one pass (reference StatsAggregator /
    ExtendedStatsAggregator)."""
    w = match * jnp.where(present, 1.0, 0.0)
    v = values_f32
    count = jnp.sum(w)
    s = jnp.sum(w * v)
    ssq = jnp.sum(w * v * v)
    mn = jnp.min(jnp.where(w > 0, v, F32_MAX))
    mx = jnp.max(jnp.where(w > 0, v, -F32_MAX))
    return count, s, mn, mx, ssq


def value_count_keyword(kw: dict, match: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(_gather_match(match, kw["doc_of_value"]))


def weighted_avg_agg(v: jnp.ndarray, v_present: jnp.ndarray,
                     w: jnp.ndarray, w_present: jnp.ndarray,
                     match: jnp.ndarray,
                     v_missing, w_missing,
                     has_v_missing: bool, has_w_missing: bool):
    """Σ value·weight and Σ weight over matched docs (reference
    WeightedAvgAggregator): docs missing value or weight are skipped unless
    the corresponding `missing` default is configured."""
    veff = jnp.where(v_present, v, v_missing)
    weff = jnp.where(w_present, w, w_missing)
    ok = match > 0
    if not has_v_missing:
        ok = ok & v_present
    if not has_w_missing:
        ok = ok & w_present
    okf = ok.astype(jnp.float32)
    return (jnp.sum(okf * veff * weff), jnp.sum(okf * weff), jnp.sum(okf))


def geo_bounds_agg(lat: jnp.ndarray, lon: jnp.ndarray, present: jnp.ndarray,
                   match: jnp.ndarray):
    """(top, bottom, left, right, count) masked extremes (reference
    GeoBoundsAggregator, wrap_longitude=false semantics)."""
    ok = (match > 0) & present
    count = jnp.sum(ok.astype(jnp.float32))
    top = jnp.max(jnp.where(ok, lat, -F32_MAX))
    bottom = jnp.min(jnp.where(ok, lat, F32_MAX))
    left = jnp.min(jnp.where(ok, lon, F32_MAX))
    right = jnp.max(jnp.where(ok, lon, -F32_MAX))
    return top, bottom, left, right, count


def geo_centroid_agg(lat: jnp.ndarray, lon: jnp.ndarray, present: jnp.ndarray,
                     match: jnp.ndarray):
    """(Σlat, Σlon, count) (reference GeoCentroidAggregator)."""
    w = match * jnp.where(present, 1.0, 0.0)
    return jnp.sum(w * lat), jnp.sum(w * lon), jnp.sum(w)


def ord_counts(ords: jnp.ndarray, match: jnp.ndarray, nord_pad: int
               ) -> jnp.ndarray:
    """Doc-major single-valued ordinal bincount (multi_terms combined ords,
    grid ords): ord < 0 = missing -> dropped."""
    o = jnp.where(ords >= 0, ords, nord_pad)
    return jnp.zeros(nord_pad, jnp.float32).at[o].add(match, mode="drop")


def cardinality_keyword(kw: dict, match: jnp.ndarray, nvocab_pad: int) -> jnp.ndarray:
    """Exact distinct count via ordinals (the reference uses global ords +
    HLL; segment-local ords are exact on-device, merged across segments on
    the host via vocab union)."""
    counts = terms_counts(kw, match, nvocab_pad)
    return jnp.sum(jnp.where(counts > 0, 1, 0))


def _hash_f32(v: jnp.ndarray) -> jnp.ndarray:
    """Cheap 32-bit integer mix (fmix32 from MurmurHash3) of float bit patterns."""
    h = jax.lax.bitcast_convert_type(v, jnp.int32).astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def hll_registers(hashes_u32: jnp.ndarray, valid: jnp.ndarray, log2m: int = 14) -> jnp.ndarray:
    """HyperLogLog registers from 32-bit hashes via scatter-max (the
    mergeable core of reference CardinalityAggregator's HLL++; merge across
    segments/shards = elementwise max on the host). Returns i32[2^log2m]."""
    m = 1 << log2m
    reg = (hashes_u32 & jnp.uint32(m - 1)).astype(jnp.int32)
    rest = hashes_u32 >> log2m
    # rank = position of the first set bit in the remaining 32-log2m bits
    nbits = 32 - log2m
    rank = (nbits + 1) - jnp.ceil(jnp.log2(rest.astype(jnp.float32) + 1.0)).astype(jnp.int32)
    rank = jnp.clip(rank, 1, nbits + 1)
    reg = jnp.where(valid, reg, m)  # invalid -> dropped
    return jnp.zeros(m, jnp.int32).at[reg].max(jnp.where(valid, rank, 0), mode="drop")


def cardinality_numeric_registers(values_f32: jnp.ndarray, present: jnp.ndarray,
                                  match: jnp.ndarray, log2m: int = 14) -> jnp.ndarray:
    return hll_registers(_hash_f32(values_f32), (match > 0) & present, log2m)


def cardinality_keyword_registers(kw: dict, match: jnp.ndarray, nvocab_pad: int,
                                  ord_hashes_u32: jnp.ndarray, log2m: int = 14) -> jnp.ndarray:
    """Keyword cardinality: HLL over per-ordinal string hashes (host-computed
    once per segment), activated by matched ordinals."""
    counts = terms_counts(kw, match, nvocab_pad)
    return hll_registers(ord_hashes_u32, counts > 0, log2m)


# DDSketch-style log-binned quantile sketch: bins are GLOBAL constants
# (value-independent), so per-segment/per-shard histograms merge by plain
# addition — the mergeability property the reference gets from TDigest.
# Layout: [0..HALF) negative magnitudes (reversed), HALF zero, (HALF..2*HALF]
# positive magnitudes. gamma^HALF spans MIN_MAG..MAX_MAG => ~0.5% rel. error.
DD_HALF = 4096
DD_MIN_MAG = 1e-9
DD_MAX_MAG = 1e9
DD_LN_GAMMA = (np.log(DD_MAX_MAG) - np.log(DD_MIN_MAG)) / DD_HALF
DD_NBINS = 2 * DD_HALF + 1


def ddsketch_hist(values_f32: jnp.ndarray, present: jnp.ndarray,
                  match: jnp.ndarray) -> jnp.ndarray:
    """f32[DD_NBINS] mergeable quantile histogram of matched values."""
    w = match * jnp.where(present, 1.0, 0.0)
    mag = jnp.abs(values_f32)
    idx = jnp.floor((jnp.log(jnp.maximum(mag, DD_MIN_MAG)) - np.log(DD_MIN_MAG))
                    / DD_LN_GAMMA).astype(jnp.int32)
    idx = jnp.clip(idx, 0, DD_HALF - 1)
    b = jnp.where(values_f32 > 0, DD_HALF + 1 + idx,
                  jnp.where(values_f32 < 0, DD_HALF - 1 - idx, DD_HALF))
    b = jnp.where(w > 0, b, DD_NBINS)  # dropped
    return jnp.zeros(DD_NBINS, jnp.float32).at[b].add(w, mode="drop")


def ddsketch_bin(v: float) -> int:
    """Host-side bin index of one value — the same arithmetic as
    `ddsketch_hist` (f32 log/floor, so a stored value and a queried value
    land in the same bin bit-for-bit; percentile_ranks inverts percentiles
    through this)."""
    # every step in f32, mirroring the device (jnp canonicalizes the f64
    # log/gamma constants to f32 before the subtract/divide; a host f64
    # intermediate shifts ~1e-4 of values one bin off the device's)
    mag = np.float32(abs(v))
    ln = np.log(np.maximum(mag, np.float32(DD_MIN_MAG)))
    idx = int(np.floor((ln - np.float32(np.log(DD_MIN_MAG)))
                       / np.float32(DD_LN_GAMMA)))
    idx = min(max(idx, 0), DD_HALF - 1)
    if v > 0:
        return DD_HALF + 1 + idx
    if v < 0:
        return DD_HALF - 1 - idx
    return DD_HALF


def ddsketch_value(b: int) -> float:
    """Representative value of bin b (host-side finalize)."""
    if b == DD_HALF:
        return 0.0
    if b > DD_HALF:
        return float(DD_MIN_MAG * np.exp((b - DD_HALF - 1 + 0.5) * DD_LN_GAMMA))
    return float(-DD_MIN_MAG * np.exp((DD_HALF - 1 - b + 0.5) * DD_LN_GAMMA))


def min_ord_sort_key(min_ord: jnp.ndarray, descending: bool, missing_last: bool) -> jnp.ndarray:
    """Keyword sort keys from per-doc min ordinals; missing docs pushed to the
    configured end (reference: SortedSetSortField missing _first/_last)."""
    key = min_ord.astype(jnp.float32)
    big = jnp.float32(2.0**30)
    missing_val = big if (missing_last != descending) else -big
    key = jnp.where(min_ord < 0, missing_val, key)
    return -key if descending else key
