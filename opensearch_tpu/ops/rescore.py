"""Device-side phase-2 exact rescore — the escalation ladder's middle rung
without the host round trip (the device analog of Lucene re-walking a WAND
candidate, reference `search/query/QueryPhase.java` two-phase iteration).

`search/fastpath.py`'s pruned pipeline escalates a clamped query by exact-
rescoring a CANDIDATE UNION (every doc any impact head mentions, ≤ T·4·L_HEAD
ids) against the FULL posting rows. The r5 implementation was a host numpy
pass (`_exact_rescore`) sandwiched between kernel launches: per escalated
query, T vectorized `searchsorted`s over rows that can span millions of
postings — serialized on the host exactly when the query is already slowest.
This module moves that pass onto the device as ONE jit launch batched across
the whole escalation queue:

    per (query, term, candidate):  branchless lower-bound binary search over
    the term's CSR window in the ALREADY-RESIDENT aligned postings buffers
    (the same `AlignedPostings.d_docs/d_tfdl` the dense scorer DMAs from) —
    no new device-resident state, no per-query transfer beyond the padded
    candidate ids — then gather packed (tf, dl), decode, and accumulate
    exact f32 BM25 + per-term match counts.

Why `jnp` and not a Pallas kernel: the access pattern is C·T independent
binary searches (log P dependent random gathers each) — there is no
contiguous DMA window to stage into VMEM, which is the only thing the fused
scorer's Pallas formulation buys. XLA compiles the probe loop into log2(P)
batched gathers over [QB, T, C]; the arithmetic after the search is plain
VPU work XLA fuses fine. A Pallas upgrade would only pay if the probe
gathers dominate on silicon — measure first (docs/FASTPATH.md).

BIT-PARITY CONTRACT: the accumulation mirrors `fastpath._exact_rescore`
op-for-op in f32 (same expression shapes, same term order, weak-typed
scalars rounding at the same points), so `_tie_serves`/theta32 comparisons
made on device scores are bit-identical to the host oracle's. The host pass
stays as the `JAX_PLATFORMS=cpu` fallback and the parity oracle
(tests/test_rescore.py asserts exact equality, not allclose).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .pallas_bm25 import DL_BITS, DL_MASK, INT_SENTINEL, TF_MAX


@functools.partial(jax.jit, static_argnames=("T", "C", "k1", "b"))
def exact_rescore_batch(docs_hbm: jnp.ndarray, tfdl_hbm: jnp.ndarray,
                        starts: jnp.ndarray, lens: jnp.ndarray,
                        weights: jnp.ndarray, avgdl: jnp.ndarray,
                        cand: jnp.ndarray,
                        T: int, C: int, k1: float, b: float):
    """Exact BM25 scores + match counts of candidate docs vs full rows.

    docs_hbm  i32[P] — aligned CSR doc ids (fastpath AlignedPostings.d_docs:
              each row doc-ascending within its true window)
    tfdl_hbm  i32[P] — packed tf << DL_BITS | dl per posting
    starts    i32[QB, T] — ELEMENT offset of each term's full-row window
    lens      i32[QB, T] — true posting count per window (0 = absent term)
    weights   f32[QB, T] — query-time idf * boost
    avgdl     f32[QB, 1]
    cand      i32[QB, C] — candidate doc ids, INT_SENTINEL padded
    k1, b     static similarity params (b pre-zeroed when norms are off)
    Returns (exact f32[QB, C], counts i32[QB, C]) — 0 on padding slots.
    """
    P = docs_hbm.shape[0]
    # lower_bound over [start, start+len): branchless bisection, static
    # probe count from the (static) buffer length. mid = lo + (hi-lo)//2
    # keeps i32 safe for buffers past 2^30 elements.
    lo = jnp.broadcast_to(starts[:, :, None], starts.shape + (C,))
    hi = lo + lens[:, :, None]
    end = hi
    c = cand[:, None, :]
    for _ in range(max(int(P).bit_length(), 1)):
        mid = lo + (hi - lo) // 2
        v = docs_hbm[jnp.clip(mid, 0, P - 1)]
        go = v < c
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    # mirror the host's clamped probe: pos_c = min(pos, row_end - 1)
    pos_c = jnp.clip(jnp.minimum(lo, end - 1), 0, P - 1)
    found = ((docs_hbm[pos_c] == c) & (lens[:, :, None] > 0)
             & (c < INT_SENTINEL))
    tfdl = tfdl_hbm[pos_c]
    tf = jnp.where(found, ((tfdl >> DL_BITS) & TF_MAX), 0
                   ).astype(jnp.float32)
    # the candidate's doc length, recovered from any matched posting (all
    # postings of one doc in one field carry the same dl; candidates are
    # head members, so a real candidate matches >= 1 full row). Padding /
    # no-match candidates get dl 0 — their contribution is masked to 0
    # anyway, matching the host oracle's zero output for them.
    dl_c = jnp.max(jnp.where(found, (tfdl & DL_MASK), 0),
                   axis=1).astype(jnp.float32)
    # EXACTLY `fastpath._exact_rescore`'s expression and evaluation order:
    # (1.0 - b) folds at trace time in f64 then rounds to f32 on the add,
    # the same NEP50 weak-scalar rounding the numpy pass performs
    avg = jnp.maximum(avgdl, jnp.float32(1e-9))           # [QB, 1]
    kfac = k1 * ((1.0 - b) + b * dl_c / avg)              # [QB, C] f32
    exact = jnp.zeros(kfac.shape, jnp.float32)
    counts = jnp.zeros(kfac.shape, jnp.int32)
    # term-order f32 accumulation: adding a masked 0.0f is an exact
    # identity on the non-negative partial sums, so skipped/absent slots
    # leave the running sum bit-identical to the host loop's
    for t in range(T):
        tft = tf[:, t, :]
        foundt = found[:, t, :]
        contrib = jnp.where(foundt,
                            weights[:, t:t + 1] * tft / (tft + kfac), 0.0)
        exact = exact + contrib.astype(jnp.float32)
        counts = counts + foundt.astype(jnp.int32)
    return exact, counts


def rescore_elem_budget(T: int, C: int, max_elems: int = 1 << 24) -> int:
    """Max queries per launch so the [QB, T, C] probe intermediates stay
    inside a bounded HBM transient (~max_elems * ~16B live at the widest
    point). The fastpath splits bigger batches into sequential launches.
    Returned as a POWER OF TWO: the caller pads QB to pow2, so a non-pow2
    step would let the padded launch overshoot the budget by up to 2x."""
    n = max(1, max_elems // max(T * C, 1))
    return 1 << (n.bit_length() - 1)


def host_exact_rescore_batch(docs: np.ndarray, tfdl: np.ndarray,
                             starts: np.ndarray, lens: np.ndarray,
                             weights: np.ndarray, avgdl: np.ndarray,
                             cand: np.ndarray, k1: float, b: float):
    """Numpy mirror of `exact_rescore_batch` over the SAME padded operands —
    the parity oracle tests pin the device path against (the per-query
    production host path stays `fastpath._exact_rescore`)."""
    QB, C = cand.shape
    T = starts.shape[1]
    exact = np.zeros((QB, C), np.float32)
    counts = np.zeros((QB, C), np.int32)
    for q in range(QB):
        valid = cand[q] < INT_SENTINEL
        dl_c = np.zeros(C, np.float32)
        tf_q = np.zeros((T, C), np.float32)
        found_q = np.zeros((T, C), bool)
        for t in range(T):
            a = int(starts[q, t])
            ln = int(lens[q, t])
            if ln <= 0:
                continue
            rowdocs = docs[a: a + ln]
            pos = np.searchsorted(rowdocs, cand[q])
            pos_c = np.minimum(pos, ln - 1)
            found = (rowdocs[pos_c] == cand[q]) & valid
            packed = tfdl[a + pos_c]
            tf_q[t] = np.where(found, (packed >> DL_BITS) & TF_MAX,
                               0.0).astype(np.float32)
            dl_c = np.maximum(dl_c, np.where(found, packed & DL_MASK,
                                             0).astype(np.float32))
            found_q[t] = found
        kfac = k1 * (1.0 - b + b * dl_c / max(float(avgdl[q, 0]), 1e-9))
        for t in range(T):
            tft = tf_q[t]
            contrib = np.where(found_q[t],
                               np.float32(weights[q, t]) * tft
                               / (tft + kfac), 0.0).astype(np.float32)
            exact[q] += contrib
            counts[q] += found_q[t]
    return exact, counts
