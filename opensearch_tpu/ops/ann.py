"""Approximate kNN: balanced IVF-flat, the TPU-native ANN layout.

Reference analog: the k-NN plugin's ANN indexes (HNSW/faiss — graph walks
with data-dependent branching, a shape XLA cannot tile). The TPU-first
design is inverted-file with BALANCED clusters instead:

- Build: k-means on device (chunked Lloyd iterations — assignment is one
  [B,D]x[D,nlist] MXU matmul per block, centroid update a scatter-add),
  then a vectorized host pass that caps every cluster at `cap` rows,
  spilling overflow to the row's second-best cluster (the ScaNN-style
  trade: bounded list length buys static shapes and dense DMA).
- Layout: `lists` is a DENSE i32[nlist, cap] matrix (-1 padded). A probe
  is `lists[top_nprobe]` — one gather of a [nprobe, cap] tile, no CSR
  walk, no dynamic shapes anywhere.
- Search (in search/compiler.py emit "knn"): centroid matvec -> static
  top-nprobe -> gather candidate rows -> MXU matvec -> scatter scores
  back into the dense per-doc score space, so ANN kNN composes with every
  other plan node (bool, filters, aggs) exactly like the exact path.

Setting nprobe = nlist provably recovers the exact search (every row is
in exactly one list), which the tests assert.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np


@dataclass
class IvfIndex:
    centroids: np.ndarray   # f32[nlist, D] (same space as the scored matrix)
    lists: np.ndarray       # i32[nlist, cap], -1 = empty slot
    nlist: int
    cap: int
    default_nprobe: int


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


_BLOCK = 8192


def _kmeans_device(vals_b, pres_b, init, iters: int):
    """Lloyd iterations over blocked data. vals_b: f32[nb, B, D],
    pres_b: f32[nb, B], init: f32[nlist, D]. Returns f32[nlist, D]."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    nlist = init.shape[0]

    def one_iter(cents, _):
        csq = jnp.sum(cents * cents, axis=1)  # [nlist]

        def block(carry, blk):
            sums, counts = carry
            v, p = blk
            # ||v-c||^2 up to a per-row constant: -2 v.c + ||c||^2
            d2 = csq - 2.0 * jnp.dot(v, cents.T,
                                     preferred_element_type=jnp.float32)
            a = jnp.argmin(d2, axis=1)
            a = jnp.where(p > 0, a, nlist)      # absent rows drop out of bounds
            sums = sums.at[a].add(v * p[:, None], mode="drop")
            counts = counts.at[a].add(p, mode="drop")
            return (sums, counts), None

        (sums, counts), _ = lax.scan(
            block, (jnp.zeros_like(cents), jnp.zeros(nlist, jnp.float32)),
            (vals_b, pres_b))
        newc = sums / jnp.maximum(counts, 1.0)[:, None]
        return jnp.where((counts > 0)[:, None], newc, cents), None

    cents, _ = lax.scan(one_iter, init, None, length=iters)
    return cents


def _assign_top2_device(vals_b, cents):
    """Per row: (best cluster, 2nd-best cluster, best distance).
    vals_b: f32[nb, B, D] -> (i32[nb,B], i32[nb,B], f32[nb,B])."""
    import jax.numpy as jnp
    from jax import lax

    csq = jnp.sum(cents * cents, axis=1)

    def block(_, v):
        d2 = csq - 2.0 * jnp.dot(v, cents.T,
                                 preferred_element_type=jnp.float32)
        a1 = jnp.argmin(d2, axis=1)
        d1 = jnp.min(d2, axis=1)
        d2b = d2.at[jnp.arange(v.shape[0]), a1].set(jnp.inf)
        a2 = jnp.argmin(d2b, axis=1)
        return None, (a1.astype(jnp.int32), a2.astype(jnp.int32), d1)

    _, (a1, a2, d1) = lax.scan(block, None, vals_b)
    return a1, a2, d1


def build_ivf(values: np.ndarray, present: np.ndarray,
              nlist: Optional[int] = None, nprobe: Optional[int] = None,
              iters: int = 8, seed: int = 0, slack: float = 1.5
              ) -> Optional[IvfIndex]:
    """values: f32[N, D] — pass the SAME matrix the scorer uses (unit-normed
    for cosine) so centroid geometry matches search geometry."""
    import jax
    import jax.numpy as jnp

    values = np.asarray(values, np.float32)
    present = np.asarray(present, bool)
    n = values.shape[0]
    pres_idx = np.nonzero(present[:n])[0]
    npres = len(pres_idx)
    if npres == 0:
        return None
    nlist = int(min(nlist or max(1, round(npres ** 0.5)), npres))
    cap = max(1, int(np.ceil(npres * slack / nlist)))
    default_nprobe = int(min(nprobe or max(1, nlist // 8), nlist))

    rng = np.random.default_rng(seed)
    init = values[rng.choice(pres_idx, nlist, replace=False)].copy()

    # block + pad for the scan (padded rows carry weight 0)
    npad = ((n + _BLOCK - 1) // _BLOCK) * _BLOCK
    vb = np.zeros((npad, values.shape[1]), np.float32)
    vb[:n] = values
    pb = np.zeros(npad, np.float32)
    pb[:n] = present[:n].astype(np.float32)
    vb = vb.reshape(-1, _BLOCK, values.shape[1])
    pbb = pb.reshape(-1, _BLOCK)

    kmeans = jax.jit(partial(_kmeans_device, iters=iters))
    cents = kmeans(jnp.asarray(vb), jnp.asarray(pbb), jnp.asarray(init))
    a1, a2, d1 = jax.jit(_assign_top2_device)(jnp.asarray(vb), cents)
    cents = np.asarray(cents)
    a1 = np.asarray(a1).reshape(-1)[:n]
    a2 = np.asarray(a2).reshape(-1)[:n]
    d1 = np.asarray(d1).reshape(-1)[:n]

    # ---- balanced fill (vectorized host pass) ----
    # round 1: rows claim their primary cluster, closest-first
    lists = np.full((nlist, cap), -1, np.int32)
    fill = np.zeros(nlist, np.int64)
    rows = pres_idx[np.lexsort((d1[pres_idx], a1[pres_idx]))]
    c = a1[rows]
    # rank of each row within its cluster run
    starts = np.searchsorted(c, np.arange(nlist))
    rank = np.arange(len(rows)) - starts[c]
    keep = rank < cap
    kept_rows, kept_c, kept_rank = rows[keep], c[keep], rank[keep]
    lists[kept_c, kept_rank] = kept_rows
    fill = np.bincount(kept_c, minlength=nlist).astype(np.int64)

    # round 2: spilled rows go to their 2nd-best cluster if it has room
    spill = rows[~keep]
    if len(spill):
        c2 = a2[spill]
        order2 = np.argsort(c2, kind="stable")
        spill, c2 = spill[order2], c2[order2]
        starts2 = np.searchsorted(c2, np.arange(nlist))
        rank2 = (np.arange(len(spill)) - starts2[c2]) + fill[c2]
        keep2 = rank2 < cap
        lists[c2[keep2], rank2[keep2]] = spill[keep2]
        fill = np.bincount(c2[keep2], minlength=nlist).astype(np.int64) + fill
        # round 3 (rare): round-robin into whatever still has room
        left = spill[~keep2]
        if len(left):
            open_slots = np.nonzero(lists.reshape(-1) == -1)[0]
            take = open_slots[: len(left)]
            lists.reshape(-1)[take] = left
    return IvfIndex(centroids=cents, lists=lists, nlist=nlist, cap=cap,
                    default_nprobe=default_nprobe)
