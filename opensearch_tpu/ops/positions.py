"""Device-side positional joins: phrase / span-near matching on the TPU.

Replaces Lucene's ExactPhraseMatcher / SloppyPhraseMatcher doc-at-a-time
position merging (reference: `search/` via Lucene PhraseQuery,
SpanNearQuery) with a fully vectorized formulation:

- Each query term i carries a flat, lexicographically sorted array of
  (doc_id, position - i) pairs for the whole segment (built on the host from
  the CSR positional postings; padded to pow2 with an INT32_MAX sentinel).
- Term 0's pairs are the *candidate anchors*. For every anchor (d, base) we
  binary-search each other term's array for the nearest adjusted position in
  the same doc; the per-term displacement |p_adj - base| is that term's move
  cost. A phrase occurrence exists when every term occurs in the doc and the
  total move cost <= slop (exact phrase: slop 0 forces full adjacency).
- The per-anchor weight 1/(1+cost) is Lucene's sloppyFreq; scatter-adding it
  per doc yields the phrase frequency that feeds the normal BM25 tf curve.

Everything is static-shaped: the binary search is a statically unrolled
log2(N) loop of gathers (compare on (doc, pos) i32 pairs — no 64-bit keys
needed), so one XLA program serves all phrase queries with equal bucket
shapes.

Semantics note (documented deviation): Lucene's SloppyPhraseMatcher computes
the minimal *total* movement over a simultaneous alignment, with repeats
handled via restarts. The per-term nearest-position relaxation here equals it
whenever terms don't compete for the same position (the overwhelmingly common
case) and is otherwise a superset that still respects the total-slop bound
per anchor.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INT32_SENTINEL = np.int32(2**31 - 1)
# plain numpy scalar, NOT jnp: a module-level jax.Array would be captured as
# a device-resident trace constant, which the jit fast path can hoist into an
# extra executable parameter and then under-supply buffers on cache hits
BIG_COST = np.float32(1e9)


def pair_searchsorted(dA: jnp.ndarray, pA: jnp.ndarray,
                      dq: jnp.ndarray, pq: jnp.ndarray) -> jnp.ndarray:
    """Index of the first element of the lex-sorted pair array (dA, pA) that
    is >= (dq, pq), vectorized over queries. Statically unrolled binary
    search: log2(N)+1 rounds of 2 gathers each."""
    n = dA.shape[0]
    lo = jnp.zeros(dq.shape, jnp.int32)
    hi = jnp.full(dq.shape, n, jnp.int32)
    for _ in range(int(n).bit_length()):
        mid = (lo + hi) >> 1
        m = jnp.minimum(mid, n - 1)
        dm = dA[m]
        pm = pA[m]
        less = (dm < dq) | ((dm == dq) & (pm < pq))
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
    return lo


def nearest_delta(dA: jnp.ndarray, pA: jnp.ndarray,
                  d0: jnp.ndarray, base: jnp.ndarray, shift=0):
    """Signed displacement (adjusted position - base) of the term occurrence
    nearest to the anchor within the anchor's doc, and a found flag.
    `shift` is the query-position offset of this term: the pair arrays stay
    RAW (device-resident per segment term), adjusted position = pA - shift —
    shipping pre-shifted copies per query would re-upload megabytes of
    positions on every search."""
    n = dA.shape[0]
    idx = pair_searchsorted(dA, pA, d0, base + shift)
    ridx = jnp.minimum(idx, n - 1)
    right_ok = (idx < n) & (dA[ridx] == d0)
    right_delta = (pA[ridx] - shift - base).astype(jnp.float32)
    right_cost = jnp.where(right_ok, right_delta, BIG_COST)
    lidx = jnp.maximum(idx - 1, 0)
    left_ok = (idx > 0) & (dA[lidx] == d0)
    left_delta = (pA[lidx] - shift - base).astype(jnp.float32)
    left_cost = jnp.where(left_ok, -left_delta, BIG_COST)
    delta = jnp.where(right_cost <= left_cost, right_delta, left_delta)
    return delta, right_ok | left_ok


def phrase_freqs(anchor_d: jnp.ndarray, anchor_p: jnp.ndarray,
                 others: List[Tuple[jnp.ndarray, jnp.ndarray]],
                 slop: jnp.ndarray, ndocs_pad: int,
                 ordered: bool = False, gap_cost: bool = False,
                 shifts: Optional[List] = None) -> jnp.ndarray:
    """Dense per-doc sloppy phrase frequency f32[ndocs_pad].

    anchor_d/anchor_p: term 0's (doc, adjusted position) pairs (sentinel
    padded). others: the remaining terms' sorted pair arrays.

    Cost of an occurrence, compared against `slop`:
    - default (match_phrase slop): total movement against the OPTIMAL common
      offset, min_s Σ|delta_i - s| — attained at the median of the per-term
      deltas — matching Lucene SloppyPhraseMatcher's "total movement" slop
      (all terms may move, e.g. `quick and nimble brown fox` vs `quick brown
      fox` costs 2, not 4, because brown+fox stay put and quick moves).
    - gap_cost=True (span_near slop / intervals max_gaps): positions inside
      the matched span not covered by a query term (span_width - m) — so an
      adjacent transposition costs 0 gaps but 2 moves.

    `ordered` (span_near in_order / intervals ordered) switches to a greedy
    sequential join: term i takes its EARLIEST adjusted position >= term
    i-1's (pos_i > pos_{i-1} in absolute terms). Greedy-earliest is exact for
    ordered existence anchored at each term-0 occurrence, and the resulting
    gap count is simply the last delta. Ordered implies gap cost (both its
    callers are span-family queries)."""
    ok = anchor_d != INT32_SENTINEL
    m = len(others) + 1
    if shifts is None:
        shifts = [0] * len(others)
    if ordered:
        prev = jnp.zeros(anchor_p.shape, jnp.int32)  # delta_0 = 0
        for (dA, pA), sh in zip(others, shifts):
            n = dA.shape[0]
            idx = pair_searchsorted(dA, pA, anchor_d, anchor_p + prev + sh)
            safe = jnp.minimum(idx, n - 1)
            found = (idx < n) & (dA[safe] == anchor_d)
            prev = pA[safe] - sh - anchor_p
            ok = ok & found
        cost = prev.astype(jnp.float32)  # = pos_last - pos_0 + 1 - m = gaps
    elif m > 1:
        deltas = [jnp.zeros(anchor_d.shape, jnp.float32)]
        for (dA, pA), sh in zip(others, shifts):
            di, found = nearest_delta(dA, pA, anchor_d, anchor_p, sh)
            ok = ok & found
            deltas.append(di)
        if gap_cost:
            # unordered gaps: span width over nearest-per-term choices — a
            # superset-leaning heuristic (exact when terms don't compete)
            abs_off = [di + jnp.float32(i) for i, di in enumerate(deltas)]
            span_hi = abs_off[0]
            span_lo = abs_off[0]
            for a in abs_off[1:]:
                span_hi = jnp.maximum(span_hi, a)
                span_lo = jnp.minimum(span_lo, a)
            cost = span_hi - span_lo + 1.0 - jnp.float32(m)
        else:
            stacked = jnp.sort(jnp.stack(deltas, axis=0), axis=0)
            med = stacked[m // 2]
            cost = jnp.zeros(anchor_d.shape, jnp.float32)
            for di in deltas:
                cost = cost + jnp.abs(di - med)
    else:
        cost = jnp.zeros(anchor_d.shape, jnp.float32)
    ok = ok & (cost <= slop)
    w = jnp.where(ok, 1.0 / (1.0 + cost), 0.0)  # Lucene sloppyFreq
    return jnp.zeros(ndocs_pad, jnp.float32).at[anchor_d].add(w, mode="drop")


def phrase_score(freq: jnp.ndarray, dl: jnp.ndarray, live: jnp.ndarray,
                 weight: jnp.ndarray, k1: float, b: float,
                 avgdl: jnp.ndarray):
    """BM25 over the phrase frequency: weight = sum of the terms' idf*boost
    (Lucene PhraseWeight scores the phrase as one pseudo-term)."""
    k = k1 * (1.0 - b + b * dl / avgdl)
    scores = weight * freq / (freq + k)
    matched = (freq > 0) & (live > 0)
    return jnp.where(matched, scores, 0.0), matched
