"""Developer tooling that ships with the repo but never runs in serving
paths: static analysis (`oslint`), future codegen/bench helpers."""
