"""oslint core: finding model, suppression comments, baseline files, and
the file/checker driver.

The linter encodes this repo's unwritten invariants as AST checks (see
docs/STATIC_ANALYSIS.md). Design rules:

- Findings carry a *stable fingerprint* (rule, path, enclosing symbol,
  detail) rather than a line number, so baselines survive unrelated edits;
  each baseline entry also records how many findings share the
  fingerprint, so an ADDITIONAL same-rule violation in a baselined
  symbol still fails the gate (count ratchet).
- Pre-existing findings are TRIAGED, not silenced: the checked-in baseline
  records a justification per entry, and `--check` fails only on findings
  absent from it.
- Inline escapes use `# oslint: disable=OSL101 -- why` on the flagged line.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*oslint:\s*disable(?:=([A-Za-z0-9_, ]+))?")


@dataclass(frozen=True)
class Finding:
    rule: str          # e.g. "OSL101"
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    symbol: str        # enclosing qualname ("" at module level)
    msg: str
    detail: str = ""   # short stable discriminator for the fingerprint

    @property
    def fingerprint(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol, self.detail)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}{sym} {self.msg}")


class Checker:
    """Base class: subclasses set `rules` and implement `check`."""

    rules: Tuple[str, ...] = ()
    name = "checker"

    def applies(self, path: str) -> bool:
        return True

    def check(self, tree: ast.Module, path: str,
              src: str) -> List[Finding]:  # pragma: no cover - interface
        raise NotImplementedError


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('np.float32', 'float');
    '' when the base is not a plain Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def qualname_map(tree: ast.Module) -> Dict[ast.AST, str]:
    """node -> enclosing dotted qualname for every function/class body
    node (the node OF a def maps to that def's qualname)."""
    out: Dict[ast.AST, str] = {}

    def visit(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = stack + [child.name]
                out[child] = ".".join(sub)
                visit(child, sub)
            else:
                out[child] = ".".join(stack)
                visit(child, stack)

    visit(tree, [])
    return out


def enclosing_symbol(qmap: Dict[ast.AST, str], node: ast.AST) -> str:
    return qmap.get(node, "")


def parse_suppressions(src: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule set (None = all rules) from
    `# oslint: disable[=RULE[,RULE]]` comments."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {r.strip().upper() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def _suppressed(f: Finding, sup: Dict[int, Optional[Set[str]]]) -> bool:
    rules = sup.get(f.line, False)
    if rules is False:
        return False
    return rules is None or f.rule in rules


def default_checkers() -> List[Checker]:
    from .actuator_rules import ActuatorDisciplineChecker
    from .breaker_rules import BreakerDisciplineChecker
    from .dtype_rules import DtypeDisciplineChecker
    from .fusion_rules import FusionDomainChecker
    from .impact_rules import ImpactDomainChecker
    from .ingest_obs_rules import IngestObsDisciplineChecker
    from .insights_rules import InsightsCardinalityChecker
    from .jit_rules import JitBoundaryChecker
    from .lock_rules import LockDisciplineChecker, WaitDisciplineChecker
    from .memory_rules import MemoryAccountingChecker
    from .recorder_rules import RecorderDisciplineChecker
    from .rpc_rules import RpcDisciplineChecker
    from .sampler_rules import SamplerDisciplineChecker
    from .score_plane_rules import ScorePlaneChecker
    from .sync_rules import DeviceSyncDisciplineChecker
    from .telemetry_rules import TelemetryDisciplineChecker
    return [DtypeDisciplineChecker(), JitBoundaryChecker(),
            BreakerDisciplineChecker(), LockDisciplineChecker(),
            TelemetryDisciplineChecker(), WaitDisciplineChecker(),
            DeviceSyncDisciplineChecker(), RecorderDisciplineChecker(),
            MemoryAccountingChecker(), ImpactDomainChecker(),
            RpcDisciplineChecker(), SamplerDisciplineChecker(),
            ScorePlaneChecker(), InsightsCardinalityChecker(),
            ActuatorDisciplineChecker(), FusionDomainChecker(),
            IngestObsDisciplineChecker()]


def run_source(src: str, path: str,
               checkers: Optional[Sequence[Checker]] = None
               ) -> List[Finding]:
    """Lint one file's source. `path` is the repo-relative posix path the
    scope filters and fingerprints use."""
    checkers = list(checkers) if checkers is not None else default_checkers()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("OSL000", path, e.lineno or 1, 0, "",
                        f"syntax error: {e.msg}", "syntax")]
    sup = parse_suppressions(src)
    findings: List[Finding] = []
    for ch in checkers:
        if ch.applies(path):
            findings.extend(ch.check(tree, path, src))
    findings = [f for f in findings if not _suppressed(f, sup)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return findings


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run_paths(paths: Sequence[str], repo_root: str,
              checkers: Optional[Sequence[Checker]] = None,
              program: Optional[bool] = None) -> List[Finding]:
    """Per-file rules over `paths`, plus — when `program` is true, or
    left None and a path covers the whole opensearch_tpu package — the
    interprocedural OSL7xx concurrency pass, which only makes sense
    with the full package in view (scripts/oslint.py --changed turns it
    off explicitly)."""
    files: List[str] = []
    whole_package = False
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if os.path.isdir(ap):
            files.extend(iter_py_files(ap))
            if os.path.basename(os.path.normpath(ap)) == "opensearch_tpu":
                whole_package = True
        else:
            files.append(ap)
    findings: List[Finding] = []
    for f in files:
        rel = os.path.relpath(f, repo_root).replace(os.sep, "/")
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(run_source(src, rel, checkers))
    if program or (program is None and whole_package):
        from .concurrency import run_program_scope  # cycle-free: lazy
        findings.extend(run_program_scope(repo_root))
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return findings


# --------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------

def _entry_fp(e: dict) -> Tuple[str, str, str, str]:
    return (e["rule"], e["path"], e.get("symbol", ""), e.get("detail", ""))


@dataclass
class Baseline:
    """Fingerprints are line-free, so several same-rule findings in one
    symbol share one; each entry therefore also records the triaged
    `count`, and the gate is a RATCHET: more occurrences of a baselined
    fingerprint than triaged is a new finding, fewer marks the entry
    stale so the count (and eventually the entry) shrinks."""

    entries: List[dict] = field(default_factory=list)

    def fingerprints(self) -> Set[Tuple[str, str, str, str]]:
        return {_entry_fp(e) for e in self.entries}

    def counts(self) -> Dict[Tuple[str, str, str, str], int]:
        return {_entry_fp(e): int(e.get("count", 1)) for e in self.entries}

    def new_findings(self, findings: Sequence[Finding]) -> List[Finding]:
        allowed = self.counts()
        by_fp: Dict[Tuple[str, str, str, str], List[Finding]] = {}
        for f in findings:
            by_fp.setdefault(f.fingerprint, []).append(f)
        out: List[Finding] = []
        for fp, fs in by_fp.items():
            extra = len(fs) - allowed.get(fp, 0)
            if extra > 0:
                # report the excess occurrences (last in line order —
                # WHICH ones are new is unknowable without line-stable
                # identity, but the count regression is the signal)
                out.extend(sorted(fs, key=lambda f: f.line)[-extra:])
        return out

    def stale_entries(self, findings: Sequence[Finding]) -> List[dict]:
        """Baseline entries firing FEWER times than triaged (candidates
        for count shrink or removal — the debt was paid)."""
        live: Dict[Tuple[str, str, str, str], int] = {}
        for f in findings:
            live[f.fingerprint] = live.get(f.fingerprint, 0) + 1
        return [e for e in self.entries
                if live.get(_entry_fp(e), 0) < int(e.get("count", 1))]


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return Baseline(entries=list(data.get("entries", [])))


def write_baseline(findings: Sequence[Finding], path: str,
                   reasons: Optional[Dict[Tuple[str, str, str, str],
                                          str]] = None) -> None:
    counts: Dict[Tuple[str, str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    entries = []
    for fp in sorted(counts):
        rule, path_, symbol, detail = fp
        entries.append({
            "rule": rule, "path": path_, "symbol": symbol,
            "detail": detail, "count": counts[fp],
            "reason": (reasons or {}).get(fp, "TRIAGE: justify or fix"),
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2,
                  sort_keys=False)
        fh.write("\n")
