"""OSL506 — memory-accounting discipline: the HBM ledger is the sole
breaker-charge path, and device residency never appears untracked.

The ledger (`obs/hbm_ledger.py`) derives every circuit-breaker charge
from an attributed allocation, which is what keeps the standing invariant
`sum(live charged ledger bytes) == breaker.used` provable and the
per-tenant residency rollups (`_nodes/stats` "hbm", `_cat/segments`)
complete. Two ways code can silently break that:

1. **Direct breaker charges.** Any `*.add_estimate(...)` call, or a
   `.release(...)` call on a breaker-named object, outside the ledger
   module (`obs/hbm_ledger.py`) and the breaker definition itself
   (`utils/breaker.py`) bypasses attribution — the bytes exist on the
   breaker but no tenant owns them, so the invariant fails and the
   rollups lie.

2. **Unregistered device residency.** A `jax.device_put(...)` call in
   `index/`, `search/` or `parallel/` moves host bytes into HBM; when the
   enclosing function scope never references the ledger (any name or
   attribute containing "ledger", e.g. `LEDGER.register(...)`), the
   residency is invisible to the byte-domain accounting. The rule is
   deliberately loose (condition: *mentions* the ledger, not *charges
   correctly*) — its job is to force the author to THINK about
   attribution, same contract as OSL301.

Transfer helpers whose CALLERS register (e.g. `_DevicePut.asarray`) and
jit-argument uploads that are transient by construction suppress with
`# oslint: disable=OSL506 -- <why the bytes are tracked or transient>`.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

# files allowed to touch the breaker API directly: the ledger (the sole
# derivation path) and the breaker definition itself
_CHARGE_EXEMPT = ("obs/hbm_ledger.py", "utils/breaker.py")

# device-residency scope: the layers that build resident device arrays
_RESIDENCY_SCOPES = ("opensearch_tpu/index/", "opensearch_tpu/search/",
                     "opensearch_tpu/parallel/")


class MemoryAccountingChecker(Checker):
    rules = ("OSL506",)
    name = "memory-accounting"

    def applies(self, path: str) -> bool:
        return path.startswith("opensearch_tpu/") \
            and "devtools" not in path

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        charge_ok = any(path.endswith(e) for e in _CHARGE_EXEMPT)

        # ---- rule 1: direct breaker charges outside the ledger ----
        if not charge_ok:
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                base = _dotted(node.func.value)
                if attr == "add_estimate" or (
                        attr == "release" and "breaker" in base.lower()):
                    findings.append(Finding(
                        "OSL506", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        f"direct breaker charge (`{attr}`) outside the "
                        "HBM ledger; register an attributed allocation "
                        "via `LEDGER.register(kind, nbytes, ...)` "
                        "(obs/hbm_ledger.py) so the charge is derived "
                        "and the ledger↔breaker invariant holds",
                        detail=f"charge:{attr}"))

        # ---- rule 2: device_put without a ledger reference in scope ----
        if not any(s in path for s in _RESIDENCY_SCOPES):
            return findings
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            mentions_ledger = False
            puts: List[ast.Call] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and \
                        "ledger" in node.id.lower():
                    mentions_ledger = True
                elif isinstance(node, ast.Attribute) and \
                        "ledger" in node.attr.lower():
                    mentions_ledger = True
                elif isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    if d.rsplit(".", 1)[-1] == "device_put":
                        puts.append(node)
            if puts and not mentions_ledger:
                sym = qmap.get(fn, fn.name)
                for p in puts:
                    findings.append(Finding(
                        "OSL506", path, p.lineno, p.col_offset, sym,
                        "device residency (`jax.device_put`) without a "
                        "ledger registration in the enclosing scope; "
                        "register the bytes with "
                        "`LEDGER.register(kind, nbytes, owner=...)` or "
                        "justify why they are tracked elsewhere",
                        detail=f"device_put@{sym}"))
        return findings
