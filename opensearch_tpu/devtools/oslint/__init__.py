"""oslint — AST-based host/device discipline linter for opensearch_tpu.

Four checkers tailored to this repo's failure modes (see
docs/STATIC_ANALYSIS.md for rationale and ADVICE.md lineage):

- OSL101/OSL102 dtype-discipline (`dtype_rules`): float domain mixing in
  score comparisons; float-rounded count planes.
- OSL201/OSL202/OSL203 jit-boundary (`jit_rules`): traced-value branches,
  host syncs, nondeterminism inside jit/shard_map/Pallas code.
- OSL301 breaker-discipline (`breaker_rules`): ndocs-scale host caches
  without a memory-breaker charge/release.
- OSL401/OSL402 lock-discipline (`lock_rules`): attributes mutated both
  under and outside a lock; lock-order inversions.
- OSL501/OSL502 telemetry-discipline (`telemetry_rules`): wall-clock
  duration subtraction; module-level counter-dict `+=` in hot paths.
- OSL503 wait-discipline (`lock_rules`): sleep-polling loops in serving
  hot paths.
- OSL504 device-sync discipline (`sync_rules`): blocking device syncs
  (`jax.device_get`, `block_until_ready`, device-named `np.asarray`)
  inside launch-stage code — the static guard on the pipelined
  launch/fetch split (docs/SERVING.md).
- OSL505 recorder/slowlog emission discipline (`recorder_rules`).
- OSL506 memory-accounting discipline (`memory_rules`): direct breaker
  `add_estimate`/`release` outside the HBM ledger; `jax.device_put`
  residency in index/search/parallel without a ledger registration in
  the enclosing scope.
- OSL508 RPC-path discipline (`rpc_rules`): no unbounded wire calls and
  no silently-swallowed transport errors in `cluster/`.
- OSL507 quantized-impact domain discipline (`impact_rules`): u8/u16
  impact planes enter f32 score math only through the designated
  dequant helpers; codec-version branches in search/ consult
  Segment.codec_version and use the named codec constants.
- OSL603 actuator discipline (`actuator_rules`): every
  remediation/shed/deprioritize engage site in serving/ or cluster/
  carries a paired release path or TTL bound in file — bounded,
  reversible actions only (docs/RESILIENCE.md "Self-healing loop").
- OSL604 fusion score-domain discipline (`fusion_rules`): linear
  combinations of sub-query scores in fusion-shaped functions pass
  through a designated normalizer (fusion.normalize_scores) or fuse in
  the rank domain (RRF) — raw BM25/cosine/sparse-dot scores are
  incomparable (docs/HYBRID.md).
- OSL605 write-path emission discipline (`ingest_obs_rules`):
  wall-clock duration subtraction / in-loop `time.time()`,
  per-iteration metric-registry emission, and unguarded recorder
  events in `index/` + `ingest/` — the ingest observatory's contract
  that hot modules call one guarded helper (docs/OBSERVABILITY.md
  "Ingest observatory").
- OSL701-OSL704 whole-program concurrency suite (`concurrency/`):
  unlike every rule above, these run INTERPROCEDURALLY over the full
  package — a lock inventory with alias resolution, a call-graph walk
  of lock regions, and fixpoint may-acquire/may-block summaries.
  OSL701 lock-order cycles (potential deadlock) + non-reentrant
  re-acquire; OSL702 locks held across blocking ops (RPC sends, device
  syncs, sleeps, foreign waits); OSL703 cross-thread unlocked attribute
  writes; OSL704 check-then-act atomicity splits. The derived
  lock-order graph is committed as `lock_order.json` (ratcheted by
  tier-1) and validated at runtime by devtools/lockwitness.py.

Run via `python scripts/oslint.py [--check]`; tier-1 runs it through
tests/test_oslint.py. Suppress inline with
`# oslint: disable=RULE -- justification`, or triage pre-existing debt in
the checked-in `oslint_baseline.json`.
"""

from .actuator_rules import ActuatorDisciplineChecker
from .breaker_rules import BreakerDisciplineChecker
from .concurrency import (CONCURRENCY_RULES, build_lock_order,
                          build_program, diff_lock_order,
                          run_program_scope)
from .core import (Baseline, Checker, Finding, default_checkers,
                   load_baseline, run_paths, run_source, write_baseline)
from .dtype_rules import DtypeDisciplineChecker
from .fusion_rules import FusionDomainChecker
from .impact_rules import ImpactDomainChecker
from .ingest_obs_rules import IngestObsDisciplineChecker
from .insights_rules import InsightsCardinalityChecker
from .jit_rules import JitBoundaryChecker
from .lock_rules import LockDisciplineChecker
from .memory_rules import MemoryAccountingChecker
from .sync_rules import DeviceSyncDisciplineChecker

__all__ = [
    "Baseline", "Checker", "Finding", "default_checkers", "load_baseline",
    "run_paths", "run_source", "write_baseline",
    "DtypeDisciplineChecker", "FusionDomainChecker",
    "JitBoundaryChecker",
    "BreakerDisciplineChecker", "LockDisciplineChecker",
    "DeviceSyncDisciplineChecker", "MemoryAccountingChecker",
    "ImpactDomainChecker", "IngestObsDisciplineChecker",
    "InsightsCardinalityChecker",
    "ActuatorDisciplineChecker",
    "CONCURRENCY_RULES", "build_lock_order", "build_program",
    "diff_lock_order", "run_program_scope",
]
