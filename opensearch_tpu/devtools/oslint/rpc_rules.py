"""OSL508 — RPC-path discipline for the cluster transport layer.

The resilience layer (docs/RESILIENCE.md) only holds if every wire call
in `cluster/` is deadline-bounded and every RPC failure is ACCOUNTED —
a single unbounded `urlopen` reintroduces the 30 s-stall class the
deadline ladder exists to kill, and a swallowed transport error is a
shard failure the response never reports. Two shapes:

1. **Unbounded wire call.** `urllib.request.urlopen(...)` (any alias
   spelling ending in `urlopen`) or `socket.create_connection(...)` in
   `cluster/` without an explicit `timeout=` keyword. The timeout must
   exist syntactically — deriving it from the deadline is the helper's
   job (`_http` / `Deadline.rpc_timeout_s`), the rule just refuses the
   unbounded default.

2. **Swallowed RPC error.** An `except` handler in `cluster/` whose
   type mentions a transport error (URLError / HTTPError / OSError /
   ConnectionError / TimeoutError / socket.timeout) and whose body is
   ONLY `pass`/`continue`/`...` — no call, no raise, no assignment:
   nothing recorded a shard failure, a metric, or an event, so the
   failure is invisible. Recording a counter (`METRICS.counter(...)
   .inc()`), re-raising, or stashing the error all satisfy the rule.

Genuinely fire-and-forget sites suppress with
`# oslint: disable=OSL508 -- <why the loss is accounted elsewhere>`.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_SCOPE = "opensearch_tpu/cluster/"

_TRANSPORT_ERRS = ("URLError", "HTTPError", "OSError", "ConnectionError",
                   "TimeoutError", "timeout")


def _mentions_transport_err(type_node) -> bool:
    if type_node is None:
        return True          # bare except swallows transport errors too
    names: List[str] = []
    nodes = (list(type_node.elts) if isinstance(type_node, ast.Tuple)
             else [type_node])
    for n in nodes:
        d = _dotted(n)
        if d:
            names.append(d.rsplit(".", 1)[-1])
    return any(n in _TRANSPORT_ERRS for n in names)


def _body_is_silent(body) -> bool:
    """True when the handler does nothing observable: only pass /
    continue / bare-ellipsis statements."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant) and \
                stmt.value.value is Ellipsis:
            continue
        return False
    return True


class RpcDisciplineChecker(Checker):
    rules = ("OSL508",)
    name = "rpc-discipline"

    def applies(self, path: str) -> bool:
        return path.startswith(_SCOPE)

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                leaf = d.rsplit(".", 1)[-1]
                is_wire = (leaf == "urlopen"
                           or d.endswith("socket.create_connection")
                           or leaf == "create_connection")
                if is_wire and not any(kw.arg == "timeout"
                                       for kw in node.keywords):
                    findings.append(Finding(
                        "OSL508", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        f"unbounded wire call (`{leaf}` without "
                        "`timeout=`): every cluster RPC must derive its "
                        "socket timeout from the request deadline "
                        "(utils/deadline.py rpc_timeout_s) or an "
                        "explicit cap",
                        detail=f"no-timeout:{leaf}"))
            elif isinstance(node, ast.ExceptHandler):
                if _mentions_transport_err(node.type) \
                        and _body_is_silent(node.body):
                    findings.append(Finding(
                        "OSL508", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        "swallowed RPC error: a transport failure in "
                        "cluster/ must record a shard failure, a "
                        "metric, or a flight-recorder event before "
                        "being dropped",
                        detail="swallowed-rpc-error"))
        return findings
