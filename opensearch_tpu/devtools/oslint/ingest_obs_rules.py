"""OSL605 — write-path emission discipline.

The ingest observatory (obs/ingest_obs.py) threads counters, gauges,
and DDSketch histograms through bulk accept, refresh, merge, translog,
and replica fan-out. Those are the hottest loops the engine owns — a
refresh walks every buffered doc, a merge walks every segment — so the
instrumentation contract is strict: hot modules take timestamps and
call ONE guarded emission helper; the loops over metric names live in
obs/ where OSL605 does not look.

Three ways a write-path emission site quietly breaks that contract:

- **Wall-clock durations.** A `time.time()` subtraction (or any
  `time.time()` call inside a `for`/`while` body) measures a duration
  with a clock NTP can step. Stage attribution that must sum to total
  refresh wall time cannot survive a negative stage. Durations come
  from `time.perf_counter()`/`time.monotonic()`; wall time is for
  metadata stamps only, outside loops.
- **Per-iteration metric emission.** `METRICS.counter(...).inc()` (or
  `.histogram(...).record(...)`, `.gauge(...).set(...)`) inside a loop
  body pays a registry lock + dict lookup per element. Hoist the
  handle, accumulate locally and emit once after the loop, or use the
  vectorized `record_many`. The ONE sanctioned in-loop form is
  `_iobs.count(...)` — it checks the observatory's enabled flag before
  touching the registry, which is the whole point.
- **Unguarded event emission.** A flight-recorder event call
  (`.record` with >= 2 positional args or any keyword) builds its
  payload dict before the callee can check `enabled`. Same contract as
  OSL505, extended to the write path: guard with `if ...enabled:` or
  `if <timeline>:`.

Scope is `index/` and `ingest/`; `obs/` and `devtools/` are exempt
(the emission helpers and this checker's own fixtures live there).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

# registry-emission attribute terminals: the lookup half and the
# emission half of a `METRICS.counter("x").inc()` chain
_REGISTRY_LOOKUPS = ("counter", "histogram", "gauge")


def _contains_enabled(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr == "enabled":
            return True
        if isinstance(n, ast.Call) and _dotted(n.func).endswith("enabled"):
            return True
    return False


def _test_names(test: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            d = _dotted(n)
            if d:
                out.add(d)
    return out


def _first_arg_name(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Name):
        return a.id
    if isinstance(a, ast.Attribute):
        return _dotted(a) or None
    return None


class IngestObsDisciplineChecker(Checker):
    rules = ("OSL605",)
    name = "ingest-obs-discipline"

    SCOPES = ("index/", "ingest/")
    EXEMPT = ("obs/", "devtools/")

    def applies(self, path: str) -> bool:
        if any(s in path for s in self.EXEMPT):
            return False
        return any(s in path for s in self.SCOPES)

    # ---------------- helpers ----------------

    @staticmethod
    def _time_aliases(tree: ast.Module):
        mods: Set[str] = set()
        funcs: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mods.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        funcs.add(a.asname or "time")
        return mods, funcs

    def _is_walltime(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = _dotted(node.func)
        if d in self._funcs:
            return True
        head, _, tail = d.rpartition(".")
        return tail == "time" and head in self._mods

    def _walltime_within(self, node: ast.AST) -> bool:
        return any(self._is_walltime(n) for n in ast.walk(node))

    @staticmethod
    def _is_registry_emission(node: ast.Call) -> bool:
        """A `METRICS.counter("x")` lookup, or an `.inc`/`.record`/`.set`
        chained directly off one. The chained form reports at the
        emission site; the bare-lookup form catches the hoistable
        handle being re-fetched each iteration."""
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return False
        if fn.attr in _REGISTRY_LOOKUPS:
            base = _dotted(fn.value)
            return base.split(".")[-1] == "METRICS" or base.endswith("registry")
        if fn.attr in ("inc", "record", "set"):
            inner = fn.value
            return (isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr in _REGISTRY_LOOKUPS)
        return False

    @staticmethod
    def _is_sanctioned_count(node: ast.Call) -> bool:
        """`_iobs.count(...)` / `ingest_obs.count(...)` — the guarded
        loop-safe form (it reads the enabled flag before the registry)."""
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "count"):
            return False
        base = _dotted(fn.value).split(".")[-1]
        return base in ("_iobs", "iobs", "ingest_obs")

    @staticmethod
    def _is_event_record(node: ast.Call) -> bool:
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and (len(node.args) >= 2 or bool(node.keywords)))

    # ---------------- check ----------------

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        self._mods, self._funcs = self._time_aliases(tree)

        # ancestor Call chains, so a chained `METRICS.counter("x").inc()`
        # reports once (at the outer emission call), not twice
        _parents = {}

        def link(node: ast.AST, chain: List[ast.Call]) -> None:
            nxt = chain + [node] if isinstance(node, ast.Call) else chain
            for child in ast.iter_child_nodes(node):
                _parents[id(child)] = nxt
                link(child, nxt)

        link(tree, [])

        def visit(node: ast.AST, guards: List[ast.AST],
                  loop_depth: int) -> None:
            if isinstance(node, ast.If):
                for child in node.body:
                    visit(child, guards + [node.test], loop_depth)
                for child in node.orelse:
                    visit(child, guards, loop_depth)
                return
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                # the iterable/test evaluates once; only the body loops
                for child in node.body + node.orelse:
                    visit(child, guards, loop_depth + 1)
                return

            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if (self._walltime_within(node.left)
                        or self._walltime_within(node.right)):
                    findings.append(Finding(
                        "OSL605", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        "duration computed by subtracting time.time() — "
                        "write-path stage attribution must use "
                        "time.perf_counter()/time.monotonic(); wall time "
                        "is for metadata stamps only",
                        detail="walltime-duration"))

            if isinstance(node, ast.Call):
                if loop_depth > 0 and self._is_walltime(node):
                    findings.append(Finding(
                        "OSL605", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        "time.time() inside a write-path loop body — "
                        "per-element stamps must be monotonic "
                        "(time.monotonic/perf_counter); one wall anchor "
                        "lives outside the loop",
                        detail="walltime-in-loop"))
                if (loop_depth > 0 and self._is_registry_emission(node)
                        and not self._is_sanctioned_count(node)
                        and not any(isinstance(p, ast.Call)
                                    and self._is_registry_emission(p)
                                    for p in _parents.get(id(node), []))):
                    findings.append(Finding(
                        "OSL605", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        "metric registry emission inside a write-path "
                        "loop — hoist the handle / accumulate and emit "
                        "once after the loop (or record_many); the "
                        "guarded `_iobs.count(...)` is the one "
                        "sanctioned in-loop form",
                        detail="metric-in-loop"))
                if self._is_event_record(node):
                    tl_name = _first_arg_name(node)
                    guarded = any(
                        _contains_enabled(t)
                        or (tl_name is not None
                            and tl_name in _test_names(t))
                        for t in guards)
                    if not guarded:
                        findings.append(Finding(
                            "OSL605", path, node.lineno, node.col_offset,
                            qmap.get(node, ""),
                            "flight-recorder event on the write path "
                            "without an enabled/timeline guard — the "
                            "payload dict is built even when the "
                            "recorder is off",
                            detail="unguarded-record"))

            for child in ast.iter_child_nodes(node):
                visit(child, guards, loop_depth)

        visit(tree, [], 0)
        findings.sort(key=lambda f: (f.line, f.detail))
        return findings
