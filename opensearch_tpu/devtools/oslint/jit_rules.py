"""OSL2xx — jit/trace boundary discipline.

Code that runs under `jax.jit`/`pjit`/`shard_map`/`vmap`/Pallas is TRACED:
its Python executes once with abstract values. Three failure modes this
repo must never reintroduce:

- OSL201: Python-level branching (`if`/`while`/conditional expressions) on
  a traced value — raises ConcretizationTypeError at runtime, or worse,
  silently bakes one branch into the compiled program.
- OSL202: host syncs — `float(x)`, `int(x)`, `bool(x)`, `np.asarray(x)`,
  `x.item()`, `x.tolist()` on traced values force a device->host transfer
  (and fail under jit).
- OSL203: nondeterminism — `time.*`, `random.*`, `np.random.*` inside a
  traced function executes at TRACE time only, so the compiled program
  freezes one sample forever (and replicas diverge across processes).

Traced contexts are found structurally: functions decorated with
jit/pjit (incl. `partial(jax.jit, ...)`), functions passed by name to
jit/pjit/vmap/pmap/shard_map/pallas_call/scan/cond/while_loop/fori_loop/
checkpoint/remat/grad, and every def nested inside one. `static_argnames`
params are exempt from taint. Shape/dtype/ndim reads, `len()`,
`isinstance()` and `is None` checks are trace-time-static and never
tainted.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_TRACING_FNS = {"jit", "pjit", "vmap", "pmap", "shard_map", "pallas_call",
                "scan", "cond", "while_loop", "fori_loop", "switch",
                "checkpoint", "remat", "grad", "value_and_grad",
                "custom_vjp", "custom_jvp"}
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding",
                 "weak_type", "itemsize", "nbytes"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                 "callable", "id", "repr", "str"}
_HOST_SYNC_CASTS = {"float", "int", "bool", "complex"}
_HOST_SYNC_NP = {"asarray", "array", "copy"}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NONDET_ROOTS = {"time", "random", "datetime"}


def _leaf(node: ast.AST) -> str:
    d = _dotted(node)
    return d.rsplit(".", 1)[-1] if d else ""


def _static_argnames(dec: ast.Call) -> Set[str]:
    """Literal static_argnames from a jit(...) / partial(jax.jit, ...)
    decorator call — best-effort, unknown forms yield the empty set."""
    out: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value,
                                                                  str):
                        out.add(e.value)
    return out


def _is_tracing_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(static_argnames=...)
        if _leaf(dec.func) == "partial" and dec.args:
            return _leaf(dec.args[0]) in ("jit", "pjit")
        return _leaf(dec.func) in ("jit", "pjit")
    return _leaf(dec) in ("jit", "pjit")


def _decorator_static_names(dec: ast.AST) -> Set[str]:
    if isinstance(dec, ast.Call):
        return _static_argnames(dec)
    return set()


class JitBoundaryChecker(Checker):
    rules = ("OSL201", "OSL202", "OSL203")
    name = "jit-boundary"

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)

        # pass 1: names passed into tracing transforms anywhere in the file
        traced_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    _leaf(node.func) in _TRACING_FNS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    traced_names.add(first.id)

        # pass 2: find traced FunctionDefs (decorated, or named above),
        # then lint each (nested defs inherit traced-ness)
        def visit(node: ast.AST, traced: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    is_traced = traced \
                        or child.name in traced_names \
                        or any(_is_tracing_decorator(d)
                               for d in child.decorator_list)
                    if is_traced and not traced:
                        static = set()
                        for d in child.decorator_list:
                            static |= _decorator_static_names(d)
                        self._lint_traced(child, qmap, path, findings,
                                          static)
                    visit(child, is_traced)
                else:
                    visit(child, traced)

        visit(tree, False)
        return findings

    # ---- taint over one traced function (incl. nested defs) ----

    def _lint_traced(self, fn: ast.FunctionDef, qmap, path: str,
                     findings: List[Finding],
                     static_names: Set[str]) -> None:
        tainted: Set[str] = set()
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a.arg not in static_names and a.arg != "self":
                tainted.add(a.arg)

        def taint(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return False
                return taint(e.value)
            if isinstance(e, ast.Call):
                if _dotted(e.func) in _STATIC_CALLS:
                    return False
                return (taint(e.func) or any(taint(a) for a in e.args)
                        or any(taint(k.value) for k in e.keywords))
            if isinstance(e, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in e.ops):
                    return False
                return taint(e.left) or any(taint(c)
                                            for c in e.comparators)
            if isinstance(e, ast.Constant):
                return False
            return any(taint(c) for c in ast.iter_child_nodes(e))

        def handle_nested_def(node: ast.FunctionDef) -> None:
            # a def inside a traced fn runs traced with the closure's
            # taint; its own params are traced values too (vmap/scan
            # bodies)
            for a in (node.args.posonlyargs + node.args.args
                      + node.args.kwonlyargs):
                tainted.add(a.arg)

        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                handle_nested_def(node)

        sym = qmap.get(fn, fn.name)
        # taint pass FIRST, to a fixpoint: ast.walk is breadth-first, so a
        # single interleaved pass would visit `if y > 0:` before the
        # deeper-nested `y = x * 2` that taints it
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and taint(node.value):
                    tgts = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                        and node.value is not None and taint(node.value):
                    tgts = [node.target]
                elif isinstance(node, ast.For) and taint(node.iter):
                    tgts = [node.target]
                else:
                    continue
                for t in tgts:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if taint(node.test):
                    findings.append(Finding(
                        "OSL201", path, node.lineno, node.col_offset, sym,
                        "Python-level branch on a traced value inside a "
                        "jit/traced function; use jnp.where / lax.cond",
                        detail=f"branch@{sym}"))
            elif isinstance(node, ast.IfExp):
                if taint(node.test):
                    findings.append(Finding(
                        "OSL201", path, node.lineno, node.col_offset, sym,
                        "conditional expression on a traced value inside "
                        "a jit/traced function; use jnp.where",
                        detail=f"ifexp@{sym}"))
            elif isinstance(node, ast.Call):
                self._check_call(node, path, sym, findings, taint)

    def _check_call(self, node: ast.Call, path: str, sym: str,
                    findings: List[Finding], taint) -> None:
        d = _dotted(node.func)
        leaf = d.rsplit(".", 1)[-1]
        root = d.split(".", 1)[0] if d else ""
        # OSL203 nondeterminism — flagged regardless of taint
        if root in _NONDET_ROOTS or d.startswith(("np.random.",
                                                  "numpy.random.")):
            findings.append(Finding(
                "OSL203", path, node.lineno, node.col_offset, sym,
                f"nondeterministic call `{d}` inside a traced function "
                "executes at trace time only (frozen into the compiled "
                "program); thread jax PRNG keys / timestamps in as "
                "arguments",
                detail=f"nondet:{d}@{sym}"))
            return
        # OSL202 host syncs on traced values
        arg_tainted = any(taint(a) for a in node.args)
        if d in _HOST_SYNC_CASTS and arg_tainted:
            findings.append(Finding(
                "OSL202", path, node.lineno, node.col_offset, sym,
                f"`{d}()` on a traced value forces a host sync and fails "
                "under jit; keep the value on-device",
                detail=f"sync:{d}@{sym}"))
        elif leaf in _HOST_SYNC_NP and root in ("np", "numpy") \
                and arg_tainted:
            findings.append(Finding(
                "OSL202", path, node.lineno, node.col_offset, sym,
                f"`{d}()` materializes a traced value on the host; use "
                "jnp equivalents inside traced code",
                detail=f"sync:{d}@{sym}"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HOST_SYNC_METHODS \
                and taint(node.func.value):
            findings.append(Finding(
                "OSL202", path, node.lineno, node.col_offset, sym,
                f"`.{node.func.attr}()` on a traced value is a host "
                "sync; not allowed inside traced code",
                detail=f"sync:{node.func.attr}@{sym}"))
