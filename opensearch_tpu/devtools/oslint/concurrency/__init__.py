"""oslint concurrency suite — whole-program lock analysis (ISSUE 16).

Where the OSL4xx lock rules are per-function pattern checks, this
package builds an interprocedural model of the entire package:

- `program.Program` inventories every lock object (threading.Lock /
  RLock / Condition / Semaphore, the `_BuildLock` hold-depth wrapper,
  module-level and instance-attribute locks), resolving aliases through
  attributes, constructor assignments, and local variables; walks each
  function with a lexical held-lock stack; resolves a best-effort call
  graph; and computes fixpoint may-acquire / may-block summaries.
- `rules` turns the model into findings:
    OSL701  lock-order cycle in the whole-program lock-order graph
            (potential deadlock), and reentrant re-acquire of a
            non-reentrant Lock (self-deadlock);
    OSL702  lock held across a blocking operation — device syncs
            (`jax.device_get` / `block_until_ready`), `/_internal` RPC
            sends (via `urlopen` reachability), `time.sleep`, and
            waits on foreign locks/events — the `_dispatch_lock`-class
            bug, generalized across call boundaries;
    OSL703  shared mutable attribute written without a lock from code
            reachable from more than one thread-entry root (dispatcher /
            completion / sampler / remediator / HTTP-handler threads);
    OSL704  check-then-act atomicity split on dict/deque attribute
            state in a lock-bearing class.
- `rules.build_lock_order` emits the reviewable `lock_order.json`
  artifact (nodes, acquired-while-held edges, cycles); tier-1 ratchets
  it — a new edge or cycle fails until the artifact is regenerated and
  any cycle justified.

The committed graph is validated at runtime by the lock-witness
sanitizer (`opensearch_tpu.devtools.lockwitness`), which records actual
acquisition orders during the 32-thread hammer tests and flags
inversions against this model. See docs/STATIC_ANALYSIS.md
("Concurrency suite").
"""

from .program import Program, build_program
from .rules import (CONCURRENCY_RULES, analyze, build_lock_order,
                    diff_lock_order, load_lock_order, program_files,
                    run_program, run_program_scope, write_lock_order)

__all__ = [
    "Program", "build_program", "analyze", "run_program",
    "run_program_scope", "program_files", "build_lock_order",
    "diff_lock_order", "load_lock_order", "write_lock_order",
    "CONCURRENCY_RULES",
]
