"""OSL7xx concurrency rules over the whole-program model, plus the
`lock_order.json` artifact (build / load / diff).

Rule family (see docs/STATIC_ANALYSIS.md "Concurrency suite"):

OSL701  potential deadlock — a cycle in the whole-program lock-order
        graph, or a lexical/interprocedural re-acquire of a
        non-reentrant `threading.Lock` (self-deadlock).
OSL702  lock held across a blocking operation: `time.sleep`, `urlopen`
        (every `/_internal` RPC send funnels through it), device syncs
        (`jax.device_get` / `block_until_ready`), waits on *foreign*
        condition variables / events, and thread joins. Waiting on a
        condition whose lock you hold is exempt (the wait releases it);
        semaphores are exempt (holding one across work is their job).
OSL703  cross-thread unlocked write: an instance attribute written
        without any lock from code reachable from two or more distinct
        thread-entry roots (Thread targets, listener callbacks, HTTP
        `do_*` handlers).
OSL704  check-then-act split: in a lock-bearing class, a container
        mutation (`self.d[k] = ...`, `self.q.popleft()`, `del`, ...)
        outside any lock region that is guarded by an earlier test of
        the same attribute — the test and the act are not atomic.

Findings go through the standard oslint triage pipeline: inline
`# oslint: disable=OSL70x -- why` suppressions and the count-ratcheted
baseline. The lock-order graph itself is ratcheted separately via
`build_lock_order` / `diff_lock_order` and the committed
`lock_order.json`.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import (Finding, _suppressed, iter_py_files,
                    parse_suppressions)
from .program import (SEMAPHORE_KINDS, Program, build_program, short_lock)

CONCURRENCY_RULES = ("OSL701", "OSL702", "OSL703", "OSL704")

UNJUSTIFIED = "UNJUSTIFIED: new cycle — break the order or justify here"


# --------------------------------------------------------------------
# rule emission
# --------------------------------------------------------------------

def _cycle_findings(prog: Program) -> List[Finding]:
    out: List[Finding] = []
    for cycle in prog.cycles():
        members = set(cycle)
        # deterministic anchor: smallest edge site inside the cycle
        sites = sorted(site for (a, b), site in prog.edges.items()
                       if a in members and b in members)
        path, qual, line, via = sites[0] if sites else ("", "", 1, ())
        shorts = [short_lock(m) for m in cycle]
        out.append(Finding(
            "OSL701", path, line, 0, qual,
            "lock-order cycle (potential deadlock): "
            + " -> ".join(shorts + [shorts[0]])
            + "; regenerate lock_order.json and justify or break the "
              "order",
            detail="cycle:" + "|".join(shorts)))
    for lid, (path, qual, line) in sorted(prog.self_edges.items()):
        out.append(Finding(
            "OSL701", path, line, 0, qual,
            f"re-acquire of non-reentrant Lock {short_lock(lid)} while "
            "already held (self-deadlock); use an RLock or a _locked "
            "variant",
            detail=f"self:{short_lock(lid)}"))
    return out


def _blocking_findings(prog: Program) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[str, str, str, str]] = set()

    def emit(fkey: Tuple[str, str], held: Tuple[str, ...],
             op: str, receiver: Optional[str], line: int,
             via: Tuple[str, ...]) -> None:
        f = prog.functions[fkey]
        for h in held:
            if h == receiver:
                continue  # cond.wait() releases the lock it guards
            if prog.lock_kind.get(h) in SEMAPHORE_KINDS:
                continue
            key = (f.path, f.qual, h, op)
            if key in seen:
                continue
            seen.add(key)
            chain = f" (via {' -> '.join(via)})" if via else ""
            out.append(Finding(
                "OSL702", f.path, line, 0, f.qual,
                f"{short_lock(h)} held across blocking {op}{chain}; "
                "snapshot under the lock, block outside it",
                detail=f"held:{short_lock(h)}~{op}"))

    for fkey in sorted(prog.functions):
        f = prog.functions[fkey]
        for b in f.blocks:
            if b.held:
                emit(fkey, b.held, b.op, b.receiver, b.line, ())
        for callee, c in prog.callees.get(fkey, []):
            if not c.held:
                continue
            for op, b in sorted(prog.may_block.get(callee, {}).items()):
                via = ((callee[1],) + b.chain)[:4]
                emit(fkey, c.held, op, b.receiver, c.line, via)
    return out


def _held_anywhere(prog: Program, fkey: Tuple[str, str]) -> bool:
    f = prog.functions[fkey]
    return f.assumed_held or fkey in prog.always_held


def _in_init(qual: str) -> bool:
    return qual.split(".<locals>")[0].endswith("__init__")


def _class_funcs(prog: Program) -> Dict[Tuple[str, str],
                                        List[Tuple[str, str]]]:
    out: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for key in sorted(prog.functions):
        f = prog.functions[key]
        if f.cls is not None:
            out.setdefault((f.path, f.cls), []).append(key)
    return out


def _xthread_findings(prog: Program) -> List[Finding]:
    out: List[Finding] = []
    for ckey, fkeys in sorted(_class_funcs(prog).items()):
        path, cls = ckey
        lock_attrs = set(prog.class_locks.get(ckey, {}))
        thread_attrs = prog.thread_attrs.get(ckey, set())
        # attr -> (roots that can run a writer, first unlocked write)
        per_attr: Dict[str, Tuple[Set[str],
                                  Optional[Tuple[int, str]]]] = {}
        for fkey in fkeys:
            f = prog.functions[fkey]
            init = _in_init(f.qual)
            for w in f.writes:
                if (w.attr in lock_attrs or w.attr in thread_attrs
                        or w.attr.endswith("lock")
                        or w.attr.endswith("cond")):
                    continue
                roots, first = per_attr.get(w.attr, (set(), None))
                if not init:
                    roots |= prog.roots_reaching.get(fkey, set())
                unlocked = (not w.locked and not init
                            and not _held_anywhere(prog, fkey))
                if unlocked and (first is None
                                 or (w.line, f.qual) < first):
                    first = (w.line, f.qual)
                per_attr[w.attr] = (roots, first)
        for attr in sorted(per_attr):
            roots, first = per_attr[attr]
            if first is None or len(roots) < 2:
                continue
            line, qual = first
            nroots = len(roots)
            out.append(Finding(
                "OSL703", path, line, 0, qual,
                f"self.{attr} written without a lock but reachable from "
                f"{nroots} thread-entry roots; guard the write or "
                "document the single-writer/GIL-atomic contract inline",
                detail=f"xthread:{cls}.{attr}"))
    return out


def _check_then_act_findings(prog: Program) -> List[Finding]:
    out: List[Finding] = []
    for ckey, fkeys in sorted(_class_funcs(prog).items()):
        if not prog.class_locks.get(ckey):
            continue  # only lock-bearing classes promise atomicity
        path, cls = ckey
        for fkey in fkeys:
            f = prog.functions[fkey]
            if (_in_init(f.qual) or f.assumed_held
                    or fkey in prog.always_held):
                continue
            flagged: Set[str] = set()
            for m in f.mutations:
                if m.region is not None or m.attr in flagged:
                    continue
                guard = next(
                    (t for t in f.tests
                     if t.attr == m.attr and t.line < m.line
                     and t.region != m.region), None)
                if guard is None:
                    continue
                flagged.add(m.attr)
                out.append(Finding(
                    "OSL704", path, m.line, 0, f.qual,
                    f"check-then-act on self.{m.attr}: tested at line "
                    f"{guard.line} but mutated outside any lock region "
                    "— the pair is not atomic; move both under "
                    "the lock",
                    detail=f"cta:{cls}.{m.attr}"))
    return out


def analyze(prog: Program) -> List[Finding]:
    """All OSL7xx findings for the model, unsuppressed and unsorted."""
    return (_cycle_findings(prog) + _blocking_findings(prog)
            + _xthread_findings(prog) + _check_then_act_findings(prog))


def run_program(files: Sequence[Tuple[str, ast.Module, str]]
                ) -> Tuple[Program, List[Finding]]:
    """Build the model from parsed (path, tree, src) triples, emit
    findings, and apply each file's inline suppressions."""
    prog = build_program(files)
    sups = {path: parse_suppressions(src) for path, _t, src in files}
    findings = [f for f in analyze(prog)
                if not _suppressed(f, sups.get(f.path, {}))]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return prog, findings


def program_files(repo_root: str, package: str = "opensearch_tpu"
                  ) -> List[Tuple[str, ast.Module, str]]:
    """Parse the package for the whole-program pass. devtools/ is
    excluded: the analyzer and the lock witness manipulate locks in
    ways the model deliberately flags."""
    files: List[Tuple[str, ast.Module, str]] = []
    pkg_root = os.path.join(repo_root, package)
    for fp in iter_py_files(pkg_root):
        rel = os.path.relpath(fp, repo_root).replace(os.sep, "/")
        if rel.startswith(f"{package}/devtools/"):
            continue
        with open(fp, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # the per-file pass reports OSL000
        files.append((rel, tree, src))
    return files


def run_program_scope(repo_root: str, package: str = "opensearch_tpu"
                      ) -> List[Finding]:
    _prog, findings = run_program(program_files(repo_root, package))
    return findings


# --------------------------------------------------------------------
# lock_order.json artifact
# --------------------------------------------------------------------

def _cycle_key(members: Sequence[str]) -> str:
    return "|".join(sorted(members))


def build_lock_order(prog: Program,
                     justifications: Optional[Dict[str, str]] = None
                     ) -> dict:
    """The reviewable artifact: every inventoried lock, every
    acquired-while-held edge with one deterministic witness site, and
    every cycle with its justification. Fully sorted so regeneration
    is byte-stable."""
    justifications = justifications or {}
    lock_ids = sorted(set(prog.lock_decl)
                      | {x for e in prog.edges for x in e})
    locks = []
    for lid in lock_ids:
        decl = prog.lock_decl.get(lid)
        locks.append({
            "id": lid,
            "kind": decl.kind if decl else "attr",
            "declared": f"{decl.path}:{decl.line}" if decl else "",
        })
    edges = []
    for (a, b) in sorted(prog.edges):
        path, qual, _line, via = prog.edges[(a, b)]
        site = f"{path}::{qual}"
        if via:
            site += f" (via {' -> '.join(via)})"
        edges.append({"from": a, "to": b, "site": site})
    cycles = []
    for members in prog.cycles():
        key = _cycle_key(members)
        cycles.append({
            "members": members,
            "justification": justifications.get(key, UNJUSTIFIED),
        })
    return {"version": 1, "locks": locks, "edges": edges,
            "cycles": cycles}


def load_lock_order(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_lock_order(graph: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(graph, fh, indent=2, sort_keys=False)
        fh.write("\n")


def diff_lock_order(committed: dict, current: dict) -> dict:
    """Ratchet comparison. Edge identity is (from, to) and cycle
    identity is the sorted member set — witness sites and declaration
    line numbers may drift with unrelated edits without failing.

    `new_edges` / `new_cycles` fail tier-1 until the artifact is
    regenerated (scripts/oslint.py --write-lock-graph) and reviewed;
    `unjustified_cycles` fail until each committed cycle carries a
    real justification; `stale_edges` are informational debt.
    """
    def edge_set(g: dict) -> Set[Tuple[str, str]]:
        return {(e["from"], e["to"]) for e in g.get("edges", [])}

    def cycle_map(g: dict) -> Dict[str, dict]:
        return {_cycle_key(c["members"]): c for c in g.get("cycles", [])}

    old_e, new_e = edge_set(committed), edge_set(current)
    old_c, new_c = cycle_map(committed), cycle_map(current)
    sites = {(e["from"], e["to"]): e.get("site", "")
             for e in current.get("edges", [])}
    return {
        "new_edges": [
            {"from": a, "to": b, "site": sites.get((a, b), "")}
            for a, b in sorted(new_e - old_e)],
        "stale_edges": [{"from": a, "to": b}
                        for a, b in sorted(old_e - new_e)],
        "new_cycles": [new_c[k]["members"]
                       for k in sorted(set(new_c) - set(old_c))],
        "stale_cycles": [old_c[k]["members"]
                         for k in sorted(set(old_c) - set(new_c))],
        "unjustified_cycles": [
            c["members"] for k, c in sorted(old_c.items())
            if k in new_c and (not c.get("justification")
                               or c["justification"].startswith(
                                   "UNJUSTIFIED"))],
    }
