"""Whole-program concurrency model: lock inventory, alias resolution,
call graph, and fixpoint held/blocking summaries.

Two phases over the package's parsed modules:

Phase A (declarations) — per module, record every lock declaration
(`self.X = threading.Lock()` in a class body/method, `NAME = RLock()`
at module level, `__dict__.setdefault("attr", _BuildLock())`), every
constructor-typed instance attribute (`self.fd = MemberFailureDetector()`
— the alias path for cross-object lock resolution), module-level
singletons (`RECORDER = FlightRecorder()`), imports, class bases, and
thread-handle attributes.

Phase B (functions) — walk each function body with a lexical held-lock
stack: `with lock:` regions (plus linear `.acquire()`/`.release()`
pairs), call sites with the held-lock tuple, attribute writes with a
locked flag, container tests/mutations for check-then-act analysis,
direct blocking operations, and thread-entry registrations
(`threading.Thread(target=...)`, listener/callback hookups).

The model is a sound-enough over-approximation, not an exact points-to
analysis: lock identity is class-attribute-level (two instances of one
class share a graph node), unresolvable lock-ish names collapse into a
shared `attr::<name>` node, and call resolution falls back to
unique-name matching. False positives flow through the standard oslint
triage workflow (inline suppression / baseline justification); the
runtime lock witness (devtools/lockwitness.py) cross-checks the model
against actual execution.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import dotted_name

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore"}
# semaphores bound concurrency; holding one across blocking work is the
# point, so OSL702 skips them
SEMAPHORE_KINDS = {"Semaphore", "BoundedSemaphore"}
NON_REENTRANT_KINDS = {"Lock"}
LOCKISH_TOKENS = ("lock", "cond", "mutex", "sem")

# container-mutating method names: a call `self.X.append(...)` is a
# write to X for the cross-thread and check-then-act rules
MUTATORS = {"append", "appendleft", "extend", "insert", "add", "discard",
            "remove", "pop", "popleft", "popitem", "clear", "update",
            "setdefault", "rotate", "move_to_end"}

# callables handed to these methods run on OTHER threads: listener
# fan-outs, cancellation callbacks, and parallel legs (utils/legs.py
# LegSet.add_leg — every leg body is a thread entry root)
CALLBACK_REGISTRARS = {"add_listener", "add_alert_listener", "on_cancel",
                       "add_leg"}


def lockish(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in LOCKISH_TOKENS)


def short_lock(lock_id: str) -> str:
    """Compact stable form for messages/details:
    'opensearch_tpu/serving/remediator.py::Remediator._lock' ->
    'serving/remediator::Remediator._lock'."""
    if lock_id.startswith("attr::"):
        return lock_id
    head, _, tail = lock_id.partition("::")
    if head.startswith("opensearch_tpu/"):
        head = head[len("opensearch_tpu/"):]
    if head.endswith(".py"):
        head = head[:-3]
    return f"{head}::{tail}"


@dataclass(frozen=True)
class LockDecl:
    lock_id: str
    kind: str          # Lock/RLock/Condition/Semaphore/.../BuildLock/attr
    path: str
    line: int


@dataclass
class CallSite:
    dotted: str
    line: int
    held: Tuple[str, ...]
    region: Optional[int]


@dataclass
class BlockOp:
    op: str                          # human label ("time.sleep", ...)
    receiver: Optional[str]          # lock id of a .wait() receiver
    line: int
    held: Tuple[str, ...] = ()
    chain: Tuple[str, ...] = ()      # call chain for propagated ops


@dataclass
class AttrWrite:
    attr: str
    line: int
    locked: bool
    container: bool                  # subscript/mutator (dict/deque op)


@dataclass
class AttrTouch:
    attr: str
    line: int
    region: Optional[int]


@dataclass
class FuncInfo:
    path: str
    qual: str
    cls: Optional[str]
    line: int
    assumed_held: bool = False       # `_locked`-suffix convention
    calls: List[CallSite] = field(default_factory=list)
    direct_acquires: List[Tuple[str, int]] = field(default_factory=list)
    local_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    self_acquires: List[Tuple[str, int]] = field(default_factory=list)
    writes: List[AttrWrite] = field(default_factory=list)
    tests: List[AttrTouch] = field(default_factory=list)
    mutations: List[AttrTouch] = field(default_factory=list)
    blocks: List[BlockOp] = field(default_factory=list)
    root_refs: List[Tuple[str, str, int]] = field(default_factory=list)
    # ^ (kind, dotted-or-qual, line): thread targets / callback args

    @property
    def key(self) -> Tuple[str, str]:
        return (self.path, self.qual)

    def is_init(self) -> bool:
        return self.qual.endswith("__init__")


def _module_name(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    if mod.endswith("/__init__"):
        mod = mod[:-len("/__init__")]
    return mod.replace("/", ".")


class Program:
    """The assembled whole-program model; see module docstring."""

    def __init__(self) -> None:
        self.files: List[Tuple[str, ast.Module, str]] = []
        # phase A
        self.class_locks: Dict[Tuple[str, str], Dict[str, LockDecl]] = {}
        self.module_locks: Dict[Tuple[str, str], LockDecl] = {}
        self.attr_locks: Dict[str, List[LockDecl]] = {}
        self.instance_attr_types: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.module_instance_types: Dict[Tuple[str, str], str] = {}
        self.class_index: Dict[str, List[str]] = {}      # name -> [path]
        self.class_bases: Dict[Tuple[str, str], List[str]] = {}
        self.thread_attrs: Dict[Tuple[str, str], Set[str]] = {}
        self.method_aliases: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.imports: Dict[str, Dict[str, str]] = {}     # path -> name->dotted
        self.mod_to_path: Dict[str, str] = {}
        # phase B
        self.functions: Dict[Tuple[str, str], FuncInfo] = {}
        # derived (finalize)
        self.callees: Dict[Tuple[str, str],
                           List[Tuple[Tuple[str, str], CallSite]]] = {}
        self.lock_kind: Dict[str, str] = {}
        self.lock_decl: Dict[str, LockDecl] = {}
        self.roots: Dict[Tuple[str, str], str] = {}      # key -> label
        self.roots_reaching: Dict[Tuple[str, str], Set[str]] = {}
        self.always_held: Set[Tuple[str, str]] = set()
        self.may_acquire: Dict[Tuple[str, str],
                               Dict[str, Tuple[str, ...]]] = {}
        self.may_block: Dict[Tuple[str, str], Dict[str, BlockOp]] = {}
        self.edges: Dict[Tuple[str, str],
                         Tuple[str, str, int, Tuple[str, ...]]] = {}
        # ^ (a,b) -> deterministic min (path, qual, line, via-chain)
        self.self_edges: Dict[str, Tuple[str, str, int]] = {}
        self.unresolved_withs: int = 0

    # ---------------- phase A: declarations ----------------

    def scan_declarations(self, path: str, tree: ast.Module) -> None:
        self.mod_to_path[_module_name(path)] = path
        imports = self.imports.setdefault(path, {})
        modname = _module_name(path)
        is_pkg = path.endswith("/__init__.py")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(modname, is_pkg,
                                          node.level, node.module)
                for a in node.names:
                    if a.name == "*":
                        continue
                    imports[a.asname or a.name] = (
                        f"{base}.{a.name}" if base else a.name)
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._scan_class(path, stmt)
            elif isinstance(stmt, ast.Assign):
                self._scan_module_assign(path, stmt)
        # `__dict__.setdefault("attr", _BuildLock())` — lazy per-instance
        # lock slots (index/segment.py): inventoried by attribute name
        for node in ast.walk(tree):
            got = self._setdefault_lock(node)
            if got is not None:
                attr, kind = got
                decl = LockDecl(f"attr::{attr}", kind, path, node.lineno)
                if not any(d.lock_id == decl.lock_id
                           for d in self.attr_locks.get(attr, [])):
                    self.attr_locks.setdefault(attr, []).append(decl)

    @staticmethod
    def _resolve_from(modname: str, is_pkg: bool, level: int,
                      module: Optional[str]) -> str:
        if level == 0:
            return module or ""
        parts = modname.split(".")
        if not is_pkg:
            parts = parts[:-1]
        if level > 1:
            parts = parts[:len(parts) - (level - 1)]
        return ".".join(parts + ([module] if module else []))

    @staticmethod
    def _lock_ctor_kind(call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        func = call.func
        # `__import__("threading").RLock()` — the lazy module-singleton
        # idiom (search/derived.py, search/fastpath.py)
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Call)
                and dotted_name(func.value.func) == "__import__"
                and func.value.args
                and isinstance(func.value.args[0], ast.Constant)
                and func.value.args[0].value == "threading"
                and func.attr in LOCK_CTORS):
            return func.attr
        d = dotted_name(call.func)
        if not d:
            return None
        head, _, tail = d.rpartition(".")
        if tail in LOCK_CTORS and head in ("", "threading"):
            return tail
        if tail.endswith("BuildLock"):
            return "BuildLock"
        return None

    @classmethod
    def _setdefault_lock(cls, node: ast.AST) -> Optional[Tuple[str, str]]:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "setdefault"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            return None
        kind = cls._lock_ctor_kind(node.args[1])
        return (node.args[0].value, kind) if kind else None

    def _record_class_attr(self, path: str, cname: str, attr: str,
                           value: ast.AST, line: int) -> None:
        kind = self._lock_ctor_kind(value)
        if kind is not None:
            decl = LockDecl(f"{path}::{cname}.{attr}", kind, path, line)
            self.class_locks.setdefault((path, cname), {})[attr] = decl
            self.attr_locks.setdefault(attr, []).append(decl)
            return
        if isinstance(value, ast.Call):
            d = dotted_name(value.func)
            tail = d.rsplit(".", 1)[-1] if d else ""
            if tail == "Thread":
                self.thread_attrs.setdefault((path, cname), set()).add(attr)
            elif tail[:1].isupper():
                self.instance_attr_types.setdefault(
                    (path, cname), {})[attr] = d

    def _scan_class(self, path: str, cdef: ast.ClassDef) -> None:
        cname = cdef.name
        self.class_index.setdefault(cname, []).append(path)
        self.class_bases[(path, cname)] = [
            dotted_name(b) for b in cdef.bases if dotted_name(b)]
        for stmt in cdef.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        # `do_GET = do_POST = _dispatch` — class-body
                        # method aliasing (http.server handler idiom)
                        if isinstance(stmt.value, ast.Name):
                            self.method_aliases.setdefault(
                                (path, cname), {})[t.id] = stmt.value.id
                        else:
                            self._record_class_attr(path, cname, t.id,
                                                    stmt.value,
                                                    stmt.lineno)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            self._record_class_attr(path, cname, t.attr,
                                                    node.value, node.lineno)

    def _scan_module_assign(self, path: str, stmt: ast.Assign) -> None:
        for t in stmt.targets:
            if not isinstance(t, ast.Name):
                continue
            kind = self._lock_ctor_kind(stmt.value)
            if kind is not None:
                decl = LockDecl(f"{path}::{t.id}", kind, path, stmt.lineno)
                self.module_locks[(path, t.id)] = decl
                self.attr_locks.setdefault(t.id, []).append(decl)
            elif isinstance(stmt.value, ast.Call):
                d = dotted_name(stmt.value.func)
                tail = d.rsplit(".", 1)[-1] if d else ""
                if tail[:1].isupper():
                    self.module_instance_types[(path, t.id)] = d

    # ---------------- name resolution helpers ----------------

    def resolve_class(self, dotted: str, path: str
                      ) -> Optional[Tuple[str, str]]:
        """'MemberFailureDetector' / 'mod.Cls' -> (decl path, class)."""
        if not dotted:
            return None
        parts = dotted.split(".")
        tail = parts[-1]
        imp = self.imports.get(path, {}).get(parts[0])
        if imp is not None:
            if len(parts) == 1:
                # `from x.y import Cls` -> imp == "x.y.Cls"
                mod, _, name = imp.rpartition(".")
                mpath = self.mod_to_path.get(mod)
                if mpath and name in self.class_index \
                        and mpath in self.class_index[name]:
                    return (mpath, name)
            else:
                # `import x.y as m` + "m.Cls"
                mpath = self.mod_to_path.get(imp)
                if mpath and tail in self.class_index \
                        and mpath in self.class_index[tail]:
                    return (mpath, tail)
        paths = self.class_index.get(tail, [])
        if path in paths:
            return (path, tail)
        if len(paths) == 1:
            return (paths[0], tail)
        return None

    def iter_bases(self, path: str, cls: str, _depth: int = 0
                   ) -> List[Tuple[str, str]]:
        if _depth > 4:
            return []
        out: List[Tuple[str, str]] = []
        for b in self.class_bases.get((path, cls), []):
            key = self.resolve_class(b, path)
            if key is not None:
                out.append(key)
                out.extend(self.iter_bases(key[0], key[1], _depth + 1))
        return out

    def _attr_fallback(self, name: str) -> Optional[str]:
        decls = self.attr_locks.get(name, [])
        uniq = sorted({d.lock_id for d in decls})
        if len(uniq) == 1:
            return uniq[0]
        if len(uniq) > 1:
            return f"attr::{name}"
        if lockish(name):
            return f"attr::{name}"
        return None

    def _class_lock(self, key: Tuple[str, str], attr: str
                    ) -> Optional[LockDecl]:
        decl = self.class_locks.get(key, {}).get(attr)
        if decl is not None:
            return decl
        for bkey in self.iter_bases(*key):
            decl = self.class_locks.get(bkey, {}).get(attr)
            if decl is not None:
                return decl
        return None

    def resolve_lock_dotted(self, dotted: str, path: str,
                            cls: Optional[str],
                            aliases: Dict[str, str],
                            local_types: Dict[str, str]) -> Optional[str]:
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] in aliases and len(parts) == 1:
            return aliases[parts[0]]
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2:
                decl = self._class_lock((path, cls), parts[1])
                if decl is not None:
                    return decl.lock_id
                return self._attr_fallback(parts[1])
            if len(parts) == 3:
                owner = self.instance_attr_types.get(
                    (path, cls), {}).get(parts[1])
                okey = self.resolve_class(owner, path) if owner else None
                if okey is not None:
                    decl = self._class_lock(okey, parts[2])
                    if decl is not None:
                        return decl.lock_id
                return self._attr_fallback(parts[2])
            return self._attr_fallback(parts[-1])
        if len(parts) == 1:
            decl = self.module_locks.get((path, parts[0]))
            if decl is not None:
                return decl.lock_id
            imp = self.imports.get(path, {}).get(parts[0])
            if imp is not None:
                mod, _, name = imp.rpartition(".")
                mpath = self.mod_to_path.get(mod)
                if mpath is not None:
                    decl = self.module_locks.get((mpath, name))
                    if decl is not None:
                        return decl.lock_id
            return self._attr_fallback(parts[0])
        if len(parts) == 2:
            okey = self._instance_key(path, parts[0], local_types)
            if okey is not None:
                decl = self._class_lock(okey, parts[1])
                if decl is not None:
                    return decl.lock_id
            imp = self.imports.get(path, {}).get(parts[0])
            if imp is not None:
                mpath = self.mod_to_path.get(imp)
                if mpath is not None:
                    decl = self.module_locks.get((mpath, parts[1]))
                    if decl is not None:
                        return decl.lock_id
        return self._attr_fallback(parts[-1])

    def _instance_key(self, path: str, name: str,
                      local_types: Dict[str, str]
                      ) -> Optional[Tuple[str, str]]:
        """Type of a bare instance name: local `reg = MetricsRegistry()`,
        module-level `RECORDER = FlightRecorder()`, or an imported
        module singleton."""
        d = local_types.get(name) \
            or self.module_instance_types.get((path, name))
        if d is None:
            imp = self.imports.get(path, {}).get(name)
            if imp is not None:
                mod, _, nm = imp.rpartition(".")
                mpath = self.mod_to_path.get(mod)
                if mpath is not None:
                    d = self.module_instance_types.get((mpath, nm))
                    if d is not None:
                        return self.resolve_class(d, mpath)
            return None
        return self.resolve_class(d, path)

    def resolve_call(self, caller: FuncInfo, dotted: str,
                     local_types: Dict[str, str]
                     ) -> Optional[Tuple[str, str]]:
        if not dotted:
            return None
        parts = dotted.split(".")
        path, cls = caller.path, caller.cls
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2:
                key = self._method_key(path, cls, parts[1])
                if key is not None:
                    return key
            if len(parts) == 3:
                owner = self.instance_attr_types.get(
                    (path, cls), {}).get(parts[1])
                okey = self.resolve_class(owner, path) if owner else None
                if okey is not None:
                    return self._method_key(okey[0], okey[1], parts[2])
            return None
        if len(parts) == 1:
            nested = (path, f"{caller.qual}.<locals>.{parts[0]}")
            if nested in self.functions:
                return nested
            if (path, parts[0]) in self.functions:
                return (path, parts[0])
            imp = self.imports.get(path, {}).get(parts[0])
            if imp is not None:
                mod, _, name = imp.rpartition(".")
                mpath = self.mod_to_path.get(mod)
                if mpath is not None and (mpath, name) in self.functions:
                    return (mpath, name)
            # constructor: Cls(...) -> Cls.__init__
            ckey = self.resolve_class(parts[0], path)
            if ckey is not None:
                return self._method_key(ckey[0], ckey[1], "__init__")
            return None
        if len(parts) == 2:
            okey = self._instance_key(path, parts[0], local_types)
            if okey is not None:
                return self._method_key(okey[0], okey[1], parts[1])
            imp = self.imports.get(path, {}).get(parts[0])
            if imp is not None:
                mpath = self.mod_to_path.get(imp)
                if mpath is not None and (mpath, parts[1]) in self.functions:
                    return (mpath, parts[1])
                # `from x import Cls` + Cls.method / Cls(...) attr chain
                ckey = self.resolve_class(parts[0], path)
                if ckey is not None:
                    return self._method_key(ckey[0], ckey[1], parts[1])
        return None

    def _method_key(self, path: str, cls: str, meth: str
                    ) -> Optional[Tuple[str, str]]:
        key = (path, f"{cls}.{meth}")
        if key in self.functions:
            return key
        for bpath, bcls in self.iter_bases(path, cls):
            bkey = (bpath, f"{bcls}.{meth}")
            if bkey in self.functions:
                return bkey
        return None

    # ---------------- phase B driver ----------------

    def extract_functions(self, path: str, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(path, None, stmt.name, stmt)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._walk_function(path, stmt.name,
                                            f"{stmt.name}.{sub.name}", sub)

    def _walk_function(self, path: str, cls: Optional[str], qual: str,
                       node: ast.AST) -> FuncInfo:
        name = qual.rsplit(".", 1)[-1]
        info = FuncInfo(path=path, qual=qual, cls=cls, line=node.lineno,
                        assumed_held=name.endswith("_locked"))
        self.functions[info.key] = info
        _FuncWalker(self, info, node).run()
        return info

    # ---------------- finalize: graph + fixpoints ----------------

    def finalize(self) -> None:
        for decls in ([d for ds in self.attr_locks.values() for d in ds]
                      + list(self.module_locks.values())):
            self.lock_kind[decls.lock_id] = decls.kind
            self.lock_decl.setdefault(decls.lock_id, decls)
        self._resolve_call_edges()
        self._collect_roots()
        self._compute_always_held()
        self._fixpoint_acquire()
        self._fixpoint_block()
        self._build_edges()

    def _resolve_call_edges(self) -> None:
        for key in sorted(self.functions):
            f = self.functions[key]
            out: List[Tuple[Tuple[str, str], CallSite]] = []
            for c in f.calls:
                callee = self.resolve_call(f, c.dotted, {})
                if callee is not None and callee != key:
                    out.append((callee, c))
            self.callees[key] = out

    def _collect_roots(self) -> None:
        for key in sorted(self.functions):
            f = self.functions[key]
            for kind, ref, _line in f.root_refs:
                rkey: Optional[Tuple[str, str]]
                if kind == "qual":
                    rkey = (f.path, ref)
                else:
                    rkey = self.resolve_call(f, ref, {})
                if rkey is not None and rkey in self.functions:
                    self.roots.setdefault(
                        rkey, f"{rkey[0]}::{rkey[1]}")
        # HTTP request-handler threads: every do_* method of a
        # BaseHTTPRequestHandler subclass is an entry root
        for (path, cname), bases in sorted(self.class_bases.items()):
            if not any(b.rsplit(".", 1)[-1] == "BaseHTTPRequestHandler"
                       for b in bases):
                continue
            for key in sorted(self.functions):
                fpath, fqual = key
                if fpath == path and fqual.startswith(f"{cname}.do_"):
                    self.roots.setdefault(key, f"{fpath}::{fqual}")
            for alias, target in sorted(
                    self.method_aliases.get((path, cname), {}).items()):
                if not alias.startswith("do_"):
                    continue
                tkey = self._method_key(path, cname, target)
                if tkey is not None:
                    self.roots.setdefault(
                        tkey, f"{path}::{cname}.{alias}")
        # reachability
        reach: Dict[Tuple[str, str], Set[str]] = {
            k: set() for k in self.functions}
        for rkey, label in sorted(self.roots.items()):
            seen = {rkey}
            frontier = [rkey]
            while frontier:
                cur = frontier.pop()
                reach[cur].add(label)
                for callee, _c in self.callees.get(cur, []):
                    if callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)
        self.roots_reaching = reach

    def _compute_always_held(self) -> None:
        incoming: Dict[Tuple[str, str],
                       List[Tuple[Tuple[str, str], bool]]] = {
            k: [] for k in self.functions}
        for key, outs in self.callees.items():
            for callee, c in outs:
                incoming[callee].append((key, bool(c.held)))
        changed = True
        while changed:
            changed = False
            for key in sorted(self.functions):
                if key in self.always_held or key in self.roots:
                    continue
                f = self.functions[key]
                if f.is_init():
                    continue
                inc = incoming[key]
                if not inc:
                    continue

                def _held(caller: Tuple[str, str], held: bool) -> bool:
                    cf = self.functions[caller]
                    return (held or cf.assumed_held
                            or caller in self.always_held)
                if all(_held(cal, h) for cal, h in inc):
                    self.always_held.add(key)
                    changed = True

    def _fixpoint_acquire(self) -> None:
        acq: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {}
        for key in sorted(self.functions):
            f = self.functions[key]
            acq[key] = {lid: () for lid, _ in f.direct_acquires}
        for _ in range(50):
            changed = False
            for key in sorted(self.functions):
                for callee, _c in self.callees[key]:
                    cqual = callee[1]
                    for lid, chain in acq.get(callee, {}).items():
                        if lid not in acq[key]:
                            acq[key][lid] = ((cqual,) + chain)[:4]
                            changed = True
            if not changed:
                break
        self.may_acquire = acq

    def _fixpoint_block(self) -> None:
        blk: Dict[Tuple[str, str], Dict[str, BlockOp]] = {}
        for key in sorted(self.functions):
            f = self.functions[key]
            blk[key] = {}
            for b in f.blocks:
                blk[key].setdefault(
                    b.op, BlockOp(b.op, b.receiver, b.line))
        for _ in range(50):
            changed = False
            for key in sorted(self.functions):
                for callee, c in self.callees[key]:
                    cqual = callee[1]
                    for op, b in blk.get(callee, {}).items():
                        if op not in blk[key]:
                            blk[key][op] = BlockOp(
                                b.op, b.receiver, c.line,
                                chain=((cqual,) + b.chain)[:4])
                            changed = True
            if not changed:
                break
        self.may_block = blk

    def _add_edge(self, a: str, b: str, path: str, qual: str, line: int,
                  via: Tuple[str, ...] = ()) -> None:
        if a == b:
            if self.lock_kind.get(a) in NON_REENTRANT_KINDS:
                cur = self.self_edges.get(a)
                site = (path, qual, line)
                if cur is None or site < cur:
                    self.self_edges[a] = site
            return
        site = (path, qual, line, via)
        cur = self.edges.get((a, b))
        if cur is None or site < cur:
            self.edges[(a, b)] = site

    def _build_edges(self) -> None:
        for key in sorted(self.functions):
            f = self.functions[key]
            for a, b, line in f.local_edges:
                self._add_edge(a, b, f.path, f.qual, line)
            for lid, line in f.self_acquires:
                self._add_edge(lid, lid, f.path, f.qual, line)
            for callee, c in self.callees[key]:
                if not c.held:
                    continue
                for lid, chain in self.may_acquire.get(callee, {}).items():
                    via = ((callee[1],) + chain)[:4]
                    for a in c.held:
                        self._add_edge(a, lid, f.path, f.qual,
                                       c.line, via)

    def cycles(self) -> List[List[str]]:
        """SCCs of the lock-order graph with more than one member —
        each is a potential deadlock (Tarjan, deterministic order)."""
        graph: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        for k in graph:
            graph[k].sort()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        out: List[List[str]] = []

        def strong(v: str) -> None:
            # iterative Tarjan (the graph is small, but avoid recursion
            # limits on adversarial fixtures)
            work = [(v, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                succs = graph[node]
                while pi < len(succs):
                    w = succs[pi]
                    pi += 1
                    if w not in index:
                        work[-1] = (node, pi)
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in sorted(graph):
            if v not in index:
                strong(v)
        out.sort()
        return out


class _FuncWalker:
    """Lexical walk of one function body with a held-lock stack.
    Nested defs/lambdas become separate FuncInfos (a closure runs when
    called, not where defined — it inherits no held locks)."""

    def __init__(self, prog: Program, info: FuncInfo,
                 node: ast.AST) -> None:
        self.prog = prog
        self.info = info
        self.node = node
        self.held: List[Tuple[str, int]] = []
        self.region_n = 0
        self.aliases: Dict[str, str] = {}
        self.local_types: Dict[str, str] = {}
        self.explicit: List[str] = []   # linear .acquire() holds

    def run(self) -> None:
        self.stmts(getattr(self.node, "body", []))

    # -------- held bookkeeping --------

    def held_ids(self) -> Tuple[str, ...]:
        return tuple(lid for lid, _ in self.held)

    def region(self) -> Optional[int]:
        return self.held[-1][1] if self.held else None

    def _push(self, lid: str, line: int) -> None:
        cur = self.held_ids()
        if lid in cur:
            self.info.self_acquires.append((lid, line))
        else:
            for a in cur:
                self.info.local_edges.append((a, lid, line))
        self.info.direct_acquires.append((lid, line))
        self.region_n += 1
        self.held.append((lid, self.region_n))

    def _pop(self, lid: str) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i][0] == lid:
                del self.held[i]
                return

    # -------- lock expression resolution --------

    def resolve_lock_expr(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            got = Program._setdefault_lock(expr)
            if got is not None:
                return f"attr::{got[0]}"
            return None
        d = dotted_name(expr)
        if not d:
            return None
        return self.prog.resolve_lock_dotted(
            d, self.info.path, self.info.cls, self.aliases,
            self.local_types)

    # -------- statement dispatch --------

    def stmts(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.prog._walk_function(
                self.info.path, self.info.cls,
                f"{self.info.qual}.<locals>.{s.name}", s)
            return
        if isinstance(s, ast.ClassDef):
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            pushed: List[str] = []
            for item in s.items:
                self.expr(item.context_expr)
                lid = self.resolve_lock_expr(item.context_expr)
                if lid is not None:
                    self._push(lid, s.lineno)
                    pushed.append(lid)
                    if isinstance(item.optional_vars, ast.Name):
                        self.aliases[item.optional_vars.id] = lid
            self.stmts(s.body)
            for lid in reversed(pushed):
                self._pop(lid)
            return
        if isinstance(s, ast.Assign):
            self.expr(s.value)
            for t in s.targets:
                self._assign_target(t, s.value, s.lineno)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.expr(s.value)
                self._assign_target(s.target, s.value, s.lineno)
            return
        if isinstance(s, ast.AugAssign):
            self.expr(s.value)
            self._write_target(s.target, s.lineno,
                               container=isinstance(s.target, ast.Subscript))
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                self._write_target(t, s.lineno,
                                   container=isinstance(t, ast.Subscript))
            return
        if isinstance(s, (ast.If, ast.While)):
            self._collect_tests(s.test, s.lineno)
            self.expr(s.test)
            self.stmts(s.body)
            self.stmts(s.orelse)
            return
        if isinstance(s, ast.For):
            self.expr(s.iter)
            self.stmts(s.body)
            self.stmts(s.orelse)
            return
        if isinstance(s, ast.Try):
            self.stmts(s.body)
            for h in s.handlers:
                self.stmts(h.body)
            self.stmts(s.orelse)
            self.stmts(s.finalbody)
            return
        if isinstance(s, (ast.Return, ast.Expr, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child)
            return
        # anything else: visit child statements/expressions generically
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.stmt):
                self.stmt(child)
            elif isinstance(child, ast.expr):
                self.expr(child)

    def _assign_target(self, t: ast.AST, value: ast.AST,
                       line: int) -> None:
        if isinstance(t, ast.Name):
            lid = self.resolve_lock_expr(value)
            if lid is not None:
                self.aliases[t.id] = lid
            else:
                self.aliases.pop(t.id, None)
                if isinstance(value, ast.Call):
                    d = dotted_name(value.func)
                    tail = d.rsplit(".", 1)[-1] if d else ""
                    if tail[:1].isupper():
                        self.local_types[t.id] = d
                    else:
                        self.local_types.pop(t.id, None)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._assign_target(el, ast.Constant(value=None), line)
            return
        self._write_target(t, line,
                           container=isinstance(t, ast.Subscript))

    def _write_target(self, t: ast.AST, line: int,
                      container: bool) -> None:
        base = t.value if isinstance(t, ast.Subscript) else t
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            locked = bool(self.held)
            self.info.writes.append(
                AttrWrite(base.attr, line, locked, container))
            if container:
                self.info.mutations.append(
                    AttrTouch(base.attr, line, self.region()))

    def _collect_tests(self, test: ast.expr, line: int) -> None:
        for node in ast.walk(test):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and isinstance(node.ctx, ast.Load)):
                self.info.tests.append(
                    AttrTouch(node.attr, line, self.region()))

    # -------- expression dispatch (calls) --------

    def expr(self, e: ast.AST) -> None:
        if isinstance(e, ast.Lambda):
            sub = self.prog._walk_function(
                self.info.path, self.info.cls,
                f"{self.info.qual}.<lambda@{e.lineno}>",
                _LambdaBody(e))
            # remember the synthetic qual so Thread(target=lambda ...)
            # resolves the lambda body as a root
            e._oslint_qual = sub.qual  # type: ignore[attr-defined]
            return
        if isinstance(e, ast.Call):
            self._call(e)
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.expr(child)

    def _call(self, call: ast.Call) -> None:
        d = dotted_name(call.func)
        tail = d.rsplit(".", 1)[-1] if d else ""
        line = call.lineno
        if d:
            self.info.calls.append(
                CallSite(d, line, self.held_ids(), self.region()))
        # container mutators on self attributes
        parts = d.split(".") if d else []
        if (len(parts) == 3 and parts[0] == "self"
                and parts[2] in MUTATORS):
            self.info.writes.append(
                AttrWrite(parts[1], line, bool(self.held), True))
            self.info.mutations.append(
                AttrTouch(parts[1], line, self.region()))
        # thread-entry registrations
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    self._root_ref(kw.value, line)
        elif tail in CALLBACK_REGISTRARS and call.args:
            self._root_ref(call.args[0], line)
        # explicit acquire/release (linear, same statement list —
        # covers the `acquire(); try: ... finally: release()` shape)
        if tail == "acquire" and len(parts) >= 2:
            rid = self.prog.resolve_lock_dotted(
                d[: -(len(tail) + 1)], self.info.path, self.info.cls,
                self.aliases, self.local_types)
            if rid is not None:
                self._push(rid, line)
                self.explicit.append(rid)
        elif tail == "release" and len(parts) >= 2:
            rid = self.prog.resolve_lock_dotted(
                d[: -(len(tail) + 1)], self.info.path, self.info.cls,
                self.aliases, self.local_types)
            if rid is not None and rid in self.explicit:
                self.explicit.remove(rid)
                self._pop(rid)
        # blocking operations
        self._classify_blocking(d, tail, line)

    def _root_ref(self, expr: ast.AST, line: int) -> None:
        if isinstance(expr, ast.Lambda):
            qual = getattr(expr, "_oslint_qual", None)
            if qual is None:
                sub = self.prog._walk_function(
                    self.info.path, self.info.cls,
                    f"{self.info.qual}.<lambda@{expr.lineno}>",
                    _LambdaBody(expr))
                qual = sub.qual
                expr._oslint_qual = qual  # type: ignore[attr-defined]
            self.info.root_refs.append(("qual", qual, line))
            return
        d = dotted_name(expr)
        if d:
            self.info.root_refs.append(("dotted", d, line))

    def _classify_blocking(self, d: str, tail: str, line: int) -> None:
        if not d:
            return
        receiver = d[: -(len(tail) + 1)] if "." in d else ""
        op: Optional[str] = None
        rid: Optional[str] = None
        if d == "time.sleep" or (d == "sleep" and "time" not in d):
            op = "time.sleep"
        elif tail == "urlopen":
            op = "urllib urlopen (RPC send)"
        elif tail == "device_get":
            op = "jax.device_get (device sync)"
        elif tail == "block_until_ready":
            op = "block_until_ready (device sync)"
        elif tail in ("wait", "wait_for"):
            rid = self.prog.resolve_lock_dotted(
                receiver, self.info.path, self.info.cls, self.aliases,
                self.local_types) if receiver else None
            op = f"{tail}() on `{receiver or '?'}`"
        elif tail == "join" and receiver:
            rparts = receiver.split(".")
            is_thread = ("thread" in rparts[-1].lower()
                         or (self.info.cls is not None
                             and rparts[-1] in self.prog.thread_attrs.get(
                                 (self.info.path, self.info.cls), set())))
            if is_thread:
                op = f"thread join() on `{receiver}`"
        if op is None:
            return
        self.info.blocks.append(
            BlockOp(op, rid, line, held=self.held_ids()))


class _LambdaBody:
    """Adapter presenting a Lambda's expression as a one-statement
    function body for _FuncWalker."""

    def __init__(self, lam: ast.Lambda) -> None:
        self.lineno = lam.lineno
        self.body = [ast.Expr(value=lam.body)]
        ast.copy_location(self.body[0], lam.body)


def build_program(files: Sequence[Tuple[str, ast.Module, str]]) -> Program:
    """Assemble the whole-program model from parsed (path, tree, src)
    triples (paths repo-relative, forward slashes)."""
    prog = Program()
    prog.files = list(files)
    for path, tree, _src in files:
        prog.scan_declarations(path, tree)
    for path, tree, _src in files:
        prog.extract_functions(path, tree)
    prog.finalize()
    return prog
