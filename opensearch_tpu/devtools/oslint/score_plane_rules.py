"""OSL601 — per-doc score-plane materialization discipline.

The north-star corpus is 1M-8.8M docs per segment. At that scale a full
per-doc f32 plane is 4-35 MB *per allocation, per query* — host serving
code that materializes one (a dense score accumulator, a per-doc rank
plane) turns every query into an O(ndocs) memory write regardless of how
selective the query is, and the allocation storms defeat the HBM
ledger's byte accounting (the plane never registers). The ONLY places a
full per-doc score plane may exist are the frontier kernels and their
program builders — `ops/` (pallas kernels, XLA scatter programs run ON
the device where the plane is the scatter target) — where XLA owns the
buffer for the duration of one launch.

Rule OSL601 fires when host serving code (`search/`, `serving/`,
`cluster/`) allocates an ndocs-scale FLOAT array with HOST numpy:

    np.zeros(seg.ndocs, np.float32)          # OSL601
    np.full(ndocs_pad, -np.inf)              # OSL601
    np.zeros(seg.ndocs, dtype=bool)          # quiet: masks are cheap+
                                             # legitimate (filters, live)
    np.zeros(len(cand), np.float32)          # quiet: candidate-scale
    jnp.zeros(ndocs_pad, jnp.float32)        # quiet: a traced jnp plane
                                             # is a DEVICE scatter target
                                             # inside one launch — the
                                             # frontier-program domain

"ndocs-scale" is syntactic: the size expression mentions an
`ndocs`/`ndocs_pad`/`dpad` name. Integer and bool planes stay quiet —
doc masks and ordinal planes are the engine's bread and butter; it is
the SCORE domain (float) that belongs to the frontier pass. `jnp`
allocations stay quiet because program builders (compiler.py emit
functions) trace them into the launch where XLA owns the buffer — the
rule patrols the HOST heap, which the HBM ledger cannot see.

Suppress deliberate exceptions with
`# oslint: disable=OSL601 -- <why this plane is size-gated or O(1)>` —
the justification should name the runtime gate (e.g. "only below
QUALITY_MIN_NDOCS", "ndocs_pad here is a nested-child space").
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_SCOPES = ("opensearch_tpu/search/", "opensearch_tpu/serving/",
           "opensearch_tpu/cluster/")
_ALLOC_FNS = {"zeros", "full", "empty", "ones", "zeros_like", "full_like",
              "ones_like", "empty_like"}
_FLOAT_DTYPES = {"float32", "float64", "float16", "bfloat16", "float"}
_NONFLOAT_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
                    "uint32", "uint64", "bool", "bool_", "intp"}
_NDOCS_NAMES = ("ndocs", "ndocs_pad", "dpad")


def _mentions_ndocs(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is None:
            continue
        low = name.lower()
        if any(tok == low or low.endswith("_" + tok) or tok in low
               for tok in _NDOCS_NAMES):
            return True
    return False


def _dtype_token(node: ast.Call) -> str:
    """Best-effort dtype of the allocation: '' = unspecified (float by
    numpy default), else the trailing dtype identifier."""
    cands = []
    fn = _dotted(node.func).rsplit(".", 1)[-1]
    # np.zeros(shape, dtype) / np.full(shape, fill, dtype)
    pos = 2 if fn in ("full", "full_like") else 1
    if len(node.args) > pos:
        cands.append(node.args[pos])
    for kw in node.keywords:
        if kw.arg == "dtype":
            cands.append(kw.value)
    for c in cands:
        tok = _dotted(c).rsplit(".", 1)[-1]
        if tok:
            return tok
    return ""


class ScorePlaneChecker(Checker):
    rules = ("OSL601",)
    name = "score-plane"

    def applies(self, path: str) -> bool:
        return any(s in path for s in _SCOPES) and "devtools" not in path

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            dn = _dotted(node.func)
            fn = dn.rsplit(".", 1)[-1]
            if fn not in _ALLOC_FNS:
                continue
            root = dn.split(".", 1)[0]
            if root not in ("np", "numpy"):
                continue
            if not _mentions_ndocs(node.args[0]):
                continue
            dt = _dtype_token(node)
            if dt in _NONFLOAT_DTYPES:
                continue            # doc masks / ordinal planes: fine
            findings.append(Finding(
                "OSL601", path, node.lineno, node.col_offset,
                qmap.get(node, ""),
                f"materializes a full per-doc float plane "
                f"(`{fn}` over an ndocs-scale shape) on the host serving "
                "path; at north-star scale (>2^20-doc segments) per-doc "
                "SCORE planes live only in the frontier kernels/programs "
                "(ops/) — score candidates, not the corpus; suppress "
                "with the runtime size-gate as justification",
                detail=f"plane:{fn}:{dt or 'default-float'}"))
        return findings
