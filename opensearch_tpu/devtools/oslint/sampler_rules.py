"""OSL509 — sampler / retention discipline.

The time-series sampler (obs/timeseries.py) runs forever in the
background of a serving node. Three ways that quietly goes wrong, each
encoded here (the discipline the module's design follows):

- **Wall-clock samples.** A sampler that stamps ticks with
  `time.time()` produces series an NTP step can reorder and rates that
  go negative; every timestamp and cadence decision in sampler code
  must come from the monotonic clock (the single (wall, mono) display
  anchor lives outside the loop).
- **Unbounded retention.** A sampler loop that `self.<attr>.append(...)`s
  onto a plain list grows without bound — a memory leak with an
  observability costume. Persistent sample storage must be a bounded
  ring: `deque(maxlen=...)` (or an equivalent the file can prove
  bounded). Per-tick LOCAL lists are fine — they die with the tick.
- **Windowless SLOs.** An `SLO(...)` definition without explicit
  `fast_window_s`/`slow_window_s` keywords is a dashboard, not an
  alert: the evaluation window is the objective's semantics
  (obs/slo.py makes them required at runtime; the lint catches the
  construction site before it runs).

Sampler scope is structural: functions named like a sampler tick
(`sample_once`, `_sample*`, `_tick*`, `_run_sampler`) and every method
of a class whose name contains `Sampler`.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_SAMPLER_FN_NAMES = ("sample_once", "_run_sampler", "sampler_loop")
_SAMPLER_FN_PREFIXES = ("_sample", "_tick")


def _is_sampler_fn(name: str, in_sampler_class: bool) -> bool:
    if in_sampler_class:
        # constructors are exempt: capturing the ONE (wall, mono)
        # display anchor at construction is the sanctioned pattern —
        # the rule patrols recurring tick code, not setup
        return not name.startswith("__")
    return (name in _SAMPLER_FN_NAMES
            or any(name.startswith(p) for p in _SAMPLER_FN_PREFIXES))


class SamplerDisciplineChecker(Checker):
    rules = ("OSL509",)
    name = "sampler-discipline"

    SCOPES = ("obs/", "serving/", "utils/", "cluster/", "search/")
    EXEMPT = ("devtools/",)

    def applies(self, path: str) -> bool:
        if any(s in path for s in self.EXEMPT):
            return False
        return any(s in path for s in self.SCOPES)

    # ---------------- helpers ----------------

    @staticmethod
    def _time_aliases(tree: ast.Module):
        mods: Set[str] = set()
        funcs: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mods.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        funcs.add(a.asname or "time")
        return mods, funcs

    @staticmethod
    def _bounded_attrs(tree: ast.Module) -> Set[str]:
        """Attribute names the file PROVES bounded: assigned from a
        `deque(...)` call carrying a `maxlen=` keyword (any enclosing
        scope — the ring is usually built in __init__)."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and _dotted(value.func).split(".")[-1] == "deque"
                    and any(kw.arg == "maxlen" for kw in value.keywords)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute):
                    out.add(t.attr)
                elif isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    @staticmethod
    def _walltime_call(node: ast.Call, mods: Set[str],
                       funcs: Set[str]) -> bool:
        d = _dotted(node.func)
        if d in funcs:
            return True
        head, _, tail = d.rpartition(".")
        return tail == "time" and head in mods

    # ---------------- check ----------------

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        mods, funcs = self._time_aliases(tree)
        bounded = self._bounded_attrs(tree)

        def scan_fn(fn: ast.AST, sym: str) -> None:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if self._walltime_call(node, mods, funcs):
                    findings.append(Finding(
                        "OSL509", path, node.lineno, node.col_offset,
                        sym,
                        "wall clock in sampler code — sample stamps and "
                        "cadence must be monotonic (time.monotonic); "
                        "wall display goes through one anchor outside "
                        "the loop",
                        detail="sampler-walltime"))
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "append"
                        and isinstance(node.func.value, ast.Attribute)):
                    attr = node.func.value.attr
                    if attr not in bounded:
                        findings.append(Finding(
                            "OSL509", path, node.lineno,
                            node.col_offset, sym,
                            f"sampler appends to `.{attr}` which this "
                            f"file never builds as a bounded ring "
                            f"(deque(maxlen=...)) — background "
                            f"retention must be bounded",
                            detail=f"unbounded-ring:{attr}"))

        def visit(node: ast.AST, in_sampler_class: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, in_sampler_class
                          or "Sampler" in child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    if _is_sampler_fn(child.name, in_sampler_class):
                        scan_fn(child, qmap.get(child, child.name))
                    else:
                        visit(child, in_sampler_class)
                else:
                    visit(child, in_sampler_class)

        visit(tree, False)

        # SLO definitions must declare their evaluation windows
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func).split(".")[-1]
            if callee != "SLO":
                continue
            kwargs = {kw.arg for kw in node.keywords}
            if None in kwargs:
                continue           # **kwargs splat: unknowable, trust it
            # positional coverage: (name, kind, target, fast, slow)
            npos = len(node.args)
            has_fast = "fast_window_s" in kwargs or npos >= 4
            has_slow = "slow_window_s" in kwargs or npos >= 5
            if not (has_fast and has_slow):
                findings.append(Finding(
                    "OSL509", path, node.lineno, node.col_offset,
                    qmap.get(node, ""),
                    "SLO defined without explicit evaluation windows "
                    "(fast_window_s / slow_window_s) — an objective "
                    "without a window is a dashboard, not an alert",
                    detail="slo-no-window"))

        findings.sort(key=lambda f: (f.line, f.detail))
        return findings
