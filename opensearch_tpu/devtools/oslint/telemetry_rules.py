"""OSL5xx — telemetry discipline.

The unified-telemetry PR made two measurement invariants load-bearing;
this family keeps them true as the codebase grows:

- OSL501: durations inside `opensearch_tpu/` must come from a monotonic
  clock (`time.monotonic()` / `time.perf_counter()`), never `time.time()`.
  Wall clocks step under NTP slew and make latency histograms lie.
  Detected structurally: a SUBTRACTION whose operand is a `time.time()`
  call, or a local name assigned from one in the same scope. Plain
  `time.time()` timestamps (slowlog entries, snapshot metadata, expiry
  comparisons) stay legal — an absolute epoch is the only correct value
  for cross-restart persistence; only differencing it is the bug.
  Subtracting against a PERSISTED wall-clock epoch (index creation date)
  is the one legitimate exception: justify it inline
  (`# oslint: disable=OSL501 -- <why>`).
- OSL502: hot-path counters (search/, ops/, parallel/) must go through
  the metrics registry (`utils/metrics.py`: Counter.inc / CounterGroup),
  not a module-level dict mutated with `+=` — the read-modify-write
  races concurrent searches and silently drops counts, exactly the
  `fastpath.STATS` bug this PR retired. Detected: `D[k] += n` where `D`
  is a module-level ALL_CAPS name bound to a dict literal.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted


class TelemetryDisciplineChecker(Checker):
    rules = ("OSL501", "OSL502")
    name = "telemetry-discipline"

    OSL502_SCOPES = ("search/", "ops/", "parallel/")

    def applies(self, path: str) -> bool:
        return True

    # ---------------- helpers ----------------

    @staticmethod
    def _time_aliases(tree: ast.Module):
        """-> (module aliases of `time`, direct callables that ARE
        time.time, e.g. `from time import time as now`)."""
        mods: Set[str] = set()
        funcs: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mods.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        funcs.add(a.asname or "time")
        return mods, funcs

    def _is_walltime_call(self, node: ast.AST, mods: Set[str],
                          funcs: Set[str]) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = _dotted(node.func)
        if d in funcs:
            return True
        head, _, tail = d.rpartition(".")
        return tail == "time" and head in mods

    # ---------------- check ----------------

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        mods, funcs = self._time_aliases(tree)

        # ---- OSL501: wall-clock subtraction = duration smell ----
        if mods or funcs:
            # scopes: module body + each function body (nested functions
            # inherit the enclosing taint set — a closure differencing
            # its enclosing scope's t0 is the same bug)
            def scan(body, tainted: Set[str], sym_default: str) -> None:
                local = set(tainted)

                def expr_tainted(e: ast.AST) -> bool:
                    if self._is_walltime_call(e, mods, funcs):
                        return True
                    return isinstance(e, ast.Name) and e.id in local

                def visit(node: ast.AST) -> None:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        scan(node.body, local, qmap.get(node, node.name))
                        return
                    if isinstance(node, ast.Assign) and \
                            self._is_walltime_call(node.value, mods, funcs):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local.add(t.id)
                    if isinstance(node, ast.BinOp) and \
                            isinstance(node.op, ast.Sub) and \
                            (expr_tainted(node.left)
                             or expr_tainted(node.right)):
                        findings.append(Finding(
                            "OSL501", path, node.lineno, node.col_offset,
                            qmap.get(node, sym_default),
                            "duration computed from time.time(); use "
                            "time.monotonic()/perf_counter() — wall "
                            "clocks step and make latency numbers lie",
                            detail="walltime-sub"))
                    for child in ast.iter_child_nodes(node):
                        visit(child)

                for stmt in body:
                    visit(stmt)

            scan(list(tree.body), set(), "")

        # ---- OSL502: module-level CAPS counter dict mutated with += ----
        if any(s in path for s in self.OSL502_SCOPES):
            counter_dicts: Set[str] = set()
            for node in tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Dict):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id.isupper() \
                                and len(t.id) > 1:
                            counter_dicts.add(t.id)
            if counter_dicts:
                for node in ast.walk(tree):
                    if isinstance(node, ast.AugAssign) and \
                            isinstance(node.target, ast.Subscript) and \
                            isinstance(node.target.value, ast.Name) and \
                            node.target.value.id in counter_dicts:
                        dn = node.target.value.id
                        findings.append(Finding(
                            "OSL502", path, node.lineno, node.col_offset,
                            qmap.get(node, ""),
                            f"hot-path counter dict `{dn}` mutated with "
                            "`+=` (read-modify-write races concurrent "
                            "searches); route it through the metrics "
                            "registry (utils/metrics.py CounterGroup/"
                            "Counter.inc)",
                            detail=f"dict:{dn}"))
        return findings
