"""OSL603 — actuator discipline for the self-healing serving loop.

The remediation actuator (serving/remediator.py, docs/RESILIENCE.md
"Self-healing loop") acts on live traffic: it sheds shapes, tightens
admission, and pins members out of copy preference. The one invariant
that keeps an actuator safe is that EVERY engage path has a visible way
back: a paired release in the same file, or a TTL bound that expires
the action without human help. An engage with neither is a permanent
config mutation wearing a remediation costume — exactly the class of
"temporary" mitigation that outlives its incident.

The rule, enforced over `serving/` and `cluster/`:

- An **engage site** is a call with arguments whose method name is an
  actuation verb (`engage*`, `shed*`, `deprioritize*`, `pin*`), or a
  `def` of such a verb taking real parameters (no-arg accessors like
  `deprioritized()` / `pinned()` are reads, not actuations).
- A file containing an engage site must, IN THE SAME FILE, show a
  **release path**: a call or `def` whose name carries a release verb
  (`release`, `unpin`, `restore`, `disarm`), or **TTL evidence**: a
  `ttl`/`ttl_s` keyword on a call or an attribute/name containing
  `ttl` (the auto-expiry bound).

Deliberately one-shot sites (none exist today) suppress with
`# oslint: disable=OSL603 -- <who releases this, and when>`.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_SCOPES = ("opensearch_tpu/serving/", "opensearch_tpu/cluster/")

_ENGAGE_VERBS = ("engage", "shed", "deprioritize", "pin")
_RELEASE_TOKENS = ("release", "unpin", "restore", "disarm")


def _is_engage_name(name: str) -> bool:
    n = name.lstrip("_")
    for v in _ENGAGE_VERBS:
        if n == v or n.startswith(v + "_"):
            return True
    return False


def _is_release_name(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _RELEASE_TOKENS)


def _has_args(call: ast.Call) -> bool:
    return bool(call.args) or bool(call.keywords)


def _real_params(fn) -> bool:
    """True when the def takes parameters beyond self/cls — an accessor
    like `def pinned(self)` is a read, not an actuation."""
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args
            if a.arg not in ("self", "cls")]
    return bool(args or fn.args.vararg or fn.args.kwonlyargs
                or fn.args.kwarg)


class ActuatorDisciplineChecker(Checker):
    rules = ("OSL603",)
    name = "actuator-discipline"

    def applies(self, path: str) -> bool:
        return any(path.startswith(s) for s in _SCOPES)

    # ---------------- release / TTL evidence ----------------

    @staticmethod
    def _file_evidence(tree: ast.Module) -> dict:
        has_release = False
        has_ttl = False
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _is_release_name(node.name):
                    has_release = True
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and _is_release_name(d.rsplit(".", 1)[-1]):
                    has_release = True
                for kw in node.keywords:
                    if kw.arg and "ttl" in kw.arg.lower():
                        has_ttl = True
            elif isinstance(node, ast.Attribute):
                if "ttl" in node.attr.lower():
                    has_ttl = True
            elif isinstance(node, ast.Name):
                if "ttl" in node.id.lower():
                    has_ttl = True
        return {"release": has_release, "ttl": has_ttl}

    def check(self, tree: ast.Module, path: str,
              src: str) -> List[Finding]:
        evidence = self._file_evidence(tree)
        if evidence["release"] or evidence["ttl"]:
            return []
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        for node in ast.walk(tree):
            name = None
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                leaf = d.rsplit(".", 1)[-1] if d else ""
                if leaf and _is_engage_name(leaf) and _has_args(node):
                    name = leaf
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                if _is_engage_name(node.name) and _real_params(node):
                    name = node.name
            if name is None:
                continue
            findings.append(Finding(
                "OSL603", path, node.lineno, node.col_offset,
                qmap.get(node, ""),
                f"engage site [{name}] with no paired release/TTL "
                "bound in file: every remediation/shed/deprioritize "
                "action needs a visible way back (a release/unpin/"
                "restore path or a ttl bound) — docs/RESILIENCE.md "
                "\"Self-healing loop\"",
                detail=f"unreleased-engage:{name}"))
        return findings
