"""OSL3xx — memory-breaker discipline for long-lived host caches.

The fastpath caches ndocs-sized host arrays (masks, doc lists, aligned
layouts) on `Segment`s and services for the lifetime of the index
generation. Every such cache must charge the memory breaker and release
on eviction — otherwise large segments accumulate untracked host memory
(the ADVICE round-5 `search/fastpath.py:1009` `_quality_tier` finding).

Rule OSL301 fires when ONE function:
  1. stores into a long-lived cache — the `obj.__dict__.setdefault(...)`
     idiom this repo uses for per-segment caches, or a subscript store
     into an attribute whose name contains "cache" — AND
  2. allocates docs-scale host arrays (np.zeros/ones/full/empty/
     flatnonzero/nonzero/arange, or a FilterList) while mentioning
     `ndocs` — AND
  3. never references the memory accounting (any name containing
     "breaker" or — since the HBM ledger became the sole charge path,
     OSL506 — "ledger", e.g. `LEDGER.register(nbytes, ...)`).

Condition 3 is deliberately loose: the rule's job is to force the author
to THINK about accounting, not to verify the arithmetic. Suppress with
`# oslint: disable=OSL301 -- <why this cache is O(1)/already charged>`.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_ALLOCATORS = {"zeros", "ones", "full", "empty", "flatnonzero", "nonzero",
               "arange", "unique", "concatenate", "copy"}
_TRACKED_CTORS = {"FilterList"}


class BreakerDisciplineChecker(Checker):
    rules = ("OSL301",)
    name = "breaker-discipline"

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_fn(node, qmap.get(node, node.name), path,
                               findings)
        return findings

    def _check_fn(self, fn: ast.FunctionDef, sym: str, path: str,
                  findings: List[Finding]) -> None:
        cache_names: Set[str] = set()
        cache_stores: List[ast.AST] = []
        mentions_ndocs = False
        allocates = False
        mentions_breaker = False

        for node in ast.walk(fn):
            # cache = obj.__dict__.setdefault("...", ...)
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                f = node.value.func
                if isinstance(f, ast.Attribute) and f.attr == "setdefault" \
                        and isinstance(f.value, ast.Attribute) \
                        and f.value.attr == "__dict__":
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            cache_names.add(t.id)
            # cache[key] = value   /   self._x_cache[key] = value
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        base = t.value
                        if isinstance(base, ast.Name) and \
                                base.id in cache_names:
                            cache_stores.append(t)
                        elif isinstance(base, ast.Attribute) and \
                                "cache" in base.attr.lower():
                            cache_stores.append(t)
            if isinstance(node, ast.Attribute) and node.attr == "ndocs":
                mentions_ndocs = True
            if isinstance(node, ast.Name):
                if node.id == "ndocs":
                    mentions_ndocs = True
                if "breaker" in node.id.lower() or \
                        "ledger" in node.id.lower():
                    mentions_breaker = True
            if isinstance(node, ast.Attribute) and \
                    ("breaker" in node.attr.lower()
                     or "ledger" in node.attr.lower()):
                mentions_breaker = True
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                leaf = d.rsplit(".", 1)[-1]
                if leaf in _ALLOCATORS and d.split(".", 1)[0] in (
                        "np", "numpy", "jnp"):
                    allocates = True
                if leaf in _TRACKED_CTORS:
                    allocates = True

        if cache_stores and mentions_ndocs and allocates \
                and not mentions_breaker:
            store = cache_stores[0]
            findings.append(Finding(
                "OSL301", path, store.lineno, store.col_offset, sym,
                "ndocs-scale host allocation cached on a long-lived "
                "object without memory accounting; register it with "
                "`LEDGER.register(kind, nbytes, owner=obj, ...)` "
                "(obs/hbm_ledger.py derives the breaker charge and the "
                "owner-GC release)",
                detail=f"cache@{sym}"))
