"""OSL507 — quantized-impact domain discipline (segment codec v2).

The codec-v2 impact planes (index/segment.py `ImpactPlane`) live in a
QUANTIZED integer domain: u8/u16 values whose only sound route into f32
score math is the designated dequant helpers
(`ops/scoring.py dequant_impact` / `dequant_impact_np`). Every ad-hoc
`astype(float32)` / `float32(...)` promotion of impact data bypasses the
one place the scale multiply (and therefore the serve-margin error
bookkeeping, docs/INDEX_FORMAT.md) is defined. Three ways code breaks
the discipline:

1. **Raw dequantization.** A float cast/constructor applied to an
   identifier that names impact-plane data (`*impact*`, `*block_max*`)
   outside the helper definitions.
2. **Version-blind layout branches.** Code in `search/` that branches on
   the v2 layout (reads a `.impact` attribute) without consulting
   `Segment.codec_version` anywhere in the same function: presence
   checks alone rot when a codec v3 arrives, and the version attribute
   is the documented gate (the `getattr(pb, "impact", ...)` duck form is
   exempt — it is the facade-tolerant probe, not a layout branch).
3. **Magic codec numbers.** Comparing `codec_version` against a bare int
   literal instead of the named `CODEC_V1`/`CODEC_V2` constants.

Suppress deliberate exceptions with
`# oslint: disable=OSL507 -- <why the domain/gate is sound>`.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

# the helper definitions themselves (and their fixtures) may touch the
# quantized domain directly
_HELPER_FILES = ("ops/scoring.py",)
_IMPACT_TOKENS = ("impact", "block_max")
_FLOAT_CTORS = {"float32", "float64", "float16", "bfloat16", "float"}
_SCOPES = ("opensearch_tpu/search/", "opensearch_tpu/ops/",
           "opensearch_tpu/index/", "opensearch_tpu/parallel/")


def _impactish(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _IMPACT_TOKENS)


def _expr_name(node: ast.AST) -> str:
    """Best-effort name of the value being cast ('plane.block_max',
    'impacts', ...)."""
    d = _dotted(node)
    if d:
        return d
    if isinstance(node, ast.Subscript):
        return _expr_name(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


class ImpactDomainChecker(Checker):
    rules = ("OSL507",)
    name = "impact-domain"

    def applies(self, path: str) -> bool:
        return any(s in path for s in _SCOPES) and "devtools" not in path

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        helper_file = any(path.endswith(h) for h in _HELPER_FILES)

        # ---- rule 1: raw float promotion of impact-plane data ----
        if not helper_file:
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                target = None
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args:
                    dt = _dotted(node.args[0]).rsplit(".", 1)[-1]
                    if dt in _FLOAT_CTORS:
                        target = _expr_name(node.func.value)
                else:
                    fn = _dotted(node.func).rsplit(".", 1)[-1]
                    if fn in _FLOAT_CTORS and node.args:
                        target = _expr_name(node.args[0])
                if target and _impactish(target):
                    findings.append(Finding(
                        "OSL507", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        f"raw float promotion of quantized impact data "
                        f"(`{target}`); route through the designated "
                        "dequant helpers (ops/scoring.py dequant_impact /"
                        " dequant_impact_np) so the scale multiply and "
                        "the serve-margin error bookkeeping stay in one "
                        "place", detail=f"dequant:{target}"))

        # ---- rules 2+3: codec-version gate discipline ----
        in_search = "opensearch_tpu/search/" in path
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            mentions_codec = False
            layout_reads: List[ast.Attribute] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) \
                        and node.attr == "codec_version":
                    mentions_codec = True
                elif isinstance(node, ast.Constant) \
                        and node.value == "codec_version":
                    mentions_codec = True   # getattr(seg, "codec_version")
                elif isinstance(node, ast.Attribute) \
                        and node.attr == "impact":
                    layout_reads.append(node)
            if in_search and layout_reads and not mentions_codec:
                n = layout_reads[0]
                findings.append(Finding(
                    "OSL507", path, n.lineno, n.col_offset,
                    qmap.get(n, ""),
                    "codec-v2 layout branch (reads `.impact`) without "
                    "consulting Segment.codec_version in the same "
                    "function — the version attribute is the documented "
                    "gate (plane presence alone rots at the next codec "
                    "rev)", detail="version-blind"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            has_codec = any(isinstance(s, ast.Attribute)
                            and s.attr == "codec_version" for s in sides)
            lit = any(isinstance(s, ast.Constant)
                      and isinstance(s.value, int)
                      and not isinstance(s.value, bool) for s in sides)
            if has_codec and lit:
                findings.append(Finding(
                    "OSL507", path, node.lineno, node.col_offset,
                    qmap.get(node, ""),
                    "codec_version compared against a bare int literal; "
                    "use the named constants (index/segment.py "
                    "CODEC_V1/CODEC_V2)", detail="magic-codec"))
        return findings
