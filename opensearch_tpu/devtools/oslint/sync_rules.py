"""OSL504 — device-sync discipline for launch-stage code.

The pipelined dispatch split (docs/SERVING.md) only buys overlap if the
LAUNCH stage never blocks on device results: one stray `jax.device_get`
in a `launch_*` body silently re-serializes host and device and the
in-flight window measures nothing. This checker is the static guard that
keeps the split from regressing.

Scope: `search/`, `parallel/` and `serving/` modules. Launch-stage
scopes are detected structurally:

- any function whose name starts with `launch_` or `_launch` (the
  repo-wide naming convention for launch-stage entry points and stages),
- plus the serving dispatcher's hot-path methods in
  `serving/scheduler.py` (`_loop`, `_wait_flush`, `_assemble`,
  `_enqueue_inflight`) — the thread that must get back to assembling the
  next batch immediately.

Nested function definitions inside a launch scope are NOT checked: a
closure's body runs when called, and the launch/fetch split's whole
idiom is a `_fetch_*`/`_finish` closure capturing unfetched arrays for
deferred execution.

Flagged inside a launch scope:

- `jax.device_get(...)` (through any module alias, or
  `from jax import device_get`),
- `<expr>.block_until_ready(...)`,
- `np.asarray(x)` / `np.array(x)` where `x`'s name follows the repo's
  device-array naming (`d_*`, `*_dev`, `dev_*`, or containing
  `device`) — the lexical slice of "np.asarray on a jax Array forces a
  transfer" that static analysis can see. Host-array asarray calls with
  host-style names stay legal.

Suppress a justified sync with
`# oslint: disable=OSL504 -- <why this launch path must block>`.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_DEVICE_NAME_RE = re.compile(r"^d_|^dev_|_dev$|device")

_DISPATCHER_METHODS = {"_loop", "_wait_flush", "_assemble",
                       "_enqueue_inflight"}


def _is_launch_scope(name: str, path: str) -> bool:
    if name.startswith("launch_") or name.startswith("_launch"):
        return True
    return path.endswith("serving/scheduler.py") \
        and name in _DISPATCHER_METHODS


def _devicey(node: ast.AST) -> bool:
    """True when the expression's trailing name segment follows the
    repo's device-array naming convention."""
    d = _dotted(node)
    if not d:
        return False
    last = d.rsplit(".", 1)[-1]
    return bool(_DEVICE_NAME_RE.search(last))


class DeviceSyncDisciplineChecker(Checker):
    rules = ("OSL504",)
    name = "device-sync-discipline"

    SCOPES = ("search/", "parallel/", "serving/")

    def applies(self, path: str) -> bool:
        return any(s in path for s in self.SCOPES)

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)

        # module aliases so `import jax as j; j.device_get` and
        # `from jax import device_get as dg` are both seen
        jax_mods: Set[str] = set()
        devget_funcs: Set[str] = set()
        np_mods: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        jax_mods.add(a.asname or "jax")
                    elif a.name == "numpy":
                        np_mods.add(a.asname or "numpy")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "device_get":
                            devget_funcs.add(a.asname or "device_get")
        np_mods.add("np")       # function-local `import numpy as np`
        jax_mods.add("jax")     # and `import jax` inside the function

        def classify(call: ast.Call) -> str:
            d = _dotted(call.func)
            if d in devget_funcs:
                return "device_get"
            head, _, tail = d.rpartition(".")
            if tail == "device_get" and head in jax_mods:
                return "device_get"
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "block_until_ready":
                return "block_until_ready"
            if tail in ("asarray", "array") and head in np_mods \
                    and call.args and _devicey(call.args[0]):
                return f"asarray:{_dotted(call.args[0])}"
            return ""

        def walk(node: ast.AST, sym: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # deferred execution: the fetch-stage closure idiom
                return
            if isinstance(node, ast.Call):
                what = classify(node)
                if what:
                    findings.append(Finding(
                        "OSL504", path, node.lineno, node.col_offset, sym,
                        f"blocking device sync ({what.split(':')[0]}) in "
                        "launch-stage code; move it into the fetch "
                        "closure — the launch stage must return with "
                        "unfetched arrays (docs/SERVING.md pipeline)",
                        detail=f"sync:{what}"))
            for child in ast.iter_child_nodes(node):
                walk(child, sym)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _is_launch_scope(node.name, path):
                sym = qmap.get(node, node.name)
                for stmt in node.body:
                    walk(stmt, sym)
        return findings
