"""OSL505 — flight-recorder / slowlog emission discipline.

The flight recorder (obs/flight_recorder.py) lives on the serving and
search hot paths; its whole design contract is that the DISABLED path
costs one attribute read. Two ways an emission site silently breaks
that, and one way it breaks forensics:

- **Eager payloads.** `RECORDER.record(tl, kind, **fields)` builds its
  keyword dict (and any f-strings inside it) BEFORE the callee can check
  `enabled`. Every event-emission call must therefore sit inside a guard
  that short-circuits when the recorder is off: an `if` whose test reads
  `.enabled`, or an `if <tl>:` on the call's own timeline id (a timeline
  id is only ever non-zero when the recorder was enabled at `start()`).
- **Wall-clock timestamps.** Event times must come from the monotonic
  clock; a `time.time()` anywhere in a record call's arguments makes the
  journal re-orderable under NTP steps (the ring's dump conversion owns
  the single wall anchor).
- **Eager slowlog extras.** `SlowLog.maybe_log(..., extra=...)` invokes
  a callable extra only when a threshold fires; passing a dict literal
  (or anything holding an f-string) builds the attribution payload on
  EVERY request — exactly the cost `maybe_log`'s lazy contract exists to
  avoid.

Event-emission calls are recognized structurally: an attribute call
named `.record` with two or more positional arguments or any keyword
argument — which distinguishes them from the one-argument histogram
(`LatencyHistogram.record(ms)`) and workload (`WorkloadGroup.record(s)`)
records.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted


def _contains_enabled(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr == "enabled":
            return True
    return False


def _test_names(test: ast.AST) -> Set[str]:
    """Plain and dotted names referenced by a guard test (`tl`,
    `e.tl`, `entry.tl` ...)."""
    out: Set[str] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            d = _dotted(n)
            if d:
                out.add(d)
    return out


def _first_arg_name(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Name):
        return a.id
    if isinstance(a, ast.Attribute):
        return _dotted(a) or None
    return None


class RecorderDisciplineChecker(Checker):
    rules = ("OSL505",)
    name = "recorder-discipline"

    SCOPES = ("serving/", "search/", "parallel/", "rest/", "cluster/",
              "utils/", "ops/")
    EXEMPT = ("obs/", "devtools/")

    def applies(self, path: str) -> bool:
        if any(s in path for s in self.EXEMPT):
            return False
        return any(s in path for s in self.SCOPES)

    # ---------------- helpers ----------------

    @staticmethod
    def _is_event_record(node: ast.Call) -> bool:
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and (len(node.args) >= 2 or bool(node.keywords)))

    @staticmethod
    def _walltime_in_args(node: ast.Call, mods: Set[str],
                          funcs: Set[str]) -> bool:
        for sub in ast.walk(node):
            if sub is node or not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d in funcs:
                return True
            head, _, tail = d.rpartition(".")
            if tail == "time" and head in mods:
                return True
        return False

    @staticmethod
    def _time_aliases(tree: ast.Module):
        mods: Set[str] = set()
        funcs: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mods.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        funcs.add(a.asname or "time")
        return mods, funcs

    @staticmethod
    def _eager_extra(kw: ast.keyword) -> bool:
        v = kw.value
        if isinstance(v, (ast.Dict, ast.DictComp)):
            return True
        return any(isinstance(n, ast.JoinedStr) for n in ast.walk(v))

    # ---------------- check ----------------

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        mods, funcs = self._time_aliases(tree)

        def visit(node: ast.AST, guards: List[ast.AST]) -> None:
            if isinstance(node, ast.If):
                for child in node.body:
                    visit(child, guards + [node.test])
                for child in node.orelse:
                    visit(child, guards)
                return
            if isinstance(node, ast.Call) and self._is_event_record(node):
                tl_name = _first_arg_name(node)
                guarded = any(
                    _contains_enabled(t)
                    or (tl_name is not None and tl_name in _test_names(t))
                    for t in guards)
                if not guarded:
                    findings.append(Finding(
                        "OSL505", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        "flight-recorder event emitted without an "
                        "`if RECORDER.enabled:` (or `if <timeline>:`)"
                        " guard — the payload dict is built even when "
                        "the recorder is disabled",
                        detail="unguarded-record"))
                if self._walltime_in_args(node, mods, funcs):
                    findings.append(Finding(
                        "OSL505", path, node.lineno, node.col_offset,
                        qmap.get(node, ""),
                        "time.time() inside a recorder event — event "
                        "timestamps must be monotonic (the dump "
                        "conversion owns the single wall anchor)",
                        detail="walltime-event"))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "maybe_log":
                for kw in node.keywords:
                    if kw.arg == "extra" and self._eager_extra(kw):
                        findings.append(Finding(
                            "OSL505", path, node.lineno, node.col_offset,
                            qmap.get(node, ""),
                            "slowlog `extra` built eagerly (dict "
                            "literal / f-string); pass a callable so "
                            "the attribution payload is only built "
                            "when a threshold fires",
                            detail="eager-slowlog-extra"))
            for child in ast.iter_child_nodes(node):
                visit(child, guards)

        visit(tree, [])
        findings.sort(key=lambda f: (f.line, f.detail))
        return findings
