"""OSL602 — cardinality discipline for workload-keyed observability.

The query-insights engine (obs/insights.py) aggregates per query SHAPE:
a key derived from user traffic. Two ways that quietly goes wrong, each
encoded here (the discipline the module's design follows):

- **Unbounded keyed growth.** A record path that does
  `self.<attr>[key] = ...` / `.setdefault(key, ...)` keyed by workload
  input grows with workload *cardinality* — O(distinct shapes) memory
  wearing an attribution costume. Every keyed store on an obs/ record
  path must carry an explicit capacity bound IN SCOPE: built as a
  `deque(maxlen=...)`, or guarded by a `len(...)`-vs-capacity check /
  eviction (`.pop`/`.popitem`/`del`) on the same attribute in the same
  file. Per-call LOCAL dicts are fine — they die with the call.
- **Raw query text in label positions.** A metric name built from a
  variable that smells like query text (`query`, `body`, `text`,
  `source`, `q_str`) puts unbounded user strings into the metrics
  registry AND leaks request content into scrape output. Labels and
  metric names carry shape HASHES, lane names, and enum-like kinds —
  never the query. (`fingerprint()` strips values structurally; this
  rule patrols the registry boundary.)

Scope: the keyed-growth rule patrols `obs/` record paths (functions
named `record*`/`note*`/`observe*`/`ingest*`/`_record*`/`_note*`);
the label rule patrols `obs/`, `utils/`, `rest/`, `search/`,
`serving/`, `cluster/` — everywhere instruments are minted.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_RECORD_PREFIXES = ("record", "note", "observe", "ingest",
                    "_record", "_note", "_observe", "_ingest")

# variables whose NAME marks them as (potential) raw query text; the
# discriminator is the name at the registry boundary, which is exactly
# what a reviewer reads
_TEXTY_NAMES = ("query", "body", "text", "source", "q_str", "raw")

_INSTRUMENT_FACTORIES = ("counter", "gauge", "histogram", "timer")

_EVICT_METHODS = ("pop", "popitem", "popleft", "clear")

_CAP_NAMES = ("cap", "capacity", "max", "limit", "bound")


def _is_record_fn(name: str) -> bool:
    return any(name.startswith(p) for p in _RECORD_PREFIXES)


def _texty(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in _TEXTY_NAMES)


class InsightsCardinalityChecker(Checker):
    rules = ("OSL602",)
    name = "insights-cardinality"

    GROWTH_SCOPES = ("obs/",)
    LABEL_SCOPES = ("obs/", "utils/", "rest/", "search/", "serving/",
                    "cluster/")
    EXEMPT = ("devtools/",)

    def applies(self, path: str) -> bool:
        if any(s in path for s in self.EXEMPT):
            return False
        return any(s in path for s in self.LABEL_SCOPES)

    # ---------------- bounded-evidence collection ----------------

    @staticmethod
    def _bounded_attrs(tree: ast.Module) -> Set[str]:
        """Attribute names the file proves bounded:
        - assigned from `deque(maxlen=...)`;
        - appearing inside a `len(self.<attr>)` comparison (the
          explicit capacity check);
        - target of an eviction call (`self.<attr>.pop/popitem/...`)
          or a `del self.<attr>[...]` anywhere in the file."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                bounded_ctor = (
                    isinstance(value, ast.Call)
                    and _dotted(value.func).split(".")[-1] == "deque"
                    and any(kw.arg == "maxlen"
                            for kw in value.keywords))
                # a fixed-size slot ring: `[None] * capacity` — bounded
                # by construction (the flight-recorder pattern)
                fixed_ring = (
                    isinstance(value, ast.BinOp)
                    and isinstance(value.op, ast.Mult)
                    and any(isinstance(s, ast.List)
                            for s in (value.left, value.right)))
                if bounded_ctor or fixed_ring:
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        if isinstance(t, ast.Attribute):
                            out.add(t.attr)
            elif isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    if (isinstance(side, ast.Call)
                            and _dotted(side.func) == "len"
                            and side.args
                            and isinstance(side.args[0], ast.Attribute)):
                        out.add(side.args[0].attr)
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _EVICT_METHODS
                        and isinstance(f.value, ast.Attribute)):
                    out.add(f.value.attr)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Attribute):
                        out.add(t.value.attr)
        return out

    # ---------------- the two sub-rules ----------------

    @staticmethod
    def _self_attr(node: ast.AST):
        """`self.<attr>` -> attr name, else None — the rule patrols
        INSTANCE state (what outlives the call); locals and entry
        objects die with their owner's own bounds."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _scan_growth(self, fn: ast.AST, sym: str, bounded: Set[str],
                     path: str, findings: List[Finding]) -> None:
        for node in ast.walk(fn):
            attr = None
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and not isinstance(t.slice, ast.Constant)):
                        attr = self._self_attr(t.value)
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in ("setdefault", "append")):
                    attr = self._self_attr(f.value)
            if attr is None or attr in bounded:
                continue
            findings.append(Finding(
                "OSL602", path, node.lineno, node.col_offset, sym,
                f"workload-keyed growth of `.{attr}` on an obs/ record "
                f"path with no capacity bound in scope — per-key stores "
                f"must be a deque(maxlen=...), len()-capacity-checked, "
                f"or evicted in this file (memory must be O(capacity), "
                f"not O(workload cardinality))",
                detail=f"unbounded-keyed-growth:{attr}"))

    @staticmethod
    def _name_smells(expr: ast.AST) -> bool:
        """Does a metric-name expression interpolate a query-texty
        variable? f-strings, %-format, .format and + concat."""
        parts: List[ast.AST] = []
        if isinstance(expr, ast.JoinedStr):
            parts = [v.value for v in expr.values
                     if isinstance(v, ast.FormattedValue)]
        elif isinstance(expr, ast.BinOp):
            parts = [expr.left, expr.right]
        elif (isinstance(expr, ast.Call)
              and isinstance(expr.func, ast.Attribute)
              and expr.func.attr == "format"):
            parts = list(expr.args)
        for p in parts:
            d = _dotted(p)
            if d and any(_texty(seg) for seg in d.split(".")):
                return True
        return False

    def _scan_labels(self, tree: ast.Module, qmap, path: str,
                     findings: List[Finding]) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _INSTRUMENT_FACTORIES):
                continue
            if not node.args:
                continue
            if self._name_smells(node.args[0]):
                findings.append(Finding(
                    "OSL602", path, node.lineno, node.col_offset,
                    qmap.get(node, ""),
                    "metric name interpolates a query/body-like "
                    "variable — labels and names carry shape hashes, "
                    "lanes and enum kinds, never raw query text "
                    "(fingerprint it first: obs/insights.py)",
                    detail="raw-query-in-metric-name"))

    # ---------------- driver ----------------

    def check(self, tree: ast.Module, path: str,
              src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        self._scan_labels(tree, qmap, path, findings)
        if any(s in path for s in self.GROWTH_SCOPES):
            bounded = self._bounded_attrs(tree)
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and _is_record_fn(node.name):
                    self._scan_growth(node, qmap.get(node, node.name),
                                      bounded, path, findings)
        findings.sort(key=lambda f: (f.line, f.detail))
        return findings
