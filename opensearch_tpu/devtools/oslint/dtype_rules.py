"""OSL1xx — dtype discipline for score/count planes.

The fastpath's correctness proofs (pruned-serve certificates, tie
witnesses) hold in ONE float domain: scores served to users are float32,
so every comparison against a served score/theta must happen after the
same float32 rounding `_exact_rescore` applies. Mixing a float64
intermediate into such a comparison reintroduces the exact bug class of
ADVICE round-5 `search/fastpath.py:823` (a contribution half an ulp below
theta in f64 rounds UP to theta in f32 — the tie witness is skipped).

Rules:
- OSL101: comparison mixing a definite-float32 value (np.float32(...),
  x.astype(np.float32), f32-dtype constructors) with a float64-tainted
  expression (float(...) / np.float64 / .astype(float64) and arithmetic
  derived from them). Cast to float32 first.
- OSL102: integer count derived by rounding a float plane —
  `int(round(x))` — where the host loop / pair-metrics program counts on
  an int32 plane. f32 sums stop counting exactly at 2^24 docs
  (ADVICE round-5 `parallel/service.py:1491`).

Scope: `search/`, `ops/`, `parallel/` — the modules where score and count
planes live.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

# inference domains
F32 = "f32"
F64 = "f64"
INT = "int"
NEUTRAL = "neutral"    # python literals: promote to nothing
UNKNOWN = "unknown"

_F32_NAMES = {"float32"}
_F64_NAMES = {"float64", "double"}


def _dtype_domain(node: ast.AST) -> Optional[str]:
    """Domain named by a dtype expression: np.float32 / 'float32' / float /
    jnp.float32 — or None if unrecognized."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value in _F32_NAMES:
            return F32
        if node.value in _F64_NAMES:
            return F64
        return None
    d = _dotted(node)
    leaf = d.rsplit(".", 1)[-1]
    if leaf in _F32_NAMES:
        return F32
    if leaf in _F64_NAMES or d == "float":
        return F64
    if leaf in ("int32", "int64", "bool_", "bool"):
        return INT
    return None


_ALLOC_FNS = {"zeros", "ones", "full", "empty", "asarray", "array",
              "zeros_like", "ones_like", "full_like", "arange", "linspace"}
_PROPAGATE_FNS = {"max", "min", "abs", "sum", "round"}


class _FnScanner:
    """Forward-pass domain inference over one function body (order of
    appearance; control flow joins are ignored — later writes win, which
    is the conservative choice for this rule's definite-only matching)."""

    def __init__(self, checker: "DtypeDisciplineChecker", path: str,
                 symbol: str, findings: List[Finding]):
        self.env: Dict[str, str] = {}
        self.checker = checker
        self.path = path
        self.symbol = symbol
        self.findings = findings

    # ---- expression classification ----

    def classify(self, node: ast.AST) -> str:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return INT
            if isinstance(node.value, int):
                return INT
            if isinstance(node.value, float):
                return NEUTRAL
            return UNKNOWN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Subscript):
            # element of an f32 array is f32; of an unknown, unknown
            return self.classify(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand)
        if isinstance(node, ast.BinOp):
            return self._combine(self.classify(node.left),
                                 self.classify(node.right))
        if isinstance(node, ast.IfExp):
            a, b = self.classify(node.body), self.classify(node.orelse)
            if F64 in (a, b):
                return F64
            return a if a == b else UNKNOWN
        if isinstance(node, ast.Call):
            return self._classify_call(node)
        return UNKNOWN

    @staticmethod
    def _combine(a: str, b: str) -> str:
        if F64 in (a, b):
            return F64
        if UNKNOWN in (a, b):
            return UNKNOWN
        if F32 in (a, b):
            return F32          # f32 op {f32, int, literal} stays f32
        if a == b:
            return a
        return UNKNOWN

    def _classify_call(self, node: ast.Call) -> str:
        d = _dotted(node.func)
        leaf = d.rsplit(".", 1)[-1]
        # direct casts: np.float32(x), float(x), np.float64(x)
        dom = _dtype_domain(node.func)
        if dom is not None:
            return dom
        # x.astype(dtype)
        if isinstance(node.func, ast.Attribute) and leaf == "astype" \
                and node.args:
            dt = _dtype_domain(node.args[0])
            return dt if dt is not None else UNKNOWN
        # int(x) / round(x) -> int plane
        if d == "int":
            return INT
        # allocators with a dtype argument
        if leaf in _ALLOC_FNS:
            dt_node = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt_node = kw.value
            if dt_node is None and len(node.args) >= 2:
                dt_node = node.args[-1]
            if dt_node is not None:
                dt = _dtype_domain(dt_node)
                if dt is not None:
                    return dt
            return UNKNOWN
        # max/min/abs/...: propagate the strongest operand domain
        if d in _PROPAGATE_FNS:
            doms = [self.classify(a) for a in node.args]
            if F64 in doms:
                return F64
            if all(x == INT for x in doms) and doms:
                return INT
            if F32 in doms and UNKNOWN not in doms:
                return F32
            return UNKNOWN
        return UNKNOWN

    # ---- statement walk ----

    def scan_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._scan_stmt(stmt)

    @staticmethod
    def _walk_same_scope(stmt: ast.stmt):
        """ast.walk that does NOT descend into nested defs/lambdas (those
        get their own scanner and environment)."""
        yield stmt
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            yield from _FnScanner._walk_same_scope(child)

    def _scan_stmt(self, stmt: ast.stmt) -> None:
        # nested defs are scanned separately by the checker
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        for node in self._walk_same_scope(stmt):
            if isinstance(node, ast.Assign):
                dom = self.classify(node.value)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.env[t.id] = dom
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    self.env[node.target.id] = self.classify(node.value)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    self.env[node.target.id] = self._combine(
                        self.env.get(node.target.id, UNKNOWN),
                        self.classify(node.value))
            elif isinstance(node, ast.Compare):
                self._check_compare(node)
            elif isinstance(node, ast.Call):
                self._check_int_round(node)

    def _check_compare(self, node: ast.Compare) -> None:
        if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in node.ops):
            return
        doms = [self.classify(e) for e in [node.left] + node.comparators]
        if F32 in doms and F64 in doms:
            self.findings.append(Finding(
                "OSL101", self.path, node.lineno, node.col_offset,
                self.symbol,
                "comparison mixes float32 and float64 score domains; "
                "cast the f64 intermediate with .astype(np.float32) so "
                "the compare runs in the served f32 domain",
                detail=f"cmp@{self.symbol or 'module'}"))

    def _check_int_round(self, node: ast.Call) -> None:
        # int(round(x)) — float-plane count laundering
        if _dotted(node.func) != "int" or len(node.args) != 1:
            return
        inner = node.args[0]
        while isinstance(inner, ast.Call) and _dotted(inner.func) == "float" \
                and len(inner.args) == 1:
            inner = inner.args[0]
        if isinstance(inner, ast.Call) and _dotted(inner.func) == "round":
            if inner.args and self.classify(inner.args[0]) == INT:
                return
            self.findings.append(Finding(
                "OSL102", self.path, node.lineno, node.col_offset,
                self.symbol,
                "integer count derived by rounding a float plane; count "
                "on an int32 plane (f32 sums stop counting exactly at "
                "2^24 docs)",
                detail=f"intround@{self.symbol or 'module'}"))


class DtypeDisciplineChecker(Checker):
    rules = ("OSL101", "OSL102")
    name = "dtype-discipline"

    SCOPES = ("search/", "ops/", "parallel/")

    def applies(self, path: str) -> bool:
        return any(s in path for s in self.SCOPES)

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        # module level + each function get an independent environment
        mod_scan = _FnScanner(self, path, "", findings)
        mod_scan.scan_body([s for s in tree.body])
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _FnScanner(self, path, qmap.get(node, node.name),
                                  findings)
                scan.scan_body(node.body)
        return findings
