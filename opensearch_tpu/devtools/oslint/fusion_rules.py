"""OSL604 — hybrid-fusion score-domain discipline.

Hybrid retrieval (search/fusion.py) fuses ranked lists whose scores live
in INCOMPARABLE similarity domains: BM25 term sums are unbounded and
corpus-dependent, cosine kNN lives in [0, 1], learned-sparse dot
products scale with model weight magnitudes. A linear combination of
raw scores from different sub-queries is therefore meaningless — it
silently ranks by whichever domain has the largest magnitude. The
engine's contract (docs/HYBRID.md):

- every LINEAR combination of sub-query scores passes each list through
  a designated normalizer first (`fusion.normalize_scores` /
  `minmax_normalize` / `l2_normalize`), and
- RRF fuses in the RANK domain (`rank_constant`), which is
  score-domain-free by construction and needs no normalizer.

The rule: inside any fusion-shaped function (name mentions
fuse/combine/hybrid) in `search/` or `serving/`, an additive
combination whose operands are score-named expressions flags UNLESS the
function either calls a normalizer or demonstrably fuses in the rank
domain (`rank_constant` in scope). Accessors and out-of-scope files
stay quiet.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_FUSE_MARKERS = ("fuse", "combine", "hybrid")

# the designated score-domain normalizers (search/fusion.py); any
# project-local helper ending in `_normalize` also counts — the point is
# an EXPLICIT normalization step, not one blessed symbol
_NORMALIZERS = ("normalize_scores", "minmax_normalize", "l2_normalize")


def _is_fuse_fn(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in _FUSE_MARKERS)


def _scorey(expr: ast.AST) -> bool:
    """Does this operand reference a score-named value (possibly through
    a weight multiply or a subscript)?"""
    for node in ast.walk(expr):
        d = _dotted(node)
        if d and any("score" in seg.lower() for seg in d.split(".")):
            return True
        if isinstance(node, ast.Subscript):
            d = _dotted(node.value)
            if d and any("score" in seg.lower() for seg in d.split(".")):
                return True
    return False


class FusionDomainChecker(Checker):
    rules = ("OSL604",)
    name = "fusion-domain"

    SCOPES = ("search/", "serving/")
    EXEMPT = ("devtools/",)

    def applies(self, path: str) -> bool:
        if any(s in path for s in self.EXEMPT):
            return False
        return any(s in path for s in self.SCOPES)

    @staticmethod
    def _has_normalizer(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                leaf = _dotted(node.func).split(".")[-1]
                if leaf in _NORMALIZERS or leaf.endswith("_normalize"):
                    return True
        return False

    @staticmethod
    def _rank_domain(fn: ast.AST) -> bool:
        """RRF evidence: the function reads `rank_constant` (a name or
        a subscript key) — reciprocal-rank fusion never touches raw
        scores, so it is exempt by construction."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and "rank_constant" in node.id:
                return True
            if isinstance(node, ast.Constant) \
                    and node.value == "rank_constant":
                return True
        return False

    def _scan_fn(self, fn: ast.AST, sym: str, path: str,
                 findings: List[Finding]) -> None:
        if self._has_normalizer(fn) or self._rank_domain(fn):
            return
        for node in ast.walk(fn):
            if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                          ast.Add):
                # a + b: both sides must look like scores (a weighted
                # multiply counts through _scorey's walk)
                if not (_scorey(node.left) and _scorey(node.right)):
                    continue
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Add):
                # accumulating into (or from) a score-named variable
                if not (_scorey(node.target) or _scorey(node.value)):
                    continue
            else:
                continue
            findings.append(Finding(
                "OSL604", path, node.lineno, node.col_offset, sym,
                "linear combination of raw sub-query scores without a "
                "score-domain normalizer in scope — BM25/cosine/"
                "sparse-dot scores are incomparable; pass each list "
                "through fusion.normalize_scores (min_max/l2) first, "
                "or fuse in the rank domain (RRF / rank_constant) "
                "(docs/HYBRID.md)",
                detail="unnormalized-linear-fusion"))

    def check(self, tree: ast.Module, path: str,
              src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _is_fuse_fn(node.name):
                self._scan_fn(node, qmap.get(node, node.name), path,
                              findings)
        findings.sort(key=lambda f: (f.line, f.detail))
        return findings
