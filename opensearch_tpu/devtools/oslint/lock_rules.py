"""OSL4xx — lock discipline for threaded modules. OSL503 — wait
discipline (no sleep-polling) for the serving/threadpool hot paths.

The cluster/rest/ingest layers and the fastpath's shared caches are hit
from request threads concurrently. Two invariants, both checked
structurally per module:

- OSL401: an instance attribute mutated BOTH under a `with <lock>:` block
  and outside any lock (in a non-__init__ method) — the unlocked write
  races the locked readers. Either take the lock or document why the
  write is safe (`# oslint: disable=OSL401 -- <why>`).
- OSL402: inconsistent lock-acquisition order — lock B taken while
  holding A in one place, and A taken while holding B in another. That
  is the textbook deadlock shape; pick one order.

Locks are recognized as (a) names/attributes assigned from
`threading.Lock()/RLock()/Condition()` anywhere in the module, or (b) any
`with` target whose dotted name contains "lock"/"cond"/"mutex".
Explicit .acquire()/.release() pairs are NOT modeled — prefer `with`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .core import Checker, Finding, qualname_map
from .core import dotted_name as _dotted

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__reduce__",
                   "__getstate__", "__setstate__"}


def _looks_like_lock(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in ("lock", "cond", "mutex"))


class LockDisciplineChecker(Checker):
    rules = ("OSL401", "OSL402")
    name = "lock-discipline"

    SCOPES = ("cluster/", "rest/", "ingest/")
    EXTRA_FILES = ("search/fastpath.py",)

    def applies(self, path: str) -> bool:
        return any(s in path for s in self.SCOPES) \
            or any(path.endswith(e) for e in self.EXTRA_FILES)

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        qmap = qualname_map(tree)

        # module-wide lock identities: textual dotted names assigned from
        # threading constructors
        declared_locks: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                if _dotted(node.value.func).rsplit(".", 1)[-1] in \
                        _LOCK_CTORS:
                    for t in node.targets:
                        d = _dotted(t)
                        if d:
                            declared_locks.add(d)
        if not declared_locks and "threading" not in src:
            return findings

        def is_lock_expr(e: ast.AST) -> str:
            """Dotted lock key of a with-item, or ''."""
            d = _dotted(e)
            if not d:
                return ""
            if d in declared_locks or _looks_like_lock(d):
                return d
            return ""

        # per-class mutation ledger: attr -> [(locked?, node, symbol)]
        # lock-order ledger: ordered pair -> first site
        order_sites: Dict[Tuple[str, str], Tuple[ast.AST, str]] = {}

        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            mutations: Dict[str, List[Tuple[bool, ast.AST, str]]] = {}

            for method in [n for n in cls.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]:
                sym = qmap.get(method, method.name)
                exempt = method.name in _EXEMPT_METHODS

                def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        locks = [k for k in
                                 (is_lock_expr(it.context_expr)
                                  for it in node.items) if k]
                        new_held = held
                        for lk in locks:
                            for outer in new_held:
                                if outer != lk:
                                    key = (outer, lk)
                                    order_sites.setdefault(
                                        key, (node, sym))
                            new_held = new_held + (lk,)
                        for child in node.body:
                            walk(child, new_held)
                        return
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            node is not method:
                        return      # nested defs: separate discipline
                    if not exempt and isinstance(node, (ast.Assign,
                                                        ast.AugAssign)):
                        targets = (node.targets
                                   if isinstance(node, ast.Assign)
                                   else [node.target])
                        for t in targets:
                            attr = self._self_attr(t)
                            if attr and not _looks_like_lock(attr):
                                mutations.setdefault(attr, []).append(
                                    (bool(held), node, sym))
                    for child in ast.iter_child_nodes(node):
                        walk(child, held)

                for stmt in method.body:
                    walk(stmt, ())

            for attr, sites in mutations.items():
                locked = [s for s in sites if s[0]]
                unlocked = [s for s in sites if not s[0]]
                if locked and unlocked:
                    for _, node, sym in unlocked:
                        findings.append(Finding(
                            "OSL401", path, node.lineno, node.col_offset,
                            sym,
                            f"attribute `self.{attr}` is written under a "
                            "lock elsewhere in this class but mutated "
                            "here without one; take the lock or justify",
                            detail=f"attr:{attr}"))

        for (a, b), (node, sym) in sorted(order_sites.items()):
            if (b, a) in order_sites and a < b:
                other = order_sites[(b, a)]
                findings.append(Finding(
                    "OSL402", path, node.lineno, node.col_offset, sym,
                    f"lock order inversion: `{a}` -> `{b}` here but "
                    f"`{b}` -> `{a}` in {other[1]} — pick one global "
                    "order to avoid deadlock",
                    detail=f"order:{a}~{b}"))
        return findings

    @staticmethod
    def _self_attr(target: ast.AST) -> str:
        """'x' for `self.x = ...` or `self.x[k] = ...`; '' otherwise."""
        if isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            return target.attr
        return ""


class WaitDisciplineChecker(Checker):
    """OSL503: no bare `time.sleep` polling loops in serving/threadpool
    hot paths — waiting must ride `threading.Condition` / `Event`.

    A sleep-poll in a request-serving loop both burns a core slot and
    adds up to a full poll interval of tail latency per hop; the serving
    scheduler's flush wait (`serving/scheduler.py: _wait_flush`) is the
    motivating case — its deadline semantics only work because
    `Condition.wait(timeout)` wakes on notify. Detected structurally: a
    call to `time.sleep` (through any module alias or
    `from time import sleep`) lexically inside a `while`/`for` loop.
    One-shot sleeps outside loops (startup grace, test scaffolding
    delays) stay legal. Suppress a justified poll of truly
    signal-less external state with
    `# oslint: disable=OSL503 -- <what cannot signal>`."""

    rules = ("OSL503",)
    name = "wait-discipline"

    SCOPES = ("serving/", "utils/", "rest/")

    def applies(self, path: str) -> bool:
        return any(s in path for s in self.SCOPES)

    def check(self, tree: ast.Module, path: str, src: str) -> List[Finding]:
        findings: List[Finding] = []
        if "sleep" not in src:
            return findings
        qmap = qualname_map(tree)
        mods: Set[str] = set()
        funcs: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mods.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        funcs.add(a.asname or "sleep")

        def is_sleep(call: ast.Call) -> bool:
            d = _dotted(call.func)
            if d in funcs:
                return True
            head, _, tail = d.rpartition(".")
            return tail == "sleep" and head in mods

        def walk(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                # only the BODY repeats; the else clause runs at most
                # once (outer context), and a for's iterable evaluates
                # once — but a while's TEST re-evaluates per iteration
                for child in node.body:
                    walk(child, True)
                for child in node.orelse:
                    walk(child, in_loop)
                if isinstance(node, ast.While):
                    walk(node.test, True)
                else:
                    walk(node.iter, in_loop)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # a nested def's body runs when CALLED, not where it sits
                for child in ast.iter_child_nodes(node):
                    walk(child, False)
                return
            if in_loop and isinstance(node, ast.Call) and is_sleep(node):
                findings.append(Finding(
                    "OSL503", path, node.lineno, node.col_offset,
                    qmap.get(node, ""),
                    "bare time.sleep inside a loop (sleep-polling) in a "
                    "serving/threadpool hot path; wait on a "
                    "threading.Condition/Event so wake-ups are "
                    "notify-driven and deadlines stay tight",
                    detail="sleep-poll"))
            for child in ast.iter_child_nodes(node):
                walk(child, in_loop)

        for stmt in tree.body:
            walk(stmt, False)
        return findings
