"""Runtime lock-witness sanitizer (lockdep-style) — ISSUE 16 tentpole.

The static concurrency pass (devtools/oslint/concurrency) commits a
whole-program lock-order graph to `lock_order.json`; this module is the
execution half of the contract: an opt-in instrumentation layer that
wraps every lock the package creates, records the acquisition orders the
running process ACTUALLY exhibits, and flags an inversion — lock B
acquired while holding A after the opposite order was witnessed — the
moment it happens, naming both stacks, instead of waiting for the
one-in-a-million scheduling that turns the inversion into a deadlock.

Activation:
    OPENSEARCH_TPU_LOCKWITNESS=1         wrap + record (report only)
    OPENSEARCH_TPU_LOCKWITNESS_STRICT=1  also raise LockOrderInversion
or programmatically `lockwitness.install(strict=...)` (tests, the
measure_concurrency overhead gate).

Mechanics: `install()` patches the `threading.Lock` / `threading.RLock`
factories. The replacement walks the creating stack frame (skipping
this module and threading.py — so a `threading.Condition()`'s inner
RLock attributes to the Condition call site) and wraps only locks
created inside the opensearch_tpu package (devtools excluded); the
witness key is the creation site `path:lineno`, which joins to the
static artifact's `declared` field so `verify_against()` can check the
observed order against the committed graph. Everything else gets a raw
lock — the witness never changes behavior outside the package.

Hot-path cost: per acquire, one thread-local list append plus one plain
dict membership probe per held lock (GIL-safe reads); the slow path
(first sighting of an edge — stack capture under an internal raw lock)
runs once per (held, acquired) pair per process. The
measure_concurrency.py `lockwitness_overhead_32t` stamp gates the
wrapped/unwrapped qps ratio at >= 0.98x.

Known modeling edges (shared with the static pass, see
docs/STATIC_ANALYSIS.md "Concurrency suite"): `Condition.wait()`
releases the underlying lock through the inner `_release_save` binding,
bypassing the witness — the waiting thread's held stack keeps the entry
until it wakes, which is sound (a blocked thread acquires nothing) but
means wait-reacquisition is not re-witnessed. Reentrant re-acquires of
an RLock are tracked for release pairing but never recorded as edges.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

# raw factories captured at import — the witness builds its own
# bookkeeping locks from these even while threading.* is patched
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)
_DEVTOOLS_DIR = os.path.join(_PKG_DIR, "devtools")
_THREADING_FILE = os.path.abspath(threading.__file__)
_SELF_FILE = os.path.abspath(__file__)


class LockOrderInversion(RuntimeError):
    """Raised in strict mode when an acquisition order inversion is
    witnessed; carries the inversion record (both stacks)."""

    def __init__(self, record: dict) -> None:
        super().__init__(
            f"lock-order inversion: acquired {record['second']} while "
            f"holding {record['first']} after the opposite order was "
            f"witnessed at {record['prior_site']}")
        self.record = record


class _WitnessState:
    """All witness bookkeeping. One per install(); `armed` gates the
    hot path so uninstall() can disarm wrapped locks already in the
    wild without touching them."""

    def __init__(self, strict: bool) -> None:
        self.strict = strict
        self.armed = True
        self.tls = threading.local()
        # (first_key, second_key) -> first-sighting info (site + stack);
        # read lock-free on the hot path (GIL-atomic dict probe),
        # written only under `mu`
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.inversions: List[dict] = []
        self._inverted_pairs: set = set()
        self.wrapped = 0
        self.mu = _RAW_LOCK()

    def held(self) -> List[str]:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h


_STATE: Optional[_WitnessState] = None
_installed = False


def _stack(skip_self: bool = True) -> str:
    frames = traceback.extract_stack()
    if skip_self:
        frames = [f for f in frames
                  if os.path.abspath(f.filename) != _SELF_FILE]
    return "".join(traceback.format_list(frames[-12:]))


def _creation_site() -> Optional[str]:
    """Walk out of lockwitness/threading frames to the frame that
    called the lock factory; repo-relative `path:lineno`, or None when
    the creator is outside the package (or inside devtools)."""
    f = sys._getframe(1)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        if fn != _SELF_FILE and fn != _THREADING_FILE:
            break
        f = f.f_back
    if f is None:
        return None
    fn = os.path.abspath(f.f_code.co_filename)
    if not fn.startswith(_PKG_DIR + os.sep):
        return None
    if fn.startswith(_DEVTOOLS_DIR + os.sep):
        return None
    rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
    return f"{rel}:{f.f_lineno}"


def _note_acquired(key: str) -> None:
    st = _STATE
    if st is None or not st.armed:
        return
    held = st.held()
    if key in held:
        held.append(key)       # reentrant: pair the release, no edge
        return
    for prev in held:
        if prev == key:
            continue
        edge = (prev, key)
        if edge not in st.edges:
            with st.mu:
                if edge not in st.edges:
                    st.edges[edge] = {
                        "site": _top_site(),
                        "stack": _stack(),
                        "thread": threading.current_thread().name,
                    }
        rev = st.edges.get((key, prev))
        if rev is not None:
            _note_inversion(st, prev, key, rev)
    held.append(key)


def _note_released(key: str) -> None:
    st = _STATE
    if st is None or not st.armed:
        return
    held = st.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == key:
            del held[i]
            return


def _top_site() -> str:
    f = sys._getframe(1)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        if fn != _SELF_FILE and fn != _THREADING_FILE:
            return (os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
                    + f":{f.f_lineno}")
        f = f.f_back
    return "?"


def _note_inversion(st: _WitnessState, first: str, second: str,
                    rev_info: dict) -> None:
    pair = (min(first, second), max(first, second))
    record = {
        "first": first,             # held now
        "second": second,           # acquired now
        "site": _top_site(),
        "stack": _stack(),
        "thread": threading.current_thread().name,
        "prior_site": rev_info.get("site", "?"),
        "prior_stack": rev_info.get("stack", ""),
        "prior_thread": rev_info.get("thread", "?"),
    }
    fresh = False
    with st.mu:
        if pair not in st._inverted_pairs:
            st._inverted_pairs.add(pair)
            fresh = True
        st.inversions.append(record)
    if fresh:
        # freeze the flight recorder: a witnessed inversion is exactly
        # the kind of once-in-a-blue-moon evidence the black box exists
        # for. Lazy import + best-effort: the witness must never take
        # the process down on a recorder problem (unless strict).
        try:
            from ..obs.flight_recorder import RECORDER
            RECORDER.note_lock_inversion(
                first, second, record["stack"], record["prior_stack"])
        except Exception:
            pass
    if st.strict:
        raise LockOrderInversion(record)


class WitnessLock:
    """Transparent proxy: forwards to the wrapped lock, reporting
    successful acquire/release transitions to the witness."""

    __slots__ = ("_inner", "_key")

    def __init__(self, inner, key: str) -> None:
        self._inner = inner
        self._key = key

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquired(self._key)
            except LockOrderInversion:
                # strict mode raises out of the bookkeeping AFTER the
                # inner lock was taken; propagating without releasing
                # would leave it held forever — turning the report into
                # the very deadlock it exists to prevent. The key was
                # never pushed onto the thread's held stack (the raise
                # happens before the append), so no _note_released here.
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_released(self._key)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        # Condition() binds _release_save/_acquire_restore/_is_owned
        # straight off the inner lock — wait() bypasses the witness by
        # design (see module docstring)
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<WitnessLock {self._key} {self._inner!r}>"


def wrap(lock, key: str):
    """Explicitly wrap an existing lock under `key` (tests, fixtures)."""
    st = _STATE
    if st is not None:
        with st.mu:
            st.wrapped += 1
    return WitnessLock(lock, key)


def _factory(raw):
    def make(*args, **kwargs):
        inner = raw(*args, **kwargs)
        st = _STATE
        if st is None or not st.armed:
            return inner
        site = _creation_site()
        if site is None:
            return inner
        with st.mu:
            st.wrapped += 1
        return WitnessLock(inner, site)
    make._lockwitness = True  # type: ignore[attr-defined]
    return make


def install(strict: Optional[bool] = None) -> _WitnessState:
    """Arm the witness and patch the threading lock factories.
    Idempotent; returns the active state (for tests)."""
    global _STATE, _installed
    if strict is None:
        strict = os.environ.get(
            "OPENSEARCH_TPU_LOCKWITNESS_STRICT") == "1"
    if _STATE is not None and _STATE.armed:
        _STATE.strict = bool(strict)
        return _STATE
    _STATE = _WitnessState(bool(strict))
    if not _installed:
        threading.Lock = _factory(_RAW_LOCK)        # type: ignore
        threading.RLock = _factory(_RAW_RLOCK)      # type: ignore
        _installed = True
    return _STATE


def uninstall() -> None:
    """Restore the raw factories and disarm. Locks already wrapped stay
    functional (the proxy forwards); they just stop reporting."""
    global _STATE, _installed
    if _installed:
        threading.Lock = _RAW_LOCK                  # type: ignore
        threading.RLock = _RAW_RLOCK                # type: ignore
        _installed = False
    if _STATE is not None:
        _STATE.armed = False
    _STATE = None


def reset() -> None:
    """Drop recorded edges/inversions, keep the witness armed."""
    st = _STATE
    if st is None:
        return
    with st.mu:
        st.edges.clear()
        st.inversions.clear()
        st._inverted_pairs.clear()


def active() -> bool:
    return _STATE is not None and _STATE.armed


def edges() -> Dict[Tuple[str, str], dict]:
    st = _STATE
    if st is None:
        return {}
    with st.mu:
        return dict(st.edges)


def inversions() -> List[dict]:
    st = _STATE
    if st is None:
        return []
    with st.mu:
        return list(st.inversions)


def verify_against(graph_path: str) -> dict:
    """Check the witnessed acquisition orders against the committed
    static lock-order graph (`lock_order.json`).

    Runtime keys are creation sites (`path:lineno`); the static
    artifact's `declared` field carries the same site for every lock the
    inventory resolved, so the join is exact where the model is. Returns:

      order_conflicts  runtime edge (a, b) whose REVERSE (b, a) is in
                       the committed graph while (a, b) is not — the
                       witnessed order contradicts the model
      unmodeled_edges  runtime edge between two modeled locks that the
                       graph has in neither direction — the model is
                       missing an interleaving (file an issue or
                       regenerate the artifact)
      unmapped         runtime keys with no static declaration (locks
                       the inventory collapsed into attr:: nodes, or
                       fixture/wrap() keys)
    """
    import json
    with open(graph_path, "r", encoding="utf-8") as fh:
        graph = json.load(fh)
    decl_to_id = {l["declared"]: l["id"] for l in graph.get("locks", [])
                  if l.get("declared")}
    static_edges = {(e["from"], e["to"]) for e in graph.get("edges", [])}
    conflicts, unmodeled, unmapped = [], [], set()
    for (a, b), info in sorted(edges().items()):
        ia, ib = decl_to_id.get(a), decl_to_id.get(b)
        if ia is None:
            unmapped.add(a)
        if ib is None:
            unmapped.add(b)
        if ia is None or ib is None or ia == ib:
            continue
        if (ia, ib) in static_edges:
            continue
        entry = {"from": a, "to": b, "from_id": ia, "to_id": ib,
                 "site": info.get("site", "?")}
        if (ib, ia) in static_edges:
            conflicts.append(entry)
        else:
            unmodeled.append(entry)
    return {"order_conflicts": conflicts, "unmodeled_edges": unmodeled,
            "unmapped": sorted(unmapped)}
