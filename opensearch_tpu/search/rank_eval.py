"""Ranking evaluation API (reference `modules/rank-eval/` —
TransportRankEvalAction, PrecisionAtK, RecallAtK, MeanReciprocalRank,
DiscountedCumulativeGain, ExpectedReciprocalRank)."""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

from . import query_dsl as dsl


class _Rated(dict):
    """rating lookup by hit; index constraint applied when the rating
    specified one (reference RatedDocument key is (index, id))."""

    def add(self, r: dict) -> None:
        self[str(r["_id"])] = (r.get("_index"), int(r["rating"]))

    def rating(self, hit_key) -> int:
        idx, did = hit_key
        v = self.get(did)
        if v is None:
            return -1
        ridx, rating = v
        if ridx is not None and idx and ridx != idx:
            return -1
        return rating

    def __contains__(self, hit_key) -> bool:  # type: ignore[override]
        return self.rating(hit_key) >= 0


def _rated(ratings) -> "_Rated":
    out = _Rated()
    for r in ratings or []:
        out.add(r)
    return out


def _hit_keys(hits) -> List[Tuple[str, str]]:
    return [(h.get("_index", ""), str(h["_id"])) for h in hits]


def _precision_at_k(hits, rated, opts) -> Tuple[float, dict]:
    k = int(opts.get("k", 10))
    thr = int(opts.get("relevant_rating_threshold", 1))
    ignore_unlabeled = bool(opts.get("ignore_unlabeled", False))
    relevant = 0
    considered = 0
    for key in _hit_keys(hits[:k]):
        if key in rated:
            considered += 1
            if rated.rating(key) >= thr:
                relevant += 1
        elif not ignore_unlabeled:
            considered += 1
    score = relevant / considered if considered else 0.0
    return score, {"relevant_docs_retrieved": relevant,
                   "docs_retrieved": considered}


def _recall_at_k(hits, rated, opts) -> Tuple[float, dict]:
    k = int(opts.get("k", 10))
    thr = int(opts.get("relevant_rating_threshold", 1))
    relevant_total = sum(1 for _, rv in rated.values() if rv >= thr)
    got = sum(1 for key in _hit_keys(hits[:k])
              if rated.rating(key) >= thr)
    score = got / relevant_total if relevant_total else 0.0
    return score, {"relevant_docs_retrieved": got,
                   "relevant_docs": relevant_total}


def _mrr(hits, rated, opts) -> Tuple[float, dict]:
    k = int(opts.get("k", 10))
    thr = int(opts.get("relevant_rating_threshold", 1))
    for rank, key in enumerate(_hit_keys(hits[:k]), start=1):
        if rated.rating(key) >= thr:
            return 1.0 / rank, {"first_relevant": rank}
    return 0.0, {"first_relevant": -1}


def _dcg(hits, rated, opts) -> Tuple[float, dict]:
    k = int(opts.get("k", 10))
    normalize = bool(opts.get("normalize", False))
    gains = [max(rated.rating(key), 0) for key in _hit_keys(hits[:k])]

    def dcg_of(gs):
        return sum((2 ** g - 1) / math.log2(i + 2) for i, g in enumerate(gs))

    score = dcg_of(gains)
    details = {"dcg": score}
    if normalize:
        ideal = dcg_of(sorted((rv for _, rv in rated.values()),
                              reverse=True)[:k])
        details["ideal_dcg"] = ideal
        score = score / ideal if ideal > 0 else 0.0
        details["normalized_dcg"] = score
    return score, details


def _err(hits, rated, opts) -> Tuple[float, dict]:
    k = int(opts.get("k", 10))
    max_rel = int(opts.get("maximum_relevance",
                           max((rv for _, rv in rated.values()),
                               default=1) or 1))
    p_stop_prev = 1.0
    err = 0.0
    for rank, key in enumerate(_hit_keys(hits[:k]), start=1):
        g = max(rated.rating(key), 0)
        r = (2 ** g - 1) / (2 ** max_rel)
        err += p_stop_prev * r / rank
        p_stop_prev *= (1 - r)
    return err, {"unrated_docs": sum(1 for key in _hit_keys(hits[:k])
                                     if key not in rated)}


_METRICS = {
    "precision": _precision_at_k,
    "recall": _recall_at_k,
    "mean_reciprocal_rank": _mrr,
    "dcg": _dcg,
    "expected_reciprocal_rank": _err,
}


def run_rank_eval(client, index: str, body: dict) -> dict:
    """Execute the _rank_eval request via `client.search` per rated query."""
    metric_spec = body.get("metric")
    if not metric_spec or len(metric_spec) != 1:
        raise dsl.QueryParseError("[rank_eval] requires exactly one [metric]")
    (mname, mopts), = metric_spec.items()
    fn = _METRICS.get(mname)
    if fn is None:
        raise dsl.QueryParseError(f"unknown rank_eval metric [{mname}]")
    details = {}
    failures = {}
    scores = []
    for req in body.get("requests", []):
        rid = req.get("id", f"q{len(details)}")
        search_body = req.get("request")
        if search_body is None and req.get("template_id"):
            from ..rest.templates import render_template
            tmpl = client._stored_scripts.get(req["template_id"])
            if tmpl is None:
                failures[rid] = f"no stored template [{req['template_id']}]"
                continue
            search_body = render_template(tmpl, req.get("params"))
        if search_body is None:
            failures[rid] = "missing [request]"
            continue
        rated = _rated(req.get("ratings"))
        k = int((mopts or {}).get("k", 10))
        search_body = dict(search_body)
        search_body.setdefault("size", k)
        try:
            resp = client.search(req.get("index", index), search_body)
        except Exception as e:  # noqa: BLE001 - reference collects failures
            failures[rid] = str(e)
            continue
        hits = resp["hits"]["hits"]
        score, mdetails = fn(hits, rated, mopts or {})
        scores.append(score)
        details[rid] = {
            "metric_score": score,
            "unrated_docs": [{"_index": h.get("_index", ""), "_id": h["_id"]}
                             for h in hits[:k]
                             if (h.get("_index", ""), str(h["_id"]))
                             not in rated],
            "hits": [{"hit": {"_index": h.get("_index", ""),
                              "_id": h["_id"], "_score": h.get("_score")},
                      "rating": (lambda rr: rr if rr >= 0 else None)(
                          rated.rating((h.get("_index", ""),
                                        str(h["_id"]))))}
                     for h in hits[:k]],
            "metric_details": {mname: mdetails},
        }
    return {"metric_score": (sum(scores) / len(scores)) if scores else 0.0,
            "details": details, "failures": failures}
