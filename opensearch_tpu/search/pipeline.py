"""Search pipelines: request / response / phase-results processors wrapped
around the search phases.

Reference analog: `server/src/main/java/org/opensearch/search/pipeline/
SearchPipelineService.java` (resolution order: request param > index
`index.search.default_pipeline` > none; `_none` disables), with the common
processor set from `modules/search-pipeline-common/` —
FilterQueryRequestProcessor.java, OversampleRequestProcessor.java,
ScriptRequestProcessor.java, RenameFieldResponseProcessor.java,
TruncateHitsResponseProcessor.java, SortResponseProcessor.java,
SplitResponseProcessor.java, CollapseResponseProcessor.java — and the
phase-results normalization hook (SearchPhaseResultsProcessor.java).

Design notes (TPU framing): request processors run on the host BEFORE plan
compilation, so a filter_query merge participates in plan canonicalization
and (segment, plan) mask caching like any user filter. The phase-results
hook runs between the device query phase and the coordinator reduce — it
sees per-shard candidate lists (scores already on host), so normalization
is a pure host pass and never forces a device sync of its own.
"""

from __future__ import annotations

import copy
import math
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class SearchPipelineException(Exception):
    pass


# dotted-path access on hit _source — one implementation, shared with ingest
from ..ingest.pipeline import _del_path, _get_path, _set_path  # noqa: E402


# ---------------------------------------------------------------------------
# request processors: (body, ctx) -> body
# ---------------------------------------------------------------------------

def _req_filter_query(cfg: dict):
    fq = cfg.get("query")
    if fq is None:
        raise SearchPipelineException("filter_query requires [query]")

    def proc(body: dict, ctx: dict) -> dict:
        orig = body.get("query")
        clause: dict = {"bool": {"filter": [copy.deepcopy(fq)]}}
        if orig is not None:
            clause["bool"]["must"] = [orig]
        body["query"] = clause
        return body
    return proc


def _req_oversample(cfg: dict):
    factor = float(cfg.get("sample_factor", 0))
    if factor < 1.0:
        raise SearchPipelineException("sample_factor must be >= 1.0")
    prefix = cfg.get("context_prefix")
    key = (prefix + "." if prefix else "") + "original_size"

    def proc(body: dict, ctx: dict) -> dict:
        size = int(body.get("size", 10))
        ctx[key] = size
        ctx["original_size"] = size
        body["size"] = int(math.ceil(size * factor))
        return body
    return proc


def _req_script(cfg: dict):
    src = cfg.get("source") or (cfg.get("script") or {}).get("source")
    if not src:
        raise SearchPipelineException("script processor requires [source]")
    params = cfg.get("params") or (cfg.get("script") or {}).get("params") or {}

    def proc(body: dict, ctx: dict) -> dict:
        from ..script.painless_lite import execute
        # `ctx` inside the script IS the mutable request map, like the
        # reference's SearchRequestMap (size/from/query/... all assignable)
        execute(src, {"ctx": body, "params": dict(params)})
        return body
    return proc


# ---------------------------------------------------------------------------
# response processors: (resp, ctx, body) -> resp
# ---------------------------------------------------------------------------

def _hits(resp: dict) -> List[dict]:
    return resp.get("hits", {}).get("hits", [])


def _res_rename_field(cfg: dict):
    field, target = cfg.get("field"), cfg.get("target_field")
    if not field or not target:
        raise SearchPipelineException(
            "rename_field requires [field] and [target_field]")
    ignore_missing = bool(cfg.get("ignore_missing", False))

    def proc(resp: dict, ctx: dict, body: dict) -> dict:
        for h in _hits(resp):
            src = h.get("_source")
            moved = False
            if isinstance(src, dict):
                v = _get_path(src, field)
                if v is not None:
                    _set_path(src, target, v)
                    _del_path(src, field)
                    moved = True
            flds = h.get("fields")
            if isinstance(flds, dict) and field in flds:
                flds[target] = flds.pop(field)
                moved = True
            if not moved and not ignore_missing:
                raise SearchPipelineException(
                    f"Document with id {h.get('_id')} is missing field [{field}]")
        return resp
    return proc


def _res_truncate_hits(cfg: dict):
    cfg_size = cfg.get("target_size")
    prefix = cfg.get("context_prefix")
    key = (prefix + "." if prefix else "") + "original_size"

    def proc(resp: dict, ctx: dict, body: dict) -> dict:
        size = cfg_size if cfg_size is not None else ctx.get(key)
        if size is None:
            raise SearchPipelineException(
                "truncate_hits: no target_size and no oversample context")
        hits = resp.get("hits", {})
        hits["hits"] = hits.get("hits", [])[: int(size)]
        return resp
    return proc


def _res_sort(cfg: dict):
    field = cfg.get("field")
    if not field:
        raise SearchPipelineException("sort processor requires [field]")
    order = cfg.get("sort_order", "asc")
    target = cfg.get("target_field", field)

    def proc(resp: dict, ctx: dict, body: dict) -> dict:
        for h in _hits(resp):
            src = h.get("_source")
            if not isinstance(src, dict):
                continue
            v = _get_path(src, field)
            if v is None:
                continue
            if not isinstance(v, list):
                raise SearchPipelineException(
                    f"field [{field}] is not an array, cannot sort")
            _set_path(src, target, sorted(v, reverse=(order == "desc")))
        return resp
    return proc


def _res_split(cfg: dict):
    field = cfg.get("field")
    sep = cfg.get("separator")
    if not field or sep is None:
        raise SearchPipelineException("split requires [field] and [separator]")
    target = cfg.get("target_field", field)
    preserve = bool(cfg.get("preserve_trailing", False))

    def proc(resp: dict, ctx: dict, body: dict) -> dict:
        for h in _hits(resp):
            src = h.get("_source")
            if not isinstance(src, dict):
                continue
            v = _get_path(src, field)
            if v is None:
                continue
            if not isinstance(v, str):
                raise SearchPipelineException(
                    f"field [{field}] is not a string, cannot split")
            parts = v.split(sep)
            if not preserve:
                while parts and parts[-1] == "":
                    parts.pop()
            _set_path(src, target, parts)
        return resp
    return proc


def _res_collapse(cfg: dict):
    field = cfg.get("field")
    if not field:
        raise SearchPipelineException("collapse processor requires [field]")

    def proc(resp: dict, ctx: dict, body: dict) -> dict:
        seen = set()
        kept = []
        for h in _hits(resp):
            v = _get_path(h.get("_source") or {}, field)
            if v is None and isinstance(h.get("fields"), dict):
                fv = h["fields"].get(field)
                v = fv[0] if isinstance(fv, list) and fv else fv
            k = ("null",) if v is None else ("v", str(v))
            if k in seen:
                continue
            seen.add(k)
            kept.append(h)
        resp["hits"]["hits"] = kept
        return resp
    return proc


# ---------------------------------------------------------------------------
# phase-results processors: (shard_results, body, ctx) -> None  (mutate)
# ---------------------------------------------------------------------------

def _phase_normalization(cfg: dict):
    technique = (cfg.get("normalization") or {}).get("technique", "min_max")
    if technique not in ("min_max", "l2"):
        raise SearchPipelineException(
            f"unknown normalization technique [{technique}]")

    def proc(shard_results: list, body: dict, ctx: dict) -> None:
        if body.get("sort"):
            return  # score normalization only applies to score-ordered results
        from .executor import _host_sort_values
        cands = [c for r in shard_results for c in r.candidates
                 if c.score is not None]
        if not cands:
            return
        scores = [c.score for c in cands]
        if technique == "min_max":
            lo, hi = min(scores), max(scores)
            rng = (hi - lo) or 1.0
            def norm(s): return (s - lo) / rng
        else:
            nrm = math.sqrt(sum(s * s for s in scores)) or 1.0
            def norm(s): return s / nrm
        for r in shard_results:
            for c in r.candidates:
                if c.score is None:
                    continue
                c.score = float(norm(c.score))
                c.sort_values, c.raw_sort_values = _host_sort_values(
                    [], r.segments[c.seg_ord], c.local_doc, c.score)
            if r.candidates:
                r.max_score = max((c.score or 0.0) for c in r.candidates)
    return proc


_REQUEST = {"filter_query": _req_filter_query, "oversample": _req_oversample,
            "script": _req_script}
_RESPONSE = {"rename_field": _res_rename_field,
             "truncate_hits": _res_truncate_hits, "sort": _res_sort,
             "split": _res_split, "collapse": _res_collapse}
_PHASE = {"normalization": _phase_normalization}


class _Proc:
    __slots__ = ("kind", "tag", "ignore_failure", "fn", "stats")

    def __init__(self, kind: str, cfg: dict, fn):
        self.kind = kind
        self.tag = cfg.get("tag")
        self.ignore_failure = bool(cfg.get("ignore_failure", False))
        self.fn = fn
        self.stats = {"count": 0, "time_ms": 0.0, "failed": 0}

    def run(self, *args):
        t0 = time.monotonic()
        self.stats["count"] += 1
        try:
            return self.fn(*args)
        except Exception as e:
            # any processor failure (script runtime errors included) honors
            # ignore_failure and surfaces as a pipeline exception -> 400
            # (reference SearchPipelineProcessingException wrapping)
            self.stats["failed"] += 1
            if self.ignore_failure:
                return None
            if isinstance(e, SearchPipelineException):
                raise
            raise SearchPipelineException(
                f"processor [{self.kind}] failed: {e}") from e
        finally:
            self.stats["time_ms"] += (time.monotonic() - t0) * 1000.0


class SearchPipeline:
    def __init__(self, pid: str, config: dict):
        self.id = pid
        self.description = config.get("description", "")
        self.version = config.get("version")
        self.request_procs: List[_Proc] = []
        self.response_procs: List[_Proc] = []
        self.phase_procs: List[_Proc] = []
        for block, registry, out in (
                ("request_processors", _REQUEST, self.request_procs),
                ("response_processors", _RESPONSE, self.response_procs),
                ("phase_results_processors", _PHASE, self.phase_procs)):
            for spec in config.get(block, []):
                if not isinstance(spec, dict) or len(spec) != 1:
                    raise SearchPipelineException(
                        f"each entry in [{block}] must be a single-key "
                        f"{{type: config}} object")
                ((kind, cfg),) = spec.items()
                if kind not in registry:
                    raise SearchPipelineException(
                        f"unknown processor [{kind}] in [{block}]")
                out.append(_Proc(kind, cfg or {}, registry[kind](cfg or {})))

    def transform_request(self, body: dict, ctx: dict) -> dict:
        for p in self.request_procs:
            out = p.run(body, ctx)
            body = out if out is not None else body
        return body

    def transform_response(self, resp: dict, ctx: dict, body: dict) -> dict:
        for p in self.response_procs:
            out = p.run(resp, ctx, body)
            resp = out if out is not None else resp
        return resp

    def phase_hook(self) -> Optional[Callable]:
        if not self.phase_procs:
            return None

        def hook(shard_results: list, body: dict, ctx: dict) -> None:
            for p in self.phase_procs:
                p.run(shard_results, body, ctx)
        return hook

    def stats(self) -> dict:
        def block(procs):
            return [{"type": p.kind, **({"tag": p.tag} if p.tag else {}),
                     "stats": dict(p.stats)} for p in procs]
        return {"request_processors": block(self.request_procs),
                "response_processors": block(self.response_procs),
                "phase_results_processors": block(self.phase_procs)}


class SearchPipelineService:
    """Registry + per-request resolution (SearchPipelineService.java)."""

    def __init__(self):
        self.pipelines: Dict[str, SearchPipeline] = {}

    def put(self, pid: str, config: dict) -> None:
        self.pipelines[pid] = SearchPipeline(pid, config)

    def delete(self, pid: str) -> None:
        if pid not in self.pipelines:
            raise SearchPipelineException(f"pipeline [{pid}] not found")
        del self.pipelines[pid]

    def get(self, pid: Optional[str] = None) -> dict:
        if pid is not None:
            p = self.pipelines.get(pid)
            if p is None:
                raise SearchPipelineException(f"pipeline [{pid}] not found")
            return {pid: {"description": p.description}}
        return {k: {"description": p.description}
                for k, p in self.pipelines.items()}

    def resolve(self, param: Optional[Any],
                default_pipeline: Optional[str]) -> Optional[SearchPipeline]:
        """param wins over the index default; "_none" disables; an inline
        dict builds an ad-hoc (unregistered) pipeline."""
        if isinstance(param, dict):
            return SearchPipeline("_ad_hoc", param)
        pid = param if param is not None else default_pipeline
        if pid is None or pid == "_none":
            return None
        p = self.pipelines.get(pid)
        if p is None:
            raise SearchPipelineException(f"pipeline [{pid}] not found")
        return p

    def stats(self) -> dict:
        return {"pipelines": {k: p.stats()
                              for k, p in self.pipelines.items()}}
