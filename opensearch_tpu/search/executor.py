"""Per-shard search execution + coordinator reduce. Analog of reference
`search/SearchService.java` (executeQueryPhase/executeFetchPhase),
`search/query/QueryPhase.java`, `search/fetch/FetchPhase.java`, and the
coordinator-side `action/search/SearchPhaseController.java`.

Query-then-fetch: the QUERY phase runs the jitted device program per segment
(scoring + top-k + aggs in one XLA program), returns light-weight candidate
descriptors; the coordinator merges candidates across shards; the FETCH phase
materializes `_source`, highlights, docvalue_fields for the winning docs only.
"""

from __future__ import annotations

import fnmatch
import time
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..index.engine import Engine
from ..index.segment import Segment, next_pow2
from ..obs import flight_recorder as _flight
from ..obs import query_cost as _qcost
from ..script.painless_lite import ScriptError as _ScriptError
from ..utils import deadline as _dl
from . import compiler as C
from . import fastpath
from . import impactpath
from . import query_dsl as dsl
from .aggregations import (AggNode, _apply_bucket_pipelines,
                           apply_pipelines_tree, finalize, merge_partials,
                           parse_aggs)
from .highlight import (collect_query_terms, highlight_field,
                        highlight_fvh, highlight_unified)

INT32_SENTINEL = np.int32(2**31 - 1)


@dataclass
class Candidate:
    """One query-phase hit descriptor (analog of Lucene ScoreDoc + shard ref)."""

    shard: int
    seg_ord: int
    local_doc: int
    score: Optional[float]
    sort_values: Tuple            # host-comparable, already direction-adjusted
    raw_sort_values: Tuple        # user-facing sort array
    collapse_key: Any = None      # field-collapse group value (None = null group)


def _tie_collect_order(keys: np.ndarray, idx: np.ndarray,
                       valid: np.ndarray, seg) -> np.ndarray:
    """Candidate append order for one top-k window: device order
    normally (the stable shard sort then breaks full-tuple ties by
    append order == device doc-id order), but on a BP-reordered segment
    (index/reorder.py) key ties re-break by ARRIVAL rank first, so the
    served page does not depend on the permuted internal ids — the
    reorder parity contract. `tie_ranks()` is None everywhere else and
    this is a plain nonzero."""
    jj = np.nonzero(valid)[0]
    f = getattr(seg, "tie_ranks", None)
    tr = f() if f is not None else None
    if tr is None or len(jj) == 0:
        return jj
    d = np.clip(idx[jj].astype(np.int64), 0, len(tr) - 1)
    return jj[np.lexsort((tr[d], -keys[jj].astype(np.float64)))]


@dataclass
class ShardQueryResult:
    shard: int
    candidates: List[Candidate] = dc_field(default_factory=list)
    total: int = 0
    total_rel: str = "eq"   # "gte" when a pruned segment undercounted
    max_score: float = float("-inf")
    agg_partials: Dict[str, dict] = dc_field(default_factory=dict)
    segments: List[Segment] = dc_field(default_factory=list)
    named_by_doc: Dict[Tuple[int, int], List[str]] = dc_field(default_factory=dict)
    took_ms: float = 0.0
    # partial-results contract (docs/RESILIENCE.md): the deadline budget
    # ran out between segments / the terminate_after doc budget was hit —
    # both cross the distnode wire inside the pickled result
    timed_out: bool = False
    terminated_early: bool = False


def _suppress_score(body: dict) -> bool:
    """Reference `track_scores` semantics under a field sort: an
    explicit `track_scores: false` nulls per-hit `_score`. Absent
    track_scores keeps this engine's historical behavior — scores are
    free on device (documented divergence, docs/RESILIENCE.md)."""
    if body.get("track_scores") is not False or not body.get("sort"):
        return False
    specs = _norm_sort_specs(body)
    return bool(specs) and specs[0]["field"] != "_score"


_GEO_SORT_OPTS = {"order", "unit", "mode", "distance_type",
                  "ignore_unmapped", "nested"}
_DIST_UNITS = {"m": 1.0, "meters": 1.0, "km": 1000.0, "kilometers": 1000.0,
               "mi": 1609.344, "miles": 1609.344, "yd": 0.9144,
               "ft": 0.3048, "in": 0.0254, "cm": 0.01, "mm": 0.001,
               "nmi": 1852.0, "nauticalmiles": 1852.0}


def _norm_sort_specs(body: dict) -> List[dict]:
    out = []
    for s in body.get("sort", []):
        if isinstance(s, str):
            out.append({"field": s, "order": "desc" if s == "_score" else "asc"})
        else:
            ((f, spec),) = s.items()
            if f == "_geo_distance":
                # {"_geo_distance": {"location": <origin>, "order": ...,
                #  "unit": "km"}} (reference GeoDistanceSortBuilder)
                from ..index.mappings import _parse_geo
                opts = {k: v for k, v in spec.items() if k in _GEO_SORT_OPTS}
                geo_fields = [k for k in spec if k not in _GEO_SORT_OPTS]
                if len(geo_fields) != 1:
                    raise dsl.QueryParseError(
                        "[_geo_distance] sort needs exactly one geo field")
                lat, lon = _parse_geo(spec[geo_fields[0]])
                out.append({"field": "_geo_distance",
                            "geo_field": geo_fields[0],
                            "origin": (lat, lon),
                            "order": opts.get("order", "asc"),
                            "unit": opts.get("unit", "m")})
            elif isinstance(spec, str):
                out.append({"field": f, "order": spec})
            else:
                out.append({"field": f, **spec})
    return out


_LNODE_CHILD_ATTRS = ("musts", "shoulds", "must_nots", "filters",
                      "children", "child", "positive", "negative")


def _cost_predicted(lroot, seg, window: int) -> None:
    """Plan-time device-cost prediction from CSR block stats alone: each
    scoring term row the query touches contributes its TRUE posting count
    (8 bytes per slot on codec v1; 4 + impact width on codec-v2 eager
    fields — the cost model in docs/OBSERVABILITY.md). Noted per planned
    segment BEFORE any launched program shape exists; the launch sites
    note the padded shapes they actually move, and the profile `cost`
    block reconciles the two."""
    qc = _qcost.current()
    if qc is None:
        return
    npost = 0
    nbytes = 0
    stack = [lroot]
    while stack:
        node = stack.pop()
        if node is None:
            continue
        terms = None
        if isinstance(node, (C.LTerms, C.LPhrase, C.LSourcePhrase)):
            terms = node.terms
        elif isinstance(node, C.LSparseDot):
            terms = node.tokens
        if terms:
            pb = seg.postings.get(node.field)
            if pb is not None:
                df = sum(pb.doc_freq(t) for t in terms)
                npost += df
                v2 = (getattr(seg, "codec_version", C.CODEC_V1)
                      >= C.CODEC_V2 and pb.impact is not None)
                if v2 and ((isinstance(node, C.LTerms)
                            and node.mode == "score")
                           or (isinstance(node, C.LSparseDot)
                               and pb.impact.kind == "feature")):
                    # codec v2: the eager plane replaces the f32 tf slot
                    # with a u8/u16 impact — predict the SMALLER volume
                    # (the claim the actual-launch stamps reconcile);
                    # learned-sparse feature planes price identically
                    nbytes += df * (4 + pb.impact.bits // 8)
                else:
                    nbytes += df * _qcost.POSTING_SLOT_BYTES
        for attr in _LNODE_CHILD_ATTRS:
            v = getattr(node, attr, None)
            if isinstance(v, (list, tuple)):
                stack.extend(v)
            elif v is not None and not isinstance(v, (str, int, float,
                                                      bool)):
                stack.append(v)
    qc.note_predicted(nbytes, npost, window, segment=seg)


def compose_knn_query(body: dict) -> Optional[dsl.Query]:
    """The body's effective query tree, folding the ES-style top-level
    `knn` section ({"field", "query_vector", "k", "filter"}) into the DSL
    tree: knn alone, or bool-should'ed with the query (reference
    SearchSourceBuilder knn handling). Shared by the per-shard query
    phase and the batched-launch classifier so the two can never
    disagree on what a body means."""
    query = dsl.parse_query(body.get("query")) if (body.get("query")
                                                   or "knn" not in body) \
        else None
    knn_spec = body.get("knn")
    if knn_spec is not None:
        _np = knn_spec.get("method_parameters", {}).get(
            "nprobe", knn_spec.get("nprobe"))
        kq = dsl.KnnQuery(field=knn_spec["field"],
                          vector=list(knn_spec.get("query_vector",
                                                   knn_spec.get("vector",
                                                                []))),
                          k=int(knn_spec.get("k", 10)),
                          filter=(dsl.parse_query(knn_spec["filter"])
                                  if knn_spec.get("filter") else None),
                          boost=float(knn_spec.get("boost", 1.0)),
                          nprobe=int(_np) if _np is not None else None,
                          exact=bool(knn_spec.get("exact", False)))
        query = dsl.BoolQuery(should=[query, kq],
                              minimum_should_match="1") \
            if query is not None else kq
    return query


class ShardSearcher:
    """Executes searches over one shard's engine (one set of segments)."""

    def __init__(self, engine: Engine, shard_id: int = 0,
                 similarity=None, field_similarities=None,
                 index_key: Optional[str] = None, device=None):
        self.engine = engine
        self.shard_id = shard_id
        self.similarity = similarity
        self.field_similarities = field_similarities
        # shards sharing an index_key share collection statistics (DFS);
        # standalone searchers all fall into one default group
        self.index_key = index_key
        # replica read path (cluster/replication.py): segments come from the
        # replica's synced checkpoint, arrays hosted on its device
        self.device = device
        self.replica = None

    def context(self) -> C.ShardContext:
        return C.ShardContext(self.engine.mappings, self.engine.segments,
                              self.similarity, self.field_similarities)

    # ---------------- QUERY phase ----------------

    def query_phase(self, body: dict, segments: Optional[List[Segment]] = None,
                    shard_ord: Optional[int] = None,
                    stats_ctx: Optional[C.ShardContext] = None,
                    task=None) -> ShardQueryResult:
        """`shard_ord` overrides the candidate shard tag so a coordinator can
        search shards of several indices in one pass without id collisions.
        `stats_ctx` carries index-wide collection statistics (the coordinator
        DFS phase, reference DFS_QUERY_THEN_FETCH) so idf/avgdl — and thus
        scores — are identical across shards."""
        t0 = time.monotonic()
        if shard_ord is None:
            shard_ord = self.shard_id
        if segments is None:
            segments = (list(self.replica.segments) if self.replica is not None
                        else list(self.engine.segments))
        ctx = stats_ctx or C.ShardContext(self.engine.mappings, segments,
                                          self.similarity, self.field_similarities)
        # derived (runtime) fields: mapping-level + search-body defs
        # materialize into per-segment columns before rewrite sees them
        ddefs = dict(getattr(ctx.mappings, "derived", {}) or {})
        if body.get("derived"):
            from . import derived as derived_mod
            try:
                req_defs = derived_mod.parse_defs(body["derived"])
                derived_mod.check_conflicts(ctx.mappings, req_defs)
                ddefs.update(req_defs)
            except ValueError as e:
                raise dsl.QueryParseError(str(e))
            import copy as _copy
            ctx = _copy.copy(ctx)
            ctx.mappings = derived_mod.MappingsOverlay(ctx.mappings, ddefs)
        if ddefs:
            from . import derived as derived_mod
            names = derived_mod.referenced(ddefs, body)
            if names:
                from ..script.painless_lite import ScriptError
                try:
                    for seg in segments:
                        derived_mod.ensure(seg, ctx.mappings, ddefs, names)
                except (ScriptError, ValueError) as e:
                    raise dsl.QueryParseError(f"derived field: {e}")
        query = compose_knn_query(body)
        lroot = C.rewrite(query, ctx, scoring=True)
        ctx._current_lroot = lroot  # children/parent aggs join against it

        size = int(body.get("size", 10))
        frm = int(body.get("from", 0))
        sort_specs = _norm_sort_specs(body)
        is_field_sort = bool(sort_specs) and sort_specs[0]["field"] not in ("_score",)
        # oversample: host tie-refinement + multi-key sorting need slack
        window = frm + size
        oversample = 2 if (is_field_sort or len(sort_specs) > 1) else 1
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
        named_nodes = _collect_named(lroot)
        rescores = body.get("rescore")
        if rescores is not None and not isinstance(rescores, list):
            rescores = [rescores]
        min_score = body.get("min_score")
        search_after = body.get("search_after")
        collapse = body.get("collapse")
        if collapse:
            if not isinstance(collapse, dict) or not collapse.get("field"):
                raise dsl.QueryParseError("[collapse] requires [field]")
            if sort_specs and sort_specs[0]["field"] == "_script":
                raise dsl.QueryParseError(
                    "cannot use [collapse] with a primary _script sort")

        # per-shard doc budget (reference terminate_after) + the ambient
        # request deadline (utils/deadline.py): both are enforced at
        # segment granularity — one segment is one device program, the
        # natural cancellation point — and both mark the result partial
        # (`terminated_early` / `timed_out`) with honest `gte` totals
        ta = int(body.get("terminate_after") or 0)
        deadline = _dl.current()

        result = ShardQueryResult(shard=shard_ord, segments=segments)
        ran_segs: List[Segment] = []

        # Pallas fast path: plain BM25 term-group top-k AND bool/filtered
        # shapes go through the fused kernels (search/fastpath.py); anything
        # they can't serve falls back to the general XLA plan per segment
        fast_spec = (fastpath.make_spec(lroot, sort_specs, agg_nodes,
                                        named_nodes, search_after, window,
                                        body)
                     if fastpath.enabled() and self.device is None else None)
        # codec-v2 eager-impact path (search/impactpath.py): the same pure
        # BM25 top-k shape class served from the quantized impact plane
        # with host block-max pruning — XLA, so it engages on every
        # backend. Segments decline per-segment (v1 codec, no plane), and
        # a failed serve certificate falls through to the exact program.
        imp_spec = (impactpath.make_spec(lroot, sort_specs, agg_nodes,
                                         named_nodes, search_after, window,
                                         body)
                    if self.device is None else None)

        # concurrent segment search, TPU-style: a many-segment shard runs
        # as ONE kernel launch over the concatenated shard view instead of
        # the serial per-segment loop (reference
        # ConcurrentQueryPhaseSearcher parallelizes with threads; a TPU
        # wants one bigger launch) — pure term-group specs only
        if fast_spec is not None and len(segments) > 1 and not rescores \
                and not ta:
            # (terminate_after needs the per-segment loop: the concat
            # shard-view launch scans every segment in one program)
            sv = fastpath.shard_search(self, ctx, fast_spec, window)
            if sv is not None:
                view, fout = sv
                if _qcost.current() is not None:
                    # the per-segment loop below won't run — predict per
                    # view segment here (the view concatenates them)
                    for vseg in view.segments:
                        _cost_predicted(lroot, vseg, window)
                self._collect_view_topk(result, view, fout, shard_ord,
                                        sort_specs, min_score, ctx)
                result.candidates.sort(key=lambda c: c.sort_values)
                result.candidates = result.candidates[: window * oversample]
                result.took_ms = (time.monotonic() - t0) * 1000.0
                if task is not None:
                    task.track(device_seconds=result.took_ms / 1000.0)
                return result

        seg_t0 = time.monotonic()
        for seg_ord, seg in enumerate(segments):
            if ta and result.total >= ta:
                result.terminated_early = True
                if any(s.live_count for s in segments[seg_ord:]):
                    result.total_rel = "gte"
                break
            if deadline is not None and deadline.exhausted():
                result.timed_out = True
                if any(s.live_count for s in segments[seg_ord:]):
                    result.total_rel = "gte"
                break
            if task is not None:
                # cooperative cancellation between segment programs
                # (reference CancellableTask checks between leaves) +
                # device-time accounting for backpressure victim selection
                task.track(device_seconds=time.monotonic() - seg_t0)
                seg_t0 = time.monotonic()
                task.ensure_not_cancelled()
            if seg.live_count == 0:
                continue
            if not _aggs_need_all_segments(agg_nodes) and not C.can_match(lroot, seg):
                # segment provably has no hits (can_match pre-filter); only
                # global/filter-family aggs see docs the query doesn't match,
                # so ordinary agg trees still allow the skip
                continue
            _cost_predicted(lroot, seg, window)
            if fast_spec is not None:
                fout = fastpath.segment_search(seg, ctx, fast_spec, window)
                if fout is not None:
                    ran_segs.append(seg)
                    self._collect_topk(result, fout, seg, seg_ord, shard_ord,
                                       sort_specs, rescores, min_score,
                                       is_field_sort, ctx)
                    continue
            if imp_spec is not None:
                iout = impactpath.segment_search(seg, ctx, imp_spec, window)
                if iout is not None:
                    ran_segs.append(seg)
                    self._collect_topk(result, iout, seg, seg_ord,
                                       shard_ord, sort_specs, rescores,
                                       min_score, is_field_sort, ctx)
                    continue
            tief = getattr(seg, "tie_ranks", None)
            tie_aware = tief is not None and tief() is not None
            if sort_specs and sort_specs[0]["field"] == "_script":
                # script order is host-computed: collect the full segment
                # window so the host re-sort sees every matching doc
                k_pad = seg.ndocs_pad
            else:
                k_pad = min(next_pow2(max(window * oversample, 16)), seg.ndocs_pad)
                if tie_aware:
                    # BP-reordered segment: seed the window deep enough
                    # that a saturated all-distinct extraction already
                    # holds >= window*oversample strictly-better lanes
                    # above its deepest key — otherwise the widen loop
                    # below pays a second launch with zero ties present
                    k_pad = min(next_pow2(max(window * oversample * 2, 32)),
                                seg.ndocs_pad)
            params: Dict[str, Any] = {}
            qspec = C.prepare(lroot, seg, ctx, params)
            qc = _qcost.current()
            if qc is not None:
                # actual launched-shape cost of the XLA path: the program
                # gathers the spec's pow2 buckets (ops.gather_postings)
                # and extracts a k_pad top-k window
                gb, slots = _qcost.spec_gather_shape(qspec)
                qc.note_actual(gb, slots, k_pad, path="xla", segment=seg)
            sspec = C.prepare_sort(sort_specs, seg, params)
            agg_specs = []
            for i, an in enumerate(agg_nodes):
                if an.kind == "top_hits":
                    continue  # resolved from this segment's top-k below
                agg_specs.append((an.name, C.prepare_agg(an, seg, ctx, params, f"a{i}")))
            named_specs = []
            for nm, nnode in named_nodes:
                nparams: Dict[str, Any] = {}
                nspec = C.prepare(nnode, seg, ctx, params)
                named_specs.append((nm, nspec))
            has_after = search_after is not None
            if has_after:
                if sort_specs and sort_specs[0]["field"] == "_script":
                    raise dsl.QueryParseError(
                        "search_after is not supported with a primary _script sort")
                params["after_key"] = np.float32(
                    _after_key_value(search_after, sort_specs, seg))
            cspec = C.prepare_collapse(collapse, seg, ctx, params)
            while True:
                try:
                    out = C.run_segment(qspec, sspec, agg_specs,
                                        named_specs, k_pad,
                                        seg.device_arrays(self.device),
                                        params, has_after,
                                        collapse_spec=cspec)
                except _ScriptError as e:
                    # device-script trace failures are user errors (HTTP 400)
                    raise dsl.QueryParseError(f"script compile error: {e}")
                keys = np.asarray(out["topk_key"])
                idx = np.asarray(out["topk_idx"])
                scores = np.asarray(out["topk_scores"])
                valid = keys > -np.inf
                if not tie_aware or sort_specs:
                    # widen only for score sorts: a field sort's primary
                    # key can tie across most of the segment (enum-like
                    # fields), where widening would walk k_pad all the
                    # way to ndocs_pad per query — those ties break by
                    # the host's full sort tuple downstream, the same
                    # oversample approximation unreordered segments use
                    break
                # BP-reordered segment (index/reorder.py): device top-k
                # breaks key ties by PERMUTED internal id, so a tie class
                # cut at the extraction edge may have dropped its
                # arrival-earliest members — _tie_collect_order can only
                # re-sort lanes that were extracted. A cut class always
                # contains the deepest extracted key; it is provably
                # complete when extraction didn't saturate. Widen until
                # the page-relevant classes are whole, then drop the
                # (possibly cut) deepest class — safe once enough
                # strictly-better candidates cover this segment's
                # contribution cap (window * oversample).
                nvalid = int(valid.sum())
                if nvalid < k_pad or k_pad >= seg.ndocs_pad:
                    break
                kmin = keys[valid].min()
                if int((keys > kmin).sum()) >= window * oversample:
                    valid &= keys > kmin
                    break
                k_pad = min(next_pow2(k_pad * 2), seg.ndocs_pad)

            ran_segs.append(seg)
            result.total += int(out["total"])
            ms = float(out["max_score"])
            if ms > result.max_score:
                result.max_score = ms

            named_np = {nm: np.asarray(v) for nm, v in out.get("named", {}).items()}
            for name, aspec in agg_specs:
                node = next(a for a in agg_nodes if a.name == name)
                partial = _device_agg_to_partial(node, aspec,
                                                 out.get("aggs", {}).get(name), seg, ctx)
                result.agg_partials.setdefault(name, []).append(partial)

            # rescore second pass over this segment's window
            if rescores:
                scores = self._apply_rescores(rescores, ctx, seg, idx, valid, scores)

            for j in _tie_collect_order(keys, idx, valid, seg):
                d = int(idx[j])
                if d >= seg.ndocs:
                    continue
                sc = float(scores[j])
                if min_score is not None and not is_field_sort and sc < min_score:
                    continue
                sort_vals, raw_vals = _host_sort_values(sort_specs, seg, d, sc)
                cand = Candidate(shard_ord, seg_ord, d, sc, sort_vals, raw_vals)
                if collapse:
                    cand.collapse_key = _collapse_key_value(
                        seg, ctx.mappings.aliases.get(collapse["field"],
                                                      collapse["field"]), d)
                result.candidates.append(cand)
                names = [nm for nm, arr in named_np.items() if arr[j]]
                if names:
                    result.named_by_doc[(seg_ord, d)] = names

        if ta and result.total >= ta:
            # the budget was crossed (possibly exactly on the final
            # segment): the reference flags terminated_early whenever the
            # collector hit its limit, whether or not docs remained
            result.terminated_early = True

        self._resample_samplers(agg_nodes, result, ran_segs, ctx, lroot)

        # top_hits root aggs from candidates
        for i, an in enumerate(agg_nodes):
            if an.kind == "top_hits":
                top = sorted(result.candidates, key=lambda c: -(c.score or 0.0))
                size_th = int(an.body.get("size", 3))
                hits = [self._fetch_one(result.segments[c.seg_ord], c, an.body)
                        for c in top[:size_th]]
                result.agg_partials[an.name] = [{"hits": hits, "total": result.total,
                                                 "size": size_th}]

        # keep only the best window per shard
        result.candidates.sort(key=lambda c: c.sort_values)
        result.candidates = result.candidates[: window * oversample]
        result.took_ms = (time.monotonic() - t0) * 1000.0
        return result

    def _collect_view_topk(self, result: ShardQueryResult, view, out: dict,
                           shard_ord: int, sort_specs, min_score,
                           ctx) -> None:
        """Fold the shard-view launch's top-k (view-space doc ids) into the
        shard result, translating to (segment, local doc)."""
        keys = np.asarray(out["topk_key"])
        idx = np.asarray(out["topk_idx"])
        scores = np.asarray(out["topk_scores"])
        valid = keys > -np.inf
        result.total += int(out["total"])
        if out.get("total_rel") == "gte":
            result.total_rel = "gte"
        ms = float(out["max_score"])
        if ms > result.max_score:
            result.max_score = ms
        for j in _tie_collect_order(keys, idx, valid, view):
            d = int(idx[j])
            if d < 0 or d >= view.ndocs:
                continue
            sc = float(scores[j])
            if min_score is not None and sc < min_score:
                continue
            seg_ord, seg, local = view.locate(d)
            sort_vals, raw_vals = _host_sort_values(sort_specs, seg, local,
                                                    sc)
            result.candidates.append(
                Candidate(shard_ord, seg_ord, local, sc, sort_vals,
                          raw_vals))

    def _collect_topk(self, result: ShardQueryResult, out: dict, seg: Segment,
                      seg_ord: int, shard_ord: int, sort_specs, rescores,
                      min_score, is_field_sort: bool, ctx) -> None:
        """Fold one segment's top-k output (fast path) into the shard result —
        the same bookkeeping the general path does inline."""
        keys = np.asarray(out["topk_key"])
        idx = np.asarray(out["topk_idx"])
        scores = np.asarray(out["topk_scores"])
        valid = keys > -np.inf
        result.total += int(out["total"])
        if out.get("total_rel") == "gte":
            result.total_rel = "gte"
        ms = float(out["max_score"])
        if ms > result.max_score:
            result.max_score = ms
        if rescores:
            scores = self._apply_rescores(rescores, ctx, seg, idx, valid, scores)
        for j in _tie_collect_order(keys, idx, valid, seg):
            d = int(idx[j])
            if d < 0 or d >= seg.ndocs:
                continue
            sc = float(scores[j])
            if min_score is not None and not is_field_sort and sc < min_score:
                continue
            sort_vals, raw_vals = _host_sort_values(sort_specs, seg, d, sc)
            result.candidates.append(
                Candidate(shard_ord, seg_ord, d, sc, sort_vals, raw_vals))

    def _resample_samplers(self, agg_nodes, result: ShardQueryResult,
                           ran_segs: List[Segment], ctx, lroot) -> None:
        """Shard-wide sampler pass 2: pass 1 thresholds per segment, so a
        multi-segment shard would sample up to segments×shard_size docs.
        Merge the per-segment top scores, derive ONE shard-wide threshold,
        and re-run just the agg tree with it (reference SamplerAggregator
        samples per shard). Top-level sampler nodes only — a sampler nested
        under another bucket agg keeps per-segment semantics."""
        for an in agg_nodes:
            if an.kind != "sampler":
                continue
            partials = [p for p in result.agg_partials.get(an.name, []) if p]
            tops = [p.pop("topscores") for p in partials if "topscores" in p]
            if len(partials) <= 1 or not tops:
                continue
            shard_size = max(int(an.body.get("shard_size", 100)), 1)
            allscores = np.concatenate(tops)
            allscores = allscores[np.isfinite(allscores)]
            if len(allscores) <= shard_size:
                continue  # fewer matches than shard_size: pass 1 was exact
            thr = float(np.sort(allscores)[-shard_size])
            an._global_thr = thr
            try:
                new_parts = []
                for seg in ran_segs:
                    params: Dict[str, Any] = {}
                    qspec = C.prepare(lroot, seg, ctx, params)
                    aspec = C.prepare_agg(an, seg, ctx, params, "rs")
                    out = C.run_agg_only(qspec, aspec, seg.device_arrays(self.device), params)
                    new_parts.append(_device_agg_to_partial(an, aspec, out, seg, ctx))
                result.agg_partials[an.name] = new_parts
            finally:
                an._global_thr = None

    def _apply_rescores(self, rescores: List[dict], ctx, seg, idx, valid, scores):
        for rs in rescores:
            spec = rs.get("query", rs)
            window = int(rs.get("window_size", 10))
            rq = dsl.parse_query(spec.get("rescore_query"))
            qw = float(spec.get("query_weight", 1.0))
            rw = float(spec.get("rescore_query_weight", 1.0))
            mode = spec.get("score_mode", "total")
            lr = C.rewrite(rq, ctx, scoring=True)
            params: Dict[str, Any] = {}
            rspec = C.prepare(lr, seg, ctx, params)
            docs = np.where(valid, idx, INT32_SENTINEL % seg.ndocs_pad).astype(np.int32)
            rscores, rmatched = C.run_gather_scores(rspec, seg.device_arrays(self.device), params,
                                                    np.minimum(docs, seg.ndocs_pad - 1))
            rscores = np.asarray(rscores)
            rmatched = np.asarray(rmatched)
            in_window = np.arange(len(scores)) < window
            combined = np.where(rmatched, _combine_rescore(mode, qw * scores, rw * rscores),
                                qw * scores)
            scores = np.where(valid & in_window, combined, scores)
        return scores

    # ---------------- FETCH phase ----------------

    def fetch_phase(self, result: ShardQueryResult, selected: List[Candidate],
                    body: dict, stats_ctx: Optional[C.ShardContext] = None) -> List[dict]:
        # explain must recompute with the SAME collection-wide statistics the
        # query phase scored with, or _explanation diverges from _score
        ctx = stats_ctx or C.ShardContext(self.engine.mappings, result.segments,
                                          self.similarity, self.field_similarities)
        qtree = dsl.parse_query(body.get("query"))
        lroot = C.rewrite(qtree, ctx, scoring=True)
        hl_terms = collect_query_terms(lroot) if body.get("highlight") else {}
        nested_ihs = _nested_queries_with_inner_hits(qtree)
        join_ihs = _join_queries_with_inner_hits(qtree)
        perc_multi = [pq for pq in _walk_query_nodes(qtree, dsl.PercolateQuery)
                      if len(pq.documents) > 1]
        ih_cache: Dict[Tuple[int, int], Any] = {}
        suppress = _suppress_score(body) if body.get("sort") else False
        hits = []
        for c in selected:
            seg = result.segments[c.seg_ord]
            hit = self._fetch_one(seg, c, body, hl_terms,
                                  suppress_score=suppress)
            names = result.named_by_doc.get((c.seg_ord, c.local_doc))
            if names:
                hit["matched_queries"] = names
            if body.get("explain") and body.get("explain") != "device_plan":
                hit["_explanation"] = explain_doc(lroot, seg, c.local_doc, ctx)
            for nq in nested_ihs:
                self._add_inner_hits(hit, nq, seg, c, ctx, ih_cache)
            for jq in join_ihs:
                self._add_join_inner_hits(hit, jq, seg, c, ctx, ih_cache)
            for pq in perc_multi:
                self._add_percolate_slots(hit, pq, seg, c, ih_cache)
            hits.append(hit)
        return hits

    def _add_percolate_slots(self, hit: dict, pq, seg: Segment, c: Candidate,
                             ih_cache: dict) -> None:
        """`_percolator_document_slot` for multi-document percolation
        (reference PercolatorMatchedSlotSubFetchPhase)."""
        from . import percolate as P

        key = ("perc", id(pq))
        if key not in ih_cache:
            ih_cache[key] = P.build_mini(self.engine.mappings, pq.documents)
        mini_seg, mini_ctx = ih_cache[key]
        field = self.engine.mappings.resolve_field(pq.field)
        slots = P.document_slots(field.name if field else pq.field, mini_seg,
                                 mini_ctx, seg, c.local_doc)
        # multiple percolate clauses disambiguate by _name, like the reference
        key = (f"_percolator_document_slot_{pq.name}" if pq.name
               else "_percolator_document_slot")
        hit.setdefault("fields", {})[key] = slots

    def _join_child_scores(self, jq_key, lnode, cseg, ctx, ih_cache):
        """Dense matched scores of a join inner query over one segment
        (cached per (query, segment) across the fetch loop)."""
        key = (jq_key, id(cseg))
        if key not in ih_cache:
            cparams: Dict[str, Any] = {}
            cspec = C.prepare(lnode, cseg, ctx, cparams)
            docs = np.arange(cseg.ndocs_pad, dtype=np.int32)
            sc, cm = C.run_gather_scores(cspec, cseg.device_arrays(self.device), cparams, docs)
            ih_cache[key] = (np.asarray(sc), np.asarray(cm))
        return ih_cache[key]

    def _add_join_inner_hits(self, hit: dict, jq, seg: Segment, c: Candidate,
                             ctx, ih_cache: dict) -> None:
        """inner_hits for has_child (matching children under each parent hit)
        and has_parent (the matched parent of each child hit) — reference
        modules/parent-join InnerHitContextBuilder."""
        from .join import get_join_index

        jf = self.engine.mappings.join_field
        if jf is None:
            return
        ji = get_join_index(ctx.segments, jf)
        ih = jq.inner_hits or {}
        if isinstance(jq, dsl.HasChildQuery):
            name = ih.get("name", jq.type)
            inner_q = dsl.BoolQuery(must=[jq.query or dsl.MatchAllQuery()],
                                    filter=[dsl.TermQuery(field=jf, value=jq.type)])
            lkey = ("jihc", id(jq))
            if lkey not in ih_cache:
                ih_cache[lkey] = C.rewrite(inner_q, ctx, scoring=True)
            lnode = ih_cache[lkey]
            kids = []
            for cseg, cd in ji.children_of(ji.seg_base(seg) + c.local_doc):
                sc, cm = self._join_child_scores(id(jq), lnode, cseg, ctx, ih_cache)
                if cm[cd] and cseg.live[cd]:
                    kids.append((float(sc[cd]), cseg, cd))
            kids.sort(key=lambda t: -t[0])
            frm, size = int(ih.get("from", 0)), int(ih.get("size", 3))
            child_hits = []
            for sc_v, cseg, cd in kids[frm: frm + size]:
                ch = {"_index": hit.get("_index", ""), "_id": cseg.ids[cd],
                      "_score": sc_v, "_routing": seg.ids[c.local_doc]}
                if ih.get("_source", True) is not False:
                    ch["_source"] = cseg.sources[cd]
                child_hits.append(ch)
            hit.setdefault("inner_hits", {})[name] = {
                "hits": {"total": {"value": len(kids), "relation": "eq"},
                         "max_score": kids[0][0] if kids else None,
                         "hits": child_hits}}
            return
        # has_parent: the one matched parent of this child hit
        name = ih.get("name", jq.parent_type)
        slot = int(ji.pslot(seg)[c.local_doc])
        loc = ji.slot_to_doc(slot) if slot >= 0 else None
        parent_hits = []
        if loc is not None:
            pseg, pd = loc
            inner_q = dsl.BoolQuery(must=[jq.query or dsl.MatchAllQuery()],
                                    filter=[dsl.TermQuery(field=jf,
                                                          value=jq.parent_type)])
            lkey = ("jihp", id(jq))
            if lkey not in ih_cache:
                ih_cache[lkey] = C.rewrite(inner_q, ctx, scoring=True)
            sc, cm = self._join_child_scores(id(jq), ih_cache[lkey], pseg, ctx,
                                             ih_cache)
            if cm[pd] and pseg.live[pd]:
                ph = {"_index": hit.get("_index", ""), "_id": pseg.ids[pd],
                      "_score": float(sc[pd])}
                if ih.get("_source", True) is not False:
                    ph["_source"] = pseg.sources[pd]
                parent_hits.append(ph)
        hit.setdefault("inner_hits", {})[name] = {
            "hits": {"total": {"value": len(parent_hits), "relation": "eq"},
                     "max_score": parent_hits[0]["_score"] if parent_hits else None,
                     "hits": parent_hits}}

    def _add_inner_hits(self, hit: dict, nq: dsl.NestedQuery, seg: Segment,
                        c: Candidate, ctx, ih_cache: dict) -> None:
        """Matching child docs for one nested query (reference InnerHitsContext
        / InnerHitsPhase): one device pass scores the whole child space per
        segment, then each parent slices its block."""
        blk = seg.nested.get(nq.path)
        if blk is None or blk.child.ndocs == 0:
            return
        ih = nq.inner_hits or {}
        name = ih.get("name", nq.path)
        key = (id(nq), c.seg_ord)
        if key not in ih_cache:
            child_ctx = C.nested_context(ctx, nq.path)
            inner_l = C.rewrite(nq.query, child_ctx, scoring=True)
            cparams: Dict[str, Any] = {}
            cspec = C.prepare(inner_l, blk.child, child_ctx, cparams)
            docs = np.arange(blk.child.ndocs_pad, dtype=np.int32)
            scores, matched = C.run_gather_scores(
                cspec, blk.child.device_arrays(self.device), cparams, docs)
            ih_cache[key] = (np.asarray(scores), np.asarray(matched))
        scores, matched = ih_cache[key]
        a, b = blk.children_of(c.local_doc)
        kids = [(float(scores[i]), i) for i in range(a, b) if matched[i]]
        kids.sort(key=lambda t: -t[0])
        frm = int(ih.get("from", 0))
        size = int(ih.get("size", 3))
        child_hits = []
        for sc, i in kids[frm: frm + size]:
            ch = {"_index": hit.get("_index", ""), "_id": hit["_id"],
                  "_nested": {"field": nq.path, "offset": i - a},
                  "_score": sc}
            if ih.get("_source", True) is not False:
                ch["_source"] = blk.child.sources[i]
            child_hits.append(ch)
        hit.setdefault("inner_hits", {})[name] = {
            "hits": {"total": {"value": len(kids), "relation": "eq"},
                     "max_score": kids[0][0] if kids else None,
                     "hits": child_hits}}

    def _fetch_one(self, seg: Segment, c: Candidate, body: dict,
                   hl_terms: Optional[dict] = None,
                   suppress_score: Optional[bool] = None) -> dict:
        # per-searcher index label (multi-index and cross-cluster searches
        # need the concrete "alias:index" name, not the joined expression)
        hit = {"_index": self.index_key or body.get("_index_name", ""),
               "_id": seg.ids[c.local_doc],
               "_score": c.score}
        if body.get("sort"):
            hit["sort"] = list(c.raw_sort_values)
            if suppress_score is None:
                suppress_score = _suppress_score(body)
            if suppress_score:
                hit["_score"] = None
        stored_opt = body.get("stored_fields")
        # reference semantics: asking for stored_fields suppresses _source
        # unless the request opts back in explicitly
        src_opt = body.get("_source",
                           True if stored_opt is None else False)
        if src_opt is not False:
            src = seg.sources[c.local_doc]
            hit["_source"] = _filter_source(src, src_opt)
        if stored_opt and stored_opt != "_none_":
            stored = (seg.stored_vals[c.local_doc]
                      if getattr(seg, "stored_vals", None) else None) or {}
            flds = hit.setdefault("fields", {})
            for f in (stored_opt if isinstance(stored_opt, list)
                      else [stored_opt]):
                if f in stored:
                    flds[f] = list(stored[f])
        if body.get("docvalue_fields"):
            # merge: stored_fields may already have populated hit["fields"]
            hit.setdefault("fields", {}).update(
                _docvalue_fields(seg, c.local_doc, body["docvalue_fields"]))
        if body.get("fields"):
            flds = hit.setdefault("fields", {})
            for f in body["fields"]:
                fname = f if isinstance(f, str) else f.get("field")
                vals = _extract_source_values(seg.sources[c.local_doc], fname)
                if vals:
                    flds[fname] = vals
        if body.get("script_fields"):
            from ..script import ScriptError, run_field_script
            from .query_dsl import parse_script_spec
            flds = hit.setdefault("fields", {})
            for fname, fspec in body["script_fields"].items():
                src_str, prm = parse_script_spec(fspec.get("script"))
                try:
                    v = run_field_script(src_str, prm, seg, c.local_doc,
                                         score=c.score)
                except ScriptError as e:
                    raise dsl.QueryParseError(f"[script_fields.{fname}]: {e}")
                flds[fname] = v if isinstance(v, list) else [v]
        if body.get("highlight") and hl_terms is not None:
            hl = {}
            hl_body = body["highlight"]
            for fname, fopts in hl_body.get("fields", {}).items():
                ft = self.engine.mappings.resolve_field(fname)
                if ft is None:
                    continue
                terms = hl_terms.get(fname, set())
                vals = _extract_source_values(seg.sources[c.local_doc], fname)
                frags = []
                analyzer = self.engine.mappings.index_analyzer(ft)
                hl_type = fopts.get("type", hl_body.get("type", "plain"))
                hl_kw = dict(
                    pre_tag=(hl_body.get("pre_tags") or ["<em>"])[0],
                    post_tag=(hl_body.get("post_tags") or ["</em>"])[0],
                    fragment_size=int(fopts.get(
                        "fragment_size", hl_body.get("fragment_size", 100))),
                    number_of_fragments=int(fopts.get(
                        "number_of_fragments",
                        hl_body.get("number_of_fragments", 5))))
                tv = (getattr(seg, "term_vectors", None) or {}).get(fname)
                entries = tv[c.local_doc] if tv else None
                if hl_type == "fvh" and entries:
                    # real FVH: persisted term-vector offsets, no
                    # re-analysis; entries are per value, offsets relative
                    # to that value (term_vector=with_positions_offsets)
                    for v, ventry in zip(vals, entries):
                        if ventry:
                            frags.extend(highlight_fvh(
                                str(v), terms, ventry, **hl_kw))
                else:
                    # fvh without stored vectors degrades to unified
                    # (offsets re-derived by re-analysis)
                    hl_fn = (highlight_unified
                             if hl_type in ("unified", "fvh")
                             else highlight_field)
                    for v in vals:
                        frags.extend(hl_fn(str(v), terms, analyzer, **hl_kw))
                if frags:
                    hl[fname] = frags
            if hl:
                hit["highlight"] = hl
        return hit


# =====================================================================
# coordinator reduce (SearchPhaseController analog)
# =====================================================================

def reduce_shard_results(shard_results: List[ShardQueryResult], body: dict,
                         agg_nodes: Optional[List[AggNode]] = None,
                         defer_pipelines: bool = False) -> dict:
    size = int(body.get("size", 10))
    frm = int(body.get("from", 0))
    all_cands: List[Candidate] = []
    total = 0
    total_rel = "eq"
    max_score = float("-inf")
    for r in shard_results:
        all_cands.extend(r.candidates)
        total += r.total
        if r.total_rel == "gte":
            total_rel = "gte"
        max_score = max(max_score, r.max_score)
    all_cands.sort(key=lambda c: c.sort_values)
    if body.get("collapse"):
        # keep only the best hit per group across shards (reference
        # SearchPhaseController + CollapseBuilder coordinator merge)
        seen = set()
        deduped = []
        for c in all_cands:
            gk = ("null",) if c.collapse_key is None else ("v", c.collapse_key)
            if gk in seen:
                continue
            seen.add(gk)
            deduped.append(c)
        all_cands = deduped
    selected = all_cands[frm: frm + size]

    if agg_nodes is None:
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
    aggs_out = {}
    for node in agg_nodes:
        partials = []
        for r in shard_results:
            partials.extend(r.agg_partials.get(node.name, []))
        merged = merge_partials(node, partials) if partials else {}
        aggs_out[node.name] = finalize(node, merged,
                                       pipelines=not defer_pipelines)

    return {"selected": selected, "total": total, "total_rel": total_rel,
            "max_score": None if max_score == float("-inf") else max_score,
            "aggs": aggs_out}


def search_shards(searchers: List[ShardSearcher], body: dict,
                  index_name: str = "", task=None, phase_hook=None,
                  phase_ctx: Optional[dict] = None) -> dict:
    """Full query-then-fetch across shards -> OpenSearch-shaped response.

    `phase_hook(shard_results, body, ctx)` is the search-pipeline
    phase-results slot (reference SearchPhaseResultsProcessor.java): it runs
    after the per-shard device query phase, before the coordinator reduce.
    """
    from . import fusion
    if fusion.is_hybrid_body(body):
        # hybrid retrieval (search/fusion.py): each sub-query runs as an
        # independent retrieval through THIS same entry (its own serving
        # ladder, its own cost accumulator feeding the shared insights
        # observation); the fused page is a pure function of the ranked
        # sub-pages
        hq = fusion.parse_hybrid(body)
        return fusion.run_hybrid(
            body,
            lambda sub: search_shards(searchers, sub, index_name,
                                      task=task),
            q=hq)
    t0 = time.monotonic()
    body = dict(body)
    body["_index_name"] = index_name
    stats = _global_stats_contexts(searchers)
    from ..utils.metrics import METRICS
    from ..utils.trace import TRACER
    if body.get("profile"):
        # jit-attribution baseline: the profile response reports the
        # DELTA this request caused (compiles triggered, cache traffic)
        body["_jit_before"] = C.jit_attribution()
    # per-query device cost accounting (obs/query_cost.py): one
    # accumulator spans the whole shard loop + fastpath ladder; plan-time
    # predictions and launched-shape actuals reconcile in the profile
    # `cost` block and the cost.* histograms at finish
    qc_token = None
    qc_acc = None
    if _qcost.enabled() and _qcost.current() is None:
        qc_acc, qc_token = _qcost.start(
            detail=body.get("explain") == "device_plan")
    # request deadline: REST/distnode installs the ambient budget at
    # accept time (queue wait counts); direct engine callers get one
    # derived from the body's `timeout` here
    dl_token = None
    if _dl.current() is None:
        try:
            _deadline = _dl.Deadline.from_body(body)
        except ValueError as e:
            raise dsl.QueryParseError(str(e))
        if _deadline is not None:
            dl_token = _dl.set_current(_deadline)
    try:
        results = []
        for i, s in enumerate(searchers):
            with TRACER.span("query_phase", shard=i), \
                    METRICS.timer("search.query_phase"):
                results.append(s.query_phase(body, shard_ord=i,
                                             stats_ctx=stats[i], task=task))
        if phase_hook is not None:
            phase_hook(results, body,
                       phase_ctx if phase_ctx is not None else {})
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
        # pipelines whose buckets_path targets a refinement-resolved
        # sub-agg are deferred until after _refine_complex_subs; the rest
        # run in finalize so bucket_selector/bucket_sort still prune
        # BEFORE per-bucket refinement
        for an in agg_nodes:
            _mark_deferred_pipelines(an)
        return _finish_search(searchers, results, body, stats, index_name,
                              t0, agg_nodes)
    finally:
        if dl_token is not None:
            _dl.reset_current(dl_token)
        if qc_token is not None:
            if qc_acc is not None and qc_acc.actual_bytes:
                # feed the measured bytes-moved into the request's
                # query-insights observation (obs/insights.py) — the
                # per-SHAPE bytes attribution `top_queries?by=bytes`
                # ranks on. Same-thread contextvar, so coalesced
                # scheduler batches (other threads) stay unattributed
                # exactly like query_cost itself documents.
                from ..obs import insights as _ins
                _ins.note_bytes(qc_acc.actual_bytes)
            _qcost.finish(qc_token)


def msearch_batched(searchers: List[ShardSearcher],
                    bodies: List[dict], index_name: str = ""
                    ) -> Optional[List[dict]]:
    """Synchronous batched msearch on the Pallas fast path: launch +
    fetch back-to-back (see `launch_msearch_batched` for the split)."""
    handle = launch_msearch_batched(searchers, bodies, index_name)
    if handle is None:
        return None
    return handle.fetch()


def launch_msearch_batched(searchers: List[ShardSearcher],
                           bodies: List[dict], index_name: str = ""):
    """Batched msearch on the Pallas fast path: eligible bodies' queries
    over each segment run as ONE kernel launch per shape group (grid over
    queries) — server-side query batching, the production shape of a TPU
    search tier (reference analog: `action/search/TransportMultiSearchAction`
    just loops; we fuse).

    LAUNCH stage: parsing, spec building, and EVERY shard/segment's
    frontier kernel enqueue run here, unfetched — all segments' launches
    pipeline on the device before the first sync. The returned handle's
    `fetch()` syncs each segment batch, collects top-ks, and finishes the
    responses: a per-body list whose entries are response dicts for
    bodies the fast path served and None for the rest (the caller runs
    those through the regular per-body search). Returns None wholesale
    when the fast path is off."""
    from .launch import LaunchHandle

    if not searchers:
        return None
    fp_on = fastpath.enabled()
    if not fp_on and not any(_maybe_knn_body(b) for b in bodies):
        # the Pallas kernels are TPU-only, but the batched pure-knn
        # route is plain XLA (vmapped executor twin) and engages on
        # every backend — only bail wholesale when NEITHER route can
        # serve anything
        return None
    stats = _global_stats_contexts(searchers)
    nb = len(bodies)
    parsed: List[Optional[tuple]] = []
    for body in bodies:
        body = dict(body)
        body["_index_name"] = index_name
        if (body.get("aggs") or body.get("aggregations") or body.get("rescore")
                or body.get("search_after") is not None or body.get("min_score")
                is not None or body.get("profile")
                or body.get("explain") == "device_plan"):
            parsed.append(None)
            continue
        try:
            query = compose_knn_query(body)
        except (dsl.QueryParseError, KeyError, TypeError, ValueError):
            parsed.append(None)     # slow path surfaces the error per body
            continue
        parsed.append((body, query, _norm_sort_specs(body),
                       int(body.get("from", 0)) + int(body.get("size", 10))))

    t0 = time.monotonic()
    ok = [p is not None for p in parsed]
    results = [[ShardQueryResult(shard=i, segments=list(s.engine.segments))
                for i, s in enumerate(searchers)] for _ in range(nb)]
    # (shard idx, searcher, ctx, seg, seg_ord, launch-time live set,
    #  fspecs, handle-or-None); a body invalidated by an EARLIER segment's
    # fetch may still ride a later launch — per-query results are
    # batch-composition invariant, so its entries are simply discarded
    launches: List[tuple] = []
    knn_launches: List[tuple] = []
    for i, s in enumerate(searchers):
        if not any(ok):
            break
        ctx = stats[i]
        segments = list(s.engine.segments)
        fspecs: List[Optional[Any]] = [None] * nb
        kroots: List[Optional[Any]] = [None] * nb
        for bi, p in enumerate(parsed):
            if not ok[bi]:
                continue
            body, query, sort_specs, window = p
            try:
                lroot = C.rewrite(query, ctx, scoring=True)
            except dsl.QueryParseError:
                ok[bi] = False
                continue
            if _collect_named(lroot):
                ok[bi] = False
                continue
            fspecs[bi] = (fastpath.make_spec(lroot, sort_specs, [], [],
                                             None, window, body)
                          if fp_on else None)
            if fspecs[bi] is None:
                # pure-knn route: a lone LKnn root (query.knn, or the
                # ES-style top-level knn section with no query) batches
                # through the vmapped twin of the SAME general program
                # the direct path runs — first-class vector serving
                # (ISSUE 15), byte-identical per query by construction
                if isinstance(lroot, C.LKnn) \
                        and _knn_batch_body_ok(sort_specs, body, window):
                    kroots[bi] = lroot
                else:
                    if isinstance(lroot, C.LKnn):
                        from ..search import fusion as _fusion
                        _fusion.STATS.inc("knn_batch_declined")
                    ok[bi] = False
        live_bis = [bi for bi in range(nb)
                    if ok[bi] and fspecs[bi] is not None]
        knn_bis = [bi for bi in range(nb) if ok[bi] and kroots[bi] is not None]
        if not live_bis and not knn_bis:
            continue
        for seg_ord, seg in enumerate(segments):
            if seg.live_count == 0:
                continue
            if live_bis:
                handle = fastpath.launch_batch(
                    seg, ctx, [fspecs[bi] for bi in live_bis],
                    max((parsed[bi][3] for bi in live_bis), default=10),
                    count_stats=False)
                if handle is None:
                    # wholesale decline, known AT LAUNCH (segment can't
                    # take the fast path at all): fail these bodies now
                    # so later shards don't enqueue kernels for work that
                    # would only be discarded at fetch (same outcome as
                    # the synchronous path's `outs is None` break, same
                    # launch count too)
                    for bi in live_bis:
                        ok[bi] = False
                    live_bis = []
                else:
                    launches.append((i, s, ctx, seg, seg_ord,
                                     list(live_bis), fspecs, handle))
            if knn_bis:
                got = _launch_knn_segment(s, ctx, seg, seg_ord, i,
                                          [(bi, kroots[bi],
                                            parsed[bi][2], parsed[bi][3])
                                           for bi in knn_bis])
                if got is None:
                    # tie-aware segment (BP reorder widen loop) or
                    # can-prepare failure: parity demands the direct
                    # path's per-segment machinery — decline these
                    # bodies wholesale
                    from ..search import fusion as _fusion
                    _fusion.STATS.inc("knn_batch_declined", len(knn_bis))
                    for bi in knn_bis:
                        ok[bi] = False
                    knn_bis = []
                else:
                    knn_launches.extend(got)

    def _finish():
        served_batches: List[tuple] = []
        for (i, s, ctx, seg, seg_ord, bis, fetch_fn) in knn_launches:
            live = [bi for bi in bis if ok[bi]]
            if not live:
                continue
            outs = fetch_fn()
            by_bi = dict(zip(bis, outs))
            for bi in live:
                _b, _q, k_sort_specs, _w = parsed[bi]
                s._collect_topk(results[bi][i], by_bi[bi], seg, seg_ord,
                                i, k_sort_specs, None, None, False, ctx)
        for (i, s, ctx, seg, seg_ord, seg_live, fspecs,
             handle) in launches:
            live = [bi for bi in seg_live if ok[bi]]
            if not live:
                continue
            # stats counted only for bodies served on every shard/segment
            # — a later fallback discards that body's results, re-runs slow
            outs = handle.fetch()
            by_bi = dict(zip(seg_live, outs))
            for bi in live:
                o = by_bi[bi]
                if o is not None:
                    served_batches.append((bi, fspecs[bi], o))
            for bi in live:
                fout = by_bi[bi]
                if fout is None:
                    ok[bi] = False
                    continue
                body, _, sort_specs, window = parsed[bi]
                s._collect_topk(results[bi][i], fout, seg, seg_ord, i,
                                sort_specs, None, None, False, ctx)
        for i in range(len(searchers)):
            for bi in range(nb):
                if not ok[bi]:
                    continue
                body, _, sort_specs, window = parsed[bi]
                r = results[bi][i]
                r.candidates.sort(key=lambda c: c.sort_values)
                r.candidates = r.candidates[:window]
                r.took_ms = (time.monotonic() - t0) * 1000.0
        if not any(ok):
            return [None] * nb
        for bi, fs, o in served_batches:
            if ok[bi]:
                fastpath.count_served([fs], [o])
        return [_finish_search(searchers, results[bi], parsed[bi][0],
                               stats, index_name, t0, [])
                if ok[bi] else None for bi in range(nb)]

    info = None
    if _flight.RECORDER.enabled:
        # launch forensics for the scheduler's per-request journal
        # (mirrors MeshSearchService.launch_msearch's handle.info)
        info = {"path": "kernel", "bodies": int(sum(ok)),
                "kernel_launches": len(launches),
                "knn_batch_launches": len(knn_launches)}
    return LaunchHandle(_finish, kind="fastpath", info=info)


def _maybe_knn_body(body) -> bool:
    """Cheap screen: could this body take the batched pure-knn route?"""
    if not isinstance(body, dict):
        return False
    if isinstance(body.get("knn"), dict):
        return True
    q = body.get("query")
    return isinstance(q, dict) and "knn" in q


def _knn_batch_body_ok(sort_specs, body: dict, window: int) -> bool:
    """Body checks for the batched pure-knn route — the shape class the
    direct general path serves with oversample 1 and no per-segment
    budget stops (terminate_after / a live timeout need the
    deadline-aware host loop; a non-score sort needs host re-sorting)."""
    if window < 1 or window > 1024:
        return False
    if sort_specs and not (len(sort_specs) == 1
                           and sort_specs[0]["field"] == "_score"
                           and sort_specs[0].get("order", "desc")
                           == "desc"):
        return False
    if body.get("collapse") or body.get("suggest") \
            or body.get("terminate_after"):
        return False
    if body.get("timeout") is not None:
        from ..utils.deadline import parse_timeout_s
        try:
            if parse_timeout_s(body["timeout"]) is not None:
                return False
        except ValueError:
            return False
    return True


def _launch_knn_segment(s: ShardSearcher, ctx, seg: Segment, seg_ord: int,
                        shard_i: int, items: List[tuple]
                        ) -> Optional[List[tuple]]:
    """LAUNCH the coalesced pure-knn batch for one segment: prepare
    each body exactly like the direct general path (same k_pad, same
    spec/params via canon_query — structurally identical bodies share
    one compiled program), enqueue every per-query invocation of the
    DIRECT-path executor unfetched, and defer the device sync to one
    fetch sweep (compiler.launch_segment_batch — deliberately not a
    vmapped mega-program; see its docstring for the byte-parity
    rationale). Returns [(shard_i, s, ctx, seg, seg_ord, [bi...],
    fetch_fn)] or None to decline the whole segment (BP-reordered
    tie-aware segments need the direct path's widen loop)."""
    from ..search import fusion as _fusion

    tief = getattr(seg, "tie_ranks", None)
    if tief is not None and tief() is not None:
        return None
    prepared: List[tuple] = []
    bis: List[int] = []
    for bi, lroot, sort_specs, window in items:
        k_pad = min(next_pow2(max(window, 16)), seg.ndocs_pad)
        params: Dict[str, Any] = {}
        try:
            qspec = C.prepare(lroot, seg, ctx, params)
            sspec = C.prepare_sort(sort_specs, seg, params)
        except dsl.QueryParseError:
            return None
        full, cparams = C.canon_query(qspec, sspec, k_pad, params)
        prepared.append((full, cparams))
        bis.append(bi)
    fetch_fn = C.launch_segment_batch(prepared, seg.device_arrays(s.device))
    _fusion.STATS.inc("knn_batch_launches")
    _fusion.STATS.inc("knn_batched", len(prepared))
    return [(shard_i, s, ctx, seg, seg_ord, bis, fetch_fn)]


def _finish_search(searchers: List[ShardSearcher],
                   results: List[ShardQueryResult], body: dict, stats,
                   index_name: str, t0: float,
                   agg_nodes: List[AggNode]) -> dict:
    """Coordinator reduce + fetch + response assembly (the tail of
    query-then-fetch, shared by search and batched msearch)."""
    from ..utils.metrics import METRICS
    from ..utils.trace import TRACER
    with TRACER.span("reduce"), METRICS.timer("search.reduce"):
        reduced = reduce_shard_results(results, body, agg_nodes=agg_nodes,
                                       defer_pipelines=bool(agg_nodes))
    by_shard: Dict[int, List[Candidate]] = {}
    for c in reduced["selected"]:
        by_shard.setdefault(c.shard, []).append(c)
    hits_by_key: Dict[Tuple, dict] = {}
    with TRACER.span("fetch_phase", hits=len(reduced["selected"])), \
            METRICS.timer("search.fetch_phase"):
        for i, r in enumerate(results):
            sel = by_shard.get(r.shard, [])
            if not sel:
                continue
            fetched = searchers[i].fetch_phase(r, sel, body,
                                               stats_ctx=stats[i])
            for c, h in zip(sel, fetched):
                hits_by_key[(c.shard, c.seg_ord, c.local_doc)] = h
    hits = [hits_by_key[(c.shard, c.seg_ord, c.local_doc)] for c in reduced["selected"]
            if (c.shard, c.seg_ord, c.local_doc) in hits_by_key]

    collapse = body.get("collapse")
    if collapse:
        _apply_collapse_inner_hits(searchers, body, index_name, collapse,
                                   reduced["selected"], hits_by_key)

    if reduced["aggs"]:
        # bucket refinement: ordinal bucket aggs execute complex sub-trees
        # (terms>terms, bucket top_hits, cardinality-under-terms, ...) as one
        # recursive sub-search per top bucket — the device pass only fuses
        # the stats-family metrics into the ordinal bincount
        for an in agg_nodes:
            _refine_complex_subs(searchers, body, index_name, an,
                                 reduced["aggs"].get(an.name),
                                 body.get("query"), [])
        for an in agg_nodes:
            _apply_deferred_tree(an, reduced["aggs"].get(an.name))

    track = body.get("track_total_hits", True)
    relation = reduced.get("total_rel", "eq")
    total = reduced["total"]
    if track is not True and track is not False:
        track_n = int(track)
        if total > track_n:
            total, relation = track_n, "gte"
    took_ms = (time.monotonic() - t0) * 1000.0
    METRICS.histogram("search.total").record(took_ms)
    timed_out = any(r.timed_out for r in results)
    terminated_early = any(r.terminated_early for r in results)
    if body.get("allow_partial_search_results", True) is False \
            and timed_out:
        # reference parity: partial pages refused -> whole-request error
        # (the REST facade maps this to a 503
        # search_phase_execution_exception)
        raise _dl.PartialResultsUnacceptable(
            "request timed out with allow_partial_search_results=false")
    # track_scores (reference): a field-sorted request normally reports
    # max_score null; track_scores=true opts the rollup back in (the
    # engine computes scores regardless — they are free on device)
    show_max = not body.get("sort") or bool(body.get("track_scores"))
    resp = {
        "took": int(took_ms),
        "timed_out": timed_out,
        "_shards": {"total": len(searchers), "successful": len(searchers),
                    "skipped": 0, "failed": 0},
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": reduced["max_score"] if show_max else None,
                 "hits": hits},
    }
    if terminated_early:
        resp["terminated_early"] = True
    if reduced["aggs"]:
        resp["aggregations"] = reduced["aggs"]
    if body.get("suggest"):
        from .suggest import run_suggest
        segs = [g for s in searchers for g in s.engine.segments
                if g.live_count > 0]
        mappings = searchers[0].engine.mappings if searchers else None
        resp["suggest"] = run_suggest(body["suggest"], segs, mappings)
    if body.get("profile"):
        # per-plan-node breakdown (reference search/profile/): the plan tree
        # with type/description per node. One honesty note a TPU engine owes
        # its users: XLA fuses the whole plan into one program, so per-node
        # device times are not separable — node entries carry the tree and
        # the root carries the measured phase time (children fused=true).
        try:
            plan_tree = C.describe_plan(
                C.rewrite(dsl.parse_query(body.get("query")),
                          stats[0], scoring=True)) if stats else None
        except Exception:
            plan_tree = None
        # device attribution: what this request cost the jit layer (cache
        # traffic + compiles triggered, the DELTA vs the pre-request
        # baseline search_shards stashed) and which phase-2 rescore path
        # is active — the per-plan-node "why was this slow" the reference
        # gets from search/profile/
        from .fastpath import rescore_mode
        device_attr = {"rescore_path": rescore_mode(),
                       "jit": _jit_delta(body.pop("_jit_before", None),
                                         C.jit_attribution())}
        shards_profile = []
        for r in results:
            entry: dict = {"id": f"[shard][{r.shard}]",
                           "query_ms": r.took_ms,
                           "device": device_attr,
                           "searches": [{"query": [], "rewrite_time": 0,
                                         "collector": [{
                                             "name": "SimpleTopKCollector",
                                             "reason": "search_top_hits",
                                             "time_in_nanos": int(
                                                 r.took_ms * 1e6)}]}]}
            if plan_tree is not None:
                root = dict(plan_tree)
                root["time_in_nanos"] = int(r.took_ms * 1e6)
                root["device"] = device_attr
                entry["searches"][0]["query"] = [root]
            shards_profile.append(entry)
        resp["profile"] = {"shards": shards_profile}
        qc = _qcost.current()
        if qc is not None:
            # per-query device cost: plan-time prediction (CSR stats)
            # reconciled against the launched program shapes — the byte
            # domain the north star's ≥20× claim is argued in
            resp["profile"]["cost"] = qc.snapshot()
    if body.get("explain") == "device_plan":
        # device-plan search view: the cost rollup + per-segment
        # predicted/actual entries, without per-hit _explanation trees
        qc = _qcost.current()
        if qc is not None:
            resp["device_plan"] = {"cost": qc.snapshot(),
                                   "segments": list(qc.segments)}
    return resp


# =====================================================================
# helpers
# =====================================================================

def _jit_delta(before, after):
    """Recursive numeric diff of two `compiler.jit_attribution()`
    snapshots: count/total fields become this-request deltas, percentile
    fields (registry-lifetime, not diffable) pass through from `after`."""
    if not isinstance(before, dict) or not isinstance(after, dict):
        return after
    out = {}
    for k, v in after.items():
        if isinstance(v, dict):
            out[k] = _jit_delta(before.get(k), v)
        elif isinstance(v, (int, float)) and not k.startswith("p") \
                and isinstance(before.get(k), (int, float)):
            d = v - before[k]
            out[k] = round(d, 3) if isinstance(d, float) else d
        else:
            out[k] = v
    return out


_STATS_FAMILY = {"min", "max", "sum", "avg", "stats", "extended_stats",
                 "value_count"}
_ORDINAL_KINDS = {"terms", "significant_terms", "histogram", "date_histogram",
                  "geohash_grid", "geotile_grid", "composite", "rare_terms",
                  "multi_terms", "auto_date_histogram", "significant_text"}
_WALK_CONTAINERS = {"filter", "filters", "range", "date_range", "global",
                    "missing"}


def _apply_collapse_inner_hits(searchers, body, index_name, collapse,
                               selected, hits_by_key) -> None:
    """Stamp the collapse field value into each hit and resolve inner_hits
    groups via per-group sub-searches (reference ExpandSearchPhase)."""
    field = collapse["field"]
    ih_specs = collapse.get("inner_hits") or []
    if isinstance(ih_specs, dict):
        ih_specs = [ih_specs]
    for c in selected:
        h = hits_by_key.get((c.shard, c.seg_ord, c.local_doc))
        if h is None:
            continue
        h.setdefault("fields", {})[field] = [c.collapse_key]
        for ih in ih_specs:
            name = ih.get("name", field)
            if c.collapse_key is None:
                gfilter = {"bool": {"must_not": [{"exists": {"field": field}}]}}
            else:
                gfilter = {"term": {field: c.collapse_key}}
            sub = {
                "query": {"bool": {
                    "must": [body.get("query") or {"match_all": {}}],
                    "filter": [gfilter]}},
                "size": int(ih.get("size", 3)),
                "from": int(ih.get("from", 0)),
            }
            if ih.get("sort"):
                sub["sort"] = ih["sort"]
            sub_resp = search_shards(searchers, sub, index_name=index_name)
            h.setdefault("inner_hits", {})[name] = {"hits": sub_resp["hits"]}


def _pipeline_input_names(p: AggNode) -> set:
    """First path components of every buckets_path (and bucket_sort sort
    fields) a pipeline node reads."""
    raw = p.body.get("buckets_path", "_count")
    paths = list(raw.values()) if isinstance(raw, dict) else [raw]
    if p.kind == "bucket_sort":
        for s in p.body.get("sort", []):
            if isinstance(s, dict):
                paths.extend(s.keys())
            elif isinstance(s, str):
                paths.append(s)
    return {str(pth).replace(">", ".").split(".")[0] for pth in paths if pth}


def _mark_deferred_pipelines(node: AggNode) -> None:
    """Flag pipelines whose inputs come from refinement-resolved sub-aggs
    (complex subs of ordinal buckets) — transitively through pipelines that
    read other deferred pipelines' outputs."""
    deferred_names = ({s.name for s in node.subs if s.kind not in _STATS_FAMILY}
                      if node.kind in _ORDINAL_KINDS else set())
    for p in node.pipelines:
        p.deferred = False
    changed = True
    while changed:
        changed = False
        for p in node.pipelines:
            if not p.deferred and (_pipeline_input_names(p) & deferred_names):
                p.deferred = True
                deferred_names.add(p.name)
                changed = True
    for s in node.subs:
        _mark_deferred_pipelines(s)


def _apply_deferred_tree(node: AggNode, result) -> None:
    """Apply deferred pipelines after refinement, mirroring the
    _refine_complex_subs walk: complex subs of reached ordinal nodes were
    REPLACED by fully-pipelined refinement sub-search results — don't descend
    into them (double application); subtrees the walk never reached get the
    plain post-order pass."""
    if not isinstance(result, dict):
        return
    if node.kind in _ORDINAL_KINDS:
        _apply_bucket_pipelines(node, result, "deferred")
        return
    if node.kind in _WALK_CONTAINERS:
        buckets = result.get("buckets")
        if isinstance(buckets, list):
            for b in buckets:
                for s in node.subs:
                    _apply_deferred_tree(s, b.get(s.name))
        elif isinstance(buckets, dict):
            for bd in buckets.values():
                for s in node.subs:
                    _apply_deferred_tree(s, bd.get(s.name))
        else:
            for s in node.subs:
                _apply_deferred_tree(s, result.get(s.name))
        _apply_bucket_pipelines(node, result, "deferred")
        return
    apply_pipelines_tree(node, result)


def _agg_to_dsl(node: AggNode) -> dict:
    spec: dict = {node.kind: node.body}
    subs = {s.name: _agg_to_dsl(s) for s in node.subs}
    subs.update({p.name: _agg_to_dsl(p) for p in node.pipelines})
    if subs:
        spec["aggs"] = subs
    return spec


def _next_calendar_ms(ms: int, cal: str) -> int:
    import datetime as dt

    d = dt.datetime.fromtimestamp(ms / 1000.0, dt.timezone.utc)
    if cal in ("month", "1M"):
        y, m = (d.year + 1, 1) if d.month == 12 else (d.year, d.month + 1)
        return int(dt.datetime(y, m, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    if cal in ("year", "1y"):
        return int(dt.datetime(d.year + 1, 1, 1,
                               tzinfo=dt.timezone.utc).timestamp() * 1000)
    if cal in ("quarter", "1q"):
        m = ((d.month - 1) // 3) * 3 + 4
        y = d.year + (1 if m > 12 else 0)
        m = 1 if m > 12 else m
        return int(dt.datetime(y, m, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    step = {"week": 7 * 86400000, "1w": 7 * 86400000, "day": 86400000,
            "1d": 86400000, "hour": 3600000, "1h": 3600000,
            "minute": 60000, "1m": 60000}[cal]
    return ms + step


def _geohash_bbox(cell: str):
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    is_lon = True
    for ch in cell:
        bits = "0123456789bcdefghjkmnpqrstuvwxyz".index(ch)
        for b in (16, 8, 4, 2, 1):
            if is_lon:
                mid = (lon_lo + lon_hi) / 2
                if bits & b:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2
                if bits & b:
                    lat_lo = mid
                else:
                    lat_hi = mid
            is_lon = not is_lon
    return lat_lo, lat_hi, lon_lo, lon_hi


def _geotile_bbox(cell: str):
    import math as _m

    z, x, y = (int(p) for p in cell.split("/"))
    n = 1 << z
    lon_lo = x / n * 360.0 - 180.0
    lon_hi = (x + 1) / n * 360.0 - 180.0

    def lat_of(yy):
        return _m.degrees(_m.atan(_m.sinh(_m.pi * (1 - 2 * yy / n))))

    return lat_of(y + 1), lat_of(y), lon_lo, lon_hi


def _bucket_filter(node: AggNode, bucket: dict) -> Optional[dict]:
    """DSL filter selecting exactly the docs of one finalized bucket."""
    body = node.body
    field = body.get("field")
    kind = node.kind
    if kind in ("terms", "significant_terms", "rare_terms",
                "significant_text"):
        # significant_text keys are analyzed tokens of a text field: a term
        # query on the same field matches exactly the docs carrying the token
        return {"term": {field: bucket["key"]}}
    if kind == "multi_terms":
        flt = [{"term": {src["field"]: v}}
               for src, v in zip(body.get("terms", []), bucket["key"])]
        return {"bool": {"filter": flt}}
    if kind == "auto_date_histogram":
        key = int(bucket["key"])
        # the chosen interval is in the finalized result, threaded onto the
        # bucket by _refine via the parent result's "interval"
        interval_ms = bucket.get("_interval_ms", 1000)
        return {"range": {field: {"gte": key, "lt": key + interval_ms}}}
    if kind == "histogram":
        interval = float(body["interval"])
        return {"range": {field: {"gte": bucket["key"],
                                  "lt": bucket["key"] + interval}}}
    if kind == "date_histogram":
        key = int(bucket["key"])
        cal = body.get("calendar_interval")
        if cal:
            end = _next_calendar_ms(key, cal)
        else:
            end = key + C.parse_interval_ms(body.get("fixed_interval",
                                                     body.get("interval", "1d")))
        return {"range": {field: {"gte": key, "lt": end}}}
    if kind in ("geohash_grid", "geotile_grid"):
        lat_lo, lat_hi, lon_lo, lon_hi = (
            _geohash_bbox(bucket["key"]) if kind == "geohash_grid"
            else _geotile_bbox(bucket["key"]))
        return {"geo_bounding_box": {field: {
            "top": lat_hi, "left": lon_lo, "bottom": lat_lo, "right": lon_hi}}}
    if kind == "composite":
        from .aggregations import composite_sources

        flt = []
        for nm, stype, scfg, _ in composite_sources(node):
            v = bucket["key"][nm]
            f = scfg.get("field")
            if stype == "terms":
                flt.append({"term": {f: v}})
            elif stype == "histogram":
                flt.append({"range": {f: {"gte": v,
                                          "lt": v + float(scfg["interval"])}}})
            else:
                cal = scfg.get("calendar_interval")
                end = (_next_calendar_ms(int(v), cal) if cal else
                       int(v) + C.parse_interval_ms(scfg.get(
                           "fixed_interval", scfg.get("interval", "1d"))))
                flt.append({"range": {f: {"gte": int(v), "lt": end}}})
        return {"bool": {"filter": flt}} if len(flt) != 1 else flt[0]
    return None


def _refine_complex_subs(searchers: List[ShardSearcher], body: dict,
                         index_name: str, node: AggNode, result: Optional[dict],
                         query: Optional[dict], filters: List[dict]) -> None:
    """Recursive bucket refinement (see search_shards). Descends through
    filter-expressible containers accumulating context filters; for each
    ordinal bucket with complex subs, runs one size-0 sub-search whose own
    aggs recurse naturally. Doc-space-changing aggs (nested, children,
    sampler) stop the walk — their device recursion covers the stats family."""
    if result is None:
        return
    kind = node.kind
    if kind in _ORDINAL_KINDS:
        complex_subs = [s for s in node.subs if s.kind not in _STATS_FAMILY]
        buckets = result.get("buckets")
        if not isinstance(buckets, list) or not complex_subs:
            return
        if kind == "auto_date_histogram":
            # thread the coordinator-chosen interval to the bucket filters
            name_to_ms = {n: ms for ms, n in C._AUTO_LADDER}
            iv = name_to_ms.get(result.get("interval"), 1000)
            for b in buckets:
                b["_interval_ms"] = iv
        for b in buckets:
            bf = _bucket_filter(node, b)
            if bf is None:
                continue
            sub_body = {"size": 0, "_index_name": index_name,
                        "query": {"bool": {"must": ([query] if query else []),
                                           "filter": filters + [bf]}},
                        "aggs": {s.name: _agg_to_dsl(s) for s in complex_subs}}
            resp = search_shards(searchers, sub_body, index_name)
            for s in complex_subs:
                b[s.name] = resp["aggregations"][s.name]
        for b in buckets:
            b.pop("_interval_ms", None)
        return
    if kind == "filter":
        for s in node.subs:
            _refine_complex_subs(searchers, body, index_name, s,
                                 result.get(s.name), query,
                                 filters + [node.body])
        return
    if kind == "filters":
        fmap = dict(C.filters_agg_items(node.body))
        for key, bucket in (result.get("buckets") or {}).items():
            bf = fmap.get(key)
            if bf is None:
                continue
            for s in node.subs:
                _refine_complex_subs(searchers, body, index_name, s,
                                     bucket.get(s.name), query, filters + [bf])
        return
    if kind in ("range", "date_range"):
        field = node.body.get("field")
        for bucket in (result.get("buckets") or []):
            rng = {}
            if bucket.get("from") is not None:
                rng["gte"] = bucket["from"]
            if bucket.get("to") is not None:
                rng["lt"] = bucket["to"]
            for s in node.subs:
                _refine_complex_subs(searchers, body, index_name, s,
                                     bucket.get(s.name), query,
                                     filters + [{"range": {field: rng}}])
        return
    if kind == "geo_distance":
        field = node.body.get("field")
        origin = node.body.get("origin")
        unit = node.body.get("unit", "m")
        for bucket in (result.get("buckets") or []):
            # match the device bucket semantics [from, to): strict < on
            # the upper edge, NOT(dist < from) = dist >= from on the lower
            flt: List[dict] = []
            if bucket.get("to") is not None:
                flt.append({"geo_distance": {
                    "distance": f"{bucket['to']}{unit}", field: origin,
                    "_inclusive": False}})
            if bucket.get("from") is not None:
                flt.append({"bool": {"must_not": [{"geo_distance": {
                    "distance": f"{bucket['from']}{unit}",
                    field: origin, "_inclusive": False}}]}})
            for s in node.subs:
                _refine_complex_subs(searchers, body, index_name, s,
                                     bucket.get(s.name), query,
                                     filters + flt)
        return
    if kind == "global":
        for s in node.subs:
            _refine_complex_subs(searchers, body, index_name, s,
                                 result.get(s.name), None, [])
        return
    if kind == "missing":
        mf = {"bool": {"must_not": [{"exists": {"field": node.body.get("field")}}]}}
        for s in node.subs:
            _refine_complex_subs(searchers, body, index_name, s,
                                 result.get(s.name), query, filters + [mf])
        return


def _global_stats_contexts(searchers: List[ShardSearcher]) -> List[Any]:
    """DFS phase: collection statistics span ALL segments of the searcher's
    index_key group, so idf/avgdl are collection-wide — but each searcher
    keeps its OWN mappings/similarity for rewrite (heterogeneous standalone
    searchers must not resolve fields against another index's mappings).
    Returns one stats context per searcher, aligned by position."""
    group_segs: Dict[Any, List] = {}
    for s in searchers:
        group_segs.setdefault(s.index_key, []).extend(
            getattr(s, "_snapshot_segments", None) or s.engine.segments)
    return [C.ShardContext(s.engine.mappings, group_segs[s.index_key],
                           s.similarity, s.field_similarities)
            for s in searchers]


def _combine_rescore(mode: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if mode == "total":
        return a + b
    if mode == "multiply":
        return a * b
    if mode == "avg":
        return (a + b) / 2
    if mode == "max":
        return np.maximum(a, b)
    if mode == "min":
        return np.minimum(a, b)
    raise ValueError(f"unknown rescore score_mode [{mode}]")


def _aggs_need_all_segments(agg_nodes) -> bool:
    """True if any agg in the tree observes docs outside the query match set
    (reference: global/filter/filters/missing aggregators; significant_terms
    needs every segment's background counts)."""
    for n in agg_nodes:
        if n.kind in ("global", "filter", "filters", "missing",
                      "significant_terms", "children", "parent"):
            return True
        if _aggs_need_all_segments(n.subs):
            return True
    return False


def _nested_queries_with_inner_hits(q) -> List[dsl.NestedQuery]:
    return [n for n in _walk_query_nodes(q, dsl.NestedQuery)
            if n.inner_hits is not None]


def _walk_query_nodes(q, types) -> List:
    out: List = []

    def walk(node):
        if not hasattr(node, "__dataclass_fields__"):
            return
        if isinstance(node, types):
            out.append(node)
        for fname in node.__dataclass_fields__:
            v = getattr(node, fname)
            if isinstance(v, dsl.Query):
                walk(v)
            elif isinstance(v, list):
                for x in v:
                    if isinstance(x, dsl.Query):
                        walk(x)
    walk(q)
    return out


def _join_queries_with_inner_hits(q) -> List:
    return [n for n in _walk_query_nodes(q, (dsl.HasChildQuery, dsl.HasParentQuery))
            if n.inner_hits is not None]


def _collect_named(lroot) -> List[Tuple[str, Any]]:
    out = []

    def walk(n):
        if n is None:
            return
        if getattr(n, "name", None):
            out.append((n.name, n))
        for attr in ("musts", "shoulds", "must_nots", "filters", "children"):
            for c in getattr(n, attr, []) or []:
                walk(c)
        for attr in ("child", "positive", "negative"):
            walk(getattr(n, attr, None))

    walk(lroot)
    return out


def _collapse_key_value(seg: Segment, field: str, doc: int):
    """Host group-key for one doc (keyword string or numeric value)."""
    kcol = seg.keyword_cols.get(field)
    if kcol is not None:
        o = int(kcol.min_ord[doc])
        return kcol.vocab[o] if o >= 0 else None
    ncol = seg.numeric_cols.get(field)
    if ncol is not None and ncol.present[doc]:
        return _render_numeric(ncol, doc)
    return None


def _host_sort_values(sort_specs: List[dict], seg: Segment, doc: int,
                      score: float) -> Tuple[Tuple, Tuple]:
    """(comparison tuple asc-ordered, raw user-facing values)."""
    if not sort_specs:
        # score ties break by (shard, segment, local doc) via the STABLE
        # final sort over shard-concatenated candidates — the reference's
        # own merge comparator (score, shard index, doc), and exactly the
        # order every device selection (kernel top-k, mesh program) uses.
        # An _id tie-break here would diverge from both.
        return ((-score,), (score,))
    comp = []
    raw = []
    for spec in sort_specs:
        f = spec["field"]
        desc = spec.get("order", "desc" if f == "_score" else "asc") == "desc"
        missing_last = spec.get("missing", "_last") == "_last"
        if f == "_score":
            v: Any = score
            comp.append(-v if desc else v)
            raw.append(v)
            continue
        if f == "_doc":
            comp.append(doc)
            raw.append(doc)
            continue
        if f == "_geo_distance":
            import math
            col = seg.geo_cols.get(spec["geo_field"])
            if col is not None and col.present[doc]:
                olat, olon = spec["origin"]
                p1 = math.radians(float(col.lat[doc]))
                p2 = math.radians(olat)
                dl = math.radians(olon - float(col.lon[doc]))
                a = (math.sin((p2 - p1) / 2) ** 2
                     + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
                dist_m = 2 * 6371008.8 * math.asin(math.sqrt(min(a, 1.0)))
                v = dist_m / _DIST_UNITS.get(spec.get("unit", "m"), 1.0)
                comp.append((0, -v if desc else v))
                raw.append(v)
            else:
                comp.append((1 if missing_last else -1, 0.0))
                raw.append(None)
            continue
        nspec = spec.get("nested")
        if nspec and nspec.get("path"):
            vals, present = C._nested_sort_values(
                seg, f, nspec["path"],
                spec.get("mode", "max" if desc else "min"))
            if vals is not None and present[doc]:
                v = float(vals[doc])
                comp.append((0, -v if desc else v))
                raw.append(v)
            else:
                comp.append((1 if missing_last else -1, 0.0))
                raw.append(None)
            continue
        if f == "_script":
            from ..script import run_field_script
            from .query_dsl import parse_script_spec
            src_str, prm = parse_script_spec(spec.get("script"))
            try:
                v = run_field_script(src_str, prm, seg, doc, score=score)
            except _ScriptError as e:
                raise dsl.QueryParseError(f"[_script sort]: {e}")
            if spec.get("type") == "string":
                comp.append((0, _StrKey(str(v), desc)))
            else:
                v = float(v)
                comp.append((0, -v if desc else v))
            raw.append(v)
            continue
        col = seg.numeric_cols.get(f)
        if col is not None and col.present[doc]:
            v = _render_numeric(col, doc)
            comp.append((0 if not missing_last else 0, -v if desc else v))
            raw.append(v)
            continue
        kcol = seg.keyword_cols.get(f)
        if kcol is not None and kcol.min_ord[doc] >= 0:
            sv = kcol.vocab[kcol.min_ord[doc]]
            comp.append((0, _StrKey(sv, desc)))
            raw.append(sv)
            continue
        comp.append((1 if missing_last else -1, 0))
        raw.append(None)
    comp.append(seg.ids[doc])  # stable tiebreak
    return (tuple(comp), tuple(raw))


class _StrKey:
    """String sort key supporting descending order in tuple comparisons."""

    __slots__ = ("s", "desc")

    def __init__(self, s: str, desc: bool):
        self.s = s
        self.desc = desc

    def __lt__(self, other):
        return (self.s > other.s) if self.desc else (self.s < other.s)

    def __eq__(self, other):
        return self.s == other.s


def _after_key_value(search_after: List, sort_specs: List[dict], seg: Segment) -> float:
    """Device-comparable primary-key cursor for search_after."""
    if not sort_specs or sort_specs[0]["field"] == "_score":
        return float(search_after[0])
    f = sort_specs[0]["field"]
    desc = sort_specs[0].get("order", "asc") == "desc"
    v = search_after[0]
    col = seg.numeric_cols.get(f)
    if col is not None:
        ords = col.sort_ords()
        pos = np.searchsorted(np.unique(col.values[col.present]), v)
        key = float(pos)
        return key if desc else -key
    kcol = seg.keyword_cols.get(f)
    if kcol is not None:
        from bisect import bisect_left
        pos = bisect_left(kcol.vocab, str(v))
        return float(pos) if desc else -float(pos)
    return float("inf")


def _filter_source(src: dict, opt) -> dict:
    if opt is True:
        return src
    if isinstance(opt, str):
        opt = {"includes": [opt]}
    if isinstance(opt, list):
        opt = {"includes": opt}
    includes = opt.get("includes", [])
    excludes = opt.get("excludes", [])

    def flatten(d, prefix=""):
        for k, v in d.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                yield from flatten(v, f"{path}.")
            else:
                yield path, v

    def keep(path):
        if includes and not any(fnmatch.fnmatch(path, p) or path.startswith(p + ".")
                                for p in includes):
            return False
        if any(fnmatch.fnmatch(path, p) for p in excludes):
            return False
        return True

    out: dict = {}
    for path, v in flatten(src):
        if keep(path):
            node = out
            parts = path.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = v
    return out


def _render_numeric(col, doc: int):
    """Column value -> JSON value; unsigned_long unbiases its i64 storage
    (index/mappings.py U64_BIAS)."""
    v = col.values[doc]
    if col.kind == "float":
        return float(v)
    if col.kind == "uint":
        return int(v) + (1 << 63)
    return int(v)


def _docvalue_fields(seg: Segment, doc: int, specs: List) -> dict:
    out = {}
    for spec in specs:
        f = spec if isinstance(spec, str) else spec.get("field")
        col = seg.numeric_cols.get(f)
        if col is not None and col.present[doc]:
            out[f] = [_render_numeric(col, doc)]
            continue
        kcol = seg.keyword_cols.get(f)
        if kcol is not None:
            a, b = int(kcol.starts[doc]), int(kcol.starts[doc + 1])
            if b > a:
                out[f] = [kcol.vocab[o] for o in kcol.ords[a:b]]
    return out


def _extract_source_values(src: dict, path: str) -> List:
    node: Any = src
    for part in path.split("."):
        if isinstance(node, dict):
            node = node.get(part)
        elif isinstance(node, list):
            node = [n.get(part) for n in node if isinstance(n, dict)]
        else:
            return []
        if node is None:
            return []
    return node if isinstance(node, list) else [node]


def _ordinal_buckets(node: AggNode, device_out: dict, vocab) -> dict:
    """Shared ordinal-bucket partial extraction (terms / significant_terms /
    geo grids): nonzero counts keyed by vocab + per-bucket stats tuples."""
    counts = np.asarray(device_out["counts"])
    buckets: dict = {}
    for o in np.nonzero(counts[: len(vocab)] > 0)[0]:
        rec: dict = {"doc_count": int(round(float(counts[o])))}
        sub_partials = {}
        for i, sub_node in enumerate(node.subs):
            t = device_out.get(f"sub{i}")
            if t is not None:
                sums, cnts, mins, maxs, sumsq = (np.asarray(x) for x in t)
                sub_partials[sub_node.name] = {
                    "count": float(cnts[o]), "sum": float(sums[o]),
                    "min": float(mins[o]), "max": float(maxs[o]),
                    "sumsq": float(sumsq[o])}
        if sub_partials:
            rec["subs"] = sub_partials
        buckets[vocab[o]] = rec
    return buckets


def _device_agg_to_partial(node: AggNode, aspec, device_out: Optional[dict],
                           seg: Segment, ctx,
                           seg_stack: Tuple[Segment, ...] = ()) -> Optional[dict]:
    """Device arrays -> host partial in the shapes `aggregations.merge_partials`
    expects."""
    if device_out is None:
        return None
    kind = aspec[0]

    if kind in ("terms_missing", "hist_missing"):
        return None

    if kind == "terms":
        _, prefix, f, nvocab_pad, subs = aspec
        return {"buckets": _ordinal_buckets(node, device_out,
                                            seg.keyword_cols[f].vocab)}

    if kind == "hist":
        _, prefix, f, interval, offset, min_b, nb, subs = aspec
        return _hist_partial(node, device_out, min_b, interval, offset)

    if kind == "date_hist":
        _, prefix, f, interval_ms, offset_ms, calendar, min_b, nb, subs = aspec
        if calendar is not None:
            # convert calendar bucket ids to epoch-ms keys host-side
            counts = np.asarray(device_out["counts"])
            buckets = {}
            for j in np.nonzero(counts > 0)[0]:
                epoch = _calendar_bucket_to_epoch_ms(min_b + int(j), calendar)
                rec = {"doc_count": int(round(float(counts[j])))}
                rec["subs"] = _bucket_subs(node, device_out, int(j))
                buckets[epoch] = rec
            return {"buckets": buckets, "interval": 1, "offset": 0.0}
        return _hist_partial(node, device_out, min_b, float(interval_ms),
                             float(offset_ms))

    if kind in ("range", "geo_range"):
        _, prefix, f, keys, col_exists, subs, bounds = aspec[:7]
        counts = np.asarray(device_out["counts"])
        buckets = {}
        for ri, key in enumerate(keys):
            rec = {"doc_count": int(round(float(counts[ri])))}
            lo, hi = bounds[ri]
            meta = {}
            if np.isfinite(lo):
                meta["from"] = lo
            if np.isfinite(hi):
                meta["to"] = hi
            rec["meta"] = meta
            sub_partials = {}
            for i, sub_node in enumerate(node.subs):
                r = device_out.get(f"r{ri}_sub{i}")
                if r is not None:
                    sub_partials[sub_node.name] = _device_agg_to_partial(
                        sub_node, _find_sub_spec(aspec, i), r, seg, ctx,
                        seg_stack)
            rec["subs"] = sub_partials
            buckets[key] = rec
        return {"buckets": buckets}

    if kind in ("filter", "global", "missing"):
        subs_field = {"filter": 3, "global": 2, "missing": 4}[kind]
        sub_specs = aspec[subs_field]
        rec = {"doc_count": int(round(float(np.asarray(device_out["count"])))),
               "subs": {}}
        for i, sub_node in enumerate(node.subs):
            r = device_out.get(f"sub{i}")
            if r is not None:
                rec["subs"][sub_node.name] = _device_agg_to_partial(
                    sub_node, sub_specs[i], r, seg, ctx, seg_stack)
        return rec

    if kind == "filters":
        _, prefix, fspecs, sub_specs = aspec
        buckets = {}
        for ki, (key, _) in enumerate(fspecs):
            ent = device_out.get(f"k{ki}", {})
            rec = {"doc_count": int(round(float(np.asarray(ent.get("count", 0.0))))),
                   "subs": {}}
            for i, sub_node in enumerate(node.subs):
                r = ent.get(f"sub{i}")
                if r is not None:
                    rec["subs"][sub_node.name] = _device_agg_to_partial(
                        sub_node, sub_specs[i], r, seg, ctx)
            buckets[key] = rec
        return {"buckets": buckets}

    if kind == "sig_missing":
        return {"buckets": {}, "fg_total": 0, "bg": {},
                "bg_total": seg.live_count}

    if kind == "sig_terms":
        _, prefix, f, nvocab_pad, subs = aspec
        return {"buckets": _ordinal_buckets(node, device_out,
                                            seg.keyword_cols[f].vocab),
                "fg_total": int(round(float(np.asarray(device_out["fg_total"])))),
                "bg": C._kw_doc_counts(seg, f),
                "bg_total": seg.live_count}

    if kind in ("sampler", "dsampler"):
        sub_specs = aspec[-1]
        rec = {"doc_count": int(round(float(np.asarray(device_out["doc_count"])))),
               "subs": {}}
        if "topscores" in device_out:
            rec["topscores"] = np.asarray(device_out["topscores"])
        for i, sub_node in enumerate(node.subs):
            r = device_out.get(f"sub{i}")
            if r is not None:
                rec["subs"][sub_node.name] = _device_agg_to_partial(
                    sub_node, sub_specs[i], r, seg, ctx, seg_stack)
        return rec

    if kind == "geo_grid":
        _, prefix, gkind, f, precision, nb, subs = aspec
        vocab, _ords = C._geo_grid_cache(seg, f, gkind, precision)
        return {"buckets": _ordinal_buckets(node, device_out, vocab)}

    if kind == "matrix_stats":
        _, prefix, fields, exists = aspec
        n = float(np.asarray(device_out["count"]))
        k = len(fields)
        if not fields or "s1" not in device_out:
            return {"count": 0, "fields": list(fields), "shift": np.zeros(k),
                    "s1": np.zeros(k), "s2": np.zeros(k), "s3": np.zeros(k),
                    "s4": np.zeros(k), "xy": np.zeros((k, k))}
        return {"count": n, "fields": list(fields),
                "shift": np.asarray(device_out["shift"], np.float64),
                "s1": np.asarray(device_out["s1"], np.float64),
                "s2": np.asarray(device_out["s2"], np.float64),
                "s3": np.asarray(device_out["s3"], np.float64),
                "s4": np.asarray(device_out["s4"], np.float64),
                "xy": np.asarray(device_out["xy"], np.float64)}

    if kind in ("nested_agg", "reverse_nested", "children_agg", "parent_agg"):
        sub_specs = aspec[3]
        sub_seg, sub_stack = seg, seg_stack
        if kind == "nested_agg":
            blk = seg.nested.get(aspec[2])
            sub_seg = blk.child if blk else seg
            sub_stack = seg_stack + (seg,)
        elif kind == "reverse_nested":
            up_k = aspec[2]
            full = seg_stack + (seg,)
            sub_seg = full[-(up_k + 1)]
            sub_stack = full[: -(up_k + 1)]
        rec = {"doc_count": int(round(float(np.asarray(device_out["doc_count"])))),
               "subs": {}}
        for i, sub_node in enumerate(node.subs):
            r = device_out.get(f"sub{i}")
            if r is not None:
                rec["subs"][sub_node.name] = _device_agg_to_partial(
                    sub_node, sub_specs[i], r, sub_seg, ctx, sub_stack)
        return rec

    if kind == "composite_mv":
        _, prefix, f, nb, subs = aspec
        flat = _ordinal_buckets(node, device_out, seg.keyword_cols[f].vocab)
        return {"buckets": {(k,): v for k, v in flat.items()}}

    if kind == "composite":
        _, prefix, infos, total, subs = aspec
        counts = np.asarray(device_out["counts"])
        nz = np.nonzero(counts[:total] > 0)[0]
        buckets = {}
        for comb in nz:
            vals = []
            rem = int(comb)
            for stype, field, n, min_b, interval, cal in reversed(infos):
                o = rem % n
                rem //= n
                if stype == "terms":
                    vals.append(seg.keyword_cols[field].vocab[o])
                elif stype == "hist":
                    vals.append((min_b + o) * interval)
                elif cal:
                    vals.append(_calendar_bucket_to_epoch_ms(min_b + o, cal))
                else:
                    vals.append(int((min_b + o) * interval))
            key = tuple(reversed(vals))
            rec = {"doc_count": int(round(float(counts[comb])))}
            sub_partials = {}
            for i, sub_node in enumerate(node.subs):
                t = device_out.get(f"sub{i}")
                if t is not None:
                    sums, cnts, mins, maxs, sumsq = (np.asarray(x) for x in t)
                    sub_partials[sub_node.name] = {
                        "count": float(cnts[comb]), "sum": float(sums[comb]),
                        "min": float(mins[comb]), "max": float(maxs[comb]),
                        "sumsq": float(sumsq[comb])}
            if sub_partials:
                rec["subs"] = sub_partials
            buckets[key] = rec
        return {"buckets": buckets}

    if kind == "stats":
        if "empty" in device_out:
            return {"count": 0, "sum": 0.0, "min": float("inf"),
                    "max": float("-inf"), "sumsq": 0.0}
        return {"count": float(np.asarray(device_out["count"])),
                "sum": float(np.asarray(device_out["sum"])),
                "min": float(np.asarray(device_out["min"])),
                "max": float(np.asarray(device_out["max"])),
                "sumsq": float(np.asarray(device_out["sumsq"]))}

    if kind == "vc_keyword":
        return {"count": float(np.asarray(device_out["count"])), "sum": 0.0,
                "min": 0.0, "max": 0.0, "sumsq": 0.0}

    if kind in ("card_kw", "card_num"):
        return {"registers": np.asarray(device_out["registers"])}

    if kind == "pctl":
        _, prefix, f, col_exists, percents = aspec
        return {"hist": np.asarray(device_out["hist"]), "percents": list(percents)}

    if kind == "pctl_ranks":
        _, prefix, f, col_exists, values = aspec
        return {"hist": np.asarray(device_out["hist"]), "values": list(values)}

    if kind == "wavg":
        return {"vwsum": float(np.asarray(device_out["vwsum"])),
                "wsum": float(np.asarray(device_out["wsum"])),
                "count": float(np.asarray(device_out["count"]))}

    if kind == "mad":
        return {"hist": np.asarray(device_out["hist"])}

    if kind == "geo_stat":
        out = {k: float(np.asarray(v)) for k, v in device_out.items()}
        return out

    if kind == "ip_range":
        _, prefix, f, keys, bounds, open_lo, open_hi, col_exists, sub_specs = aspec
        counts = np.asarray(device_out.get("counts", np.zeros(len(keys))))
        buckets = {}
        for ri, key in enumerate(keys):
            rec = {"doc_count": int(round(float(counts[ri]))), "subs": {}}
            meta = {}
            frm, to = bounds[ri]
            if frm is not None:
                meta["from"] = frm
            if to is not None:
                meta["to"] = to
            rec["meta"] = meta
            for i, sub_node in enumerate(node.subs):
                r = device_out.get(f"r{ri}_sub{i}")
                if r is not None:
                    rec["subs"][sub_node.name] = _device_agg_to_partial(
                        sub_node, sub_specs[i], r, seg, ctx, seg_stack)
            buckets[key] = rec
        return {"buckets": buckets}

    if kind == "multi_terms":
        _, prefix, nord_pad, nvocab, sub_specs = aspec
        fields = tuple(s["field"] for s in node.body.get("terms", []))
        vocab, _ords = C._multi_terms_cache(seg, ctx, node, fields)
        return {"buckets": _ordinal_buckets(node, device_out, vocab)}

    if kind == "adjacency":
        _, prefix, fspecs, sep, sub_specs = aspec
        names = [key for key, _ in fspecs]
        labels = list(names)
        for ai in range(len(names)):
            for bi in range(ai + 1, len(names)):
                labels.append(f"{names[ai]}{sep}{names[bi]}")
        buckets = {}
        for ci, label in enumerate(labels):
            cnt = int(round(float(np.asarray(device_out[f"c{ci}"]))))
            rec = {"doc_count": cnt, "subs": {}}
            for i, sub_node in enumerate(node.subs):
                r = device_out.get(f"c{ci}_sub{i}")
                if r is not None:
                    rec["subs"][sub_node.name] = _device_agg_to_partial(
                        sub_node, sub_specs[i], r, seg, ctx, seg_stack)
            buckets[label] = rec
        return {"buckets": buckets}

    if kind == "auto_date_hist":
        _, prefix, f, interval_ms, target, min_b, nb, sub_specs = aspec
        part = _hist_partial(node, device_out, min_b, float(interval_ms), 0.0)
        # re-key to absolute epoch ms (merge coarsens across intervals)
        part["buckets"] = {int(b * interval_ms): rec
                           for b, rec in part["buckets"].items()}
        part["interval_ms"] = int(interval_ms)
        return part

    if kind == "scripted":
        return _scripted_metric_partial(node, device_out, seg)

    if kind == "sig_text":
        return _significant_text_partial(node, device_out, seg, ctx)

    raise ValueError(f"cannot build partial for agg spec [{kind}]")


def _scripted_metric_partial(node: AggNode, device_out: dict, seg: Segment) -> dict:
    """Host map/combine passes of scripted_metric (reference
    ScriptedMetricAggregator): painless-lite over each matched doc."""
    from ..script.painless_lite import execute
    from ..script.painless_lite import doc_view_for

    body = node.body
    sparams = body.get("params", {})
    state: Dict[str, Any] = {}
    if body.get("init_script"):
        src, prm = _script_spec(body["init_script"], sparams)
        execute(src, {"state": state, "params": prm})
    map_src, map_prm = _script_spec(body.get("map_script", ""), sparams)
    mask = np.asarray(device_out["match_mask"])[: seg.ndocs] > 0

    class _Doc(dict):
        def __init__(self, d):
            self._d = d
            super().__init__()

        def __getitem__(self, f):
            return doc_view_for(seg, self._d, f)

        def get(self, f, default=None):
            return doc_view_for(seg, self._d, f)

        def containsKey(self, f):  # noqa: N802 (painless API)
            return not doc_view_for(seg, self._d, f).empty

    for d in np.nonzero(mask)[0]:
        execute(map_src, {"state": state, "params": map_prm,
                          "doc": _Doc(int(d))})
    if body.get("combine_script"):
        src, prm = _script_spec(body["combine_script"], sparams)
        combined = execute(src, {"state": state, "params": prm})
    else:
        combined = state
    return {"states": [combined]}


def _script_spec(spec, defaults: dict):
    if isinstance(spec, str):
        return spec, dict(defaults)
    prm = dict(defaults)
    prm.update(spec.get("params", {}))
    return spec.get("source", ""), prm


def _significant_text_partial(node: AggNode, device_out: dict, seg: Segment,
                              ctx) -> dict:
    """significant_text (reference SignificantTextAggregator): sample the
    best-scoring matched docs, re-analyze the text field from _source, and
    score candidate terms against the index background (postings df)."""
    body = node.body
    field = body.get("field", "")
    shard_size = int(body.get("shard_size", 200))
    mask = np.asarray(device_out["match_mask"])[: seg.ndocs] > 0
    scores = np.asarray(device_out["score_vec"])[: seg.ndocs]
    docs = np.nonzero(mask)[0]
    if len(docs) > shard_size:
        order = np.argsort(-scores[docs], kind="stable")
        docs = docs[order[:shard_size]]
    from .compiler import _analyze_query_text
    fg: Dict[str, int] = {}
    for d in docs:
        src = seg.sources[int(d)]
        v = src.get(field) if isinstance(src, dict) else None
        if v is None:
            continue
        texts = v if isinstance(v, list) else [v]
        seen = set()
        for t in texts:
            for tok in _analyze_query_text(field, str(t), ctx):
                seen.add(tok)
        for tok in seen:
            fg[tok] = fg.get(tok, 0) + 1
    pb = seg.postings.get(field)
    bg = {}
    for tok in fg:
        bg[tok] = pb.doc_freq(tok) if pb is not None else 0
    buckets = {tok: {"doc_count": c, "subs": {}} for tok, c in fg.items()}
    return {"buckets": buckets, "bg": bg, "fg_total": int(len(docs)),
            "bg_total": int(seg.live_count)}


def _find_sub_spec(aspec, i):
    for item in aspec:
        if isinstance(item, tuple) and len(item) > i and isinstance(item[i], tuple):
            return item[i]
    return None


def _bucket_subs(node: AggNode, device_out: dict, j: int) -> dict:
    subs = {}
    for i, sub_node in enumerate(node.subs):
        t = device_out.get(f"sub{i}")
        if t is not None:
            sums, cnts, mins, maxs, sumsq = (np.asarray(x) for x in t)
            subs[sub_node.name] = {"count": float(cnts[j]), "sum": float(sums[j]),
                                   "min": float(mins[j]), "max": float(maxs[j]),
                                   "sumsq": float(sumsq[j])}
    return subs


def _hist_partial(node: AggNode, device_out: dict, min_b: int, interval: float,
                  offset: float) -> dict:
    counts = np.asarray(device_out["counts"])
    buckets = {}
    for j in np.nonzero(counts > 0)[0]:
        rec = {"doc_count": int(round(float(counts[j])))}
        rec["subs"] = _bucket_subs(node, device_out, int(j))
        buckets[min_b + int(j)] = rec
    return {"buckets": buckets, "interval": interval, "offset": offset}


def _calendar_bucket_to_epoch_ms(b: int, calendar: str) -> int:
    import datetime as dt

    if calendar in ("month", "1M"):
        y, m = 1970 + b // 12, b % 12 + 1
        return int(dt.datetime(y, m, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    if calendar in ("year", "1y"):
        return int(dt.datetime(1970 + b, 1, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    if calendar in ("quarter", "1q"):
        y, q = 1970 + b // 4, b % 4
        return int(dt.datetime(y, q * 3 + 1, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    if calendar in ("week", "1w"):
        return (b * 7 - 3) * 86400000
    if calendar in ("day", "1d"):
        return b * 86400000
    if calendar in ("hour", "1h"):
        return b * 3600000
    if calendar in ("minute", "1m"):
        return b * 60000
    raise ValueError(calendar)


# =====================================================================
# explain (host recompute, reference TransportExplainAction)
# =====================================================================

def _host_phrase_freq(node, seg: Segment, doc: int) -> float:
    """Host mirror of ops.positions.phrase_freqs for one doc (explain)."""
    from .compiler import _prefix_rows

    pb = seg.postings.get(node.field)
    if pb is None or pb.pos_starts is None:
        return 0.0
    pos_lists: List[np.ndarray] = []
    last = len(node.terms) - 1
    for i, t in enumerate(node.terms):
        if node.prefix_last and i == last:
            rows = list(_prefix_rows(pb, t, node.max_expansions))
        else:
            r = pb.row(t)
            rows = [r] if r >= 0 else []
        plist: List[int] = []
        for r in rows:
            a, b = pb.row_slice(r)
            k = a + int(np.searchsorted(pb.doc_ids[a:b], doc))
            if k < b and pb.doc_ids[k] == doc:
                plist.extend((pb.positions[pb.pos_starts[k]: pb.pos_starts[k + 1]]
                              - i).tolist())
        if not plist:
            return 0.0
        pos_lists.append(np.asarray(sorted(plist)))
    freq = 0.0
    for base in pos_lists[0]:
        ok = True
        if node.ordered:
            # greedy sequential join, mirroring the device ordered path
            prev = 0.0
            for arr in pos_lists[1:]:
                j = int(np.searchsorted(arr, base + prev))
                if j >= len(arr):
                    ok = False
                    break
                prev = float(arr[j]) - float(base)
            cost = prev if ok else 0.0
        else:
            deltas = [0.0]
            for arr in pos_lists[1:]:
                j = int(np.searchsorted(arr, base))
                # tie prefers the right neighbor, like the device kernel
                cands = [int(arr[jj]) - int(base)
                         for jj in (j, j - 1) if 0 <= jj < len(arr)]
                if not cands:
                    ok = False
                    break
                deltas.append(float(min(cands, key=abs)))
            if ok:
                if node.gap_cost:
                    abs_off = [d + i for i, d in enumerate(deltas)]
                    cost = max(abs_off) - min(abs_off) + 1 - len(deltas)
                else:
                    med = sorted(deltas)[len(deltas) // 2]  # optimal offset
                    cost = sum(abs(d - med) for d in deltas)
        if ok and cost <= node.slop:
            freq += 1.0 / (1.0 + cost)
    return freq

def explain_doc(lroot, seg: Segment, doc: int, ctx) -> dict:
    from .compiler import LBool, LConstScore, LDisMax, LPhrase, LTerms
    from ..ops.scoring import SIM_BM25

    def walk(n) -> Tuple[float, dict]:
        if isinstance(n, C.LSpanHost):
            freq = float(n._freqs.get(seg.uid, np.zeros(1))[doc]
                         if doc < len(n._freqs.get(seg.uid, [])) else 0.0)
            dl = float(seg.doc_lens.get(n.field, np.zeros(seg.ndocs))[doc]) \
                if n.field in seg.doc_lens else 0.0
            avgdl = max(ctx.avgdl(n.field), 1e-9)
            b_eff = n.sim.b if n.has_norms else 0.0
            kk = n.sim.k1 * (1 - b_eff + b_eff * dl / avgdl)
            total = n.weight * freq / (freq + kk) if freq > 0 else 0.0
            return total, {"value": total,
                           "description": f"span/intervals on [{n.field}]: "
                                          f"sloppyFreq {freq:.3f}",
                           "details": []}
        if isinstance(n, LPhrase):
            freq = _host_phrase_freq(n, seg, doc)
            dl = float(seg.doc_lens.get(n.field, np.zeros(seg.ndocs))[doc]) \
                if n.field in seg.doc_lens else 0.0
            avgdl = max(ctx.avgdl(n.field), 1e-9)
            b_eff = n.sim.b if n.has_norms else 0.0
            kk = n.sim.k1 * (1 - b_eff + b_eff * dl / avgdl)
            total = n.weight * freq / (freq + kk) if freq > 0 else 0.0
            desc = (f'phrase "{" ".join(n.terms)}" on [{n.field}]: idf-sum*boost '
                    f'{n.weight:.4f} * sloppyFreq {freq:.3f}/(freq+{kk:.3f})')
            return total, {"value": total, "description": desc, "details": []}
        if isinstance(n, LTerms):
            details = []
            total = 0.0
            dl = float(seg.doc_lens.get(n.field, np.zeros(seg.ndocs))[doc]) \
                if n.field in seg.doc_lens else 0.0
            avgdl = ctx.avgdl(n.field)
            pb = seg.postings.get(n.field)
            for i, t in enumerate(n.terms):
                if pb is None:
                    continue
                r = pb.row(t)
                if r < 0:
                    continue
                a, b = pb.row_slice(r)
                k = a + int(np.searchsorted(pb.doc_ids[a:b], doc))
                if k >= b or pb.doc_ids[k] != doc:
                    continue
                tf = float(pb.tfs[k])
                w = float(n.weights[i])
                sim = n.sim
                if sim.sim_id == SIM_BM25:
                    b_eff = sim.b if n.has_norms else 0.0
                    kk = sim.k1 * (1 - b_eff + b_eff * dl / max(avgdl, 1e-9))
                    contrib = w * tf / (tf + kk)
                    desc = (f"weight({n.field}:{t}) = idf*boost {w:.4f} * "
                            f"tf {tf:.0f}/(tf+{kk:.3f})")
                else:
                    contrib = w
                    desc = f"weight({n.field}:{t})"
                total += contrib
                details.append({"value": contrib, "description": desc, "details": []})
            return total, {"value": total,
                           "description": f"sum of term scores on [{n.field}]",
                           "details": details}
        if isinstance(n, LBool):
            total = 0.0
            details = []
            for c in n.musts + n.shoulds:
                v, d = walk(c)
                total += v
                details.append(d)
            total *= n.boost
            return total, {"value": total, "description": "sum of:", "details": details}
        if isinstance(n, LConstScore):
            return n.boost, {"value": n.boost, "description": "ConstantScore",
                             "details": []}
        if isinstance(n, LDisMax):
            vals = [walk(c) for c in n.children]
            best = max((v for v, _ in vals), default=0.0)
            total = best + n.tie_breaker * (sum(v for v, _ in vals) - best)
            return total, {"value": total, "description": "max plus tie_breaker of:",
                           "details": [d for _, d in vals]}
        from .compiler import LExists, LMatchAll, LRange
        if isinstance(n, LRange):
            col = seg.numeric_cols.get(n.field)
            ok = col is not None and bool(col.present[doc])
            if ok:
                v = float(col.values[doc])
                if n.lo is not None:
                    ok = v >= float(n.lo) if n.include_lo else v > float(n.lo)
                if ok and n.hi is not None:
                    ok = v <= float(n.hi) if n.include_hi else v < float(n.hi)
            val = n.boost if ok else 0.0
            return val, {"value": val,
                         "description": f"range filter on [{n.field}]", "details": []}
        if isinstance(n, LMatchAll):
            return n.boost, {"value": n.boost, "description": "*:*", "details": []}
        if isinstance(n, LExists):
            ok = ((n.field in seg.numeric_cols and bool(seg.numeric_cols[n.field].present[doc]))
                  or (n.field in seg.keyword_cols and int(seg.keyword_cols[n.field].min_ord[doc]) >= 0)
                  or (n.field in seg.doc_lens and int(seg.doc_lens[n.field][doc]) > 0))
            val = n.boost if ok else 0.0
            return val, {"value": val,
                         "description": f"exists [{n.field}]", "details": []}
        from .compiler import LNested
        if isinstance(n, LNested):
            blk = seg.nested.get(n.path)
            if blk is None or blk.child.ndocs == 0:
                return 0.0, {"value": 0.0, "description": "no nested docs",
                             "details": []}
            # match/score truth comes from the same device program the query
            # ran (host explains can't see filter-context matches); the host
            # child explains are attached as details only
            from . import compiler as _C
            cparams: Dict[str, Any] = {}
            cspec = _C.prepare(n.child, blk.child, n.child_ctx, cparams)
            a, b = blk.children_of(doc)
            docs = np.arange(blk.child.ndocs_pad, dtype=np.int32)
            csc, cm = _C.run_gather_scores(cspec, blk.child.device_arrays(),
                                           cparams, docs)
            csc, cm = np.asarray(csc), np.asarray(cm)
            vals = [float(csc[i]) for i in range(a, b) if cm[i]]
            if not vals:
                return 0.0, {"value": 0.0,
                             "description": f"no matching children in [{n.path}]",
                             "details": []}
            mode = n.score_mode
            total = (sum(vals) / len(vals) if mode == "avg" else
                     max(vals) if mode == "max" else
                     min(vals) if mode == "min" else
                     1.0 if mode == "none" else sum(vals))
            total *= n.boost
            details = [explain_doc(n.child, blk.child, cd, n.child_ctx)
                       for cd in range(a, b) if cm[cd]]
            return total, {"value": total,
                           "description": f"nested [{n.path}] {mode} of children:",
                           "details": details}
        from .compiler import LHasChild, LHasParent
        if isinstance(n, LHasChild):
            from . import compiler as _C
            ji = n.join_index
            cache: Dict[int, Any] = {}
            vals = []
            for cseg, cd in ji.children_of(ji.seg_base(seg) + doc):
                if id(cseg) not in cache:
                    cparams: Dict[str, Any] = {}
                    cspec = _C.prepare(n.child, cseg, ctx, cparams)
                    darr = np.arange(cseg.ndocs_pad, dtype=np.int32)
                    csc, cm = _C.run_gather_scores(cspec, cseg.device_arrays(),
                                                   cparams, darr)
                    cache[id(cseg)] = (np.asarray(csc), np.asarray(cm))
                csc, cm = cache[id(cseg)]
                if cm[cd] and cseg.live[cd]:
                    vals.append(float(csc[cd]))
            ok = max(n.min_children, 1) <= len(vals) <= n.max_children
            mode = n.score_mode
            total = 0.0
            if ok:
                total = (1.0 if mode == "none" else
                         sum(vals) / len(vals) if mode == "avg" else
                         max(vals) if mode == "max" else
                         min(vals) if mode == "min" else sum(vals)) * n.boost
            return total, {"value": total,
                           "description": (f"has_child [{n.child_rel}] {mode} of "
                                           f"{len(vals)} matching children"),
                           "details": []}
        if isinstance(n, LHasParent):
            from . import compiler as _C
            ji = n.join_index
            slot = int(ji.pslot(seg)[doc])
            loc = ji.slot_to_doc(slot) if slot >= 0 else None
            total = 0.0
            if loc is not None:
                pseg, pd = loc
                cparams = {}
                cspec = _C.prepare(n.child, pseg, ctx, cparams)
                darr = np.arange(pseg.ndocs_pad, dtype=np.int32)
                psc, pm = _C.run_gather_scores(cspec, pseg.device_arrays(),
                                               cparams, darr)
                if np.asarray(pm)[pd] and pseg.live[pd]:
                    total = (float(np.asarray(psc)[pd]) if n.use_score else 1.0) * n.boost
            return total, {"value": total,
                           "description": f"has_parent [{n.parent_rel}]",
                           "details": []}
        return 0.0, {"value": 0.0, "description": type(n).__name__, "details": []}

    _, expl = walk(lroot)
    return expl
