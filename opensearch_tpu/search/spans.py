"""Host span/interval algebra over positional postings.

Reference `index/query/Span*QueryBuilder.java` (Lucene SpanQuery family) and
`index/query/IntervalsSourceProvider.java` (Lucene intervals). The TPU split:
the HOT phrase path (match_phrase, simple span_near, intervals match) runs
the device pair-join in ops/positions.py; the full ALGEBRA — or/not/first/
containing/within/multi, interval all_of/any_of and filters — is evaluated
here on the host with vectorized numpy over the same positional postings,
producing a dense per-doc frequency vector the device program scores exactly
like a phrase (BM25 over sloppy frequency). Span queries are rare and
position-bound; their cost is the posting scan, which numpy does at memory
bandwidth — no per-doc iterator trees like the JVM.

A span set is (docs, starts, ends) arrays lex-sorted by (doc, start, end);
all combinators are O(n log n) sorts/searchsorteds.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from . import query_dsl as dsl

BIG = np.int64(1) << 32


class SpanSet(NamedTuple):
    docs: np.ndarray     # i64[n]
    starts: np.ndarray   # i64[n]
    ends: np.ndarray     # i64[n]  (exclusive)

    def key(self) -> np.ndarray:
        return self.docs * BIG + self.starts

    @staticmethod
    def empty() -> "SpanSet":
        z = np.empty(0, np.int64)
        return SpanSet(z, z.copy(), z.copy())


def _sorted(docs, starts, ends) -> SpanSet:
    order = np.lexsort((ends, starts, docs))
    return SpanSet(docs[order], starts[order], ends[order])


def _dedup(s: SpanSet) -> SpanSet:
    if len(s.docs) == 0:
        return s
    k = np.stack([s.docs, s.starts, s.ends])
    keep = np.ones(len(s.docs), bool)
    keep[1:] = np.any(k[:, 1:] != k[:, :-1], axis=0)
    return SpanSet(s.docs[keep], s.starts[keep], s.ends[keep])


def term_spans(seg, field: str, term: str) -> SpanSet:
    pb = seg.postings.get(field)
    if pb is None or pb.pos_starts is None:
        return SpanSet.empty()
    r = pb.row(term)
    if r < 0:
        return SpanSet.empty()
    a, b = pb.row_slice(r)
    counts = pb.pos_starts[a + 1: b + 1] - pb.pos_starts[a: b]
    docs = np.repeat(pb.doc_ids[a:b], counts).astype(np.int64)
    pos = pb.positions[pb.pos_starts[a]: pb.pos_starts[b]].astype(np.int64)
    return _sorted(docs, pos, pos + 1)


def rows_spans(seg, field: str, rows: np.ndarray) -> SpanSet:
    """Union of term spans for a set of vocab rows (span_multi expansions)."""
    pb = seg.postings.get(field)
    if pb is None or pb.pos_starts is None or len(rows) == 0:
        return SpanSet.empty()
    dparts, pparts = [], []
    for r in rows:
        a, b = pb.row_slice(int(r))
        counts = pb.pos_starts[a + 1: b + 1] - pb.pos_starts[a: b]
        dparts.append(np.repeat(pb.doc_ids[a:b], counts).astype(np.int64))
        pparts.append(pb.positions[pb.pos_starts[a]: pb.pos_starts[b]]
                      .astype(np.int64))
    docs = np.concatenate(dparts)
    pos = np.concatenate(pparts)
    return _sorted(docs, pos, pos + 1)


def or_spans(sets: List[SpanSet]) -> SpanSet:
    sets = [s for s in sets if len(s.docs)]
    if not sets:
        return SpanSet.empty()
    return _dedup(_sorted(np.concatenate([s.docs for s in sets]),
                          np.concatenate([s.starts for s in sets]),
                          np.concatenate([s.ends for s in sets])))


def _seg_suffix_min(values: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """Per-doc suffix minimum: out[i] = min(values[i:j]) within doc i's run."""
    if not len(values):
        return values
    vmax = int(values.max())
    dmax = int(docs.max())
    rev_v = (vmax - values)[::-1]            # suffix-min -> prefix-max
    rev_g = (dmax - docs)[::-1]              # nondecreasing group ids
    out = _seg_cummax(rev_v, rev_g)
    return (vmax - out)[::-1]


def near_spans(sets: List[SpanSet], slop: int, in_order: bool) -> SpanSet:
    """Combine clause span sets like SpanNearQuery: one result span per
    first-clause anchor when every clause matches nearby; `slop` bounds the
    uncovered positions inside the combined span (gap count).

    Ordered: for each anchor, each next clause takes the valid span
    (start >= previous end, same doc) with the MINIMAL end — the
    interval-scheduling greedy, exact for ordered existence even with
    variable-width alternatives. Unordered: nearest span per clause around
    the anchor — exact when clauses don't compete for positions (the device
    phrase engine's documented relaxation)."""
    if not sets or any(len(s.docs) == 0 for s in sets):
        return SpanSet.empty()
    a = sets[0]
    docs, starts, ends = a.docs, a.starts, a.ends.copy()
    ok = np.ones(len(docs), bool)
    if in_order:
        width_used = ends - starts
        prev_end = ends.copy()
        for s in sets[1:]:
            key = s.key()
            smin_end = _seg_suffix_min(s.ends, s.docs)
            # second order (doc, end) -> recover the chosen span's start
            # (max start for that end = narrowest, still >= prev_end)
            o2 = np.lexsort((s.starts, s.ends, s.docs))
            key2 = s.docs[o2] * BIG + s.ends[o2]
            starts2 = s.starts[o2]
            idx = np.searchsorted(key, docs * BIG + prev_end, "left")
            safe = np.minimum(idx, len(key) - 1)
            found = (idx < len(key)) & (s.docs[safe] == docs)
            e_star = smin_end[safe]
            j2 = np.searchsorted(key2, docs * BIG + e_star, "right") - 1
            j2safe = np.maximum(j2, 0)
            s_star = starts2[j2safe]
            ok &= found
            prev_end = np.where(found, e_star, prev_end)
            width_used = width_used + np.where(found, e_star - s_star, 0)
        span_lo, span_hi = starts, prev_end
    else:
        span_lo = starts.copy()
        span_hi = ends.copy()
        width_used = ends - starts
        for s in sets[1:]:
            key = s.key()
            q = docs * BIG + starts
            idx = np.searchsorted(key, q, "left")
            ridx = np.minimum(idx, len(key) - 1)
            r_ok = (idx < len(key)) & (s.docs[ridx] == docs)
            lidx = np.maximum(idx - 1, 0)
            l_ok = (idx > 0) & (s.docs[lidx] == docs)
            rdist = np.where(r_ok, np.abs(s.starts[ridx] - starts), BIG)
            ldist = np.where(l_ok, np.abs(s.starts[lidx] - starts), BIG)
            pick = np.where(rdist <= ldist, ridx, lidx)
            found = r_ok | l_ok
            ok &= found
            span_lo = np.minimum(span_lo, np.where(found, s.starts[pick],
                                                   span_lo))
            span_hi = np.maximum(span_hi, np.where(found, s.ends[pick],
                                                   span_hi))
            width_used = width_used + np.where(
                found, s.ends[pick] - s.starts[pick], 0)
    gaps = (span_hi - span_lo) - width_used
    if slop >= 0:
        ok &= gaps <= slop
    keep = ok
    return _dedup(_sorted(docs[keep], span_lo[keep], span_hi[keep]))


_POS_RANGE = np.int64(1) << 22   # positions/ends < 2^22 (dl cap is 2^21)


def _seg_cummax(values: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """Per-doc running maximum, vectorized: docs are nondecreasing, so
    cummax(v + doc*R) with R > value range restarts at each doc boundary
    (earlier docs' shifted values can never dominate)."""
    if not len(values):
        return values
    shifted = values + docs * _POS_RANGE
    return np.maximum.accumulate(shifted) - docs * _POS_RANGE


def not_spans(inc: SpanSet, exc: SpanSet, pre: int, post: int) -> SpanSet:
    """Include spans with no exclude span overlapping [start-pre, end+post)."""
    if len(inc.docs) == 0 or len(exc.docs) == 0:
        return inc
    # clamp windows to the position range so huge pre/post can't push the
    # packed (doc, pos) key into another doc's range
    pre = int(min(max(pre, 0), _POS_RANGE))
    post = int(min(max(post, 0), _POS_RANGE))
    key = exc.key()
    cmax_end = _seg_cummax(exc.ends, exc.docs)
    hi = np.searchsorted(key, inc.docs * BIG + (inc.ends + post), "left")
    has = hi > 0
    safe = np.maximum(hi - 1, 0)
    same_doc = exc.docs[safe] == inc.docs
    overlap = has & same_doc & (cmax_end[safe] > inc.starts - pre)
    keep = ~overlap
    return SpanSet(inc.docs[keep], inc.starts[keep], inc.ends[keep])


def first_spans(s: SpanSet, end: int) -> SpanSet:
    keep = s.ends <= end
    return SpanSet(s.docs[keep], s.starts[keep], s.ends[keep])


def containing_spans(big: SpanSet, little: SpanSet) -> SpanSet:
    """Big spans that fully contain at least one little span."""
    if len(big.docs) == 0 or len(little.docs) == 0:
        return SpanSet.empty()
    order = np.lexsort((little.starts, little.ends, little.docs))
    le_docs = little.docs[order]
    le_ends = little.ends[order]
    le_starts = little.starts[order]
    cmax_start = _seg_cummax(le_starts, le_docs)
    key = le_docs * BIG + le_ends
    hi = np.searchsorted(key, big.docs * BIG + big.ends, "right")
    has = hi > 0
    safe = np.maximum(hi - 1, 0)
    ok = has & (le_docs[safe] == big.docs) & (cmax_start[safe] >= big.starts)
    return SpanSet(big.docs[ok], big.starts[ok], big.ends[ok])


def within_spans(little: SpanSet, big: SpanSet) -> SpanSet:
    """Little spans fully contained in at least one big span."""
    if len(big.docs) == 0 or len(little.docs) == 0:
        return SpanSet.empty()
    cmax_end = _seg_cummax(big.ends, big.docs)
    key = big.key()
    hi = np.searchsorted(key, little.docs * BIG + little.starts, "right")
    has = hi > 0
    safe = np.maximum(hi - 1, 0)
    ok = has & (big.docs[safe] == little.docs) & \
        (cmax_end[safe] >= little.ends)
    return SpanSet(little.docs[ok], little.starts[ok], little.ends[ok])


def before_spans(s: SpanSet, f: SpanSet) -> SpanSet:
    """Spans that end at or before some filter span's start (intervals
    `before`)."""
    if len(s.docs) == 0 or len(f.docs) == 0:
        return SpanSet.empty()
    # per doc maximum filter start
    order = np.lexsort((f.starts, f.docs))
    fd = f.docs[order]
    fs = f.starts[order]
    cmax = _seg_cummax(fs, fd)
    key = fd * BIG + fs
    hi = np.searchsorted(key, s.docs * BIG + np.int64(BIG - 1), "left")
    has = hi > 0
    safe = np.maximum(hi - 1, 0)
    ok = has & (fd[safe] == s.docs) & (cmax[safe] >= s.ends)
    return SpanSet(s.docs[ok], s.starts[ok], s.ends[ok])


def after_spans(s: SpanSet, f: SpanSet) -> SpanSet:
    """Spans that start at or after some filter span's end."""
    if len(s.docs) == 0 or len(f.docs) == 0:
        return SpanSet.empty()
    order = np.lexsort((f.ends, f.docs))
    fd = f.docs[order]
    fe = f.ends[order]
    # per doc minimum filter end: reverse cummax trick via negation
    cmin = -_seg_cummax(-fe, fd)
    # index of FIRST entry for each doc: searchsorted on doc keys
    first_idx = np.searchsorted(fd, s.docs, "left")
    has = first_idx < len(fd)
    safe = np.minimum(first_idx, len(fd) - 1)
    ok = has & (fd[safe] == s.docs)
    # min end per doc = running min evaluated at the doc's LAST entry
    last_idx = np.searchsorted(fd, s.docs, "right") - 1
    lsafe = np.maximum(last_idx, 0)
    ok = ok & (cmin[lsafe] <= s.starts)
    return SpanSet(s.docs[ok], s.starts[ok], s.ends[ok])


def freq_vector(s: SpanSet, ndocs: int) -> np.ndarray:
    """Per-doc sloppy frequency Σ 1/(1 + width-1) over the final spans
    (Lucene SpanScorer's sloppyFreq accumulation)."""
    out = np.zeros(ndocs, np.float32)
    if len(s.docs):
        w = 1.0 / (1.0 + (s.ends - s.starts - 1).astype(np.float32))
        np.add.at(out, s.docs.astype(np.int64), w)
    return out


# ---------------------------------------------------------------------
# DSL tree evaluation
# ---------------------------------------------------------------------

class SpanEvalError(dsl.QueryParseError):
    pass


def eval_span_query(q, seg, ctx) -> Tuple[str, SpanSet, List[str]]:
    """-> (field, spans, terms involved) for a span query tree."""
    from . import compiler as C

    if isinstance(q, dsl.SpanTermQuery):
        term = C._index_term(q.field, q.value, ctx)
        ft = ctx.mappings.resolve_field(q.field)
        field = ft.name if ft else q.field
        return field, term_spans(seg, field, term), [term]

    if isinstance(q, dsl.SpanNearQuery):
        parts = [eval_span_query(c, seg, ctx) for c in q.clauses]
        field = _one_field(parts, "span_near")
        spans = near_spans([p[1] for p in parts], q.slop, q.in_order)
        return field, spans, _terms(parts)

    if isinstance(q, dsl.SpanOrQuery):
        parts = [eval_span_query(c, seg, ctx) for c in q.clauses]
        field = _one_field(parts, "span_or")
        return field, or_spans([p[1] for p in parts]), _terms(parts)

    if isinstance(q, dsl.SpanNotQuery):
        fi, inc, ti = eval_span_query(q.include, seg, ctx)
        fe, exc, _te = eval_span_query(q.exclude, seg, ctx)
        if fi != fe:
            raise SpanEvalError("[span_not] clauses must share a field")
        return fi, not_spans(inc, exc, q.pre, q.post), ti

    if isinstance(q, dsl.SpanFirstQuery):
        f, s, t = eval_span_query(q.match, seg, ctx)
        return f, first_spans(s, q.end), t

    if isinstance(q, dsl.SpanContainingQuery):
        fb, big, tb = eval_span_query(q.big, seg, ctx)
        fl, little, _tl = eval_span_query(q.little, seg, ctx)
        if fb != fl:
            raise SpanEvalError("[span_containing] clauses must share a field")
        return fb, containing_spans(big, little), tb

    if isinstance(q, dsl.SpanWithinQuery):
        fb, big, _tb = eval_span_query(q.big, seg, ctx)
        fl, little, tl = eval_span_query(q.little, seg, ctx)
        if fb != fl:
            raise SpanEvalError("[span_within] clauses must share a field")
        return fl, within_spans(little, big), tl

    if isinstance(q, dsl.SpanMultiQuery):
        return _eval_span_multi(q, seg, ctx)

    if isinstance(q, dsl.FieldMaskingSpanQuery):
        # evaluate on the inner query's true field; report the masked field
        # so enclosing span_near accepts mixed-field clauses (reference
        # FieldMaskingSpanQuery)
        _f, s, t = eval_span_query(q.query, seg, ctx)
        ft = ctx.mappings.resolve_field(q.field)
        return (ft.name if ft else q.field), s, t

    raise SpanEvalError(
        f"[{type(q).__name__}] is not a span query")


def _eval_span_multi(q, seg, ctx):
    from . import compiler as C

    inner = q.match
    if isinstance(inner, dsl.PrefixQuery):
        field, expander = inner.field, C._prefix_expander(
            inner.field, inner.value, False)
    elif isinstance(inner, dsl.WildcardQuery):
        field, expander = inner.field, C._wildcard_expander(
            inner.field, inner.value, False)
    elif isinstance(inner, dsl.FuzzyQuery):
        field, expander = inner.field, C._fuzzy_expander(
            inner.field, inner.value, inner.fuzziness, inner.prefix_length)
    elif isinstance(inner, dsl.RegexpQuery):
        field, expander = inner.field, C._regexp_expander(
            inner.field, inner.value)
    else:
        raise SpanEvalError(
            "[span_multi] needs a prefix/wildcard/fuzzy/regexp query")
    ft = ctx.mappings.resolve_field(field)
    field = ft.name if ft else field
    rows = expander(seg)
    pb = seg.postings.get(field)
    terms = [pb.vocab[int(r)] for r in rows[:16]] if pb is not None else []
    return field, rows_spans(seg, field, rows), terms


def eval_interval_rule(rule: dsl.IntervalRule, field: str, seg, ctx
                       ) -> Tuple[SpanSet, List[str]]:
    from . import compiler as C

    if rule.kind == "match":
        terms = C._analyze_query_text(field, rule.query, ctx, rule.analyzer)
        sets = [term_spans(seg, field, t) for t in terms]
        if len(sets) == 1:
            spans = sets[0]
        else:
            spans = near_spans(sets, rule.max_gaps, rule.ordered)
    elif rule.kind in ("prefix", "wildcard", "fuzzy"):
        if rule.kind == "prefix":
            expander = C._prefix_expander(field, rule.query, False)
        elif rule.kind == "wildcard":
            expander = C._wildcard_expander(field, rule.query, False)
        else:
            expander = C._fuzzy_expander(field, rule.query, rule.fuzziness,
                                         rule.prefix_length)
        rows = expander(seg)
        pb = seg.postings.get(field)
        terms = [pb.vocab[int(r)] for r in rows[:16]] if pb is not None else []
        spans = rows_spans(seg, field, rows)
    elif rule.kind in ("all_of", "any_of"):
        parts = [eval_interval_rule(r, field, seg, ctx) for r in rule.rules]
        terms = [t for _s, ts in parts for t in ts]
        if rule.kind == "any_of":
            spans = or_spans([s for s, _t in parts])
        else:
            spans = near_spans([s for s, _t in parts], rule.max_gaps,
                               rule.ordered)
    else:
        raise SpanEvalError(f"unknown intervals rule [{rule.kind}]")

    if rule.filter_kind:
        fspans, _ft = eval_interval_rule(rule.filter_rule, field, seg, ctx)
        fk = rule.filter_kind
        if fk == "containing":
            spans = containing_spans(spans, fspans)
        elif fk == "contained_by":
            spans = within_spans(spans, fspans)
        elif fk == "not_containing":
            kept = containing_spans(spans, fspans)
            spans = _difference(spans, kept)
        elif fk == "not_contained_by":
            kept = within_spans(spans, fspans)
            spans = _difference(spans, kept)
        elif fk == "not_overlapping":
            spans = not_spans(spans, fspans, 0, 0)
        elif fk == "before":
            spans = before_spans(spans, fspans)
        elif fk == "after":
            spans = after_spans(spans, fspans)
    return spans, terms


def _difference(all_s: SpanSet, minus: SpanSet) -> SpanSet:
    """Set difference by tagged merge (exact for deduped span sets)."""
    if len(minus.docs) == 0 or len(all_s.docs) == 0:
        return all_s
    na = len(all_s.docs)
    docs = np.concatenate([all_s.docs, minus.docs])
    starts = np.concatenate([all_s.starts, minus.starts])
    ends = np.concatenate([all_s.ends, minus.ends])
    tag = np.concatenate([np.zeros(na, np.int8),
                          np.ones(len(minus.docs), np.int8)])
    src = np.concatenate([np.arange(na), np.full(len(minus.docs), -1)])
    order = np.lexsort((tag, ends, starts, docs))
    d, s, e, t, sr = (docs[order], starts[order], ends[order], tag[order],
                      src[order])
    dup_next = np.zeros(len(d), bool)
    dup_next[:-1] = ((d[:-1] == d[1:]) & (s[:-1] == s[1:])
                     & (e[:-1] == e[1:]) & (t[1:] == 1))
    removed_src = sr[(t == 0) & dup_next]
    keep = np.ones(na, bool)
    keep[removed_src] = False
    return SpanSet(all_s.docs[keep], all_s.starts[keep], all_s.ends[keep])


def collect_terms(query, ctx, cap: int = 16) -> List[str]:
    """Light term collection for the pseudo-term idf weight: no positional
    evaluation, only term-dict scans for expansions (cheap)."""
    from . import compiler as C

    out: List[str] = []

    def expand(field, make_expander):
        ft = ctx.mappings.resolve_field(field)
        f = ft.name if ft else field
        for seg in ctx.segments:
            pb = seg.postings.get(f)
            if pb is None:
                continue
            rows = make_expander(f)(seg)
            out.extend(pb.vocab[int(r)] for r in rows[:cap])

    def walk(q):
        if isinstance(q, dsl.SpanTermQuery):
            out.append(C._index_term(q.field, q.value, ctx))
        elif isinstance(q, (dsl.SpanNearQuery, dsl.SpanOrQuery)):
            for c in q.clauses:
                walk(c)
        elif isinstance(q, dsl.SpanNotQuery):
            walk(q.include)
        elif isinstance(q, dsl.SpanFirstQuery):
            walk(q.match)
        elif isinstance(q, dsl.SpanContainingQuery):
            walk(q.big)
        elif isinstance(q, dsl.SpanWithinQuery):
            walk(q.little)
        elif isinstance(q, dsl.FieldMaskingSpanQuery):
            walk(q.query)
        elif isinstance(q, dsl.SpanMultiQuery):
            inner = q.match
            if isinstance(inner, dsl.PrefixQuery):
                expand(inner.field, lambda f: C._prefix_expander(
                    f, inner.value, False))
            elif isinstance(inner, dsl.WildcardQuery):
                expand(inner.field, lambda f: C._wildcard_expander(
                    f, inner.value, False))
            elif isinstance(inner, dsl.FuzzyQuery):
                expand(inner.field, lambda f: C._fuzzy_expander(
                    f, inner.value, inner.fuzziness, inner.prefix_length))
            elif isinstance(inner, dsl.RegexpQuery):
                expand(inner.field, lambda f: C._regexp_expander(
                    f, inner.value))

    def walk_rule(rule, field):
        if rule.kind == "match":
            out.extend(C._analyze_query_text(field, rule.query, ctx,
                                             rule.analyzer))
        elif rule.kind == "prefix":
            expand(field, lambda f: C._prefix_expander(f, rule.query, False))
        elif rule.kind == "wildcard":
            expand(field, lambda f: C._wildcard_expander(f, rule.query, False))
        elif rule.kind == "fuzzy":
            expand(field, lambda f: C._fuzzy_expander(
                f, rule.query, rule.fuzziness, rule.prefix_length))
        else:
            for r in rule.rules:
                walk_rule(r, field)

    if isinstance(query, tuple):
        walk_rule(query[2], query[1])
    else:
        walk(query)
    return out


def span_query_field(q, ctx) -> Optional[str]:
    """Structural validation without data: resolve the tree's single field
    (field-mismatch and shape errors surface on empty indices too)."""
    def resolve(f):
        ft = ctx.mappings.resolve_field(f)
        return ft.name if ft else f

    if isinstance(q, dsl.SpanTermQuery):
        return resolve(q.field)
    if isinstance(q, (dsl.SpanNearQuery, dsl.SpanOrQuery)):
        label = ("span_near" if isinstance(q, dsl.SpanNearQuery)
                 else "span_or")
        fields = {span_query_field(c, ctx) for c in q.clauses}
        fields.discard(None)
        if len(fields) > 1:
            raise SpanEvalError(f"[{label}] clauses must share a field")
        return next(iter(fields), None)
    if isinstance(q, dsl.SpanNotQuery):
        fi = span_query_field(q.include, ctx)
        fe = span_query_field(q.exclude, ctx)
        if fi is not None and fe is not None and fi != fe:
            raise SpanEvalError("[span_not] clauses must share a field")
        return fi
    if isinstance(q, dsl.SpanFirstQuery):
        return span_query_field(q.match, ctx)
    if isinstance(q, (dsl.SpanContainingQuery, dsl.SpanWithinQuery)):
        label = ("span_containing" if isinstance(q, dsl.SpanContainingQuery)
                 else "span_within")
        fb = span_query_field(q.big, ctx)
        fl = span_query_field(q.little, ctx)
        if fb is not None and fl is not None and fb != fl:
            raise SpanEvalError(f"[{label}] clauses must share a field")
        return fb or fl
    if isinstance(q, dsl.SpanMultiQuery):
        inner = q.match
        if not isinstance(inner, (dsl.PrefixQuery, dsl.WildcardQuery,
                                  dsl.FuzzyQuery, dsl.RegexpQuery)):
            raise SpanEvalError(
                "[span_multi] needs a prefix/wildcard/fuzzy/regexp query")
        return resolve(inner.field)
    if isinstance(q, dsl.FieldMaskingSpanQuery):
        span_query_field(q.query, ctx)   # validate inner shape
        return resolve(q.field)
    raise SpanEvalError(f"[{type(q).__name__}] is not a span query")


def _one_field(parts, label: str) -> str:
    fields = {p[0] for p in parts}
    if len(fields) != 1:
        raise SpanEvalError(f"[{label}] clauses must share a field")
    return next(iter(fields))


def _terms(parts) -> List[str]:
    return [t for p in parts for t in p[2]]
