"""Production Pallas fast path for the term/match hot path.

Routes single-group BM25 term queries (term / terms / match / multi-term
match with minimum_should_match — the traffic Lucene serves through
BulkScorer, reference `search/query/QueryPhase.java`) through the fused
Pallas kernel `ops/pallas_bm25.fused_bm25_topk_tfdl` instead of the XLA
gather→scatter path. The XLA path stays as the general fallback for complex
plans, segments with deletes, non-BM25 similarities, or posting rows larger
than the VMEM bucket cap.

Per (segment, field) we lazily build a DMA-friendly postings layout:
128-lane-aligned CSR rows of (doc_id i32, tf<<21|dl i32); DMA windows
align down to the 1024-element HBM tile with a positional skip mask. The packing is
lossless (tf < 2048, dl < 2^21 — segments violating it are ineligible), and
the kernel evaluates the SAME f32 BM25 expression as the XLA path with avgdl
as a query-time scalar, so both paths rank identically.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..index.segment import CODEC_V1, CODEC_V2, Segment, next_pow2
from ..ops import scoring as ops
from ..ops.pallas_bm25 import (DL_BITS, DL_MAX, HBM_ALIGN, INT_SENTINEL,
                               LANES, REQ_W, TF_MAX, align_csr_rows,
                               fused_bm25_bool_topk, fused_bm25_topk_impact,
                               fused_bm25_topk_tfdl)

MAX_T = 8            # pow2-padded term slots per query group
MAX_L = 1 << 16      # per-term VMEM bucket cap (elements)
MAX_TL = 1 << 17     # T_pad * L cap (~16MB VMEM incl. merge working set)
MAX_K = 128          # top-k lanes the kernel returns
MAX_CHUNKS = 4096    # doc-range split bound. Postings are <=1 per doc, so a
                     # chunk spanning W doc ids holds <=W postings per term;
                     # at 4096 chunks a 50M-doc ClueWeb-class segment has
                     # W ~= 12.2K <= the per-term VMEM budget even at
                     # T_pad=8 (MAX_TL/8 = 16K) — EVERY df, including an
                     # every-doc stopword, stays on-kernel (config 5).
                     # _chunk_slots starts at the predicted count, so the
                     # planning loop doesn't crawl up from 2 by doubling.
INT_MAX = np.int32(2**31 - 1)

# Impact-ordered head pruning (the device analog of Lucene's block-max
# pruning, reference `search/query/TopDocsCollectorContext.java` over
# Lucene MAXSCORE/WAND): a term with more than L_HEAD postings keeps an
# extra on-device copy of its L_HEAD HIGHEST-IMPACT postings (selected by
# tf/(tf+k·norm), stored doc-ascending so the kernel's merge network is
# unchanged). Pruned queries stream heads only — fixed cost per term no
# matter the df — then a host verify pass proves the result exact against
# the remainder's upper bound, or reruns that query dense. See
# `_verify_pruned` for the bound.
L_HEAD = 1 << 12

_enabled = True      # flipped by tests / OPENSEARCH_TPU_NO_FASTPATH

# served/fallback counters (surfaced in _nodes/stats; also used by tests to
# prove the kernel actually engaged rather than silently falling back).
# CounterGroup: dict-shaped reads (same keys/values as the old plain dict)
# with atomic inc() writes through the metrics registry — concurrent
# searches no longer lose counts to the `d[k] += 1` read-modify-write race
from ..utils.metrics import METRICS, CounterGroup
from ..utils.trace import TRACER
# flight-recorder (obs/): escalation-ladder rung events on the ambient
# request timeline. Emission discipline (oslint OSL505): every record()
# below is guarded by RECORDER.enabled so the disabled path never builds
# an event payload
from ..obs import flight_recorder as _fr
# per-query device cost accounting (obs/query_cost.py): every kernel
# launch notes the bytes its DMA windows actually move — reconciled
# against the plan-time CSR-stat prediction in the profile `cost` block
from ..obs import query_cost as _qc

STATS = CounterGroup(METRICS, "fastpath", {
    "pure_served": 0, "bool_served": 0, "fallback": 0,
    "pruned_served": 0, "pruned_dview": 0, "pruned_rescued": 0,
    "pruned_rescued2": 0, "pruned_escalated": 0,
    "shard_view_served": 0, "impact_frontier": 0,
    "reorder_tie_fallback": 0})

# phase-2 rescore instrumentation (surfaced in _nodes/stats and read by
# scripts/measure_escalation.py): where the candidate-union rescore ran
# and what it cost. wall_ms includes the device_get sync, so device
# numbers are honest end-to-end, not launch-and-forget.
RESCORE_STATS = CounterGroup(METRICS, "fastpath.rescore", {
    "host_calls": 0, "host_wall_ms": 0.0,
    "device_launches": 0, "device_queries": 0,
    "device_cands": 0, "device_wall_ms": 0.0})

_rescore_override: Optional[str] = None   # tests/scripts pin a path


def set_rescore_mode(mode: Optional[str]) -> None:
    """Force the phase-2 rescore path: "device", "host", or None (auto).
    Rejects anything else — a silently-ignored typo would make a parity
    harness compare the host path against itself."""
    global _rescore_override
    if mode not in (None, "device", "host"):
        raise ValueError(f"rescore mode must be 'device', 'host' or None, "
                         f"got {mode!r}")
    _rescore_override = mode


def rescore_mode() -> str:
    """Where the candidate-union rescore runs. Auto: device on TPU, host
    numpy under JAX_PLATFORMS=cpu (the fallback + parity oracle). Env
    OPENSEARCH_TPU_RESCORE=device|host overrides; set_rescore_mode wins."""
    import os
    if _rescore_override in ("device", "host"):
        return _rescore_override
    env = os.environ.get("OPENSEARCH_TPU_RESCORE", "").lower()
    if env in ("device", "host"):
        return env
    import jax
    return "device" if jax.default_backend() == "tpu" else "host"


def rescore_stats() -> dict:
    return dict(RESCORE_STATS)

# memory accounting: aligned postings, filter lists, filtered copies and
# quality-tier views register with the HBM ledger (obs/hbm_ledger.py),
# which derives the fielddata-breaker charge — the ledger is the sole
# charge path (oslint OSL506). Released when the owning layout object
# (or its segment) is GC'd; segments are immutable and replaced on
# refresh/merge.


def set_breaker(breaker) -> None:
    """Legacy wiring shim: the breaker now lives on the ledger."""
    from ..obs.hbm_ledger import LEDGER
    LEDGER.set_breaker(breaker)


def set_enabled(flag: bool) -> None:
    global _enabled
    _enabled = flag


_backend_ok = None


def enabled() -> bool:
    import os
    global _backend_ok
    if _backend_ok is None:
        import jax
        _backend_ok = jax.default_backend() == "tpu"
    return (_enabled and _backend_ok
            and not os.environ.get("OPENSEARCH_TPU_NO_FASTPATH"))


def _frontier(tfs: np.ndarray, dls: np.ndarray, ids: np.ndarray = None
              ) -> tuple:
    """(tf -> min dl over docs with that tf) of a posting set — its Pareto
    frontier under the BM25 contribution tf/(tf+k(dl)), which is increasing
    in tf and decreasing in dl. The max contribution of the set under ANY
    (k1, b, avgdl) is attained on this frontier, so ~a dozen (tf, dl) pairs
    give an EXACT set bound for every query-time similarity.

    With `ids`, additionally returns per frontier point TWO tie witnesses:
    the MIN doc id among postings attaining the point exactly (tf == tf_i
    and dl == min dl — the attainer set when length norms matter) and the
    MIN doc id over the whole tf class (the attainer set when b_eff ~ 0
    makes dl irrelevant). The verifier needs these to prove a boundary TIE
    non-displacing under the (score desc, doc asc) result order."""
    if len(tfs) == 0:
        z = np.zeros(0, np.float32)
        zi = np.zeros(0, np.int64)
        return (z, z) if ids is None else (z, z, zi, zi)
    tf = tfs.astype(np.int64)
    dl_s32 = dls.astype(np.float32)
    if ids is not None:
        order = np.lexsort((ids, dl_s32, tf))
        tf_s = tf[order]
        id_s = ids[order].astype(np.int64)
        first = np.flatnonzero(
            np.concatenate(([True], tf_s[1:] != tf_s[:-1])))
        id_any = np.minimum.reduceat(id_s, first)
        return (tf_s[first].astype(np.float32), dl_s32[order][first],
                id_s[first], id_any)
    order = np.argsort(tf, kind="stable")
    tf_s = tf[order]
    dl_s = dl_s32[order]
    # min dl per distinct tf via reduceat
    heads = np.flatnonzero(np.concatenate(([True], tf_s[1:] != tf_s[:-1])))
    return (tf_s[heads].astype(np.float32),
            np.minimum.reduceat(dl_s, heads).astype(np.float32))


def _frontier_bound(fr: Tuple[np.ndarray, np.ndarray], k1: float,
                    b_eff: float, avgdl: float) -> float:
    """Max contribution tf/(tf+k1·(1-b+b·dl/avgdl)) over a frontier."""
    tf, dl = fr[0], fr[1]
    if len(tf) == 0:
        return 0.0
    k = k1 * (1.0 - b_eff + b_eff * dl / max(avgdl, 1e-9))
    return float(np.max(tf / (tf + np.maximum(k, 1e-9))))


class AlignedPostings:
    """Device-resident aligned (doc, tf·dl) postings for one segment field,
    plus the impact-selected heads of oversized rows (appended to the same
    buffer) and the remainder frontiers that make pruned results provable."""

    __slots__ = ("starts_rows", "lens", "d_docs", "d_tfdl", "nbytes",
                 "head_starts_rows", "head_lens", "rem_frontiers",
                 "head_ids", "_full_frontiers", "_head2", "d_imp")

    def __init__(self, starts_rows: np.ndarray, lens: np.ndarray,
                 d_docs, d_tfdl, nbytes: int,
                 head_starts_rows: Optional[np.ndarray] = None,
                 head_lens: Optional[np.ndarray] = None,
                 rem_frontiers: Optional[dict] = None,
                 head_ids: Optional[dict] = None,
                 d_imp=None):
        self.starts_rows = starts_rows    # i64[nterms] aligned start / LANES
        self.lens = lens                  # i64[nterms] true posting counts
        self.d_docs = d_docs
        self.d_tfdl = d_tfdl
        self.nbytes = nbytes
        # head view: == (starts_rows, lens) for rows with <= L_HEAD postings;
        # points at the appended impact-head region for clamped rows
        self.head_starts_rows = (head_starts_rows if head_starts_rows
                                 is not None else starts_rows)
        self.head_lens = (head_lens if head_lens is not None
                          else np.minimum(lens, L_HEAD))
        # row -> frontier of the postings OUTSIDE the head (clamped rows
        # only); absence means the head is the whole row
        self.rem_frontiers = rem_frontiers or {}
        # row -> np doc ids of the head postings (clamped rows only) — the
        # candidate-union escalation path rescores exactly these
        self.head_ids = head_ids or {}
        self._full_frontiers: dict = {}
        # row -> (ids, remainder frontier) of the TIER-2 head (4x deeper,
        # host-only): built lazily on first escalation past tier 1, cached
        self._head2: dict = {}
        # codec v2 only: the quantized impact plane in the SAME aligned
        # layout as d_docs (u8/u16 widened to the i32 lane granularity) —
        # the frontier pass then rides `fused_bm25_topk_impact`, one
        # multiply per posting, no per-query tf/doclen math
        self.d_imp = d_imp

    def head2(self, pb, dl_col, row: int) -> tuple:
        """Lazy 4x-deeper head for the second escalation rung: top
        4*L_HEAD postings by nominal impact (ids only — the rescore is a
        host pass) plus the frontier of what remains. O(df log df) once
        per queried row, amortized across every later escalation."""
        got = self._head2.get(row)
        if got is None:
            a, b = pb.row_slice(row)
            dls = (dl_col[pb.doc_ids[a:b]] if dl_col is not None
                   else np.zeros(b - a, np.int64))
            plane = getattr(pb, "impact", None)
            keep, fr = _head_select(pb.doc_ids[a:b], pb.tfs[a:b],
                                    np.asarray(dls, np.int64),
                                    l_head=4 * L_HEAD,
                                    imp=(_plane_impacts_slice(plane, a, b)
                                         if plane is not None
                                         else None))
            got = (pb.doc_ids[a:b][keep], fr)
            self._head2[row] = got
        return got

    def clamped(self, row: int) -> bool:
        return row in self.rem_frontiers

    def rem_bound(self, row: int, k1: float, b_eff: float,
                  avgdl: float) -> float:
        """Upper bound of one remaining (non-head) posting's contribution
        for this row under query-time similarity params."""
        fr = self.rem_frontiers.get(row)
        return 0.0 if fr is None else _frontier_bound(fr, k1, b_eff, avgdl)

    def full_bound(self, pb, row: int, k1: float, b_eff: float,
                   avgdl: float, dl_col) -> float:
        """Upper bound of ANY single posting's contribution in this row
        (lazy per-row frontier, cached — O(df) once per queried term)."""
        fr = self._full_frontiers.get(row)
        if fr is None:
            a, b = pb.row_slice(row)
            dls = (dl_col[pb.doc_ids[a:b]] if dl_col is not None
                   else np.zeros(b - a, np.float32))
            fr = _frontier(pb.tfs[a:b], dls)
            self._full_frontiers[row] = fr
        return _frontier_bound(fr, k1, b_eff, avgdl)


def get_aligned(seg: Segment, field: str) -> Optional[AlignedPostings]:
    """Build (or fetch cached) aligned postings; None when the segment is
    ineligible (tf/dl exceed the lossless packing bounds, or no postings)."""
    cache = seg.__dict__.setdefault("_fastpath_aligned", {})
    if field in cache:
        return cache[field]
    out = _build_aligned(seg, field)
    cache[field] = out
    return out


def _nominal_impact(tfs: np.ndarray, dls: np.ndarray,
                    avg: float) -> np.ndarray:
    """The ONE nominal-similarity impact (k1=1.2, b=0.75) both pruning
    mechanisms order by: head selection and the quality tier must never
    diverge on what 'high impact' means."""
    return tfs / (tfs + 1.2 * (0.25 + 0.75 * dls / avg))


def _plane_impacts(pb) -> Optional[np.ndarray]:
    """Codec-v2 fast source for the nominal impact order: the segment
    already carries quantized eager impacts built with the SAME nominal
    params (index/segment.py IMPACT_K1/IMPACT_B), so head selection and
    the quality tier reuse them instead of re-deriving an O(P) f32 map
    per (segment, field) layout build. Ordering by the quantized plane
    is sound — selection only steers which postings are kept; the exact
    (tf, dl) remainder frontiers still carry correctness. None on v1
    segments and facade views (recompute path unchanged)."""
    plane = getattr(pb, "impact", None)
    if plane is None:
        return None
    from ..ops.scoring import dequant_impact_np
    return dequant_impact_np(plane.q, plane.scale)


def _plane_impacts_slice(plane, a: int, b: int) -> np.ndarray:
    """Dequantized impacts of ONE row slice — per-row consumers (tier-2
    head cuts) must stay O(df), not O(P) over the whole field plane."""
    from ..ops.scoring import dequant_impact_np
    return dequant_impact_np(plane.q[a:b], plane.scale)


def _head_select(doc_ids: np.ndarray, tfs: np.ndarray, dl_of: np.ndarray,
                 l_head: int = None, imp: Optional[np.ndarray] = None
                 ) -> Tuple[np.ndarray, tuple]:
    """Pick the L_HEAD highest-impact postings of one oversized row.
    Impact = tf/(tf + k1·(1-b+b·dl/avgdl)) with nominal params — the order
    only steers which postings we keep; correctness rides on the returned
    REMAINDER FRONTIER (tf -> min dl of the non-kept postings), which
    bounds any remaining posting's contribution under any query-time
    similarity. On codec v2 `imp` carries the row's precomputed quantized
    impacts (`_plane_impacts`) so no per-posting math reruns here.
    Returns (kept positions ASCENDING — i.e. doc-ascending, as the
    kernel's merge network requires —, remainder frontier)."""
    tf = tfs.astype(np.float32)
    dlf = dl_of.astype(np.float32)
    if imp is not None:
        c = imp
    else:
        avg = max(float(dlf.mean()), 1.0)
        c = _nominal_impact(tf, dlf, avg)
    # stable sort: impact ties keep doc-ascending order, matching the exact
    # path's doc-id tie-break so a tied top-k boundary selects the same docs
    order = np.argsort(-c, kind="stable")
    lh = L_HEAD if l_head is None else l_head
    keep = order[:lh]
    rest = order[lh:]
    return np.sort(keep), _frontier(tf[rest], dlf[rest], doc_ids[rest])


def _build_aligned(seg: Segment, field: str) -> Optional[AlignedPostings]:
    import jax

    pb = seg.postings.get(field)
    dl = seg.doc_lens.get(field)
    if pb is None or pb.size == 0:
        return None
    tfs = pb.tfs
    if len(tfs) and tfs.max() > TF_MAX:
        return None
    dl_of = (dl[pb.doc_ids].astype(np.int64) if dl is not None
             else np.zeros(len(pb.doc_ids), np.int64))
    if len(dl_of) and dl_of.max() > DL_MAX:
        return None
    packed = ((tfs.astype(np.int64) << DL_BITS) | dl_of).astype(np.int32)
    lens = np.diff(pb.starts).astype(np.int64)
    nterms = len(lens)

    # impact heads for oversized rows, appended as EXTRA CSR rows so one
    # aligned buffer serves both the dense path (original row region,
    # offsets unchanged) and the pruned path (head region for big rows)
    big = np.nonzero(lens > L_HEAD)[0]
    rem_frontiers: dict = {}
    head_ids: dict = {}
    cat_starts = pb.starts
    cat_docs = pb.doc_ids
    cat_packed = packed
    # codec v2 (gate: Segment.codec_version, OSL507): carry the quantized
    # impact plane through the SAME aligned layout (widened to i32 — the
    # impact kernel's HBM lane granularity) so the frontier pass can ride
    # `fused_bm25_topk_impact`
    plane = (pb.impact
             if getattr(seg, "codec_version", CODEC_V1) >= CODEC_V2
             else None)
    cat_imp = (plane.q.astype(np.int32) if plane is not None else None)
    if len(big):
        plane_imp = _plane_impacts(pb)
        h_docs, h_packed, h_lens, h_imp = [], [], [], []
        for r in big:
            a, b = int(pb.starts[r]), int(pb.starts[r + 1])
            keep, rem_fr = _head_select(pb.doc_ids[a:b], tfs[a:b],
                                        dl_of[a:b],
                                        imp=(plane_imp[a:b]
                                             if plane_imp is not None
                                             else None))
            h_docs.append(pb.doc_ids[a:b][keep])
            h_packed.append(packed[a:b][keep])
            h_lens.append(len(keep))
            if cat_imp is not None:
                h_imp.append(plane.q[a:b][keep].astype(np.int32))
            rem_frontiers[int(r)] = rem_fr
            head_ids[int(r)] = h_docs[-1]
        cat_docs = np.concatenate([pb.doc_ids] + h_docs)
        cat_packed = np.concatenate([packed] + h_packed)
        if cat_imp is not None:
            cat_imp = np.concatenate([cat_imp] + h_imp)
        cat_starts = np.concatenate([
            pb.starts,
            pb.starts[-1] + np.cumsum(np.asarray(h_lens, np.int64))])

    # rows align to 128 lanes only; DMA windows align DOWN to the 1024
    # HBM tile and mask the spilled prefix positionally (skip) — the Zipf
    # long tail would otherwise pay up to 1023 pad slots per rare term
    extra = (cat_imp,) if cat_imp is not None else ()
    aligned = align_csr_rows(cat_starts, cat_docs, cat_packed, *extra,
                             margin=MAX_L, alignment=LANES)
    a_starts, a_docs, a_packed = aligned[0], aligned[1], aligned[2]
    a_imp = aligned[3] if cat_imp is not None else None
    nbytes = a_docs.nbytes + a_packed.nbytes \
        + (a_imp.nbytes if a_imp is not None else 0)
    from ..obs.hbm_ledger import LEDGER
    LEDGER.register("aligned_postings", nbytes, owner=seg, segment=seg,
                    label=f"fastpath[{seg.name}][{field}]")
    starts_rows = (a_starts[:-1] // LANES).astype(np.int64)
    head_starts_rows = starts_rows[:nterms].copy()
    head_lens = np.minimum(lens, L_HEAD)
    if len(big):
        head_starts_rows[big] = starts_rows[nterms:]
    return AlignedPostings(starts_rows[:nterms], lens,
                           jax.device_put(a_docs), jax.device_put(a_packed),
                           nbytes, head_starts_rows, head_lens,
                           rem_frontiers, head_ids,
                           d_imp=(jax.device_put(a_imp)
                                  if a_imp is not None else None))


def _body_eligible(sort_specs: List[dict], agg_nodes, named_nodes,
                   search_after, window: int, body: dict) -> bool:
    """Non-query body checks shared by every fastpath shape."""
    if agg_nodes or named_nodes or search_after is not None:
        return False
    if window > MAX_K or window < 1:
        return False
    if sort_specs and not (len(sort_specs) == 1
                           and sort_specs[0]["field"] == "_score"
                           and sort_specs[0].get("order", "desc") == "desc"):
        return False
    if body.get("collapse") or body.get("suggest") or body.get("knn"):
        return False
    return True


def _ok_group(lt) -> bool:
    """LTerms usable as a fastpath scoring clause (plain BM25 term group)."""
    from . import compiler as C

    if not isinstance(lt, C.LTerms):
        return False
    if lt.mode != "score" or lt.sim is None or lt.sim.sim_id != ops.SIM_BM25:
        return False
    nt = len(lt.terms)
    if nt < 1:
        return False
    if lt.aux is not None and np.any(np.asarray(lt.aux)[:nt] != 0.0):
        return False
    return True


def query_eligible(lroot, sort_specs: List[dict], agg_nodes, named_nodes,
                   search_after, window: int, body: dict) -> bool:
    """Host-cheap check that this search is the plain BM25 top-k hot path
    (single unfiltered term group — the original fused kernel shape)."""
    if not _ok_group(lroot):
        return False
    if next_pow2(len(lroot.terms), floor=1) > MAX_T:
        return False
    return _body_eligible(sort_specs, agg_nodes, named_nodes, search_after,
                          window, body)


class FastSpec:
    """A search the fastpath can serve. kind 'pure' = single term group on
    the original kernel; kind 'bool' = weighted-threshold bool/filtered
    shape on `fused_bm25_bool_topk` (reference BooleanQuery semantics,
    `search/query/QueryPhase.java`): required slots (single-term musts +
    the combined filter/must_not mask), one optional count-constrained
    family (a multi-term group's msm, or shoulds under the outer
    minimum_should_match), and zero-count bonus shoulds."""

    __slots__ = ("kind", "lt", "slots", "fam_msm", "filter_clauses",
                 "field", "sim", "has_norms", "boost", "const_score",
                 "window", "prune_ok")

    def __init__(self, kind: str, **kw):
        self.kind = kind
        self.lt = None
        self.slots = []            # [(term, weight, cw)] cw in {REQ_W, 1, 0}
        self.fam_msm = 0
        self.filter_clauses = []   # [(LNode, negated)] ANDed dense masks
        self.field = None
        self.sim = None
        self.has_norms = True
        self.boost = 1.0
        self.const_score = None    # fixed score for every hit (filter-only)
        self.window = None         # requested from+size (for pruned verify)
        self.prune_ok = False      # body allows impact-head pruning
        for k, v in kw.items():
            setattr(self, k, v)

    @property
    def n_required(self) -> int:
        return sum(1 for _, _, cw in self.slots if cw == REQ_W)


def _flatten_bool(lroot) -> Optional[FastSpec]:
    """Map an LBool/LConstScore tree onto the weighted-threshold slot model;
    None = not expressible (falls back to the XLA plan path)."""
    from . import compiler as C

    if isinstance(lroot, C.LConstScore):
        if lroot.child is None or lroot.boost < 0:
            return None
        return FastSpec("bool", filter_clauses=[(lroot.child, False)],
                        const_score=float(lroot.boost), boost=1.0)
    if not isinstance(lroot, C.LBool):
        return None
    b = lroot
    if b.boost <= 0:
        # boost 0 zeroes every score BEFORE top-k on the XLA path (ties then
        # break by doc id); the kernel ranks pre-boost, so fall back
        return None
    for g in b.musts + b.shoulds:
        if not _ok_group(g):
            return None
    groups = b.musts + b.shoulds
    field = sim = None
    has_norms = True
    if groups:
        field, sim, has_norms = (groups[0].field, groups[0].sim,
                                 groups[0].has_norms)
        for g in groups:
            if (g.field != field or g.sim.k1 != sim.k1 or g.sim.b != sim.b
                    or g.has_norms != has_norms):
                return None

    req: List[Tuple[str, float]] = []
    fam: List[Tuple[str, float]] = []
    bonus: List[Tuple[str, float]] = []
    fam_msm = 0

    def slot_weights(g):
        return [(t, float(np.asarray(g.weights)[i]))
                for i, t in enumerate(g.terms)]

    for m in b.musts:
        if len(m.terms) == 1 or m.msm >= len(m.terms):
            req.extend(slot_weights(m))        # AND semantics: all required
        elif not fam:
            fam.extend(slot_weights(m))        # the one constrained family
            fam_msm = max(int(m.msm), 1)
        else:
            return None
    if b.shoulds:
        outer = int(b.msm)
        if outer == 0:
            # pure score bonus: no count constraint, cw=0 so bonus matches
            # can never stand in for a missing required/family slot
            for s in b.shoulds:
                if len(s.terms) > 1 and s.msm > 1:
                    return None
                bonus.extend(slot_weights(s))
        else:
            if fam:
                return None                    # two constrained families
            if all(len(s.terms) == 1 for s in b.shoulds):
                for s in b.shoulds:
                    fam.extend(slot_weights(s))
                fam_msm = outer
            elif len(b.shoulds) == 1 and outer == 1:
                g = b.shoulds[0]
                fam.extend(slot_weights(g))
                fam_msm = max(int(g.msm), 1)
            else:
                return None

    filter_clauses = ([(f, False) for f in b.filters]
                      + [(n, True) for n in b.must_nots])
    slots = ([(t, w, REQ_W) for t, w in req]
             + [(t, w, 1.0) for t, w in fam]
             + [(t, w, 0.0) for t, w in bonus])
    if not slots and not filter_clauses:
        return None                            # empty bool = match_all
    if len(slots) > MAX_T:
        return None
    return FastSpec("bool", slots=slots, fam_msm=fam_msm,
                    filter_clauses=filter_clauses, field=field, sim=sim,
                    has_norms=has_norms, boost=float(b.boost),
                    const_score=0.0 if not slots else None)


def make_spec(lroot, sort_specs: List[dict], agg_nodes, named_nodes,
              search_after, window: int, body: dict) -> Optional[FastSpec]:
    """-> FastSpec when this search can ride a fused kernel, else None."""
    if not _body_eligible(sort_specs, agg_nodes, named_nodes, search_after,
                          window, body):
        return None
    # pruning changes total-hit semantics on clamped terms (lower bound,
    # relation "gte" — same contract as the reference's default 10k
    # total-hits cap); an explicit track_total_hits demands exact counts,
    # so those bodies ride the dense kernel
    prune_ok = "track_total_hits" not in body
    if _ok_group(lroot) and next_pow2(len(lroot.terms), floor=1) <= MAX_T:
        return FastSpec("pure", lt=lroot, field=lroot.field, window=window,
                        prune_ok=prune_ok)
    spec = _flatten_bool(lroot)
    if spec is not None:
        spec.window = window
        spec.prune_ok = prune_ok
    return spec


class _VQuery:
    """One kernel-row: a whole query, one doc-range chunk of it, or its
    impact-head pruned form (`head=True`)."""

    __slots__ = ("qi", "T_pad", "L", "rowstarts", "nrows", "lens", "skips",
                 "weights", "msm", "avgdl", "dlo", "dhi", "k1", "b_eff",
                 "field", "head", "clamped", "miss", "msm_true", "rows",
                 "impact_pass", "eps")

    def __init__(self, **kw):
        self.head = False       # streams impact heads instead of full rows
        self.clamped = False    # at least one term's head excludes postings
        self.miss = None        # f32[T_pad]: w_t * remainder bound per term
        self.msm_true = 1.0     # real msm (kernel gets 1.0 when clamped)
        self.rows = None        # i64[T_pad] term-dict rows (for rescore)
        self.impact_pass = False  # frontier pass rides the impact kernel
        self.eps = 0.0          # per-doc |exact - kernel| bound (impact
        #                         kernel only; 0.0 = exact f32 kernel)
        for k, v in kw.items():
            setattr(self, k, v)


def _chunk_slots(slots: List[Optional[Tuple[np.ndarray, int]]], ndocs: int,
                 T_total: int, nchunk: int = 2
                 ) -> Optional[List[tuple]]:
    """Split a query whose slot windows exceed the VMEM budget into
    doc-range chunks: uniform doc-id edges, verified against exact
    per-(slot, chunk) posting counts (host searchsorted over the ORIGINAL
    sorted doc lists), doubling the chunk count until every chunk fits.
    `slots[i]` = (sorted_docs, aligned_start_elem) or None for an absent
    slot (term/filter buffers alike — rowstarts are per-buffer row units).
    Returns a list of (dlo, dhi, rowstarts, nrows, lens) tuples covering
    disjoint doc ranges; None -> fall back."""
    budget = MAX_TL // T_total        # elements per slot
    # start at the provably-needed chunk count instead of doubling up from
    # the caller's floor: a slot of L postings needs >= L/budget chunks
    max_len = max((len(s[0]) for s in slots if s is not None), default=0)
    if max_len > budget:
        nchunk = max(nchunk, next_pow2(-(-max_len // budget), floor=2))
    while nchunk <= MAX_CHUNKS:
        edges = np.linspace(0, ndocs, nchunk + 1).astype(np.int64)
        edges[-1] = np.int64(2**31 - 1)
        ok = True
        per_chunk = []
        for c in range(nchunk):
            rowstarts = np.zeros(T_total, np.int32)
            nrows = np.zeros(T_total, np.int32)
            lens = np.zeros(T_total, np.int32)
            skips = np.zeros(T_total, np.int32)
            max_nr = HBM_ALIGN // LANES
            for i, slot in enumerate(slots):
                if slot is None:
                    continue
                seg_docs, start_el = slot
                lo_off = int(np.searchsorted(seg_docs, edges[c], "left"))
                hi_off = int(np.searchsorted(seg_docs, edges[c + 1], "left"))
                if hi_off == lo_off:
                    continue
                # DMA starts at the 1024 HBM tile below the window; the
                # spilled prefix (which may belong to the previous row) is
                # masked positionally by `skip` in the kernel
                abs_el = start_el + lo_off
                dma_el = (abs_el // HBM_ALIGN) * HBM_ALIGN
                skip = abs_el - dma_el
                ln = hi_off - lo_off
                if skip + ln > budget:
                    ok = False
                    break
                rowstarts[i] = dma_el // LANES
                nr = next_pow2((skip + ln + LANES - 1) // LANES,
                               floor=HBM_ALIGN // LANES)
                nrows[i] = nr
                lens[i] = ln
                skips[i] = skip
                max_nr = max(max_nr, nr)
            if not ok:
                break
            if T_total * max_nr * LANES > MAX_TL:
                ok = False
                break
            per_chunk.append((int(edges[c]), int(edges[c + 1]),
                              rowstarts, nrows, lens, skips))
        if ok:
            return per_chunk
        nchunk *= 2
    return None


def _impact_eps(plane, weights: np.ndarray, rows: np.ndarray, k1: float,
                b_eff: float, avgdl: float) -> float:
    """Sound per-doc |exact f32 score − impact-kernel score| bound —
    THE impactpath._error_bound serve margin (one definition: the
    frontier kernel's verify rungs must certify against exactly the
    epsilon the XLA impact pass uses, or a future bound fix silently
    diverges the two ladders)."""
    from .impactpath import _error_bound
    return _error_bound(plane, weights, rows, k1, b_eff, avgdl)


def impact_frontier_enabled() -> bool:
    """The codec-v2 frontier-kernel gate: on by default, pinned off via
    OPENSEARCH_TPU_NO_IMPACT_FRONTIER (ablation / rollback — the dense
    tf·dl kernel then serves the frontier pass as before the rev).
    `=0` means "not disabled", matching the `!= "0"` parse every other
    flag in this module family uses (OPENSEARCH_TPU_REORDER & co.)."""
    import os
    return os.environ.get("OPENSEARCH_TPU_NO_IMPACT_FRONTIER", "0") \
        in ("", "0")


def _term_slot(al: AlignedPostings, pb, r: int
               ) -> Optional[Tuple[np.ndarray, int]]:
    if r < 0:
        return None
    a, b = pb.row_slice(r)
    return pb.doc_ids[a:b], int(al.starts_rows[r]) * LANES


def _chunk_slices(al: AlignedPostings, pb, rows: np.ndarray, ndocs: int
                  ) -> Optional[List[tuple]]:
    """Doc-range chunk decomposition for the pure term-group path."""
    return _chunk_slots([_term_slot(al, pb, int(r)) for r in rows], ndocs,
                        len(rows))


def _prepare_vqueries(seg: Segment, ctx, lts: Sequence, avgdl_cache: dict,
                      prune: Optional[Sequence[bool]] = None
                      ) -> Optional[List[List[_VQuery]]]:
    """-> per input query, its list of kernel rows (1 or NCHUNK); None entry
    = that query falls back to the XLA path. When `prune[qi]` is true the
    query streams impact heads (always single-launch) and carries the
    verify metadata; otherwise the full rows, chunked when oversized."""
    out: List[Optional[List[_VQuery]]] = []
    for qi, lt in enumerate(lts):
        al = get_aligned(seg, lt.field)
        pb = seg.postings.get(lt.field)
        if al is None or pb is None:
            out.append(None)
            continue
        nt = len(lt.terms)
        T_pad = next_pow2(nt, floor=1)
        rows = np.full(T_pad, -1, np.int64)
        for i, t in enumerate(lt.terms):
            rows[i] = pb.row(t)
        weights = np.zeros(T_pad, np.float32)
        weights[:nt] = np.asarray(lt.weights, np.float32)[:nt]
        if lt.field not in avgdl_cache:
            avgdl_cache[lt.field] = np.float32(ctx.avgdl(lt.field))
        sim = lt.sim
        b_eff = float(sim.b) if lt.has_norms else 0.0
        common = dict(qi=qi, T_pad=T_pad, weights=weights,
                      msm=float(lt.msm), avgdl=avgdl_cache[lt.field],
                      k1=float(sim.k1), b_eff=b_eff, field=lt.field)
        use_head = bool(prune[qi]) if prune is not None else False
        src_starts = al.head_starts_rows if use_head else al.starts_rows
        src_lens = al.head_lens if use_head else al.lens

        # single-launch case: every row fits the per-term bucket (always
        # true for heads: L_HEAD <= MAX_L)
        min_rows = HBM_ALIGN // LANES
        rowstarts = np.zeros(T_pad, np.int32)
        nrows = np.zeros(T_pad, np.int32)
        lens = np.zeros(T_pad, np.int32)
        skips = np.zeros(T_pad, np.int32)
        max_nr = min_rows
        fits = True
        clamped = False
        miss = np.zeros(T_pad, np.float32)
        for i, r in enumerate(rows):
            if r < 0:
                continue
            ln = int(src_lens[r])
            if use_head and al.clamped(int(r)):
                clamped = True
                miss[i] = float(weights[i]) * al.rem_bound(
                    int(r), float(sim.k1), b_eff, float(common["avgdl"]))
            if ln == 0:
                continue
            abs_el = int(src_starts[r]) * LANES
            dma_el = (abs_el // HBM_ALIGN) * HBM_ALIGN
            skip = abs_el - dma_el
            if skip + ln > MAX_L:
                fits = False
                break
            rowstarts[i] = dma_el // LANES
            nr = next_pow2((skip + ln + LANES - 1) // LANES, floor=min_rows)
            nrows[i] = nr
            lens[i] = ln
            skips[i] = skip
            max_nr = max(max_nr, nr)
        if fits and T_pad * max_nr * LANES <= MAX_TL:
            vq = _VQuery(L=max_nr * LANES, rowstarts=rowstarts,
                         nrows=nrows, lens=lens, skips=skips, dlo=0,
                         dhi=int(INT_MAX), **common)
            if use_head:
                vq.head = True
                vq.clamped = clamped
                vq.miss = miss
                vq.msm_true = float(lt.msm)
                vq.rows = rows
                # codec-v2 frontier kernel: the head pass scores from the
                # aligned quantized impact plane (fused_bm25_topk_impact,
                # ONE multiply per posting) and the verify rungs absorb
                # the kernel epsilon — outputs are candidate partials
                # either way. Negative boosts void the one-sided error
                # bound; those stay on the exact tf·dl kernel.
                plane = getattr(pb, "impact", None)
                if (plane is not None and al.d_imp is not None
                        and impact_frontier_enabled()
                        and not np.any(weights[:nt] < 0)):
                    vq.impact_pass = True
                    vq.eps = _impact_eps(plane, weights, rows,
                                         float(sim.k1), b_eff,
                                         float(common["avgdl"]))
                if clamped and vq.msm_true > 1.0:
                    # kernel collects by raw sum; the true msm filter runs
                    # in the exact rescore (a doc matching all terms but
                    # only some heads must not be dropped on partial counts)
                    vq.msm = 1.0
            out.append([vq])
            continue

        # oversized: doc-range chunk decomposition (each doc's postings live
        # in exactly one chunk, so msm counting and score sums stay exact)
        chunks = _chunk_slices(al, pb, rows, seg.ndocs)
        if chunks is None:
            out.append(None)
            continue
        vqs = []
        for dlo, dhi, rowstarts, nrows, lens, skips in chunks:
            L = int(max(nrows.max(), min_rows)) * LANES
            vqs.append(_VQuery(L=L, rowstarts=rowstarts, nrows=nrows,
                               lens=lens, skips=skips, dlo=dlo, dhi=dhi,
                               **common))
        out.append(vqs)
    return out


def _launch_pure_groups_async(seg: Segment,
                              vq_lists: List[Optional[List[_VQuery]]],
                              K: int) -> list:
    """LAUNCH stage: group all kernel rows by shape, enqueue one kernel
    per group, and return the pending launches WITHOUT any device sync
    (oslint OSL504) — `_fetch_pure_groups` turns them into host results.
    -> [(gvqs, K_keep, unfetched (scores, docs, totals)), ...]."""
    tie_aware = _seg_tie_aware(seg)
    groups = {}
    for vqs in vq_lists:
        if vqs is None:
            continue
        for vq in vqs:
            # impact-frontier rows compile a DIFFERENT kernel (no
            # similarity statics), so they group apart from tf·dl rows —
            # and BECAUSE it takes no statics, (k1, b) must not split
            # their groups: one launch coalesces rows whose similarity
            # params differ (k1/b only feed each row's eps + host rescore)
            key = ((vq.field, vq.T_pad, None, None, True) if vq.impact_pass
                   else (vq.field, vq.T_pad, vq.k1, vq.b_eff, False))
            groups.setdefault(key, []).append(vq)
    pending = []
    for (field, T_pad, k1, b_eff, impact), gvqs in groups.items():
        al = get_aligned(seg, field)
        # ONE launch per group: DMA volume is set by per-term `nrows`, not L,
        # so every row rides the group's max-L variant — launch (and its
        # host<->device round trip) amortizes across the whole batch while
        # rare terms still move only their own bytes
        L = max(v.L for v in gvqs)
        # clamped (pruned) queries extract the FULL 128 output lanes, not
        # just the page window: the verifier's unseen-doc bound uses the
        # deepest kernel partial, and a 10-candidate pool leaves it so
        # high that every realistic multi-term query escalates (the
        # balanced mid-partial docs the page needs sit at ranks 10..128).
        # Impact-kernel rows do the same — their verify certifies seen-
        # but-lost docs against the deepest (approx + eps) partial.
        K_launch = (LANES if any(v.head and (v.clamped or v.impact_pass)
                                 for v in gvqs)
                    else K)
        if tie_aware:
            # BP-reordered segment: the kernel breaks score ties by
            # PERMUTED doc id, so `_assemble` re-breaks them by arrival
            # rank on host — extract the full lane window so the re-sort
            # sees past the page boundary (a tie class cut exactly at K
            # would otherwise keep the wrong member)
            K_launch = max(K_launch, LANES)
        rowstarts = np.stack([v.rowstarts for v in gvqs])
        nrows = np.stack([v.nrows for v in gvqs])
        lens = np.stack([v.lens for v in gvqs])
        skips = np.stack([v.skips for v in gvqs])
        weights = np.stack([v.weights for v in gvqs])
        msm = np.array([[v.msm] for v in gvqs], np.float32)
        avg = np.array([[v.avgdl] for v in gvqs], np.float32)
        dlo = np.array([[v.dlo] for v in gvqs], np.int32)
        dhi = np.array([[v.dhi] for v in gvqs], np.int32)
        # per-launch attribution (scripts/measure_concurrency.py divides
        # served queries by launches to report the coalescing ratio)
        METRICS.counter("fastpath.launches").inc()
        cost = _qc.current()
        if impact:
            # frontier pass on the quantized plane: weights fold
            # idf·boost·scale so the kernel is ONE multiply per posting
            # (the designated dequant shape, oslint OSL507); no
            # similarity statics — one compiled (T, L, K) variant serves
            # every (k1, b). Only codec-v2 segments emit impact_pass rows
            # (the aligned-layout build consults Segment.codec_version)
            assert getattr(seg, "codec_version", CODEC_V1) >= CODEC_V2
            plane = seg.postings[field].impact
            w_fold = (weights * np.float32(plane.scale)).astype(np.float32)
            if cost is not None:
                # the profile `cost` block names the kernel (acceptance:
                # fused_bm25_topk_impact reachable from the fastpath)
                cost.note_actual(int(nrows.sum()) * LANES * 8,
                                 int(lens.sum()), K_launch * len(gvqs),
                                 path="fused_bm25_topk_impact",
                                 segment=seg)
            STATS.inc("impact_frontier", len(gvqs))
            pending.append((gvqs, K_launch, fused_bm25_topk_impact(
                al.d_docs, al.d_imp, rowstarts, nrows, lens, skips,
                w_fold, msm, dlo, dhi, T=T_pad, L=L, K=K_launch)))
            continue
        if cost is not None:
            # actual bytes moved = the kernel's DMA windows: per term,
            # nrows lane-rows of 8-byte (doc, packed tf·dl) slots;
            # scatter work = the true posting counts; top-k work = the
            # K output lanes extracted per kernel row
            cost.note_actual(int(nrows.sum()) * LANES * 8,
                             int(lens.sum()), K_launch * len(gvqs),
                             path="kernel")
        pending.append((gvqs, K_launch, fused_bm25_topk_tfdl(
            al.d_docs, al.d_tfdl, rowstarts, nrows, lens, skips, weights,
            msm, avg, dlo, dhi, T=T_pad, L=L, K=K_launch, k1=k1, b=b_eff)))
    return pending


def _fetch_pure_groups(pending: list, K: int,
                       tie_aware: bool = False) -> dict:
    """FETCH stage for `_launch_pure_groups_async`:
    -> id(vq) -> (scores, docs, total, relation). `tie_aware` (the
    launching segment is BP-reordered) keeps every extracted lane so
    `_assemble`'s arrival-rank re-sort sees the full window."""
    # ONE device->host transfer for ALL groups' outputs: each np.asarray
    # is its own round trip, and on a tunneled host a round trip is
    # ~70ms — per-array fetches would multiply the batch-1 latency floor
    import jax
    fetched = jax.device_get([arrs for _gvqs, _kl, arrs in pending])
    results = {}
    for (gvqs, K_launch, _), (scores, docs, totals) in zip(pending,
                                                           fetched):
        for j, vq in enumerate(gvqs):
            keep = (K_launch
                    if (vq.head and (vq.clamped or vq.impact_pass))
                    or tie_aware else K)
            results[id(vq)] = (scores[j][:keep], docs[j][:keep],
                               int(totals[j][0]), "eq")
    return results


def _launch_pure_groups(seg: Segment,
                        vq_lists: List[Optional[List[_VQuery]]],
                        K: int) -> dict:
    """Synchronous launch+fetch (escalation rungs, host-loop callers)."""
    return _fetch_pure_groups(_launch_pure_groups_async(seg, vq_lists, K),
                              K, tie_aware=_seg_tie_aware(seg))


def _unseen_bound(al: AlignedPostings, pb, dl_col, vq: _VQuery,
                  partial_k: float) -> float:
    """Max possible TRUE score of any doc OUTSIDE the kernel's candidate
    set — the MaxScore-style analysis adapted to head pruning.

    An unseen doc misses some (possibly empty) subset S of the clamped
    terms' heads. Its score splits as (contributions from terms whose rows/
    heads contain it) + (remainder contributions of terms in S):
      - in-head part: <= partial_k (it lost the kernel top-K) AND
                      <= sum_{t not in S} w_t * full_bound_t
      - remainder:    <= sum_{t in S} miss_t  (exact frontier bounds)
    Take min of the two in-head bounds per subset, max over NONEMPTY
    subsets. S = {} (doc fully scored by the kernel but outside its top-K)
    is NOT a displacement threat when msm == 1: every candidate's exact
    score dominates its kernel score, so theta >= partial_k and the kernel
    already ranked the loser under the (score desc, doc asc) result order —
    it sorts strictly after every window member even on an exact tie.
    With msm > 1 that argument breaks (the kernel collects with msm
    relaxed to 1, and the host msm filter can drop high-kernel-score
    candidates, pushing theta BELOW partial_k), so the S = {} bound must
    stay in. The IMPACT frontier kernel (vq.eps > 0) breaks it too: its
    partials live in the quantized domain, so a candidate's exact score
    no longer dominates its kernel score — callers pass partial_k
    already inflated by eps, and S = {} stays in."""
    T = len(vq.rows)
    cl = [i for i in range(T) if vq.miss is not None and vq.miss[i] > 0.0]
    # per-term single-posting bounds (lazy frontier, cached on the layout)
    fb = np.zeros(T, np.float32)
    for i, r in enumerate(vq.rows):
        if r >= 0:
            fb[i] = vq.weights[i] * al.full_bound(
                pb, int(r), vq.k1, vq.b_eff, float(vq.avgdl), dl_col)
    best = partial_k if (vq.msm_true > 1.0 or vq.eps > 0.0) else -np.inf
    for mask in range(1, 1 << len(cl)):
        in_s = [cl[j] for j in range(len(cl)) if mask >> j & 1]
        rem_part = float(sum(vq.miss[i] for i in in_s))
        inhead = float(sum(fb[i] for i in range(T) if i not in in_s))
        best = max(best, min(partial_k + rem_part, inhead + rem_part))
    return best


def _tie_serves(al: AlignedPostings, vq: _VQuery, theta: float,
                cand: np.ndarray, order: np.ndarray, window: int) -> bool:
    """Boundary-tie witness for SINGLE-term pruned queries: when the unseen
    bound exactly ties theta, the only docs that can attain it are remainder
    postings on the frontier points whose contribution equals the bound.
    The frontier stores the MIN doc id attaining each point; head selection
    is a stable impact sort (ties keep doc-ascending order), so those ids
    are typically larger than every in-head tie.  A tying unseen doc
    displaces the window iff its id sorts before the window's worst member —
    so min attaining id > id(window[-1]) proves the served page exact."""
    if len(vq.rows) != 1 or theta == -np.inf:
        return False
    fr = al.rem_frontiers.get(int(vq.rows[0]))
    if fr is None or len(fr) != 4:
        return False
    tfv, dlv, id_dlmin, id_any = fr
    if len(tfv) == 0:
        return False
    # MIRROR `_verify_pruned`'s exact-rescore arithmetic (same dtypes, same
    # op order) so tie detection is BIT-exact in the f32 domain theta lives
    # in: any frontier point strictly above theta escalates; only bit-equal
    # points count as attainers needing the id witness
    avg = max(float(vq.avgdl), 1e-9)
    kfac = vq.k1 * (1.0 - vq.b_eff + vq.b_eff * dlv / avg)
    # the final f32 cast PINS the compare to `_exact_rescore`'s per-term
    # rounding whatever dtype the frontier carries: an f64 contribution
    # half an ulp below theta would silently promote the whole compare to
    # f64 (NEP50) and miss a tie that exists in the served f32 domain.
    # `_frontier` emits f32 today, so this is an enforced invariant, not a
    # live-bug fix — see TestTieServesF32Domain
    contrib = (vq.weights[0] * tfv / (tfv + kfac)).astype(np.float32)
    theta32 = np.float32(theta)
    if np.any(contrib > theta32):
        return False                      # genuinely above: real displacer
    att = contrib == theta32
    if not att.any():
        return True                       # no remainder doc reaches theta
    # the dl_min witness covers a point only when one dl step strictly
    # lowers the f32 contribution (then no longer-doc posting can tie);
    # otherwise fall back to the whole-tf-class min id (always sound)
    kfac2 = vq.k1 * (1.0 - vq.b_eff
                     + vq.b_eff * (dlv + np.float32(1.0)) / avg)
    contrib2 = (vq.weights[0] * tfv / (tfv + kfac2)).astype(np.float32)
    ids = np.where(contrib2 < contrib, id_dlmin, id_any)
    return int(ids[att].min()) > int(cand[order[window - 1]])


def _seg_tie_aware(seg) -> bool:
    """True when `seg` is BP-reordered (index/reorder.py): host sorts
    must re-break score ties by arrival rank, and kernel-verbatim
    windows cannot be served past an unresolved boundary tie."""
    f = getattr(seg, "tie_ranks", None)
    return f is not None and f() is not None


def _tie_key(seg, cand: np.ndarray) -> np.ndarray:
    """Layout-invariant tie-break key for host (score, tie) sorts: the
    arrival rank on BP-reordered segments (index/reorder.py parity
    contract — pages must not depend on the permuted internal ids), the
    doc id everywhere else (identical by construction when doc order IS
    arrival order, so unreordered segments keep their historical
    ordering bit for bit)."""
    f = getattr(seg, "tie_ranks", None)
    tr = f() if f is not None else None
    return tr[cand] if tr is not None else cand


def _arrival_sort(seg, sc: np.ndarray, dc: np.ndarray):
    """Re-break a kernel window's score ties by arrival rank (invalid
    lanes last). Returns (sc, dc, full) — `full` True when every lane is
    valid, i.e. the extraction saturated and a boundary tie class may
    extend past its edge. THE one definition shared by every serving
    rung that re-sorts kernel-verbatim windows (a divergent copy here is
    a parity hole)."""
    ok = np.isfinite(sc) & (dc >= 0)
    key = np.where(ok, _tie_key(seg, np.maximum(dc, 0)),
                   np.int64(np.iinfo(np.int64).max))
    order = np.lexsort((key, -sc))
    return sc[order], dc[order], int(ok.sum()) == len(sc)


def _tie_cut_at_edge(sc: np.ndarray, full: bool, K: int) -> bool:
    """True when the page-boundary tie class reaches the END of a
    saturated extracted window: an unextracted doc with the same score
    but earlier arrival may deserve the slot — the caller must decline
    to a rung that resolves the class exactly."""
    return full and len(sc) >= K and sc[K - 1] == sc[-1]


def _chunk_tie_ambiguous(parts, sc: np.ndarray, dc: np.ndarray,
                        K: int) -> bool:
    """Multi-chunk analog of `_tie_cut_at_edge` over the merged window:
    a FULL chunk window whose deepest lane ties (or beats) the merged
    page boundary may have cut an arrival-earlier tie member at its own
    extraction edge (unextracted chunk docs score <= its deepest lane,
    so a strictly lower deepest lane proves the chunk complete above the
    boundary)."""
    if len(sc) < K or not np.isfinite(sc[K - 1]) or int(dc[K - 1]) < 0:
        return False
    boundary = float(sc[K - 1])
    for p in parts:
        psc, pdc = p[0], p[1]
        pok = np.isfinite(psc) & (pdc >= 0)
        if len(psc) and int(pok.sum()) == len(psc) \
                and float(psc[-1]) >= boundary:
            return True
    return False


def _exact_rescore(seg: Segment, vq: _VQuery, cand: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact scores + per-term match counts of `cand` against the FULL
    rows (vectorized searchsorted per term — the analog of Lucene
    re-walking a WAND candidate)."""
    pb = seg.postings.get(vq.field)
    dl = seg.doc_lens.get(vq.field)
    dl_c = (dl[cand].astype(np.float32) if dl is not None
            else np.zeros(len(cand), np.float32))
    kfac = vq.k1 * (1.0 - vq.b_eff
                    + vq.b_eff * dl_c / max(float(vq.avgdl), 1e-9))
    exact = np.zeros(len(cand), np.float32)
    counts = np.zeros(len(cand), np.int64)
    for i, r in enumerate(vq.rows):
        if r < 0:
            continue
        a, b = pb.row_slice(int(r))
        if b <= a:
            continue   # term has no postings here (e.g. empty FILTERED row)
        rowdocs = pb.doc_ids[a:b]
        pos = np.searchsorted(rowdocs, cand)
        pos_c = np.minimum(pos, b - a - 1)
        found = rowdocs[pos_c] == cand
        tf = np.where(found, pb.tfs[a + pos_c], 0.0).astype(np.float32)
        exact += np.where(found, vq.weights[i] * tf / (tf + kfac),
                          0.0).astype(np.float32)
        counts += found
    return exact, counts


def _noheads_bound(al: AlignedPostings, vq: _VQuery,
                   frontier_of=None, rows_all: bool = False) -> float:
    """Max TRUE score of any doc outside EVERY queried head (the unseen
    docs of the candidate-union escalation): all of its contributions come
    from clamped remainders and share ONE doc length d, so
        bound = max_d  sum_t  g_t(d),
    where g_t(d) = w_t * max{tf/(tf+k(d)) : (tf, dlmin) in rem frontier of
    t, dlmin <= d} and d ranges over the frontier dl minima (contribution
    is decreasing and feasibility increasing in d, so the max over real
    lengths is attained on that grid). Docs matching fewer than msm terms
    can't pass, so grid points with too few feasible terms are skipped.
    Unclamped rows don't appear: any doc matching one is a candidate.
    `frontier_of` overrides the per-row remainder frontier (the tier-2
    rescue passes its deeper-cut frontiers); `rows_all` makes EVERY valid
    row participate (the quality-tier view restricts every row, so every
    term has out-of-view postings an unseen doc could match)."""
    if rows_all:
        cl = [i for i, r in enumerate(vq.rows) if r >= 0]
    else:
        cl = [i for i, r in enumerate(vq.rows)
              if r >= 0 and al.clamped(int(r))]
    if not cl:
        return -np.inf
    fronts = []
    ds = []
    for i in cl:
        row = int(vq.rows[i])
        fr = (frontier_of(row) if frontier_of is not None
              else al.rem_frontiers.get(row))
        if fr is None:
            continue
        tfv = np.asarray(fr[0], np.float64)
        dlv = np.asarray(fr[1], np.float64)
        if len(tfv):
            fronts.append((i, tfv, dlv))
            ds.append(dlv)
    if not fronts:
        return -np.inf
    avg = max(float(vq.avgdl), 1e-9)
    best = -np.inf
    for d in np.unique(np.concatenate(ds)):
        k = max(vq.k1 * (1.0 - vq.b_eff + vq.b_eff * float(d) / avg),
                1e-9)
        total = 0.0
        nfeas = 0
        for i, tfv, dlv in fronts:
            feas = dlv <= d
            if not feas.any():
                continue
            nfeas += 1
            total += float(vq.weights[i]) * float(
                np.max(tfv[feas] / (tfv[feas] + k)))
        if nfeas and nfeas >= vq.msm_true:
            best = max(best, total)
    return best


def _p2_candidates(vq: _VQuery, pb, ids_of) -> Optional[np.ndarray]:
    """The candidate union of one query: every doc any queried head
    mentions (`ids_of(row)`; None = the head is the full row)."""
    ids = []
    for r in vq.rows:
        if r < 0:
            continue
        r = int(r)
        hid = ids_of(r)
        if hid is None:
            a, b = pb.row_slice(r)
            hid = pb.doc_ids[a:b]
        ids.append(np.asarray(hid, np.int64))
    if not ids:
        return None
    cand = np.unique(np.concatenate(ids))
    return cand if len(cand) else None


def _p2_decide(al: AlignedPostings, vq: _VQuery, cand: np.ndarray,
               exact: np.ndarray, counts: np.ndarray, window: int, K: int,
               frontier_of, tie: Optional[np.ndarray] = None
               ) -> Optional[tuple]:
    """Serve-or-escalate decision on exact-rescored candidates: certify the
    window against the dl-consistent `_noheads_bound` or return None."""
    pass_msm = counts >= vq.msm_true
    n_pass = int(pass_msm.sum())
    exact_m = np.where(pass_msm, exact, -np.inf).astype(np.float32)
    order = np.lexsort((cand if tie is None else tie, -exact_m))
    theta = (float(exact_m[order[window - 1]]) if n_pass >= window
             else -np.inf)
    bound = _noheads_bound(al, vq, frontier_of)
    # equality escalates (frontier bounds are attained), as in phase 1
    if bound >= theta:
        return None
    keep = order[pass_msm[order]][:K]
    sc2 = np.full(K, -np.inf, np.float32)
    dc2 = np.full(K, -1, np.int32)
    sc2[: len(keep)] = exact_m[keep]
    dc2[: len(keep)] = cand[keep].astype(np.int32)
    return (sc2, dc2, n_pass, "gte")


def _rescore_many(seg: Segment, jobs: List[tuple]) -> List[tuple]:
    """Exact scores + match counts for a BATCH of (vq, cand) rescore jobs.

    rescore_mode() "device": one jit launch per (field, T, candidate
    bucket, sim) group over the already-resident aligned buffers
    (ops/rescore.exact_rescore_batch via compiler.build_rescore_program)
    — the whole escalation queue rides a handful of launches instead of a
    host searchsorted pass per query. "host": the numpy oracle
    `_exact_rescore` per job (JAX_PLATFORMS=cpu fallback; also the path
    parity tests pin the device results against, bit for bit)."""
    import time
    if not jobs:
        return []
    if rescore_mode() != "device":
        t0 = time.perf_counter()
        out = [_exact_rescore(seg, vq, cand) for vq, cand in jobs]
        dt_ms = (time.perf_counter() - t0) * 1e3
        RESCORE_STATS.inc("host_calls", len(jobs))
        RESCORE_STATS.inc("host_wall_ms", dt_ms)
        METRICS.histogram("fastpath.rescore.host_ms").record(dt_ms)
        return out
    return _rescore_many_device(seg, jobs)


def _rescore_many_device(seg: Segment, jobs: List[tuple]) -> List[tuple]:
    import time

    import jax

    from . import compiler as C
    from ..ops.rescore import rescore_elem_budget

    t0 = time.perf_counter()
    out: List[Optional[tuple]] = [None] * len(jobs)
    groups: dict = {}
    host_jobs: List[int] = []
    for j, (vq, cand) in enumerate(jobs):
        cb = C.rescore_cand_bucket(len(cand))
        al = get_aligned(seg, vq.field)
        # ineligible shapes (union past the bucket cap, element offsets
        # beyond i32 on a pathologically large buffer) take the host pass
        # for just that job — the rest of the batch stays on device
        if (cb is None or al is None
                or int(al.starts_rows[-1] + 1) * LANES + int(al.lens[-1])
                > 2**31 - 1):
            host_jobs.append(j)
            continue
        key = (vq.field, len(vq.rows), cb, vq.k1, vq.b_eff)
        groups.setdefault(key, []).append(j)
    for (field, T, cb, k1, b_eff), idxs in groups.items():
        al = get_aligned(seg, field)
        run = C.build_rescore_program(T, cb, k1, b_eff)
        # bounded [QB, T, C] probe intermediates: split oversized groups
        # into sequential launches
        step = rescore_elem_budget(T, cb)
        for lo in range(0, len(idxs), step):
            part = idxs[lo: lo + step]
            QB = next_pow2(len(part), floor=1)
            starts = np.zeros((QB, T), np.int32)
            lens = np.zeros((QB, T), np.int32)
            weights = np.zeros((QB, T), np.float32)
            avgdl = np.ones((QB, 1), np.float32)
            cands = np.full((QB, cb), INT_MAX, np.int32)
            for qj, j in enumerate(part):
                vq, cand = jobs[j]
                for i, r in enumerate(vq.rows):
                    if r < 0:
                        continue
                    starts[qj, i] = int(al.starts_rows[int(r)]) * LANES
                    lens[qj, i] = int(al.lens[int(r)])
                weights[qj] = vq.weights
                avgdl[qj, 0] = vq.avgdl
                cands[qj, : len(cand)] = cand.astype(np.int32)
            exact, counts = jax.device_get(
                run(al.d_docs, al.d_tfdl, starts, lens, weights, avgdl,
                    cands))
            for qj, j in enumerate(part):
                n = len(jobs[j][1])
                out[j] = (exact[qj, :n], counts[qj, :n].astype(np.int64))
            RESCORE_STATS.inc("device_launches")
            RESCORE_STATS.inc("device_queries", len(part))
            RESCORE_STATS.inc("device_cands", int(
                sum(len(jobs[j][1]) for j in part)))
    t_host = 0.0
    for j in host_jobs:
        vq, cand = jobs[j]
        th = time.perf_counter()
        out[j] = _exact_rescore(seg, vq, cand)
        t_host += time.perf_counter() - th
        RESCORE_STATS.inc("host_calls")
    # per-path attribution: a host-ineligible job's numpy time must not
    # inflate device_wall_ms — that's the serialization signal these
    # stats exist to expose
    dev_ms = (time.perf_counter() - t0 - t_host) * 1e3
    RESCORE_STATS.inc("host_wall_ms", t_host * 1e3)
    RESCORE_STATS.inc("device_wall_ms", dev_ms)
    METRICS.histogram("fastpath.rescore.device_ms").record(dev_ms)
    return out


def _phase2_batch(seg: Segment, vq_lists, specs: Sequence, results: dict,
                  redo: List[int], K: int) -> List[int]:
    """Candidate-union escalation — the cheap middle rung between the
    pruned kernel pass and the dense rerun, batched across every query the
    phase-1 verify failed. The kernel's top-K-by-PARTIAL misses 'balanced'
    docs whose per-term partials are mid-pack but whose sum is competitive
    (measured: 100% of clamped multi-term bench queries escalated on it).
    Rescoring the ENTIRE head union (every doc any head mentions,
    <= T*L_HEAD candidates) recovers exactly those docs: a doc outside ALL
    heads is then bounded by the dl-consistent `_noheads_bound`, which
    sits well below the top-K threshold on real corpora. Totals stay the
    'gte' contract.

    Tier 1 rescores every failed query's head union in ONE `_rescore_many`
    batch; the still-unproven tail retries on lazily-built 4x-deeper
    tier-2 heads (the remainder bound drops with the cut depth, catching
    most of the multi-term stopword-class tail) as a second batch. Returns
    the queries still unproven (-> quality-tier rung, then dense)."""
    jobs: List[tuple] = []
    meta: List[tuple] = []          # (qi, vq, cand)
    still: List[int] = []
    for qi in redo:
        vq = vq_lists[qi][0]
        pb = seg.postings.get(vq.field)
        al = get_aligned(seg, vq.field)
        cand = _p2_candidates(vq, pb, al.head_ids.get)
        if cand is None:
            still.append(qi)
            continue
        jobs.append((vq, cand))
        meta.append((qi, vq, cand))
    tier2: List[tuple] = []
    for (qi, vq, cand), (exact, counts) in zip(meta,
                                               _rescore_many(seg, jobs)):
        al = get_aligned(seg, vq.field)
        ver = _p2_decide(al, vq, cand, exact, counts,
                         int(specs[qi].window or K), K, None,
                         tie=_tie_key(seg, cand))
        if ver is not None:
            results[id(vq)] = ver
            STATS.inc("pruned_rescued")
        else:
            tier2.append((qi, vq))
    jobs2: List[tuple] = []
    meta2: List[tuple] = []
    for qi, vq in tier2:
        pb = seg.postings.get(vq.field)
        al = get_aligned(seg, vq.field)
        dl_col = seg.doc_lens.get(vq.field)
        h2 = {int(r): al.head2(pb, dl_col, int(r))
              for r in vq.rows if r >= 0 and al.clamped(int(r))}
        cand = _p2_candidates(
            vq, pb, lambda row: h2[row][0] if row in h2 else None)
        if cand is None:
            still.append(qi)
            continue
        jobs2.append((vq, cand))
        meta2.append((qi, vq, cand, h2))
    for (qi, vq, cand, h2), (exact, counts) in zip(
            meta2, _rescore_many(seg, jobs2)):
        al = get_aligned(seg, vq.field)
        ver = _p2_decide(al, vq, cand, exact, counts,
                         int(specs[qi].window or K), K,
                         lambda row, _h2=h2, _al=al:
                         _h2[row][1] if row in _h2
                         else _al.rem_frontiers.get(row),
                         tie=_tie_key(seg, cand))
        if ver is not None:
            results[id(vq)] = ver
            STATS.inc("pruned_rescued")
            STATS.inc("pruned_rescued2")
        else:
            still.append(qi)
    return still


def _phase2_rescore(seg: Segment, vq: _VQuery, window: int, K: int
                    ) -> Optional[tuple]:
    """Single-query wrapper over the batched middle rung (kept for tests
    and external callers; `_run_pure` batches via `_phase2_batch`)."""
    results: dict = {}

    class _S:
        pass

    s = _S()
    s.window = window
    still = _phase2_batch(seg, [[vq]], [s], results, [0], K)
    return None if still else results[id(vq)]


QUALITY_SHARE = 8       # quality tier keeps ~ndocs/QUALITY_SHARE docs
QUALITY_MIN_NDOCS = 1 << 16   # below this, dense is already cheap


def _quality_tier(seg: Segment, field: str):
    """Query-independent static index pruning (the device analog of the
    'quality-tier' / static pruning literature Lucene-world engines use
    for service tiers): keep the ~1/QUALITY_SHARE docs whose BEST
    per-posting nominal impact is highest. Scores on the restricted view
    are EXACT for view docs (the view restricts DOCS, so a kept doc keeps
    every posting), and every posting of an outside doc has nominal
    impact < tau by construction — the per-row out-of-view frontiers
    certify the served window under any query-time similarity. One
    vectorized pass per (segment, field), cached.

    Returns (FilterList, frontier_of) or None (segment too small /
    ineligible layout)."""
    cache = seg.__dict__.setdefault("_fastpath_quality", {})
    if field in cache:
        return cache[field]
    out = None
    pb = seg.postings.get(field)
    dl = seg.doc_lens.get(field)
    # real Segments only: the filter-cache infrastructure keys on seg.uid,
    # which ShardView/FilteredSegView facades don't have — those continue
    # to the dense rung as before
    if (pb is not None and pb.size > 0 and seg.ndocs >= QUALITY_MIN_NDOCS
            and getattr(seg, "uid", None) is not None
            and get_aligned(seg, field) is not None):
        imp = _plane_impacts(pb)     # codec v2: precomputed, no O(P) map
        if imp is None:
            dl_of = (dl[pb.doc_ids].astype(np.float32) if dl is not None
                     else np.zeros(len(pb.doc_ids), np.float32))
            avg = max(float(dl_of.mean()), 1.0)
            imp = _nominal_impact(pb.tfs, dl_of, avg)
        docmax = np.zeros(seg.ndocs, np.float32)
        np.maximum.at(docmax, pb.doc_ids, imp)
        target = max(seg.ndocs // QUALITY_SHARE, QUALITY_MIN_NDOCS // 4)
        tau = np.float32(np.partition(docmax, seg.ndocs - target)
                         [seg.ndocs - target])
        mask = docmax >= tau
        # impact ties at tau can inflate the kept set far past the
        # target, inverting the rung's cost model — decline rather than
        # launch a near-dense-sized view
        if 0 < mask.sum() <= 2 * target:
            host_docs = np.flatnonzero(mask).astype(np.int32)
            nbytes = mask.nbytes + host_docs.nbytes
            fl = FilterList(host_docs, None, len(host_docs), nbytes, mask,
                            ("_quality", field, QUALITY_SHARE))
            from ..obs.hbm_ledger import LEDGER
            LEDGER.register(
                "quality_tier", nbytes, owner=fl, segment=seg,
                label=f"fastpath-quality[{seg.name}][{field}]")
            frontiers: dict = {}

            def frontier_of(row: int, _f=frontiers, _pb=pb, _dl=dl,
                            _mask=mask):
                # per-row slices derived on demand: only the tiny
                # frontiers are retained, not per-posting arrays
                fr = _f.get(row)
                if fr is None:
                    a, b = _pb.row_slice(row)
                    rd = _pb.doc_ids[a:b]
                    sel = ~_mask[rd]
                    dls = (_dl[rd[sel]].astype(np.float32)
                           if _dl is not None
                           else np.zeros(int(sel.sum()), np.float32))
                    fr = _frontier(_pb.tfs[a:b][sel], dls)
                    _f[row] = fr
                return fr

            out = (fl, frontier_of)
    cache[field] = out
    return out


def _dview_rescue(seg: Segment, ctx, lts: Sequence, specs: Sequence,
                  vq_lists, results: dict, redo: List[int], K: int
                  ) -> List[int]:
    """Quality-tier escalation rung: run ALL still-unproven queries as ONE
    batched dense launch over the quality view (exact scores, ~1/8 the
    postings), certify each against the out-of-view frontiers, and return
    the queries that still need the full dense pass. Mixed-field batches
    group per field (one view launch each)."""
    by_field: dict = {}
    for qi in redo:
        by_field.setdefault(vq_lists[qi][0].field, []).append(qi)
    still: List[int] = []
    for field, qis in by_field.items():
        still.extend(_dview_rescue_field(seg, ctx, lts, specs, vq_lists,
                                         results, qis, K, field))
    STATS.inc("pruned_dview", len(redo) - len(still))
    return still


def _dview_rescue_field(seg: Segment, ctx, lts: Sequence, specs: Sequence,
                        vq_lists, results: dict, redo: List[int], K: int,
                        field: str) -> List[int]:
    qt = _quality_tier(seg, field)
    if qt is None:
        return redo
    fl, frontier_of = qt
    fp = _filtered_postings(seg, field, fl)
    if fp is None:
        return redo
    view = _filtered_view(seg, field, fp, (seg.uid, field, fl.key))
    al = get_aligned(seg, field)
    dlists = _prepare_vqueries(view, ctx, [lts[qi] for qi in redo], {})
    if dlists is None:
        return redo
    vres = _launch_pure_groups(view, dlists, K)
    tie_aware = _seg_tie_aware(seg)
    still = []
    for qi, dvqs in zip(redo, dlists):
        served = False
        ambiguous = False
        if dvqs is not None:
            if len(dvqs) == 1:
                sc, dc, total, _ = vres[id(dvqs[0])]
                if tie_aware:
                    # reordered segment: re-break the device window's
                    # score ties in arrival order (view docs are
                    # original ids, so the parent plane applies) —
                    # decline on a boundary tie at the extraction edge:
                    # this rung serves into `exact_ids`, so nothing
                    # downstream would re-check
                    sc, dc, full = _arrival_sort(seg, sc, dc)
                    ambiguous = _tie_cut_at_edge(sc, full, K)
            else:
                parts = [vres[id(v)] for v in dvqs]
                sc = np.concatenate([p[0] for p in parts])
                dc = np.concatenate([p[1] for p in parts])
                total = sum(p[2] for p in parts)
                ok = dc >= 0
                key = np.where(ok, _tie_key(seg, np.maximum(dc, 0)),
                               np.int64(np.iinfo(np.int64).max))
                order = np.lexsort((key, -sc))[:K]
                sc, dc = sc[order], dc[order]
                if tie_aware:
                    ambiguous = _chunk_tie_ambiguous(parts, sc, dc, K)
            if ambiguous:
                STATS.inc("reorder_tie_fallback")
        if dvqs is not None and not ambiguous:
            valid = np.isfinite(sc) & (dc >= 0)
            window = int(specs[qi].window or K)
            theta = (float(sc[valid][window - 1])
                     if int(valid.sum()) >= window else -np.inf)
            # the ORIGINAL (pruned) vq carries .rows/.weights — same term
            # rows as the view launch, which runs the dense shape
            ovq = vq_lists[qi][0]
            bound = _noheads_bound(al, ovq, frontier_of, rows_all=True)
            if bound < theta:
                results[id(ovq)] = (sc[:K], dc[:K], int(total), "gte")
                served = True
        if not served:
            still.append(qi)
    return still


def _verify_pruned(seg: Segment, vq: _VQuery, sc: np.ndarray, dc: np.ndarray,
                   total: int, window: int, K: int) -> Optional[tuple]:
    """Prove a clamped pruned result exact, or None -> rerun dense.

    The kernel saw only each term's impact head, so candidate partial
    scores may miss contributions (doc outside some term's head). Exact-
    rescore the candidates on host (the analog of Lucene re-walking a WAND
    candidate), then accept iff the `_unseen_bound` subset analysis proves
    no unseen doc can displace the served window. Totals become a lower
    bound (relation "gte"), the contract the reference's default
    track-total-hits cap already has."""
    pb = seg.postings.get(vq.field)
    dl = seg.doc_lens.get(vq.field)
    al = get_aligned(seg, vq.field)
    valid = np.isfinite(sc) & (dc >= 0)
    cand = dc[valid].astype(np.int64)
    if len(cand) == 0:
        # heads matched nothing; matches could still exist past the heads
        if any(vq.miss[i] > 0 for i in range(len(vq.rows))):
            return None
        return (sc[:K], dc[:K], total, "eq")
    exact, counts = _exact_rescore(seg, vq, cand)
    pass_msm = counts >= vq.msm_true
    n_pass = int(pass_msm.sum())
    exact_m = np.where(pass_msm, exact, -np.inf).astype(np.float32)
    # the unseen-doc in-head bound: the DEEPEST kernel partial. Zero when
    # the kernel window wasn't full — then every head-matched doc is
    # already a candidate and an unseen doc has no in-head part at all.
    # Impact-kernel partials are quantized-domain: + eps lifts them to a
    # sound exact-domain bound (eps == 0.0 on the tf·dl kernel)
    partial_k = (float(sc[valid][-1]) + vq.eps
                 if len(cand) == len(sc) else 0.0)
    bound = _unseen_bound(al, pb, dl, vq, partial_k)
    tie = _tie_key(seg, cand)
    order = np.lexsort((tie, -exact_m))
    theta = (float(exact_m[order[window - 1]]) if n_pass >= window
             else -np.inf)
    # >= not >: the frontier bounds are ATTAINED by real docs, so an unseen
    # doc can tie theta exactly and would deserve the window slot under the
    # doc-id tie-break — equality must escalate to the dense pass, UNLESS
    # the tie witness below proves every attaining doc sorts after the
    # window boundary (single-term case: score quantization makes boundary
    # ties the COMMON case, and escalating on them re-runs dense every
    # time). The witness argument needs the EXACT kernel domain, so
    # impact-frontier passes (eps > 0) always escalate on a tie; so do
    # reordered segments (tie is the ARRIVAL rank there, and the frontier
    # id witness only bounds the permuted-id order).
    if bound >= theta:
        if (vq.eps > 0.0 or tie is not cand
                or not _tie_serves(al, vq, theta, cand, order, window)):
            return None
    keep = order[pass_msm[order]][:K]
    sc2 = np.full(K, -np.inf, np.float32)
    dc2 = np.full(K, -1, np.int32)
    sc2[: len(keep)] = exact_m[keep]
    dc2[: len(keep)] = cand[keep]
    total_out = n_pass if vq.msm_true > 1 else total
    return (sc2, dc2, total_out, "gte")


def _verify_impact_exact(seg: Segment, vq: _VQuery, sc: np.ndarray,
                         dc: np.ndarray, total: int, window: int, K: int
                         ) -> Optional[tuple]:
    """Certify an UNCLAMPED impact-kernel frontier pass (heads were the
    full rows, so the kernel saw EVERY posting — but its partials live in
    the quantized domain and cannot serve directly). Candidates are
    exact-rescored; when the kernel window wasn't full the candidate set
    is every matching doc and the page is exact by construction;
    otherwise a seen-but-lost doc carries kernel partial <= the deepest
    extracted value, so exact <= that + eps — certify it under theta or
    escalate. Totals are exact either way (the kernel counts every
    matching doc)."""
    valid = np.isfinite(sc) & (dc >= 0)
    cand = dc[valid].astype(np.int64)
    if len(cand) == 0:
        return (sc[:K], dc[:K], total, "eq")    # truly empty result set
    exact, counts = _exact_rescore(seg, vq, cand)
    pass_msm = counts >= vq.msm_true
    n_pass = int(pass_msm.sum())
    exact_m = np.where(pass_msm, exact, -np.inf).astype(np.float32)
    order = np.lexsort((_tie_key(seg, cand), -exact_m))
    if len(cand) == len(sc):
        theta = (float(exact_m[order[window - 1]]) if n_pass >= window
                 else -np.inf)
        bound = float(sc[valid][-1]) + vq.eps
        # equality escalates: a lost doc's exact score can tie theta and
        # would deserve the slot under the doc-id tie-break
        if bound >= theta:
            return None
    keep = order[pass_msm[order]][:K]
    sc2 = np.full(K, -np.inf, np.float32)
    dc2 = np.full(K, -1, np.int32)
    sc2[: len(keep)] = exact_m[keep]
    dc2[: len(keep)] = cand[keep]
    return (sc2, dc2, total, "eq")


def _launch_pure(seg: Segment, ctx, lts: Sequence,
                 specs: Sequence[FastSpec], K: int) -> Optional[tuple]:
    """LAUNCH stage of the pure term-group path: vquery prep + the
    impact-head (pruned) kernel first pass, enqueued but unfetched.
    Returns opaque state for `_finish_pure`, or None to fall back."""
    prune = [bool(s.prune_ok) for s in specs]
    vq_lists = _prepare_vqueries(seg, ctx, lts, {}, prune=prune)
    if vq_lists is None:
        return None
    # frontier rung: the impact-head (pruned) kernel first pass
    with TRACER.span("fastpath.frontier", queries=len(lts)), \
            METRICS.timer("fastpath.frontier"):
        pending = _launch_pure_groups_async(seg, vq_lists, K)
    return (vq_lists, pending)


def _finish_pure(seg: Segment, ctx, lts: Sequence,
                 specs: Sequence[FastSpec], K: int,
                 state: tuple) -> Optional[List[Optional[dict]]]:
    """FETCH stage of the pure path: device sync of the frontier pass,
    then host verification and the escalation ladder (whose rungs launch
    their own follow-up device work synchronously — only the hard tail
    pays a sync here) and final assembly."""
    vq_lists, pending = state
    results = _fetch_pure_groups(pending, K,
                                 tie_aware=_seg_tie_aware(seg))
    redo = []
    # id(vq) whose served entry the verify/rescue rungs produced in exact
    # arrival order — _assemble's reorder tie handling skips these
    exact_ids = set()
    with TRACER.span("fastpath.verify"), METRICS.timer("fastpath.verify"):
        for qi, vqs in enumerate(vq_lists):
            if vqs is None or len(vqs) != 1 or not vqs[0].head:
                continue
            vq = vqs[0]
            if not vq.clamped and not vq.impact_pass:
                continue                # heads were the full rows: exact
            sc, dc, total, _ = results[id(vq)]
            if vq.clamped:
                ver = _verify_pruned(seg, vq, sc, dc, total,
                                     int(specs[qi].window or K), K)
            else:
                # impact kernel over full rows: exact-rescore + certify
                # against (deepest approx partial + eps)
                ver = _verify_impact_exact(seg, vq, sc, dc, total,
                                           int(specs[qi].window or K), K)
            if ver is None:
                redo.append(qi)
            else:
                results[id(vq)] = ver
                exact_ids.add(id(vq))
    # rescued CLAMPED queries only: `pruned_served` below counts clamped
    # heads, so rescued impact-frontier (unclamped) queries must not be
    # subtracted from it — they were never in its base (the counter is
    # monotonic; an unmatched subtraction drives it negative)
    rescued_clamped = 0
    if redo:
        # middle rung: the candidate-union rescore for ALL failed queries,
        # batched into as few device launches as their shape buckets allow
        # (host numpy under JAX_PLATFORMS=cpu — see _rescore_many)
        n_redo = len(redo)
        if _fr.RECORDER.enabled and _fr.current():
            _fr.RECORDER.record(_fr.current(), "fastpath.rung",
                                rung="phase2_rescore", queries=n_redo,
                                mode=rescore_mode())
        with TRACER.span("fastpath.phase2_rescore", queries=n_redo,
                         mode=rescore_mode()), \
                METRICS.timer("fastpath.phase2_rescore"):
            before = redo
            redo = _phase2_batch(seg, vq_lists, specs, results, redo, K)
        for qi in set(before) - set(redo):
            vq = vq_lists[qi][0]
            exact_ids.add(id(vq))
            if vq.clamped:
                rescued_clamped += 1
    if redo:
        # last rung before dense: ONE batched exact launch over the
        # quality-tier view (~1/8 the postings). Only the hard tail pays
        # it; a certify saves the 8x-bigger dense launch, a miss adds a
        # small fraction of the dense cost it was about to pay anyway
        n_redo = len(redo)
        if _fr.RECORDER.enabled and _fr.current():
            _fr.RECORDER.record(_fr.current(), "fastpath.rung",
                                rung="quality_tier", queries=n_redo)
        with TRACER.span("fastpath.quality_tier", queries=n_redo), \
                METRICS.timer("fastpath.quality_tier"):
            before = redo
            redo = _dview_rescue(seg, ctx, lts, specs, vq_lists, results,
                                 redo, K)
        for qi in set(before) - set(redo):
            vq = vq_lists[qi][0]
            exact_ids.add(id(vq))
            if vq.clamped:
                rescued_clamped += 1
    if redo:
        STATS.inc("pruned_escalated", len(redo))
        if _fr.RECORDER.enabled and _fr.current():
            _fr.RECORDER.record(_fr.current(), "fastpath.rung",
                                rung="dense_escalation", queries=len(redo))
        with TRACER.span("fastpath.dense", queries=len(redo)), \
                METRICS.timer("fastpath.dense"):
            dense_lists = _prepare_vqueries(seg, ctx,
                                            [lts[qi] for qi in redo], {})
            if dense_lists is None:
                dense_lists = [None] * len(redo)
            for qi, dvqs in zip(redo, dense_lists):
                vq_lists[qi] = dvqs
            results.update(_launch_pure_groups(seg, dense_lists, K))
    STATS.inc("pruned_served", sum(
        1 for vqs in vq_lists
        if vqs is not None and len(vqs) == 1 and vqs[0].head
        and vqs[0].clamped) - rescued_clamped)
    return _assemble(vq_lists, results, K, seg=seg, exact_ids=exact_ids)


def _run_pure(seg: Segment, ctx, lts: Sequence, specs: Sequence[FastSpec],
              K: int) -> Optional[List[Optional[dict]]]:
    """The pure term-group path, synchronous: pruned first pass, host
    verification, dense rerun for the (rare) queries whose bound check
    fails. Launch/fetch split available via `_launch_pure`/`_finish_pure`
    (the serving pipeline's seam)."""
    state = _launch_pure(seg, ctx, lts, specs, K)
    if state is None:
        return None
    return _finish_pure(seg, ctx, lts, specs, K, state)


def _assemble(vq_lists, results: dict, K: int, transform=None,
              seg=None, exact_ids=frozenset()) -> List[Optional[dict]]:
    """Reassemble per-query outputs from per-kernel-row results (chunked
    queries merge their chunk top-Ks on host; stable merge: score desc,
    doc asc on ties, matching the kernel — arrival-rank ties on
    reordered segments when `seg` is passed). `exact_ids`: id(vq) of
    entries the verify/rescue rungs already produced in exact arrival
    order (they skip the reorder tie handling)."""
    tie_aware = seg is not None and _seg_tie_aware(seg)
    out: List[Optional[dict]] = []
    for qi, vqs in enumerate(vq_lists):
        if vqs is None:
            out.append(None)
            continue
        rel = "eq"
        if len(vqs) == 1:
            entry = results[id(vqs[0])]
            sc, dc, total = entry[0], entry[1], entry[2]
            if len(entry) > 3:
                rel = entry[3]
            if tie_aware and id(vqs[0]) not in exact_ids:
                # kernel-verbatim window on a BP-reordered segment: the
                # kernel broke score ties by PERMUTED id — re-break by
                # arrival rank (reorder parity contract). The deep
                # K_launch extraction (tie_aware launch) makes this sort
                # see past the page boundary. Entries the verify/rescue
                # rungs produced (`exact_ids`) are already arrival-
                # ordered exact pages and skip this.
                sc, dc, full = _arrival_sort(seg, sc, dc)
                if _tie_cut_at_edge(sc, full, K):
                    # decline: the general path widens its extraction
                    # window until the boundary class is whole
                    STATS.inc("reorder_tie_fallback")
                    out.append(None)
                    continue
                sc, dc = sc[:K], dc[:K]
        else:
            parts = [results[id(v)] for v in vqs]
            sc_all = np.concatenate([p[0] for p in parts])
            dc_all = np.concatenate([p[1] for p in parts])
            total = sum(p[2] for p in parts)
            if seg is not None:
                key = np.where(dc_all >= 0,
                               _tie_key(seg, np.maximum(dc_all, 0)),
                               np.int64(np.iinfo(np.int64).max))
            else:
                key = dc_all
            order = np.lexsort((key, -sc_all))[:K]
            sc = sc_all[order]
            dc = dc_all[order]
            if tie_aware and _chunk_tie_ambiguous(parts, sc, dc, K):
                STATS.inc("reorder_tie_fallback")
                out.append(None)
                continue
        if transform is not None:
            sc = transform(qi, sc)
        total_i = int(total)
        ms = float(sc[0]) if total_i > 0 and np.isfinite(sc[0]) else -np.inf
        out.append({"topk_key": sc, "topk_idx": dc, "topk_scores": sc,
                    "total": total_i, "max_score": ms, "total_rel": rel})
    return out


# ---------------------------------------------------------------------
# bool/filtered path: filter doc lists + weighted-threshold kernel rows
# ---------------------------------------------------------------------

class FilterList:
    """Aligned sorted doc-id list for one (segment, filter conjunction) —
    the fastpath analog of the reference's cached filter bitsets
    (IndicesQueryCache): built once from the XLA path's dense masks, then
    every query carrying this filter rides it as a merge slot (selective
    filters) or triggers filter-specialized postings (dense filters)."""

    __slots__ = ("host_docs", "d_docs", "n", "nbytes", "mask", "key",
                 "hits", "__weakref__")

    def __init__(self, host_docs: np.ndarray, d_docs, n: int, nbytes: int,
                 mask: np.ndarray, key):
        self.host_docs = host_docs
        self.d_docs = d_docs
        self.n = n
        self.nbytes = nbytes
        self.mask = mask          # dense bool[ndocs] (for materialization)
        self.key = key
        self.hits = 0


_MAX_FILTER_LISTS = 32      # per segment


def _filter_list(seg: Segment, ctx, clauses) -> Optional[FilterList]:
    """Combined (ANDed) filter doc list for [(node, negated), ...]; cached
    per segment (LRU) keyed by the clauses' mask-cache digests — a cache hit
    costs only the host-cheap spec hashing, no mask materialization. None ->
    fall back (a clause's params were too big to hash)."""
    import collections

    import jax

    from . import compiler as C

    cache = seg.__dict__.setdefault("_fastpath_filters",
                                    collections.OrderedDict())
    key_parts = []
    prepped = []
    for node, neg in clauses:
        local: dict = {}
        spec = C.prepare(node, seg, ctx, local)
        mkey, mapping = C._filter_cache_key(spec, local, seg)
        if mkey is None:
            return None
        key_parts.append((mkey, neg))
        prepped.append((mkey, spec, local, mapping, neg))
    key = tuple(key_parts)
    fl = cache.get(key)
    if fl is not None:
        cache.move_to_end(key)
        return fl
    nd = seg.ndocs
    combined = np.ones(nd, bool)
    for (node, neg), (mkey, spec, local, mapping, _n) in zip(clauses,
                                                             prepped):
        mask = np.asarray(C._mask_for_key(mkey, spec, local, mapping, seg,
                                          needs=C.node_needs(node)))
        m = mask[:nd].astype(bool)
        combined &= ~m if neg else m
    docs = np.nonzero(combined)[0].astype(np.int32)
    n = len(docs)
    total = ((n + LANES - 1) // LANES) * LANES + MAX_L
    buf = np.full(total, INT_SENTINEL, np.int32)
    buf[:n] = docs
    # keep the dense mask only when this filter could ever take the
    # materialized-postings route; breaker-charge what we actually retain
    dense_capable = (n > _MATERIALIZE_MIN_DOCS
                     and n * _MATERIALIZE_DENSITY > seg.ndocs)
    mask_kept = combined if dense_capable else None
    fl = FilterList(docs, jax.device_put(buf), n, buf.nbytes, mask_kept, key)
    from ..obs.hbm_ledger import LEDGER
    charged = buf.nbytes + (combined.nbytes if dense_capable else 0)
    LEDGER.register("filter_list", charged, owner=fl, segment=seg,
                    label=f"fastpath-filter[{seg.name}]")
    while len(cache) >= _MAX_FILTER_LISTS:
        cache.popitem(last=False)
    cache[key] = fl
    return fl


# ---------------------------------------------------------------------
# dense filters: filter-specialized postings
# ---------------------------------------------------------------------
#
# The list-slot intersection pays O(filter size) merge work per query —
# right for selective filters (Lucene's conjunction likewise walks the
# rarer side), but a dense guardrail filter (status:published over half
# the corpus) would cost more than the scoring itself. The TPU answer is
# layout specialization: pre-intersect the postings with the filter ONCE
# per (segment, field, filter), realign, and run every later query at
# full pure-kernel speed — beating the reference, which re-walks its
# cached bitset on every query (reference IndicesQueryCache +
# ConjunctionDISI). Materialized on the filter's second use (dense +
# hot), byte-bounded global LRU.

_MATERIALIZE_MIN_DOCS = 1 << 18    # absolute floor
_MATERIALIZE_DENSITY = 8           # n * density > ndocs -> "dense" (>12.5%)
_FILTERED_MAX_BYTES = 6 << 30
_FILTERED_LRU: "OrderedDict[tuple, FilteredPostings]" = __import__(
    "collections").OrderedDict()
_FILTERED_BYTES = [0]
# msearch's per-body fallback runs searches on a thread pool; the LRU's
# move_to_end/popitem and the byte counter are not atomic under that
_FILTERED_LOCK = __import__("threading").RLock()


class FilteredPostings:
    """Filter-specialized aligned postings for one (segment, field,
    filter): the term rows of `field` restricted to filter-passing docs."""

    __slots__ = ("al", "starts", "host_docs", "host_tfs", "nbytes",
                 "view", "__weakref__")

    def __init__(self, al: AlignedPostings, starts: np.ndarray,
                 host_docs: np.ndarray, host_tfs: np.ndarray, nbytes: int):
        self.al = al
        self.starts = starts       # i64[nterms+1] filtered CSR row bounds
        self.host_docs = host_docs  # i32 filtered doc ids (chunk windows)
        self.host_tfs = host_tfs    # f32 filtered tfs (pruned-path rescore)
        self.nbytes = nbytes
        self.view = None            # lazy FilteredSegView (pruned bool path)


def _purge_filtered_for_uid(uid: int) -> None:
    with _FILTERED_LOCK:
        for k in [k for k in _FILTERED_LRU if k[0] == uid]:
            _FILTERED_BYTES[0] -= _FILTERED_LRU[k].nbytes
            del _FILTERED_LRU[k]


def _filtered_postings(seg: Segment, field: str, fl: FilterList
                       ) -> Optional[FilteredPostings]:
    import jax

    key = (seg.uid, field, fl.key)
    with _FILTERED_LOCK:
        fp = _FILTERED_LRU.get(key)
        if fp is not None:
            _FILTERED_LRU.move_to_end(key)
            return fp
    if get_aligned(seg, field) is None:     # validates tf/dl pack bounds
        return None
    pb = seg.postings.get(field)
    dl = seg.doc_lens.get(field)
    keep = fl.mask[pb.doc_ids]
    kc = np.zeros(len(pb.doc_ids) + 1, np.int64)
    np.cumsum(keep, out=kc[1:])
    new_starts = kc[pb.starts]
    new_docs = pb.doc_ids[keep]
    tfs = pb.tfs[keep]
    dl_of = (dl[new_docs].astype(np.int64) if dl is not None
             else np.zeros(len(new_docs), np.int64))
    packed = ((tfs.astype(np.int64) << DL_BITS) | dl_of).astype(np.int32)
    a_starts, a_docs, a_packed = align_csr_rows(new_starts, new_docs, packed,
                                                margin=MAX_L,
                                                alignment=LANES)
    nbytes = a_docs.nbytes + a_packed.nbytes
    al = AlignedPostings((a_starts[:-1] // LANES).astype(np.int64),
                         np.diff(new_starts).astype(np.int64),
                         jax.device_put(a_docs), jax.device_put(a_packed),
                         nbytes)
    fp = FilteredPostings(al, new_starts, new_docs, tfs, nbytes)
    from ..obs.hbm_ledger import LEDGER
    LEDGER.register("filtered_postings", nbytes, owner=fp, segment=seg,
                    label=f"fastpath-filtered[{seg.name}][{field}]")
    if not hasattr(seg, "_filtered_fin"):
        import weakref
        seg._filtered_fin = weakref.finalize(seg, _purge_filtered_for_uid,
                                             seg.uid)
    with _FILTERED_LOCK:
        # two threads can race the same miss: keep the winner so the byte
        # counter never double-counts one key (the loser's breaker charge is
        # released by its weakref finalizer when `fp` is dropped)
        prev = _FILTERED_LRU.get(key)
        if prev is not None:
            _FILTERED_LRU.move_to_end(key)
            return prev
        _FILTERED_LRU[key] = fp
        _FILTERED_BYTES[0] += nbytes
        while _FILTERED_BYTES[0] > _FILTERED_MAX_BYTES \
                and len(_FILTERED_LRU) > 1:
            _k, _v = _FILTERED_LRU.popitem(last=False)
            _FILTERED_BYTES[0] -= _v.nbytes
    return fp


class FilteredSegView:
    """Segment facade over filter-specialized postings: the filtered CSR
    (ORIGINAL doc ids) presented as a one-field segment, so the PURE
    pipeline — impact heads, remainder frontiers, verified pruning — runs
    unchanged on filtered bool queries. Doc lens/live come from the real
    segment (doc ids are original); docs outside the filter appear in no
    row, so match counts and totals are filtered automatically."""

    def __init__(self, seg: Segment, field: str, fp: "FilteredPostings"):
        from ..index.segment import PostingsBlock

        pb = seg.postings[field]
        self.name = f"{seg.name}|filtered"
        self.ndocs = seg.ndocs
        self.ndocs_pad = seg.ndocs_pad
        self.live_count = seg.live_count
        self.postings = {field: PostingsBlock(
            field=field, vocab=pb.vocab, terms=pb.terms,
            starts=fp.starts.astype(np.int64), doc_ids=fp.host_docs,
            tfs=fp.host_tfs)}
        self.doc_lens = seg.doc_lens
        # doc ids are original, so the parent's arrival tie ranks apply
        # verbatim (reorder parity: ties must not break on permuted ids)
        self.tie_ranks = seg.tie_ranks


def _filtered_view(seg: Segment, field: str, fp: "FilteredPostings",
                   key) -> FilteredSegView:
    with _FILTERED_LOCK:
        if fp.view is None:
            view = FilteredSegView(seg, field, fp)
            # build the view's aligned layout eagerly and charge it to the
            # SAME byte budget as fp itself: it is a second device copy of
            # the filtered postings, and the LRU cap must see both. Only
            # account while fp is still a live LRU member — a concurrent
            # eviction already subtracted fp.nbytes, and inflating the
            # counter for a dead entry would never be undone
            al = get_aligned(view, field)
            if al is not None and _FILTERED_LRU.get(key) is fp:
                fp.nbytes += al.nbytes
                _FILTERED_BYTES[0] += al.nbytes
            fp.view = view
    return fp.view


class _PseudoLT:
    """LTerms-shaped adapter for a family-only bool spec, so it can ride
    the pure pruned pipeline over a FilteredSegView."""

    def __init__(self, spec: FastSpec):
        self.field = spec.field
        self.terms = [t for t, _w, _c in spec.slots]
        self.weights = np.asarray([w for _t, w, _c in spec.slots],
                                  np.float32)
        self.raw_boosts = self.weights
        # all-required slots (operator=and) == msm over every term
        self.msm = (len(spec.slots) if spec.n_required == len(spec.slots)
                    else max(int(spec.fam_msm), 1))
        self.sim = spec.sim
        self.has_norms = spec.has_norms
        self.aux = None


def _family_only(spec: FastSpec) -> bool:
    """bool spec == a single term group + filters, where the pass rule is
    a plain minimum-match count: either one counted family (shoulds /
    msm), or ALL slots required (operator=and -> msm = nterms). Both are
    a pure msm term group over the filtered doc set."""
    if not (spec.kind == "bool" and spec.filter_clauses
            and spec.const_score is None and spec.field is not None
            and len(spec.slots) > 0
            and spec.sim is not None and spec.sim.sim_id == ops.SIM_BM25):
        return False
    counted_family = (spec.fam_msm >= 1
                      and all(cw == 1 for _t, _w, cw in spec.slots))
    all_required = (spec.n_required == len(spec.slots)
                    and spec.fam_msm == 0)
    return counted_family or all_required


def _dense_hot(seg: Segment, fl: FilterList, nslots: int) -> bool:
    """Materialize when the filter is dense-capable (mask retained) AND
    either repeated (hits counted AFTER this check, so >=1 means second
    use) or too large for the list path at all — falling back to the XLA
    plan there would cost far more than one pre-intersection."""
    if fl.mask is None:
        return False
    ts = next_pow2(max(nslots, 1), floor=1)
    list_cap = MAX_CHUNKS * (MAX_TL // (2 * ts))
    return fl.hits >= 1 or fl.n > list_cap // 2


_dummy_hbm_arr = None


def _dummy_hbm():
    """Minimal aligned HBM operand for the unused buffer slots."""
    global _dummy_hbm_arr
    if _dummy_hbm_arr is None:
        import jax
        # one 4KB process-lifetime sentinel buffer; attributing it
        # would be noise, not accounting
        _dummy_hbm_arr = jax.device_put(  # oslint: disable=OSL506
            np.full(HBM_ALIGN, INT_SENTINEL, np.int32))
    return _dummy_hbm_arr


class _BVQuery:
    """One bool-kernel row: a whole query, or one doc-range chunk of it."""

    __slots__ = ("qi", "TS", "T", "L", "filtered", "rowstarts", "nrows",
                 "lens", "skips", "weights", "cw", "thresh", "avgdl", "dlo",
                 "dhi", "field", "k1", "b_eff", "fl", "albuf")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def _prepare_bool_vqueries(seg: Segment, ctx, specs: Sequence[FastSpec],
                           avgdl_cache: dict
                           ) -> List[Optional[List[_BVQuery]]]:
    out: List[Optional[List[_BVQuery]]] = []
    for qi, spec in enumerate(specs):
        fl = None
        fp = None
        nslots = len(spec.slots)
        if spec.filter_clauses:
            fl = _filter_list(seg, ctx, spec.filter_clauses)
            if fl is None:
                out.append(None)
                continue
            # specialized postings only see docs that match SOME term, so
            # the route is sound only when passing requires a term match
            # (required slot or a counted family) — a bonus-only bool's
            # hits are the whole filter and need the filter slot
            needs_term = spec.n_required > 0 or spec.fam_msm >= 1
            if (nslots and needs_term and spec.field is not None
                    and _dense_hot(seg, fl, nslots)):
                # dense hot filter: run on filter-specialized postings at
                # full kernel speed instead of merging a huge doc list
                fp = _filtered_postings(seg, spec.field, fl)
            fl.hits += 1
        TS = next_pow2(max(nslots, 1), floor=1)
        filtered = fl is not None and fp is None
        T = 2 * TS if filtered else TS
        al = pb = None
        if nslots:
            al = fp.al if fp is not None else get_aligned(seg, spec.field)
            pb = seg.postings.get(spec.field)
            if al is None or pb is None:
                out.append(None)
                continue
        weights = np.zeros(TS, np.float32)
        cw = np.zeros(T, np.float32)
        slot_descs: List[Optional[Tuple[np.ndarray, int]]] = [None] * T
        for i, (term, w, cwv) in enumerate(spec.slots):
            weights[i] = w
            cw[i] = cwv
            r = pb.row(term)
            if r < 0:
                continue
            if fp is not None:
                a, b = int(fp.starts[r]), int(fp.starts[r + 1])
                if a < b:
                    slot_descs[i] = (fp.host_docs[a:b],
                                     int(al.starts_rows[r]) * LANES)
            else:
                slot_descs[i] = _term_slot(al, pb, r)
        if filtered:
            cw[TS] = REQ_W
            slot_descs[TS] = (fl.host_docs, 0)
        thresh = REQ_W * (spec.n_required + (1 if filtered else 0)) \
            + spec.fam_msm
        if spec.field is not None and spec.field not in avgdl_cache:
            avgdl_cache[spec.field] = np.float32(ctx.avgdl(spec.field))
        avgdl = avgdl_cache.get(spec.field, np.float32(1.0))
        k1 = float(spec.sim.k1) if spec.sim is not None else 1.2
        b_eff = (float(spec.sim.b)
                 if spec.sim is not None and spec.has_norms else 0.0)
        chunks = _chunk_slots(slot_descs, seg.ndocs, T, nchunk=1)
        if chunks is None:
            out.append(None)
            continue
        vqs = []
        for dlo, dhi, rowstarts, nrows, lens, skips in chunks:
            L = int(max(int(nrows.max()), HBM_ALIGN // LANES)) * LANES
            vqs.append(_BVQuery(qi=qi, TS=TS, T=T, L=L, filtered=filtered,
                                rowstarts=rowstarts, nrows=nrows, lens=lens,
                                skips=skips, weights=weights, cw=cw,
                                thresh=np.float32(thresh), avgdl=avgdl,
                                dlo=dlo, dhi=dhi, field=spec.field, k1=k1,
                                b_eff=b_eff, fl=fl if filtered else None,
                                albuf=al))
        out.append(vqs)
    return out


def _launch_bool(seg: Segment, ctx, specs: Sequence[FastSpec], K: int
                 ) -> tuple:
    """LAUNCH stage of the bool/filtered path: one kernel enqueue per
    shape group, no device sync. Returns state for `_finish_bool`."""
    vq_lists = _prepare_bool_vqueries(seg, ctx, specs, {})
    # BP-reordered segment: extract the full lane window so _assemble's
    # arrival-rank re-sort sees past the page boundary (reorder parity —
    # the kernel's own tie order is the permuted id)
    K_extract = max(K, LANES) if _seg_tie_aware(seg) else K
    groups = {}
    for vqs in vq_lists:
        if vqs is None:
            continue
        for vq in vqs:
            gk = (id(vq.albuf), vq.TS, vq.filtered,
                  id(vq.fl) if vq.fl is not None else None, vq.k1, vq.b_eff)
            groups.setdefault(gk, []).append(vq)
    pending = []
    for (_alid, TS, filtered, _flid, k1, b_eff), gvqs in groups.items():
        al = gvqs[0].albuf
        if al is not None:
            d_docs, d_tfdl = al.d_docs, al.d_tfdl
        else:
            d_docs = d_tfdl = _dummy_hbm()
        fl = gvqs[0].fl
        filt = fl.d_docs if fl is not None else _dummy_hbm()
        L = max(v.L for v in gvqs)
        rowstarts = np.stack([v.rowstarts for v in gvqs])
        nrows = np.stack([v.nrows for v in gvqs])
        lens = np.stack([v.lens for v in gvqs])
        skips = np.stack([v.skips for v in gvqs])
        weights = np.stack([v.weights for v in gvqs])
        cw = np.stack([v.cw for v in gvqs])
        thresh = np.array([[v.thresh] for v in gvqs], np.float32)
        avg = np.array([[v.avgdl] for v in gvqs], np.float32)
        dlo = np.array([[v.dlo] for v in gvqs], np.int32)
        dhi = np.array([[v.dhi] for v in gvqs], np.int32)
        METRICS.counter("fastpath.launches").inc()
        cost = _qc.current()
        if cost is not None:
            cost.note_actual(int(nrows.sum()) * LANES * 8,
                             int(lens.sum()), K_extract * len(gvqs),
                             path="kernel_bool")
        pending.append((gvqs, fused_bm25_bool_topk(
            d_docs, d_tfdl, filt, rowstarts, nrows, lens, skips, weights,
            cw, thresh, avg, dlo, dhi, TS=TS, L=L, K=K_extract, k1=k1,
            b=b_eff, filtered=filtered)))
    return (vq_lists, pending)


def _finish_bool(specs: Sequence[FastSpec], K: int, state: tuple,
                 seg=None) -> List[Optional[dict]]:
    """FETCH stage of the bool/filtered path: one transfer for all
    groups, then boost/const-score transform and assembly."""
    vq_lists, pending = state
    import jax
    fetched = jax.device_get([arrs for _gvqs, arrs in pending])
    results = {}
    for (gvqs, _), (scores, docs, totals) in zip(pending, fetched):
        for j, vq in enumerate(gvqs):
            # keep every extracted lane (K on plain segments, the deep
            # K_extract window on reordered ones — _assemble cuts to K
            # after its arrival-rank re-sort)
            results[id(vq)] = (scores[j], docs[j], int(totals[j][0]))

    def transform(qi, sc):
        spec = specs[qi]
        finite = np.isfinite(sc)
        if spec.const_score is not None:
            return np.where(finite, np.float32(spec.const_score), -np.inf)
        if spec.boost != 1.0:
            return np.where(finite, sc * np.float32(spec.boost), -np.inf)
        return sc

    return _assemble(vq_lists, results, K, transform, seg=seg)


def _run_bool(seg: Segment, ctx, specs: Sequence[FastSpec], K: int
              ) -> List[Optional[dict]]:
    return _finish_bool(specs, K, _launch_bool(seg, ctx, specs, K),
                        seg=seg)


def segment_search(seg: Segment, ctx, spec: FastSpec, k: int
                   ) -> Optional[dict]:
    """Run the fused kernel for one FastSpec over one segment. Returns a
    dict shaped like compiler.run_segment output, or None to fall back."""
    res = batch_search(seg, ctx, [spec], k)
    return res[0] if res else None


# ---------------------------------------------------------------------
# concurrent segment search, the TPU way: ONE launch per shard
# ---------------------------------------------------------------------
#
# The reference parallelizes a many-segment shard across threads
# (`search/query/ConcurrentQueryPhaseSearcher.java`). A TPU doesn't want
# more threads — it wants fewer, larger launches: concatenate the shard's
# segment postings into ONE aligned layout (doc ids offset per segment)
# and run the whole shard as a single kernel invocation, then map hits
# back to (segment, local doc). Built lazily per (shard, generation),
# pure term-group specs only (bool/filter specs need per-segment column
# state and keep the per-segment loop).

class ShardView:
    """Segment-shaped facade over a shard's concatenated postings — just
    the attribute surface the pure fastpath touches."""

    def __init__(self, name: str, segments: List[Segment],
                 seg_ords: Optional[List[int]] = None):
        self.name = name
        self.segments = segments
        # original positions in the engine's segment list (the view may
        # skip empty segments, and downstream Candidates index that list)
        self.seg_ords = seg_ords or list(range(len(segments)))
        self.seg_bases = np.cumsum([0] + [s.ndocs for s in segments])
        self.ndocs = int(self.seg_bases[-1])
        self.ndocs_pad = next_pow2(max(self.ndocs, 1))
        self.live_count = sum(s.live_count for s in segments)
        self.postings: dict = {}
        self.doc_lens: dict = {}
        self._built: set = set()

    def ensure_field(self, field: str) -> bool:
        from ..index.segment import PostingsBlock
        from ..parallel.spmd import _concat_shard

        if field in self._built:
            return field in self.postings
        self._built.add(field)
        if not any(field in s.postings for s in self.segments):
            return False
        m = _concat_shard(self.segments, field)
        self.postings[field] = PostingsBlock(
            field=field, vocab=list(m["terms"]), terms=m["terms"],
            starts=np.asarray(m["starts"], np.int64),
            doc_ids=m["doc_ids"], tfs=m["tfs"])
        if any(s.doc_lens.get(field) is not None for s in self.segments):
            self.doc_lens[field] = m["dl"]
        return True

    def locate(self, view_doc: int):
        """view-space doc -> (engine seg_ord, segment, local doc)."""
        vi = int(np.searchsorted(self.seg_bases, view_doc, "right") - 1)
        return (self.seg_ords[vi], self.segments[vi],
                int(view_doc - self.seg_bases[vi]))

    def tie_ranks(self) -> Optional[np.ndarray]:
        """Concatenated arrival tie ranks over the member segments, or
        None when no member is reordered. Members sit in engine creation
        order with disjoint ascending seq ranges, so base + member-rank
        is the view-global arrival rank."""
        if "_tie_rank" not in self.__dict__:
            per = [s.tie_ranks() for s in self.segments]
            if all(p is None for p in per):
                self.__dict__["_tie_rank"] = None
            else:
                parts = []
                for s, p, base in zip(self.segments, per, self.seg_bases):
                    local = (p if p is not None
                             else np.arange(s.ndocs, dtype=np.int64))
                    parts.append(int(base) + local)
                self.__dict__["_tie_rank"] = (
                    np.concatenate(parts) if parts
                    else np.zeros(0, np.int64))
        return self.__dict__["_tie_rank"]


def shard_view(searcher) -> Optional[ShardView]:
    """Cached per (engine, generation-ish identity of the segment list):
    rebuilt whenever refresh/merge changes the segment set."""
    eng = searcher.engine
    pairs = [(i, s) for i, s in enumerate(eng.segments)
             if s.live_count > 0]
    if len(pairs) < 2:
        return None
    if any(s.live_count != s.ndocs for _, s in pairs):
        return None     # deletes: per-segment loop (same rule as the kernel)
    key = tuple(id(s) for _, s in pairs)
    cached = eng.__dict__.get("_shard_view")
    if cached is not None and cached[0] == key:
        return cached[1]
    view = ShardView(f"view:{id(eng):x}", [s for _, s in pairs],
                     [i for i, _ in pairs])
    eng.__dict__["_shard_view"] = (key, view)
    return view


def shard_search(searcher, ctx, spec: FastSpec, k: int
                 ) -> Optional[Tuple[ShardView, dict]]:
    """One kernel launch over ALL the shard's segments for a pure spec;
    None -> per-segment loop."""
    if spec.kind != "pure":
        return None
    view = shard_view(searcher)
    if view is None or not view.ensure_field(spec.lt.field):
        return None
    out = batch_search(view, ctx, [spec], k, count_stats=False)
    if out is None or out[0] is None:
        return None
    STATS.inc("pure_served")
    STATS.inc("shard_view_served")
    return view, out[0]


def launch_batch(seg: Segment, ctx, specs: Sequence[FastSpec], k: int,
                 count_stats: bool = True):
    """LAUNCH stage of the batched kernel path: many FastSpecs over ONE
    segment in as few kernel launches as possible (grid over queries —
    the server-side query batching a TPU search tier runs on). Pure term
    groups and the filtered-pure rung enqueue their frontier kernels
    here, unfetched; the returned `LaunchHandle.fetch()` syncs them and
    runs the verify/escalation ladder plus the leftover bool shapes
    (whose eligibility is only known post-fetch) and returns the per-spec
    result list (None entries -> per-query fallback). Returns None when
    the segment can't take the fast path at all."""
    from .launch import LaunchHandle

    if seg.live_count != seg.ndocs:
        return None
    K = min(next_pow2(max(k, 16)), MAX_K)
    pure_idx = [i for i, s in enumerate(specs) if s.kind == "pure"]
    bool_idx = [i for i, s in enumerate(specs) if s.kind == "bool"]
    pure_state = None
    if pure_idx:
        pure_state = _launch_pure(seg, ctx,
                                  [specs[i].lt for i in pure_idx],
                                  [specs[i] for i in pure_idx], K)
    filtered_launched = []
    if bool_idx:
        # family-only bool specs over a dense hot filter ride the PURE
        # pruned pipeline on the filter-specialized postings view —
        # impact heads cut the per-query work from O(filtered df) to
        # O(L_HEAD) exactly like unfiltered match queries
        filtered_launched = _launch_filtered_pure_batch(
            seg, ctx, [(i, specs[i]) for i in bool_idx], K)

    def _finish():
        out: List[Optional[dict]] = [None] * len(specs)
        if pure_state is not None:
            rs = _finish_pure(seg, ctx, [specs[i].lt for i in pure_idx],
                              [specs[i] for i in pure_idx], K, pure_state)
            if rs is not None:
                for i, r in zip(pure_idx, rs):
                    out[i] = r
        rem = list(bool_idx)
        if filtered_launched:
            served = _finish_filtered_pure_batch(ctx, K, filtered_launched)
            for i, r in served.items():
                out[i] = r
            rem = [i for i in rem if i not in served]
        if rem:
            for i, r in zip(rem, _run_bool(seg, ctx,
                                           [specs[i] for i in rem], K)):
                out[i] = r
        if count_stats:
            count_served(specs, out)
        return out

    return LaunchHandle(_finish, kind="fastpath")


def batch_search(seg: Segment, ctx, specs: Sequence[FastSpec], k: int,
                 count_stats: bool = True
                 ) -> Optional[List[Optional[dict]]]:
    """Synchronous batched kernel path: `launch_batch(...).fetch()`."""
    handle = launch_batch(seg, ctx, specs, k, count_stats)
    if handle is None:
        return None
    return handle.fetch()


def _launch_filtered_pure_batch(seg: Segment, ctx, idx_specs,
                                K: int) -> list:
    """LAUNCH stage of the filtered-pure rung: serve family-only filtered
    bool specs through the pure pruned pipeline over their
    FilteredSegViews, ONE frontier launch per (field, filter) group so an
    msearch batch pays one launch per view, not one per query. Returns
    pending group launches for `_finish_filtered_pure_batch`."""
    groups: dict = {}
    for i, spec in idx_specs:
        if not _family_only(spec):
            continue
        fl = _filter_list(seg, ctx, spec.filter_clauses)
        if fl is None or not _dense_hot(seg, fl, len(spec.slots)):
            continue
        fp = _filtered_postings(seg, spec.field, fl)
        if fp is None:
            continue
        key = (seg.uid, spec.field, fl.key)
        groups.setdefault(key, (spec.field, fl, fp, []))[3].append((i, spec))
    launched = []
    for key, (field, fl, fp, items) in groups.items():
        view = _filtered_view(seg, field, fp, key)
        lts = [_PseudoLT(s) for _, s in items]
        sspecs = [s for _, s in items]
        state = _launch_pure(view, ctx, lts, sspecs, K)
        if state is None:
            continue
        launched.append((view, fl, items, lts, sspecs, state))
    return launched


def _finish_filtered_pure_batch(ctx, K: int, launched: list) -> dict:
    """FETCH stage of the filtered-pure rung. -> {spec index: result
    dict}; missing indices take the regular bool path."""
    out: dict = {}
    for view, fl, items, lts, sspecs, state in launched:
        res = _finish_pure(view, ctx, lts, sspecs, K, state)
        if res is None:
            continue
        for (i, spec), r in zip(items, res):
            if r is None:
                continue   # the bool fallback will count this query's hit
            fl.hits += 1
            if spec.boost != 1.0:
                sc = r["topk_scores"]
                sc = np.where(np.isfinite(sc),
                              sc * np.float32(spec.boost),
                              sc).astype(np.float32)
                r = dict(r, topk_scores=sc, topk_key=sc,
                         max_score=(float(sc[0]) if r["total"] > 0
                                    and np.isfinite(sc[0]) else -np.inf))
            out[i] = r
    return out


def count_served(specs: Sequence[FastSpec], outs: Sequence[Optional[dict]]
                 ) -> None:
    served = fell = 0
    for spec, r in zip(specs, outs):
        if r is None:
            STATS.inc("fallback")
            fell += 1
        else:
            STATS.inc("pure_served" if spec.kind == "pure"
                      else "bool_served")
            served += 1
    if _fr.RECORDER.enabled and _fr.current():
        _fr.RECORDER.record(_fr.current(), "fastpath.served",
                            served=served, fallback=fell)
