"""Production Pallas fast path for the term/match hot path.

Routes single-group BM25 term queries (term / terms / match / multi-term
match with minimum_should_match — the traffic Lucene serves through
BulkScorer, reference `search/query/QueryPhase.java`) through the fused
Pallas kernel `ops/pallas_bm25.fused_bm25_topk_tfdl` instead of the XLA
gather→scatter path. The XLA path stays as the general fallback for complex
plans, segments with deletes, non-BM25 similarities, or posting rows larger
than the VMEM bucket cap.

Per (segment, field) we lazily build a DMA-friendly postings layout:
1024-element-aligned CSR rows of (doc_id i32, tf<<21|dl i32). The packing is
lossless (tf < 2048, dl < 2^21 — segments violating it are ineligible), and
the kernel evaluates the SAME f32 BM25 expression as the XLA path with avgdl
as a query-time scalar, so both paths rank identically.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..index.segment import Segment, next_pow2
from ..ops import scoring as ops
from ..ops.pallas_bm25 import (DL_BITS, DL_MAX, HBM_ALIGN, LANES, TF_MAX,
                               align_csr_rows, fused_bm25_topk_tfdl)

MAX_T = 8            # pow2-padded term slots per query group
MAX_L = 1 << 16      # per-term VMEM bucket cap (elements)
MAX_TL = 1 << 17     # T_pad * L cap (~16MB VMEM incl. merge working set)
MAX_K = 128          # top-k lanes the kernel returns
MAX_CHUNKS = 64      # doc-range split bound for huge posting rows
INT_MAX = np.int32(2**31 - 1)

_enabled = True      # flipped by tests / OPENSEARCH_TPU_NO_FASTPATH

# optional memory accounting set by the Node (utils/breaker.py): charged
# before aligned arrays go to device, released when the segment is GC'd
# (segments are immutable and replaced on refresh/merge)
_breaker = None


def set_breaker(breaker) -> None:
    global _breaker
    _breaker = breaker


def set_enabled(flag: bool) -> None:
    global _enabled
    _enabled = flag


_backend_ok = None


def enabled() -> bool:
    import os
    global _backend_ok
    if _backend_ok is None:
        import jax
        _backend_ok = jax.default_backend() == "tpu"
    return (_enabled and _backend_ok
            and not os.environ.get("OPENSEARCH_TPU_NO_FASTPATH"))


class AlignedPostings:
    """Device-resident aligned (doc, tf·dl) postings for one segment field."""

    __slots__ = ("starts_rows", "lens", "d_docs", "d_tfdl", "nbytes")

    def __init__(self, starts_rows: np.ndarray, lens: np.ndarray,
                 d_docs, d_tfdl, nbytes: int):
        self.starts_rows = starts_rows    # i64[nterms] aligned start / LANES
        self.lens = lens                  # i64[nterms] true posting counts
        self.d_docs = d_docs
        self.d_tfdl = d_tfdl
        self.nbytes = nbytes


def get_aligned(seg: Segment, field: str) -> Optional[AlignedPostings]:
    """Build (or fetch cached) aligned postings; None when the segment is
    ineligible (tf/dl exceed the lossless packing bounds, or no postings)."""
    cache = seg.__dict__.setdefault("_fastpath_aligned", {})
    if field in cache:
        return cache[field]
    out = _build_aligned(seg, field)
    cache[field] = out
    return out


def _build_aligned(seg: Segment, field: str) -> Optional[AlignedPostings]:
    import jax

    pb = seg.postings.get(field)
    dl = seg.doc_lens.get(field)
    if pb is None or pb.size == 0:
        return None
    tfs = pb.tfs
    if len(tfs) and tfs.max() > TF_MAX:
        return None
    dl_of = (dl[pb.doc_ids].astype(np.int64) if dl is not None
             else np.zeros(len(pb.doc_ids), np.int64))
    if len(dl_of) and dl_of.max() > DL_MAX:
        return None
    packed = ((tfs.astype(np.int64) << DL_BITS) | dl_of).astype(np.int32)
    a_starts, a_docs, a_packed = align_csr_rows(
        pb.starts, pb.doc_ids, packed, margin=MAX_L)
    nbytes = a_docs.nbytes + a_packed.nbytes
    if _breaker is not None:
        import weakref
        _breaker.add_estimate(nbytes, f"fastpath[{seg.name}][{field}]")
        weakref.finalize(seg, _breaker.release, nbytes)
    lens = np.diff(pb.starts).astype(np.int64)
    starts_rows = (a_starts[:-1] // LANES).astype(np.int64)
    return AlignedPostings(starts_rows, lens,
                           jax.device_put(a_docs), jax.device_put(a_packed),
                           nbytes)


def query_eligible(lroot, sort_specs: List[dict], agg_nodes, named_nodes,
                   search_after, window: int, body: dict) -> bool:
    """Host-cheap check that this search is the plain BM25 top-k hot path."""
    from . import compiler as C

    if not isinstance(lroot, C.LTerms):
        return False
    lt = lroot
    if lt.mode != "score" or lt.sim is None or lt.sim.sim_id != ops.SIM_BM25:
        return False
    nt = len(lt.terms)
    if nt < 1 or next_pow2(nt, floor=1) > MAX_T:
        return False
    if lt.aux is not None and np.any(np.asarray(lt.aux)[:nt] != 0.0):
        return False
    if agg_nodes or named_nodes or search_after is not None:
        return False
    if window > MAX_K or window < 1:
        return False
    if sort_specs and not (len(sort_specs) == 1
                           and sort_specs[0]["field"] == "_score"
                           and sort_specs[0].get("order", "desc") == "desc"):
        return False
    if body.get("collapse") or body.get("suggest") or body.get("knn"):
        return False
    return True


class _VQuery:
    """One kernel-row: a whole query, or one doc-range chunk of it."""

    __slots__ = ("qi", "T_pad", "L", "rowstarts", "nrows", "lens", "weights",
                 "msm", "avgdl", "dlo", "dhi", "k1", "b_eff", "field")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def _chunk_slices(al: AlignedPostings, pb, rows: np.ndarray, ndocs: int
                  ) -> Optional[List[np.ndarray]]:
    """Split a query whose postings exceed the VMEM budget into doc-range
    chunks: uniform doc-id edges, verified against exact per-(term, chunk)
    posting counts (host searchsorted over the ORIGINAL CSR), doubling the
    chunk count until every chunk fits. Returns per-chunk
    [T, 4] = (rowstart_rows, nrows, lens, edge_lo) arrays via a list of
    (dlo, dhi, rowstarts, nrows, lens) tuples; None -> fall back."""
    T_pad = len(rows)
    budget = MAX_TL // T_pad          # elements per term slot
    nchunk = 2
    while nchunk <= MAX_CHUNKS:
        edges = np.linspace(0, ndocs, nchunk + 1).astype(np.int64)
        edges[-1] = np.int64(2**31 - 1)
        ok = True
        per_chunk = []
        for c in range(nchunk):
            rowstarts = np.zeros(T_pad, np.int32)
            nrows = np.zeros(T_pad, np.int32)
            lens = np.zeros(T_pad, np.int32)
            max_nr = HBM_ALIGN // LANES
            for i, r in enumerate(rows):
                if r < 0:
                    continue
                a, b = pb.row_slice(r)
                seg_docs = pb.doc_ids[a:b]
                lo_off = int(np.searchsorted(seg_docs, edges[c], "left"))
                hi_off = int(np.searchsorted(seg_docs, edges[c + 1], "left"))
                if hi_off == lo_off:
                    continue
                # align the DMA start down to the HBM tile; the doc-range
                # window masks the spilled-in prefix
                start_el = int(al.starts_rows[r]) * LANES
                al_off = (lo_off // HBM_ALIGN) * HBM_ALIGN
                ln = hi_off - al_off
                if ln > budget:
                    ok = False
                    break
                rowstarts[i] = (start_el + al_off) // LANES
                nr = next_pow2((ln + LANES - 1) // LANES,
                               floor=HBM_ALIGN // LANES)
                nrows[i] = nr
                lens[i] = ln
                max_nr = max(max_nr, nr)
            if not ok:
                break
            if T_pad * max_nr * LANES > MAX_TL:
                ok = False
                break
            per_chunk.append((int(edges[c]), int(edges[c + 1]),
                              rowstarts, nrows, lens))
        if ok:
            return per_chunk
        nchunk *= 2
    return None


def _prepare_vqueries(seg: Segment, ctx, lts: Sequence, avgdl_cache: dict
                      ) -> Optional[List[List[_VQuery]]]:
    """-> per input query, its list of kernel rows (1 or NCHUNK); None entry
    = that query falls back to the XLA path."""
    out: List[Optional[List[_VQuery]]] = []
    for qi, lt in enumerate(lts):
        al = get_aligned(seg, lt.field)
        pb = seg.postings.get(lt.field)
        if al is None or pb is None:
            out.append(None)
            continue
        nt = len(lt.terms)
        T_pad = next_pow2(nt, floor=1)
        rows = np.full(T_pad, -1, np.int64)
        for i, t in enumerate(lt.terms):
            rows[i] = pb.row(t)
        weights = np.zeros(T_pad, np.float32)
        weights[:nt] = np.asarray(lt.weights, np.float32)[:nt]
        if lt.field not in avgdl_cache:
            avgdl_cache[lt.field] = np.float32(ctx.avgdl(lt.field))
        sim = lt.sim
        b_eff = float(sim.b) if lt.has_norms else 0.0
        common = dict(qi=qi, T_pad=T_pad, weights=weights,
                      msm=float(lt.msm), avgdl=avgdl_cache[lt.field],
                      k1=float(sim.k1), b_eff=b_eff, field=lt.field)

        # single-launch case: every row fits the per-term bucket
        min_rows = HBM_ALIGN // LANES
        rowstarts = np.zeros(T_pad, np.int32)
        nrows = np.zeros(T_pad, np.int32)
        lens = np.zeros(T_pad, np.int32)
        max_nr = min_rows
        fits = True
        for i, r in enumerate(rows):
            if r < 0:
                continue
            ln = int(al.lens[r])
            if ln == 0:
                continue
            if ln > MAX_L:
                fits = False
                break
            rowstarts[i] = al.starts_rows[r]
            nr = next_pow2((ln + LANES - 1) // LANES, floor=min_rows)
            nrows[i] = nr
            lens[i] = ln
            max_nr = max(max_nr, nr)
        if fits and T_pad * max_nr * LANES <= MAX_TL:
            out.append([_VQuery(L=max_nr * LANES, rowstarts=rowstarts,
                                nrows=nrows, lens=lens, dlo=0,
                                dhi=int(INT_MAX), **common)])
            continue

        # oversized: doc-range chunk decomposition (each doc's postings live
        # in exactly one chunk, so msm counting and score sums stay exact)
        chunks = _chunk_slices(al, pb, rows, seg.ndocs)
        if chunks is None:
            out.append(None)
            continue
        vqs = []
        for dlo, dhi, rowstarts, nrows, lens in chunks:
            L = int(max(nrows.max(), min_rows)) * LANES
            vqs.append(_VQuery(L=L, rowstarts=rowstarts, nrows=nrows,
                               lens=lens, dlo=dlo, dhi=dhi, **common))
        out.append(vqs)
    return out


def _run_vqueries(seg: Segment, vq_lists: List[Optional[List[_VQuery]]],
                  K: int) -> List[Optional[dict]]:
    """Group all kernel rows by shape, launch once per group, reassemble
    per-query results (chunked queries merge their chunk top-Ks on host)."""
    groups = {}
    for vqs in vq_lists:
        if vqs is None:
            continue
        for vq in vqs:
            groups.setdefault((vq.field, vq.T_pad, vq.k1, vq.b_eff),
                              []).append(vq)
    results = {}   # id(vq) -> (scores, docs, total)
    for (field, T_pad, k1, b_eff), gvqs in groups.items():
        al = get_aligned(seg, field)
        # ONE launch per group: DMA volume is set by per-term `nrows`, not L,
        # so every row rides the group's max-L variant — launch (and its
        # host<->device round trip) amortizes across the whole batch while
        # rare terms still move only their own bytes
        L = max(v.L for v in gvqs)
        QB = len(gvqs)
        rowstarts = np.stack([v.rowstarts for v in gvqs])
        nrows = np.stack([v.nrows for v in gvqs])
        lens = np.stack([v.lens for v in gvqs])
        weights = np.stack([v.weights for v in gvqs])
        msm = np.array([[v.msm] for v in gvqs], np.float32)
        avg = np.array([[v.avgdl] for v in gvqs], np.float32)
        dlo = np.array([[v.dlo] for v in gvqs], np.int32)
        dhi = np.array([[v.dhi] for v in gvqs], np.int32)
        scores, docs, totals = fused_bm25_topk_tfdl(
            al.d_docs, al.d_tfdl, rowstarts, nrows, lens, weights,
            msm, avg, dlo, dhi, T=T_pad, L=L, K=K, k1=k1, b=b_eff)
        scores = np.asarray(scores)
        docs = np.asarray(docs)
        totals = np.asarray(totals)
        for j, vq in enumerate(gvqs):
            results[id(vq)] = (scores[j][:K], docs[j][:K],
                               int(totals[j][0]))
    out: List[Optional[dict]] = []
    for vqs in vq_lists:
        if vqs is None:
            out.append(None)
            continue
        if len(vqs) == 1:
            sc, dc, total = results[id(vqs[0])]
        else:
            parts = [results[id(v)] for v in vqs]
            sc_all = np.concatenate([p[0] for p in parts])
            dc_all = np.concatenate([p[1] for p in parts])
            total = sum(p[2] for p in parts)
            # stable merge: score desc, doc asc on ties (matches the kernel)
            order = np.lexsort((dc_all, -sc_all))[:K]
            sc = sc_all[order]
            dc = dc_all[order]
        total_i = int(total)
        ms = float(sc[0]) if total_i > 0 and np.isfinite(sc[0]) else -np.inf
        out.append({"topk_key": sc, "topk_idx": dc, "topk_scores": sc,
                    "total": total_i, "max_score": ms})
    return out


def segment_search(seg: Segment, ctx, lt, k: int) -> Optional[dict]:
    """Run the fused kernel for LTerms `lt` over one segment. Returns a dict
    shaped like compiler.run_segment output, or None to fall back."""
    res = batch_search(seg, ctx, [lt], k)
    return res[0] if res else None


def batch_search(seg: Segment, ctx, lts: Sequence, k: int
                 ) -> Optional[List[Optional[dict]]]:
    """Many LTerms over ONE segment in as few kernel launches as possible
    (grid over queries — the server-side query batching a TPU search tier
    runs on). Oversized posting rows split into doc-range chunks that ride
    the same launches. Per-query fallbacks are None entries."""
    if seg.live_count != seg.ndocs:
        return None
    vq_lists = _prepare_vqueries(seg, ctx, lts, {})
    if vq_lists is None:
        return None
    K = min(next_pow2(max(k, 16)), MAX_K)
    return _run_vqueries(seg, vq_lists, K)
