"""Star-tree composite index: a pre-aggregated metric cube that answers
eligible aggregation requests in O(cube) instead of O(ndocs).

Reference analogs: `index/compositeindex/` + `index/mapper/StarTreeMapper.java`
(the reference builds a star-tree of aggregated doc-value nodes at flush).
The TPU re-design is a DENSE CUBE instead of a tree: for configured
dimensions (keyword ordinals, optionally a date dimension at a fixed
calendar interval) and metrics (sum/value_count/min/max, avg = sum+count),
each segment lazily materializes `cube[metric, cell]` where `cell` ravels
the dimension ordinals. A dense array in HBM is the natural TPU shape — a
terms or date_histogram aggregation over a dimension becomes a reduction
over the other axes, and a term filter on a dimension becomes a slice.

Serving contract (`try_answer`): size=0 requests whose query is match_all
(or a single term on a dimension) and whose agg tree is terms/
date_histogram over dimensions with metric leaf sub-aggs on configured
metrics. Anything else returns None and runs the live path; results are
identical either way (asserted in tests/test_startree.py). Cubes live on
the immutable segment, so invalidation is segment GC like every other
derived structure."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAX_CELLS = 1 << 20      # refuse cubes larger than ~1M cells per segment
METRIC_STATS = ("sum", "value_count", "min", "max", "avg")


class StarTreeConfig:
    __slots__ = ("name", "dims", "date_dim", "interval_ms", "metrics")

    def __init__(self, name: str, dims: List[str],
                 date_dim: Optional[str], interval_ms: Optional[int],
                 metrics: List[str]):
        self.name = name
        self.dims = dims              # keyword dimension fields, in order
        self.date_dim = date_dim      # optional date dimension field
        self.interval_ms = interval_ms
        self.metrics = metrics        # numeric metric fields


def parse_config(name: str, cfg: dict) -> StarTreeConfig:
    spec = cfg.get("config", cfg)
    dims: List[str] = []
    date_dim = None
    interval_ms = None
    for d in spec.get("ordered_dimensions", spec.get("dimensions", [])):
        if isinstance(d, str):
            dims.append(d)
            continue
        dname = d.get("name", d.get("field"))
        if d.get("type") == "date" or "calendar_intervals" in d \
                or "interval" in d:
            date_dim = dname
            interval_ms = _interval_ms(d.get("interval",
                                             (d.get("calendar_intervals")
                                              or ["day"])[0]))
        else:
            dims.append(dname)
    metrics = []
    for m in spec.get("metrics", []):
        metrics.append(m if isinstance(m, str)
                       else m.get("name", m.get("field")))
    if not (dims or date_dim) or not metrics:
        raise ValueError(
            f"star_tree field [{name}] needs dimensions and metrics")
    return StarTreeConfig(name, dims, date_dim, interval_ms, metrics)


_CAL_MS = {"minute": 60_000, "1m": 60_000, "hour": 3_600_000,
           "1h": 3_600_000, "day": 86_400_000, "1d": 86_400_000,
           "week": 7 * 86_400_000, "1w": 7 * 86_400_000}


def _interval_ms(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v)
    if s in _CAL_MS:
        return _CAL_MS[s]
    raise ValueError(f"unsupported star_tree date interval [{v}]")


class SegmentCube:
    """Per-segment dense cube: axes = dims (+ date buckets last)."""

    __slots__ = ("axes", "vocabs", "date_min", "counts", "sums", "mins",
                 "maxs", "present")

    def __init__(self, axes, vocabs, date_min, counts, sums, mins, maxs,
                 present):
        self.axes = axes          # per-axis size
        self.vocabs = vocabs      # per dim axis: list of values (or None=date)
        self.date_min = date_min  # first date bucket id (date axis)
        self.counts = counts      # {metric: f64[cells]} value_count
        self.sums = sums
        self.mins = mins
        self.maxs = maxs
        self.present = present    # f64[cells] docs per cell (all-docs count)


def get_cube(seg, cfg: StarTreeConfig) -> Optional[SegmentCube]:
    cache = seg.__dict__.setdefault("_startree_cubes", {})
    if cfg.name in cache:
        return cache[cfg.name]
    cube = _build_cube(seg, cfg)
    cache[cfg.name] = cube
    return cube


def _build_cube(seg, cfg: StarTreeConfig) -> Optional[SegmentCube]:
    n = seg.ndocs
    live = seg.live.astype(bool)
    axis_ords: List[np.ndarray] = []
    axes: List[int] = []
    vocabs: List[Optional[list]] = []
    for d in cfg.dims:
        col = seg.keyword_cols.get(d)
        if col is None:
            return None
        # multi-valued docs are not cube-able (reference star-tree has the
        # same single-value restriction)
        counts = np.diff(col.starts)
        if counts.max(initial=0) > 1:
            return None
        card = len(col.vocab) + 1          # last slot = missing
        axis_ords.append(np.where(col.min_ord >= 0, col.min_ord,
                                  card - 1).astype(np.int64))
        axes.append(card)
        vocabs.append(list(col.vocab))
    date_min = 0
    if cfg.date_dim is not None:
        col = seg.numeric_cols.get(cfg.date_dim)
        if col is None or not col.present.all():
            return None
        b = np.floor_divide(col.values.astype(np.int64), cfg.interval_ms)
        date_min = int(b.min()) if n else 0
        card = int(b.max() - date_min + 1) if n else 1
        axis_ords.append((b - date_min).astype(np.int64))
        axes.append(card)
        vocabs.append(None)
    cells = int(np.prod(axes)) if axes else 1
    if cells > MAX_CELLS:
        return None
    flat = np.zeros(n, np.int64)
    for ords, card in zip(axis_ords, axes):
        flat = flat * card + ords
    flat = flat[live]
    present = np.zeros(cells, np.float64)
    np.add.at(present, flat, 1.0)
    counts: Dict[str, np.ndarray] = {}
    sums: Dict[str, np.ndarray] = {}
    mins: Dict[str, np.ndarray] = {}
    maxs: Dict[str, np.ndarray] = {}
    for m in cfg.metrics:
        col = seg.numeric_cols.get(m)
        if col is None:
            return None
        vals = col.values.astype(np.float64)[live]
        pres = col.present[live]
        f = flat[pres]
        v = vals[pres]
        c = np.zeros(cells, np.float64)
        s = np.zeros(cells, np.float64)
        mn = np.full(cells, np.inf)
        mx = np.full(cells, -np.inf)
        np.add.at(c, f, 1.0)
        np.add.at(s, f, v)
        np.minimum.at(mn, f, v)
        np.maximum.at(mx, f, v)
        counts[m], sums[m], mins[m], maxs[m] = c, s, mn, mx
    return SegmentCube(axes, vocabs, date_min, counts, sums, mins, maxs,
                       present)


# ---------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------

def _eligible_aggs(cfg: StarTreeConfig, aggs: dict) -> Optional[list]:
    """-> [(name, kind, field, params, sub_metrics)] or None. sub_metrics =
    [(name, stat, field)]."""
    out = []
    for name, spec in (aggs or {}).items():
        spec = dict(spec)
        sub = spec.pop("aggs", spec.pop("aggregations", None))
        kinds = [k for k in spec if k in ("terms", "date_histogram",
                                          *METRIC_STATS)]
        # strict: exactly the agg-kind key, nothing else (no meta, scripts...)
        if len(kinds) != 1 or len(spec) != 1:
            return None
        kind = kinds[0]
        body = spec[kind]
        if not isinstance(body, dict):
            return None
        field = body.get("field")
        # the cube serves DEFAULT semantics only: any param beyond the
        # supported set (custom order, min_doc_count, missing, offset,
        # time_zone, script, ...) must take the live path or results would
        # silently diverge (advisor finding, round 3)
        if kind in METRIC_STATS:
            if field not in cfg.metrics or sub:
                return None
            if set(body) - {"field"}:
                return None
            out.append((name, "metric", field, {"stat": kind}, []))
            continue
        if kind == "terms":
            if field not in cfg.dims:
                return None
            if set(body) - {"field", "size", "order", "min_doc_count"}:
                return None
            order = body.get("order")
            if order is not None:
                if not (isinstance(order, dict) and len(order) == 1):
                    return None
                ((okey, odir),) = order.items()
                if okey not in ("_key", "_count") \
                        or odir not in ("asc", "desc"):
                    return None   # order-by-subagg: live path
            params = {"size": int(body.get("size", 10)),
                      "order": order,
                      "min_doc_count": int(body.get("min_doc_count", 1))}
        else:
            if field != cfg.date_dim:
                return None
            if set(body) - {"field", "fixed_interval", "calendar_interval"}:
                return None
            iv = body.get("fixed_interval", body.get("calendar_interval"))
            if iv is None or _interval_ms(iv) != cfg.interval_ms:
                return None
            params = {}
        subs = []
        for sname, sspec in (sub or {}).items():
            skinds = [k for k in sspec if k in METRIC_STATS]
            if len(skinds) != 1 or len(sspec) != 1:
                return None
            sbody = sspec[skinds[0]]
            if not isinstance(sbody, dict) or set(sbody) - {"field"}:
                return None
            sfield = sbody.get("field")
            if sfield not in cfg.metrics:
                return None
            subs.append((sname, skinds[0], sfield))
        out.append((name, kind, field, params, subs))
    return out if out else None


def try_answer(searchers, body: dict, configs: List[StarTreeConfig]
               ) -> Optional[dict]:
    """Answer an eligible size=0 aggregation request from the cubes, or
    None to run the live path."""
    if not configs or int(body.get("size", 10)) != 0:
        return None
    if body.get("sort") or body.get("search_after") or body.get("post_filter"):
        return None
    aggs = body.get("aggs", body.get("aggregations"))
    if not aggs:
        return None
    query = body.get("query") or {"match_all": {}}
    qk = list(query.keys())
    term_filter: Optional[Tuple[str, str]] = None
    if qk == ["term"]:
        ((f, spec),) = query["term"].items()
        v = spec.get("value") if isinstance(spec, dict) else spec
        term_filter = (f, str(v))
    elif qk != ["match_all"]:
        return None
    for cfg in configs:
        if term_filter is not None and term_filter[0] not in cfg.dims:
            continue
        plan = _eligible_aggs(cfg, aggs)
        if plan is None:
            continue
        return _answer(searchers, body, cfg, plan, term_filter)
    return None


def _answer(searchers, body: dict, cfg: StarTreeConfig, plan, term_filter):
    import time
    t0 = time.monotonic()
    segs = []
    for s in searchers:
        for seg in s.engine.segments:
            if seg.live_count == 0:
                continue
            cube = get_cube(seg, cfg)
            if cube is None:
                return None                    # some segment not cube-able
            segs.append(cube)
    total = 0
    # accumulate per-agg across segments in VALUE space (per-segment
    # ordinals differ)
    acc: Dict[str, dict] = {name: {} for name, *_ in plan}
    root: Dict[str, float] = {}
    for cube in segs:
        naxes = len(cube.axes)
        shape = tuple(cube.axes)
        sel = np.ones(shape, bool)
        if term_filter is not None:
            daxis = cfg.dims.index(term_filter[0])
            vocab = cube.vocabs[daxis]
            try:
                o = vocab.index(term_filter[1])
            except ValueError:
                continue                       # value absent in this segment
            mask = np.zeros(cube.axes[daxis], bool)
            mask[o] = True
            shape1 = [1] * naxes
            shape1[daxis] = cube.axes[daxis]
            sel = sel & mask.reshape(shape1)
        selw = sel.astype(np.float64)
        total += int((cube.present.reshape(shape) * selw).sum())
        for name, kind, field, params, subs in plan:
            if kind == "metric":
                st = params["stat"]
                r = root.setdefault(name, _stat_zero(st))
                root[name] = _stat_fold(st, r, _reduce_all(cube, field,
                                                           st, selw, shape))
                continue
            axis = (cfg.dims.index(field) if kind == "terms"
                    else len(cfg.dims))
            other = tuple(i for i in range(naxes) if i != axis)
            cnts = (cube.present.reshape(shape) * selw).sum(axis=other)
            submats = {}
            for sname, stat, sfield in subs:
                submats[(sname, stat, sfield)] = _reduce_axis(
                    cube, sfield, stat, selw, shape, other)
            for o in range(cube.axes[axis]):
                if cnts[o] == 0:
                    continue
                if kind == "terms":
                    if o == cube.axes[axis] - 1:
                        continue               # missing slot
                    key = cube.vocabs[axis][o]
                else:
                    key = (cube.date_min + o) * cfg.interval_ms
                b = acc[name].setdefault(key, {"doc_count": 0.0, "subs": {}})
                b["doc_count"] += float(cnts[o])
                for sk, mat in submats.items():
                    b["subs"][sk] = _stat_fold(sk[1],
                                               b["subs"].get(sk),
                                               mat[o] if mat is not None
                                               else None)
    # ---- render the standard response shape ----
    aggregations: Dict[str, Any] = {}
    for name, kind, field, params, subs in plan:
        if kind == "metric":
            aggregations[name] = _stat_render(params["stat"], root.get(name))
            continue
        buckets = []
        if kind == "terms":
            order = params.get("order")
            if order:
                ((okey, odir),) = order.items()
                if okey == "_key":
                    items = sorted(acc[name].items(),
                                   key=lambda kv: str(kv[0]),
                                   reverse=(odir == "desc"))
                elif odir == "asc":
                    items = sorted(acc[name].items(),
                                   key=lambda kv: (kv[1]["doc_count"],
                                                   str(kv[0])))
                else:   # _count desc: count desc, key asc on ties
                    items = sorted(acc[name].items(),
                                   key=lambda kv: (-kv[1]["doc_count"],
                                                   str(kv[0])))
            else:
                items = sorted(acc[name].items(),
                               key=lambda kv: (-kv[1]["doc_count"],
                                               str(kv[0])))
            mdc = params.get("min_doc_count", 1)
            if mdc > 1:
                items = [kv for kv in items if kv[1]["doc_count"] >= mdc]
            # live-path semantics (aggregations.finalize): sum_other is the
            # DOC COUNT of post-filter buckets beyond `size`, not a bucket
            # count
            terms_total = sum(kv[1]["doc_count"] for kv in items)
            items = items[: params["size"]]
        else:
            items = sorted(acc[name].items(), key=lambda kv: kv[0])
        for key, b in items:
            bucket = {"key": key, "doc_count": int(b["doc_count"])}
            if kind == "date_histogram":
                bucket["key_as_string"] = _iso(key)
            for (sname, stat, _f), v in b["subs"].items():
                bucket[sname] = _stat_render(stat, v)
            buckets.append(bucket)
        aggregations[name] = {"buckets": buckets}
        if kind == "terms":
            shown = sum(b["doc_count"] for b in buckets)
            aggregations[name]["doc_count_error_upper_bound"] = 0
            aggregations[name]["sum_other_doc_count"] = int(
                max(0, terms_total - shown))
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "_shards": {"total": len(searchers), "successful": len(searchers),
                    "skipped": 0, "failed": 0},
        "hits": {"total": {"value": total, "relation": "eq"},
                 "max_score": None, "hits": []},
        "aggregations": aggregations,
        "_star_tree": True,          # diagnosable acceleration marker
    }


def _reduce_all(cube, field, stat, selw, shape):
    return _fold_mat(cube, field, stat, selw, shape, axis=None)


def _reduce_axis(cube, field, stat, selw, shape, other):
    return _fold_mat(cube, field, stat, selw, shape, axis=other)


def _fold_mat(cube, field, stat, selw, shape, axis):
    c = cube.counts[field].reshape(shape) * selw
    if stat == "value_count":
        return c.sum(axis=axis)
    if stat in ("sum", "avg"):
        s = cube.sums[field].reshape(shape) * selw
        if stat == "sum":
            return s.sum(axis=axis)
        return np.stack([s.sum(axis=axis), c.sum(axis=axis)], axis=-1) \
            if axis is not None else np.array([s.sum(), c.sum()])
    m = cube.mins[field] if stat == "min" else cube.maxs[field]
    m = m.reshape(shape)
    masked = np.where(selw > 0, m, np.inf if stat == "min" else -np.inf)
    return masked.min(axis=axis) if stat == "min" else masked.max(axis=axis)


def _stat_zero(stat):
    if stat == "min":
        return np.inf
    if stat == "max":
        return -np.inf
    if stat == "avg":
        return np.zeros(2)
    return 0.0


def _stat_fold(stat, acc, v):
    if v is None:
        return acc
    if acc is None:
        acc = _stat_zero(stat)
    if stat == "min":
        return min(acc, float(np.min(v)) if np.ndim(v) else float(v))
    if stat == "max":
        return max(acc, float(np.max(v)) if np.ndim(v) else float(v))
    if stat == "avg":
        return np.asarray(acc, np.float64) + np.asarray(v, np.float64)
    return float(acc) + float(v)


def _stat_render(stat, v):
    if v is None:
        return {"value": None if stat in ("min", "max", "avg") else 0.0}
    if stat == "avg":
        s, c = float(v[0]), float(v[1])
        return {"value": s / c if c else None}
    if stat in ("min", "max"):
        f = float(v)
        return {"value": None if not np.isfinite(f) else f}
    return {"value": float(v)}


def _iso(ms: int) -> str:
    import datetime as _dt
    return _dt.datetime.fromtimestamp(
        ms / 1000.0, tz=_dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.000Z")
