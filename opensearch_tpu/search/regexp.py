"""Lucene regexp syntax -> DFA, with a vectorized term-dictionary runner.

Reference analog: `index/query/RegexpQueryBuilder.java` over Lucene's
`RegExp`/`Automaton` (org.apache.lucene.util.automaton). Full default
operator set:

    concat   ab        union  a|b        group  (a)
    repeat   a* a+ a?  bounds a{2} a{1,3}
    classes  [a-z] [^a-z]     any char  .
    anystring @        empty  #          numeric interval <10-99>
    intersection a&b   complement ~a     escaping \\x

Pipeline: parse -> Thompson NFA over disjoint char ranges -> subset-
construction DFA; `~` complements a completed DFA, `&` takes a product.
Matching a query against the whole term dictionary is VECTORIZED: terms
become a padded uint32 char matrix once per (segment, field), and the DFA
steps all terms simultaneously (`state = trans[state, class_of_char]`, one
numpy gather per character position) — one query vs 100k terms is ~maxlen
table lookups, not 100k Python regex calls.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

MAXCP = 0x10FFFF + 1


class RegexpError(ValueError):
    pass


# ---------------------------------------------------------------------------
# parser (Lucene RegExp grammar, operator precedence: | < & < concat < ~ <
# repeat < atom)
# ---------------------------------------------------------------------------

class _P:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def eof(self) -> bool:
        return self.i >= len(self.s)

    def peek(self) -> str:
        return self.s[self.i]

    def next(self) -> str:
        c = self.s[self.i]
        self.i += 1
        return c

    def expect(self, c: str) -> None:
        if self.eof() or self.s[self.i] != c:
            raise RegexpError(
                f"expected [{c}] at position {self.i} in /{self.s}/")
        self.i += 1

    # union := inter ('|' inter)*
    def union(self):
        left = self.inter()
        while not self.eof() and self.peek() == "|":
            self.next()
            left = ("union", left, self.inter())
        return left

    # inter := concat ('&' concat)*
    def inter(self):
        left = self.concat()
        while not self.eof() and self.peek() == "&":
            self.next()
            left = ("inter", left, self.concat())
        return left

    # concat := repeat+
    def concat(self):
        parts = []
        while not self.eof() and self.peek() not in "|&)":
            parts.append(self.repeat())
        if not parts:
            return ("empty_string",)
        node = parts[0]
        for p in parts[1:]:
            node = ("concat", node, p)
        return node

    # repeat := complement (('*'|'+'|'?'|'{m,n}') )*
    def repeat(self):
        node = self.complement()
        while not self.eof() and self.peek() in "*+?{":
            c = self.next()
            if c == "*":
                node = ("rep", node, 0, None)
            elif c == "+":
                node = ("rep", node, 1, None)
            elif c == "?":
                node = ("rep", node, 0, 1)
            else:  # {m} {m,} {m,n}
                m = self._int("}")
                if not self.eof() and self.peek() == ",":
                    self.next()
                    if not self.eof() and self.peek() == "}":
                        n = None
                    else:
                        n = self._int("}")
                else:
                    n = m
                self.expect("}")
                node = ("rep", node, m, n)
        return node

    def _int(self, *stops) -> int:
        start = self.i
        while not self.eof() and self.peek().isdigit():
            self.next()
        if start == self.i:
            raise RegexpError(f"expected number at {start} in /{self.s}/")
        return int(self.s[start: self.i])

    # complement := '~' complement | atom
    def complement(self):
        if not self.eof() and self.peek() == "~":
            self.next()
            return ("not", self.complement())
        return self.atom()

    def atom(self):  # noqa: C901
        if self.eof():
            return ("empty_string",)
        c = self.next()
        if c == "(":
            if not self.eof() and self.peek() == ")":
                self.next()
                return ("empty_string",)
            node = self.union()
            self.expect(")")
            return node
        if c == "[":
            return self._char_class()
        if c == ".":
            return ("ranges", ((0, MAXCP - 1),))
        if c == "@":
            return ("anystring",)
        if c == "#":
            return ("empty_lang",)
        if c == "<":
            return self._interval()
        if c == "\\":
            if self.eof():
                raise RegexpError("trailing backslash")
            e = self.next()
            return ("ranges", ((ord(e), ord(e)),))
        if c in ")|&":
            raise RegexpError(f"unexpected [{c}] at {self.i - 1}")
        return ("ranges", ((ord(c), ord(c)),))

    def _char_class(self):
        negate = False
        if not self.eof() and self.peek() == "^":
            self.next()
            negate = True
        ranges: List[Tuple[int, int]] = []
        first = True
        while True:
            if self.eof():
                raise RegexpError("unterminated character class")
            c = self.next()
            if c == "]" and not first:
                break
            first = False
            if c == "\\":
                if self.eof():
                    raise RegexpError("trailing backslash in class")
                c = self.next()
            lo = ord(c)
            hi = lo
            if (not self.eof() and self.peek() == "-"
                    and self.i + 1 < len(self.s)
                    and self.s[self.i + 1] != "]"):
                self.next()
                c2 = self.next()
                if c2 == "\\":
                    if self.eof():
                        raise RegexpError("trailing backslash in class")
                    c2 = self.next()
                hi = ord(c2)
                if hi < lo:
                    raise RegexpError(f"bad range {chr(lo)}-{chr(hi)}")
            ranges.append((lo, hi))
        if negate:
            ranges = _negate_ranges(ranges)
            if not ranges:
                return ("empty_lang",)
        return ("ranges", tuple(sorted(ranges)))

    def _interval(self):
        """<m-n>: any decimal string numerically within [m, n], with the
        shorter-number zero-pad convention Lucene uses (leading zeros
        allowed up to the max width)."""
        start = self.i
        while not self.eof() and self.peek() != ">":
            self.next()
        body = self.s[start: self.i]
        self.expect(">")
        m = body.split("-")
        if len(m) != 2 or not m[0].isdigit() or not m[1].isdigit():
            raise RegexpError(f"bad numeric interval <{body}>")
        lo, hi = int(m[0]), int(m[1])
        if lo > hi:
            lo, hi = hi, lo
        # union of the explicit decimal strings (bounded widths); Lucene
        # builds a digit automaton — an explicit union is equivalent for
        # the practical widths (guarded) and reuses the machinery
        if hi - lo > 2000:
            raise RegexpError(f"numeric interval too large <{body}>")
        # Lucene's interval automaton accepts leading zeros up to the max
        # operand width: <1-31> matches "07" as well as "7"
        width = max(len(m[0]), len(m[1]))
        node = None
        for v in range(lo, hi + 1):
            for w in range(len(str(v)), width + 1):
                alt = _string_node(str(v).zfill(w))
                node = alt if node is None else ("union", node, alt)
        return node if node is not None else ("empty_lang",)


def _string_node(s: str):
    node = ("empty_string",)
    for ch in s:
        node = ("concat", node, ("ranges", ((ord(ch), ord(ch)),)))
    return node


def _negate_ranges(ranges: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    out = []
    cur = 0
    for lo, hi in sorted(ranges):
        if lo > cur:
            out.append((cur, lo - 1))
        cur = max(cur, hi + 1)
    if cur < MAXCP:
        out.append((cur, MAXCP - 1))
    return out


# ---------------------------------------------------------------------------
# NFA (Thompson) -> DFA (subset construction); complement/product on DFAs
# ---------------------------------------------------------------------------

class _Nfa:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.edges: List[List[Tuple[int, int, int]]] = []  # (lo, hi, dst)

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1


class Dfa:
    """Transitions over a partition of the codepoint space.
    `cuts`: sorted boundary starts; char -> class = searchsorted(cuts).
    `trans`: int32[nstates, nclasses]; -1 = dead. State 0 = start."""

    __slots__ = ("cuts", "trans", "accept", "_completed")

    def __init__(self, cuts: np.ndarray, trans: np.ndarray,
                 accept: np.ndarray):
        self.cuts = cuts
        self.trans = trans
        self.accept = accept
        self._completed = None

    def match(self, term: str) -> bool:
        st = 0
        for ch in term:
            cls = int(np.searchsorted(self.cuts, ord(ch), side="right") - 1)
            st = int(self.trans[st, cls])
            if st < 0:
                return False
        return bool(self.accept[st])

    def match_matrix(self, mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """Vectorized run: mat u32[nterms, maxlen] codepoints (0-padded),
        lens i32[nterms]. One gather per char position for ALL terms."""
        n, maxlen = mat.shape
        cls = np.searchsorted(self.cuts, mat, side="right") - 1
        state = np.zeros(n, np.int64)
        if self._completed is None:
            self._completed = _complete(self)  # shared with complement()
        trans, accept = self._completed
        dead = trans.shape[0] - 1
        for pos in range(maxlen):
            step = trans[state, cls[:, pos]]
            state = np.where(pos < lens, step, state)
            if (state == dead).all():
                break
        return accept[state]


def _ast_to_nfa(ast, nfa: _Nfa) -> Tuple[int, int]:  # noqa: C901
    kind = ast[0]
    if kind == "empty_string":
        s = nfa.state()
        return s, s
    if kind == "empty_lang":
        a, b = nfa.state(), nfa.state()
        return a, b          # no path
    if kind == "ranges":
        a, b = nfa.state(), nfa.state()
        for lo, hi in ast[1]:
            nfa.edges[a].append((lo, hi, b))
        return a, b
    if kind == "anystring":
        a = nfa.state()
        nfa.edges[a].append((0, MAXCP - 1, a))
        return a, a
    if kind == "concat":
        a1, b1 = _ast_to_nfa(ast[1], nfa)
        a2, b2 = _ast_to_nfa(ast[2], nfa)
        nfa.eps[b1].append(a2)
        return a1, b2
    if kind == "union":
        a1, b1 = _ast_to_nfa(ast[1], nfa)
        a2, b2 = _ast_to_nfa(ast[2], nfa)
        s, e = nfa.state(), nfa.state()
        nfa.eps[s] += [a1, a2]
        nfa.eps[b1].append(e)
        nfa.eps[b2].append(e)
        return s, e
    if kind == "rep":
        _, sub, mn, mx = ast
        if mx is not None and mx < mn:
            raise RegexpError(f"bad repeat bounds {{{mn},{mx}}}")
        s = nfa.state()
        cur = s
        for _i in range(mn):
            a, b = _ast_to_nfa(sub, nfa)
            nfa.eps[cur].append(a)
            cur = b
        if mx is None:
            a, b = _ast_to_nfa(sub, nfa)
            nfa.eps[cur].append(a)
            nfa.eps[b].append(cur)   # loop
            return s, cur
        end = nfa.state()
        nfa.eps[cur].append(end)
        for _i in range(mx - mn):
            a, b = _ast_to_nfa(sub, nfa)
            nfa.eps[cur].append(a)
            cur = b
            nfa.eps[cur].append(end)
        return s, end
    if kind in ("inter", "not"):
        # handled at the DFA level (compile sub-automata first)
        raise RegexpError("internal: inter/not must be compiled via _to_dfa")
    raise RegexpError(f"internal: unknown node {kind}")


def _eclosure(nfa: _Nfa, states: FrozenSet[int]) -> FrozenSet[int]:
    stack = list(states)
    seen = set(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def _nfa_to_dfa(nfa: _Nfa, start: int, end: int) -> Dfa:
    # alphabet partition from all edge boundaries
    cutset = {0}
    for edges in nfa.edges:
        for lo, hi, _ in edges:
            cutset.add(lo)
            if hi + 1 < MAXCP:
                cutset.add(hi + 1)
    cuts = np.asarray(sorted(cutset), np.int64)
    ncls = len(cuts)

    start_set = _eclosure(nfa, frozenset([start]))
    index: Dict[FrozenSet[int], int] = {start_set: 0}
    order = [start_set]
    rows: List[List[int]] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = []
        for ci in range(ncls):
            lo = int(cuts[ci])
            nxt = set()
            for s in cur:
                for elo, ehi, dst in nfa.edges[s]:
                    if elo <= lo <= ehi:
                        nxt.add(dst)
            if not nxt:
                row.append(-1)
                continue
            closed = _eclosure(nfa, frozenset(nxt))
            if closed not in index:
                index[closed] = len(order)
                order.append(closed)
            row.append(index[closed])
        rows.append(row)
    trans = np.asarray(rows, np.int64).reshape(len(order), ncls)
    accept = np.asarray([end in st for st in order], bool)
    return Dfa(cuts, trans, accept)


def _complete(d: Dfa) -> Tuple[np.ndarray, np.ndarray]:
    """trans with an explicit dead state appended (total function)."""
    n, ncls = d.trans.shape
    trans = np.vstack([d.trans, np.full((1, ncls), n, np.int64)])
    trans = np.where(trans < 0, n, trans)
    accept = np.concatenate([d.accept, [False]])
    return trans, accept


def _dfa_complement(d: Dfa) -> Dfa:
    trans, accept = _complete(d)
    return Dfa(d.cuts, trans, ~accept)


def _merge_cuts(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.unique(np.concatenate([a, b]))


def _reclass(d: Dfa, cuts: np.ndarray) -> Dfa:
    """Re-express transitions over a finer partition."""
    cols = np.searchsorted(d.cuts, cuts, side="right") - 1
    return Dfa(cuts, d.trans[:, cols], d.accept)


def _dfa_product(a: Dfa, b: Dfa, op) -> Dfa:
    cuts = _merge_cuts(a.cuts, b.cuts)
    a = _reclass(a, cuts)
    b = _reclass(b, cuts)
    ta, aa = _complete(a)
    tb, ab = _complete(b)
    na, nb = ta.shape[0], tb.shape[0]
    ncls = len(cuts)
    # reachable product states only
    index = {(0, 0): 0}
    order = [(0, 0)]
    rows = []
    i = 0
    while i < len(order):
        sa, sb = order[i]
        i += 1
        row = []
        for c in range(ncls):
            ns = (int(ta[sa, c]), int(tb[sb, c]))
            if ns not in index:
                index[ns] = len(order)
                order.append(ns)
            row.append(index[ns])
        rows.append(row)
    trans = np.asarray(rows, np.int64)
    accept = np.asarray([op(bool(aa[sa]), bool(ab[sb]))
                         for sa, sb in order], bool)
    return Dfa(cuts, trans, accept)


def _to_dfa(ast) -> Dfa:
    kind = ast[0]
    if kind == "not":
        return _dfa_complement(_to_dfa(ast[1]))
    if kind == "inter":
        return _dfa_product(_to_dfa(ast[1]), _to_dfa(ast[2]),
                            lambda x, y: x and y)
    if _has_setops(ast):
        # a set-op (~ / &) below this node: compile the children to DFAs
        # and recombine at the automaton level (a DFA is a valid NFA, so
        # concat/repeat splice via epsilon edges)
        if kind == "union":
            return _dfa_product(_to_dfa(ast[1]), _to_dfa(ast[2]),
                                lambda x, y: x or y)
        if kind == "concat":
            return _concat_dfas(_to_dfa(ast[1]), _to_dfa(ast[2]))
        if kind == "rep":
            return _repeat_dfa(_to_dfa(ast[1]), ast[2], ast[3])
    nfa = _Nfa()
    s, e = _ast_to_nfa(ast, nfa)
    return _nfa_to_dfa(nfa, s, e)


def _has_setops(ast) -> bool:
    if not isinstance(ast, tuple):
        return False
    if ast[0] in ("not", "inter"):
        return True
    return any(_has_setops(x) for x in ast[1:] if isinstance(x, tuple))


def _dfa_fragment(nfa: _Nfa, d: Dfa) -> Tuple[int, List[int]]:
    """Splice a DFA into an NFA under construction; returns (start,
    accepting-state list)."""
    off = [nfa.state() for _ in range(d.trans.shape[0])]
    n, ncls = d.trans.shape
    for s in range(n):
        for c in range(ncls):
            dst = int(d.trans[s, c])
            if dst < 0:
                continue
            lo = int(d.cuts[c])
            hi = (int(d.cuts[c + 1]) - 1 if c + 1 < len(d.cuts)
                  else MAXCP - 1)
            nfa.edges[off[s]].append((lo, hi, off[dst]))
    return off[0], [off[s] for s in range(n) if d.accept[s]]


def _concat_dfas(a: Dfa, b: Dfa) -> Dfa:
    nfa = _Nfa()
    sa, enda = _dfa_fragment(nfa, a)
    sb, endb = _dfa_fragment(nfa, b)
    end = nfa.state()
    for s in enda:
        nfa.eps[s].append(sb)
    for s in endb:
        nfa.eps[s].append(end)
    return _nfa_to_dfa(nfa, sa, end)


def _repeat_dfa(d: Dfa, mn: int, mx: Optional[int]) -> Dfa:
    if mx is not None and mx < mn:
        raise RegexpError(f"bad repeat bounds {{{mn},{mx}}}")
    nfa = _Nfa()
    start = nfa.state()
    cur = [start]
    for _i in range(mn):
        s, ends = _dfa_fragment(nfa, d)
        for c in cur:
            nfa.eps[c].append(s)
        cur = ends
    end = nfa.state()
    if mx is None:
        s, ends = _dfa_fragment(nfa, d)
        for c in cur:
            nfa.eps[c].append(s)
            nfa.eps[c].append(end)
        for e in ends:
            nfa.eps[e].append(s)       # loop
            nfa.eps[e].append(end)
    else:
        for c in cur:
            nfa.eps[c].append(end)
        for _i in range(mx - mn):
            s, ends = _dfa_fragment(nfa, d)
            for c in cur:
                nfa.eps[c].append(s)
            cur = ends
            for c in cur:
                nfa.eps[c].append(end)
    return _nfa_to_dfa(nfa, start, end)


_COMPILE_CACHE: Dict[str, Dfa] = {}


def compile_regexp(pattern: str) -> Dfa:
    d = _COMPILE_CACHE.get(pattern)
    if d is None:
        ast = _parse(pattern)
        d = _to_dfa(ast)
        if len(_COMPILE_CACHE) > 256:
            _COMPILE_CACHE.clear()
        _COMPILE_CACHE[pattern] = d
    return d


def _parse(pattern: str):
    p = _P(pattern)
    ast = p.union()
    if not p.eof():
        raise RegexpError(
            f"unexpected [{p.peek()}] at position {p.i} in /{pattern}/")
    return ast


# ---------------------------------------------------------------------------
# vocab matrix cache: one padded codepoint matrix per term list identity
# ---------------------------------------------------------------------------

_MATRIX_CACHE: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}


def vocab_matrix(vocab: List[str], cache_key: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    if cache_key is not None and cache_key in _MATRIX_CACHE:
        return _MATRIX_CACHE[cache_key]
    lens = np.asarray([len(t) for t in vocab], np.int32)
    maxlen = int(lens.max()) if len(lens) else 0
    mat = np.zeros((len(vocab), maxlen), np.uint32)
    for i, t in enumerate(vocab):
        if t:
            mat[i, : len(t)] = np.frombuffer(
                t.encode("utf-32-le"), np.uint32)
    if cache_key is not None:
        if len(_MATRIX_CACHE) > 64:
            _MATRIX_CACHE.clear()
        _MATRIX_CACHE[cache_key] = (mat, lens)
    return mat, lens


def match_vocab(pattern: str, vocab: List[str],
                cache_key: Optional[int] = None) -> np.ndarray:
    """bool[len(vocab)]: anchored (full-term) matches."""
    d = compile_regexp(pattern)
    if not vocab:
        return np.zeros(0, bool)
    mat, lens = vocab_matrix(vocab, cache_key)
    return d.match_matrix(mat, lens)
