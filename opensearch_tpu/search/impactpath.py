"""Codec-v2 eager-impact serving path: quantized gather → scatter-add
with device block-max pruning, certified exact against the f32 oracle.

The XLA hot path for plain BM25 term/match top-k (the same shape class
`search/fastpath.py` serves through the Pallas kernels on TPU) over
codec-v2 segments (index/segment.py `ImpactPlane`). Per query:

1. **Plan (host).** The per-row block-max sidecar prices every
   IMPACT_BLOCK-posting block at `w_t · scale · block_max` and keeps the
   top-valued blocks until the kept posting mass covers the candidate
   window; every pruned block is *skipped at gather time* — its bytes
   never move (GPUSparse-style block-level metadata, arxiv 2606.26441).
   The pruned remainder is summarized as one sound scalar `B_rem =
   Σ_t w_t·scale·max(pruned block_max of t)`.
2. **First pass (device).** ONE jit program (compiler.build_impact_program,
   keyed by the codec layout): integer impact gather over the kept block
   windows, a single dequant multiply through `ops.scoring.dequant_impact`
   (weights pre-folded per block), scatter-add, masked top-C. No
   per-posting tf/doclen math anywhere — the BM25 saturation was
   evaluated at index time (BM25S eager scoring, arxiv 2407.03618).
3. **Certify (host).** Candidates are exact-rescored against the full
   f32 BM25 expression (the same arithmetic the v1 XLA program and the
   fastpath oracle serve — parity-tested bit-for-bit). The served window
   is proven exact when no non-candidate doc can displace it:
   `max(approx_floor + E + B_rem, B_rem) < θ`, where θ is the window
   boundary's exact score and `E` folds the quantization half-step,
   the build→query similarity-param drift bound and f32 accumulation
   slack (ImpactPlane.quant_err / drift_bound).
4. **Escalate.** A failed certificate first widens candidates to every
   doc any kept block mentions (the fastpath `_phase2_batch` trick — the
   union bound drops to `B_rem` alone), then falls back to the exact
   dense program (the caller reruns the v1-style XLA plan; codec v2
   promotes the tf plane lazily for exactly this rung).

Totals are exact (`eq`) on unpruned passes and a lower bound (`gte`)
under pruning — the same contract as the reference's default
track-total-hits cap; bodies with an explicit `track_total_hits` are
planned unpruned. Pruning also requires msm == 1 (a pruned pass cannot
count matched terms exactly; multi-msm queries ride the unpruned impact
pass, which still moves 5/6 bytes per slot instead of 8).

Served scores live in the HOST-ORACLE f32 domain (term-ordered numpy
accumulation — the same domain the fastpath ladder's rescued pages
serve). The XLA dense program may contract mul+add chains into FMA and
land ~1 ULP away on individual scores; page IDS and order agree. For
that reason the path only engages on MESH-LESS serving (see
`_MESH_ATTACHED`): a mesh-attached node's host loop must stay
byte-identical to its coalesced SPMD siblings.
"""

from __future__ import annotations

import contextvars
import os
from typing import List, Optional, Sequence

import numpy as np

from ..index.segment import (CODEC_V1, CODEC_V2, IMPACT_BLOCK, Segment,
                             next_pow2)
from ..obs import flight_recorder as _fr
from ..obs import insights as _ins
from ..obs import query_cost as _qc
from ..ops import scoring as ops
from ..ops.scoring import dequant_impact_np
from ..utils.metrics import METRICS, CounterGroup
from ..utils.trace import TRACER
from .fastpath import _body_eligible, _ok_group

# candidate window floor for the first pass; the block prune keeps at
# least KEEP_FACTOR * C postings so the candidate pool stays deep enough
# to certify without an escalation on well-behaved corpora
CAND_FLOOR = 32
KEEP_FACTOR = 8
KEEP_MIN = 512

STATS = CounterGroup(METRICS, "impactpath", {
    "served": 0, "pruned_served": 0, "phase2_served": 0,
    "escalated": 0, "fallback": 0,
    "blocks_total": 0, "blocks_skipped": 0,
    "postings_total": 0, "postings_skipped": 0})


# bit-consistency gate: the impact ladder serves the HOST-ORACLE f32
# domain (term-ordered numpy accumulation); batched SPMD mesh programs
# and device-pinned replica searchers serve XLA's (FMA-contracted)
# domain, and the two can differ by ~1 ULP per posting. When a node's
# serving is multi-domain — an SPMD mesh owns the hot path (declines,
# scheduler bypasses and degradation retries must stay BYTE-identical to
# their coalesced siblings), or replica read copies round-robin with the
# primary — the node pins this contextvar around search_shards and the
# impact path stands down. Single-domain serving (no mesh, no replica
# copies: single-device nodes, the direct-path benches) gets the eager
# path unconditionally.
_MESH_ATTACHED: contextvars.ContextVar = contextvars.ContextVar(
    "impactpath_mesh_attached", default=False)


def mesh_attached_token(attached: bool):
    return _MESH_ATTACHED.set(bool(attached))


def reset_mesh_attached(token) -> None:
    _MESH_ATTACHED.reset(token)


def enabled() -> bool:
    if _MESH_ATTACHED.get():
        return False
    return not os.environ.get("OPENSEARCH_TPU_NO_IMPACT")


def stats() -> dict:
    return dict(STATS)


def block_skip_rate() -> float:
    """Fraction of sidecar blocks the device never gathered (planned
    queries only) — the bench `extra.impacts.block_skip_rate` stamp."""
    total = STATS["blocks_total"]
    return (STATS["blocks_skipped"] / total) if total else 0.0


class ImpactSpec:
    """A search the impact path can serve: the pure BM25 term-group
    top-k shape (kind "bm25") or the pure learned-sparse dot-product
    top-k shape over a feature-impact field (kind "sparse") — single
    unfiltered group, _score sort, no aggs."""

    __slots__ = ("lt", "window", "prune_ok", "kind")

    def __init__(self, lt, window: int, prune_ok: bool,
                 kind: str = "bm25"):
        self.lt = lt
        self.window = window
        self.prune_ok = prune_ok
        self.kind = kind


def _ok_sparse(lroot) -> bool:
    """LSparseDot usable as the sparse impact-ladder root: a plain
    `neural_sparse` dot product (non-negative token weights — the plan's
    witness/remainder bounds assume monotone contributions)."""
    from . import compiler as C

    if not isinstance(lroot, C.LSparseDot):
        return False
    if not len(lroot.tokens):
        return False
    w = np.asarray(lroot.weights, np.float32)
    return bool(np.all(w >= 0)) and float(lroot.boost) >= 0.0


def make_spec(lroot, sort_specs: List[dict], agg_nodes, named_nodes,
              search_after, window: int, body: dict
              ) -> Optional[ImpactSpec]:
    if not enabled():
        return None
    if not _body_eligible(sort_specs, agg_nodes, named_nodes, search_after,
                          window, body):
        return None
    if _ok_group(lroot):
        # pruning changes total-hit semantics (lower bound, "gte") and
        # relaxed-msm counting is unsound — explicit total tracking or
        # msm > 1 ride the unpruned impact pass
        prune_ok = ("track_total_hits" not in body
                    and int(lroot.msm) <= 1)
        return ImpactSpec(lroot, int(window), prune_ok)
    if _ok_sparse(lroot):
        # learned-sparse: any-token match (msm == 1 semantics), so only
        # explicit total tracking blocks the prune
        return ImpactSpec(lroot, int(window),
                          "track_total_hits" not in body, kind="sparse")
    return None


# pruned-remainder budget as a fraction of θ̂: the per-term cut keeps
# Σ_t max(pruned_t) ≤ PRUNE_MARGIN·θ̂ < θ̂ ≤ θ2 (the θ̂-witness blocks are
# priced ≥ θ̂ > τ, so their docs are always in the phase-2 union), which
# makes a pruned plan certify by construction up to live/tie edge cases.
# 0.5 leaves enough headroom that the PHASE-1 certificate
# (approx_C + E + rem < θ) usually passes outright — the phase-2 union
# rescore stays an escalation rung, not a per-query tax; raising the
# margin prunes more and leans harder on phase 2.
PRUNE_MARGIN = 0.5

# ---------------------------------------------------------------------
# doc-range (live-block) pruning — the plan equal-idf multi-term
# queries need, and the one BP doc-id reordering (index/reorder.py)
# feeds. The per-term cut above is structurally blind to them: with T
# equal weights, τ = PRUNE_MARGIN·θ̂/T sits BELOW the smallest possible
# posting impact (tf=1 at dl_max still lands ~0.3·max), so no block of
# any term can ever price under it. The doc-space cut works on the SUM:
# partition doc ids into 2^DOC_RANGE_SHIFT-doc ranges, upper-bound every
# range at Σ_t w_t·scale·max_q(t, range), and prune ranges that cannot
# reach RANGE_MARGIN·θ̂. Soundness: a doc in a pruned range scores
# ≤ bound(range) + Σ_t w_t·eps ≤ rem, and the existing certificate /
# phase-2 machinery consumes that rem unchanged; a doc in a KEPT range
# is fully gathered (every posting of it lies in a 128-posting block
# that intersects its kept range, and blocks are kept per intersection),
# so the seen-but-lost analysis is also unchanged. On an arrival-order
# corpus every block spans nearly the whole doc space and intersects
# some kept range — nothing skips, which is why this plan only fires
# after the merge-time reorder clusters each term's impact mass into
# narrow doc runs (the classic BMW/live-block force-multiplier).
# Per-row range maxima are query-independent and cached on the plane.
DOC_RANGE_SHIFT = 7          # 128-doc ranges (the BP leaf granularity)
RANGE_MARGIN = 0.99          # prune ranges priced under 0.99·θ̂: the 1%
#                              keep-band is certify headroom — rem lands
#                              ≤ 0.99·θ̂ + eps, strictly under θ, so the
#                              phase-1 certificate holds with room for E
#                              (the probe witness keeps θ̂ within ~eps of
#                              the real boundary, so the band is real)
PROBE_TOP = 32               # top postings per row feeding the probe-doc
#                              witness (sound multi-term θ̂ sharpener)


def _probe_witness(pb, plane, act_rows, act_w, window: int,
                   eps_sum: float) -> float:
    """Sharper sound θ̂ for multi-term queries: take each row's top
    PROBE_TOP postings by quantized impact (REAL docs), sum each probe
    doc's approx score across ALL queried rows, and witness the
    window-th highest minus the summed error. The single-term kth
    witness only ever sees one row; when query terms co-occur the true
    boundary sits near the SUM and this witness finds it — which is
    what lets the doc-range cut price single-term ranges out."""
    if window > PROBE_TOP:
        return 0.0
    docs_l = []
    for row in act_rows:
        cache = plane.__dict__.setdefault("_probe_top", {})
        got = cache.get(row)
        if got is None:
            a, b = pb.row_slice(row)
            qs = plane.q[a:b]
            m = min(PROBE_TOP, b - a)
            sel = np.argpartition(qs, b - a - m)[b - a - m:] if b - a > m \
                else np.arange(b - a)
            got = pb.doc_ids[a:b][sel].astype(np.int64)
            if len(cache) >= (1 << 15):
                cache.clear()   # <=PROBE_TOP i64 per row; hard cap ~8MB
            cache[row] = got
        docs_l.append(got)
    probe = np.unique(np.concatenate(docs_l))
    if len(probe) < window:
        return 0.0
    approx = np.zeros(len(probe), np.float64)
    scale = float(plane.scale)
    for row, w in zip(act_rows, act_w):
        a, b = pb.row_slice(row)
        rowdocs = pb.doc_ids[a:b]
        pos = np.searchsorted(rowdocs, probe)
        pos_c = np.minimum(pos, b - a - 1)
        found = rowdocs[pos_c] == probe
        approx += np.where(found,
                           w * scale * plane.q[a:b][pos_c].astype(
                               np.float64), 0.0)
    kth = float(np.partition(approx, len(approx) - window)
                [len(approx) - window])
    return kth - eps_sum


_RANGE_MAX_CACHE_BYTES = 1 << 25    # 32MB per plane, then start over


def _row_range_max(pb, plane, row: int, shift: int):
    """(range_ids i64[R], max_q[R]) of one row — max quantized impact
    per touched doc range; cached (query-independent). Entries are
    O(touched ranges) arrays (~9 B/range — a 1M-doc stopword row is
    ~70KB), so the cache is byte-capped, not entry-capped: a long-lived
    node serving a wide vocabulary must not accumulate host memory
    proportional to every row ever queried."""
    cache = plane.__dict__.setdefault("_range_max", {})
    got = cache.get(row)
    if got is None:
        a, b = pb.row_slice(row)
        docs = pb.doc_ids[a:b]
        buck = (docs >> shift).astype(np.int64)
        head = np.flatnonzero(np.diff(buck)) + 1
        idx = np.concatenate(([np.int64(0)], head))
        maxq = np.maximum.reduceat(plane.q[a:b], idx) if b > a \
            else np.zeros(0, plane.q.dtype)
        got = (buck[idx] if b > a else np.zeros(0, np.int64), maxq)
        nb = int(got[0].nbytes) + int(got[1].nbytes)
        used = plane.__dict__.get("_range_max_bytes", 0)
        if used + nb > _RANGE_MAX_CACHE_BYTES:
            cache.clear()       # benign to race: values are deterministic
            used = 0
        plane.__dict__["_range_max_bytes"] = used + nb
        cache[row] = got
    return got


def _range_plan(pb, plane, act_rows, act_w, offs, lens,
                theta_hat: float, eps: float, ndocs: int):
    """Doc-range plan over the active rows' blocks. Returns
    (keep_mask bool[nblocks], rem) or None when the cut keeps everything
    (or prices itself out)."""
    if ndocs <= 0 or theta_hat <= 0.0:
        return None
    shift = DOC_RANGE_SHIFT
    nb = ((ndocs - 1) >> shift) + 1
    bound = np.zeros(nb, np.float64)
    scale = float(plane.scale)
    eps_sum = 0.0
    for row, w in zip(act_rows, act_w):
        bids, maxq = _row_range_max(pb, plane, row, shift)
        bound[bids] += w * scale * maxq.astype(np.float64)
        eps_sum += w * eps
    tau_r = RANGE_MARGIN * theta_hat - eps_sum
    if tau_r <= 0.0:
        return None
    kept_r = bound >= tau_r
    if kept_r.all():
        return None
    # block kept iff its doc span intersects any kept range
    cum = np.zeros(nb + 1, np.int64)
    np.cumsum(kept_r, out=cum[1:])
    first = pb.doc_ids[offs].astype(np.int64) >> shift
    last = pb.doc_ids[offs + lens.astype(np.int64) - 1].astype(
        np.int64) >> shift
    keep_b = (cum[last + 1] - cum[first]) > 0
    pruned_b = bound[~kept_r]
    rem = float(pruned_b.max() + eps_sum) if len(pruned_b) else 0.0
    return keep_b, rem


def _plan_blocks(pb, plane, rows: np.ndarray, weights: np.ndarray,
                 C: int, prune: bool, window: int, eps: float,
                 ndocs: int = 0):
    """Select the gathered block set. Returns (bstart i64[NB], blen
    i32[NB], bweight f32[NB], kept_postings, rem_bound, n_total_blocks,
    total_postings) — bweight folds w_t·scale so the device does ONE
    multiply per posting.

    The prune threshold is derived from a SOUND lower bound θ̂ on the
    true window-boundary score: distinct blocks of one row are distinct
    docs, and each block contains a posting attaining its block_max, so
    the window-th highest block_max of any single term witnesses `window`
    real docs scoring ≥ w·(scale·bmax − eps) (eps = quantization +
    param-drift error). Pruning only blocks priced below
    `PRUNE_MARGIN·θ̂/T` keeps the remainder bound Σ_t max(pruned_t) ≤
    PRUNE_MARGIN·θ̂ < θ — so a pruned plan certifies by construction
    (phase 2 at the latest) instead of escalating to the dense rerun.
    `eps` also prices the abstention: when quantization/drift error
    swamps θ̂, nothing is pruned."""
    offs_l, lens_l, w_l, term_l, val_l, act_w = [], [], [], [], [], []
    act_rows = []
    scale = np.float32(plane.scale)
    row_ends = pb.starts[1:]
    for i, r in enumerate(rows):
        if r < 0:
            continue
        a, b = plane.row_block_range(int(r))
        if b <= a:
            continue
        act_rows.append(int(r))
        off = plane.block_off[a:b]
        ln = np.minimum(np.int64(IMPACT_BLOCK),
                        int(row_ends[int(r)]) - off).astype(np.int32)
        bm = plane.block_max[a:b]
        offs_l.append(off)
        lens_l.append(ln)
        w_l.append(np.full(b - a, np.float32(weights[i]) * scale,
                           np.float32))
        term_l.append(np.full(b - a, i, np.int32))
        val_l.append(dequant_impact_np(bm, float(weights[i])
                                       * float(plane.scale)))
        act_w.append(abs(float(weights[i])))
    if not offs_l:
        z = np.zeros(0, np.int64)
        return (z, np.zeros(0, np.int32), np.zeros(0, np.float32),
                0, 0.0, 0, 0)
    offs = np.concatenate(offs_l)
    lens = np.concatenate(lens_l)
    bw = np.concatenate(w_l)
    terms = np.concatenate(term_l)
    vals = np.concatenate(val_l)
    total_post = int(lens.sum())
    nblocks = len(offs)
    keep_min = max(KEEP_FACTOR * C, KEEP_MIN)
    if not prune or total_post <= keep_min:
        return offs, lens, bw, total_post, 0.0, nblocks, total_post
    # θ̂: best single-term witness on the window-th highest impact,
    # error-deducted. Postings of one row are distinct docs, so the
    # window-th highest quantized impact of ANY term witnesses `window`
    # real docs scoring ≥ w·(scale·q − eps) — sharper than the
    # block-level witness (top postings can concentrate in few blocks)
    # and exactly the MaxScore insight: one rare high-idf term alone can
    # price every stopword block out of the gather. Rows past the
    # partition budget fall back to the block-max witness (each block
    # max is attained by a distinct doc, so it is also sound).
    theta_hat = 0.0
    n_active = len(val_l)
    kcache = plane.__dict__.setdefault("_kth_cache", {})
    for r, bm_v, w_i in zip(act_rows, val_l, act_w):
        a, b = int(pb.starts[r]), int(pb.starts[r + 1])
        if b - a >= window and b - a <= (1 << 17):
            # cached per (row, window): the partition over a stopword
            # row is the plan's only O(df) step, and zipf queries repeat
            # rows constantly (benign to race — value is deterministic)
            kth_q = kcache.get((r, window))
            if kth_q is None:
                kth_q = float(np.partition(plane.q[a:b], b - a - window)
                              [b - a - window])
                if len(kcache) >= (1 << 16):
                    kcache.clear()      # scalar entries; hard cap ~6MB
                # one float per (row, window), never an ndocs-scale
                # array, and the cap above bounds the dict itself
                kcache[(r, window)] = kth_q  # oslint: disable=OSL301
            wit = float(dequant_impact_np(
                np.float32(kth_q), w_i * float(plane.scale)))
            theta_hat = max(theta_hat, wit - w_i * eps)
        elif len(bm_v) >= window:
            kth = float(np.partition(bm_v, len(bm_v) - window)
                        [len(bm_v) - window])
            theta_hat = max(theta_hat, kth - w_i * eps)
    # probe-doc witness: real docs' summed approx scores — sharpens θ̂
    # past the single-term kth when query terms co-occur
    eps_sum = float(sum(act_w)) * eps
    theta_hat = max(theta_hat,
                    _probe_witness(pb, plane, act_rows, act_w, window,
                                   eps_sum))
    if theta_hat <= 0.0:
        return offs, lens, bw, total_post, 0.0, nblocks, total_post
    tau = PRUNE_MARGIN * theta_hat / max(n_active, 1)
    prune_mask = vals < tau
    kept_post = int(lens[~prune_mask].sum())
    if kept_post < keep_min:
        # un-prune the priciest pruned blocks back to the posting floor
        pruned_idx = np.nonzero(prune_mask)[0]
        order = pruned_idx[np.argsort(-vals[pruned_idx], kind="stable")]
        cum = kept_post + np.cumsum(lens[order])
        back = int(np.searchsorted(cum, keep_min, side="left")) + 1
        prune_mask[order[:back]] = False
        kept_post = int(lens[~prune_mask].sum())
    rem = 0.0
    if prune_mask.any():
        # per-term max pruned block value, summed — the sound bound on
        # any doc's missing (never-gathered) contribution
        T = int(rows.shape[0])
        pruned_idx = np.nonzero(prune_mask)[0]
        per_term = np.zeros(T, np.float64)
        np.maximum.at(per_term, terms[pruned_idx],
                      vals[pruned_idx].astype(np.float64))
        rem = float(per_term.sum())

    # doc-range plan (the equal-idf multi-term cut): compete against the
    # per-term plan and take whichever ships fewer postings — on a
    # BP-reordered segment the range cut usually wins multi-term shapes
    # outright, on arrival-order corpora it keeps everything and the
    # per-term plan stands
    if n_active >= 1:
        rp = _range_plan(pb, plane, act_rows, act_w, offs, lens,
                         theta_hat, eps, ndocs)
        if rp is not None:
            keep_b, rem_r = rp
            kept_post_r = int(lens[keep_b].sum())
            if kept_post_r >= keep_min and kept_post_r < kept_post:
                kept = np.nonzero(keep_b)[0]
                return (offs[kept], lens[kept], bw[kept], kept_post_r,
                        rem_r, nblocks, total_post)
    kept = np.nonzero(~prune_mask)[0]
    return (offs[kept], lens[kept], bw[kept], kept_post,
            rem, nblocks, total_post)


def _exact_scores(seg: Segment, field: str, rows: np.ndarray,
                  weights: np.ndarray, k1: float, b_eff: float,
                  avgdl: float, cand: np.ndarray, dot: bool = False):
    """Exact f32 scores of `cand` against the FULL rows — term-ordered
    accumulation mirroring the fastpath host oracle (`_exact_rescore`)
    bit for bit, which is the domain served pages live in. `dot=True` is
    the learned-sparse domain: contribution w_t · weight(t, d) (the CSR
    "tf" slot of a feature field IS the stored weight) instead of the
    BM25 saturation."""
    pb = seg.postings.get(field)
    dl = seg.doc_lens.get(field)
    dl_c = (dl[cand].astype(np.float32) if dl is not None
            else np.zeros(len(cand), np.float32))
    kfac = float(k1) * (1.0 - b_eff + b_eff * dl_c
                        / max(float(avgdl), 1e-9))
    exact = np.zeros(len(cand), np.float32)
    counts = np.zeros(len(cand), np.int64)
    for i, r in enumerate(rows):
        if r < 0:
            continue
        a, b = pb.row_slice(int(r))
        if b <= a:
            continue
        rowdocs = pb.doc_ids[a:b]
        pos = np.searchsorted(rowdocs, cand)
        pos_c = np.minimum(pos, b - a - 1)
        found = rowdocs[pos_c] == cand
        tf = np.where(found, pb.tfs[a + pos_c], 0.0).astype(np.float32)
        contrib = (np.float32(weights[i]) * tf if dot
                   else np.float32(weights[i]) * tf / (tf + kfac))
        exact += np.where(found, contrib, 0.0).astype(np.float32)
        counts += found
    return exact, counts


def _error_bound(plane, weights: np.ndarray, rows: np.ndarray,
                 k1q: float, bq: float, avgdlq: float,
                 drift: Optional[float] = None) -> float:
    """Sound |exact − approx| per-doc bound: per-term quantization
    half-step + build→query param drift, plus f32 accumulation slack on
    both sums (≤ T adds each against the max representable score).
    Feature planes pass drift=0.0 explicitly — their weights are
    query-independent, so drift_bound (a BM25 construct) never applies
    (ImpactPlane.kind, OSL507)."""
    quant = plane.quant_err()
    if drift is None:
        drift = plane.drift_bound(k1q, bq, avgdlq)
    wsum = float(np.abs(weights[rows >= 0]).sum())
    e = wsum * (quant + drift)
    t = int((rows >= 0).sum())
    umax = max(wsum * float(plane.scale) * plane.qmax, 1e-30)
    e += 4.0 * (t + 2) * float(np.spacing(np.float32(umax)))
    return e


def _result(exact_m: np.ndarray, cand: np.ndarray, order: np.ndarray,
            window: int, total: int, rel: str) -> dict:
    keep = order[:window]
    sc = exact_m[keep]
    dc = cand[keep].astype(np.int32)
    finite = np.isfinite(sc)
    sc = np.where(finite, sc, -np.inf).astype(np.float32)
    dc = np.where(finite, dc, -1)
    ms = float(sc[0]) if len(sc) and np.isfinite(sc[0]) else -np.inf
    return {"topk_key": sc, "topk_idx": dc, "topk_scores": sc,
            "total": int(total), "max_score": ms, "total_rel": rel}


def segment_search(seg: Segment, ctx, spec: ImpactSpec, k: int
                   ) -> Optional[dict]:
    """Serve one pure spec over one codec-v2 segment, or None to fall
    back to the exact dense program. Codec-version gate consults
    Segment.codec_version (OSL507); v1 segments and facade views (shard
    views, filtered views — their PostingsBlocks carry no plane) decline
    here, so every caller keeps serving the legacy path unchanged."""
    lt = spec.lt
    if getattr(seg, "codec_version", CODEC_V1) < CODEC_V2:
        return None
    pb = seg.postings.get(lt.field)
    if pb is None or pb.impact is None or pb.size == 0:
        return None
    import jax

    from . import compiler as C

    plane = pb.impact
    is_sparse = spec.kind == "sparse"
    # plane/spec kind agreement (OSL507 version-discipline sibling): a
    # BM25 group must read a BM25 plane, a learned-sparse dot a FEATURE
    # plane — the dequant domain is baked into the quantized values
    if (plane.kind if plane.kind else "bm25") != (
            "feature" if is_sparse else "bm25"):
        return None
    window = max(int(spec.window or k), 1)
    ndocs_pad = seg.ndocs_pad
    Ccand = min(next_pow2(max(2 * window, CAND_FLOOR)), ndocs_pad)
    if is_sparse:
        # learned-sparse dot: rows are feature vocab entries. The PLAN
        # (τ/θ̂/rem pricing) works in the boost-folded domain
        # (w·boost), but the SERVED exact scores mirror the generic
        # sparse_dot XLA program's ordering — term-ordered Σ w·weight,
        # THEN one multiply by boost — so certified and escalated
        # segments of one query serve the same score domain. The ≤ ~T-
        # ULP gap between Σ(w·boost)·tf and (Σ w·tf)·boost is inside
        # the certificate's f32 accumulation slack (_error_bound).
        tokens = list(lt.tokens)
        nt = len(tokens)
        rows = np.full(nt, -1, np.int64)
        for i, t in enumerate(tokens):
            rows[i] = pb.row(t)
        exact_weights = np.asarray(lt.weights, np.float32)[:nt]
        exact_scale = np.float32(lt.boost)
        weights = exact_weights * exact_scale
        k1q, b_eff, avgdlq = 0.0, 0.0, 1.0
        msm = 1.0
        drift = 0.0
    else:
        nt = len(lt.terms)
        rows = np.full(nt, -1, np.int64)
        for i, t in enumerate(lt.terms):
            rows[i] = pb.row(t)
        weights = np.asarray(lt.weights, np.float32)[:nt]
        sim = lt.sim
        k1q = float(sim.k1)
        b_eff = float(sim.b) if lt.has_norms else 0.0
        avgdlq = float(ctx.avgdl(lt.field))
        msm = float(lt.msm)
        drift = None
        exact_weights = weights
        exact_scale = np.float32(1.0)
    if np.any(weights < 0):
        return None              # negative boosts void the prune bounds

    eps_imp = plane.quant_err() + (
        0.0 if is_sparse else plane.drift_bound(k1q, b_eff, avgdlq))
    offs, lens, bw, kept_post, rem, nblocks, total_post = _plan_blocks(
        pb, plane, rows, weights, Ccand, spec.prune_ok, window, eps_imp,
        ndocs=seg.ndocs)
    pruned = rem > 0.0 or kept_post < total_post
    STATS.inc("blocks_total", nblocks)
    STATS.inc("blocks_skipped", nblocks - len(offs))
    STATS.inc("postings_total", total_post)
    STATS.inc("postings_skipped", total_post - kept_post)
    # per-SHAPE skip attribution (obs/insights.py): the global STATS
    # smear under concurrency; the request's observation doesn't
    _ins.note_blocks(nblocks, nblocks - len(offs))
    if kept_post == 0:
        # no queried term has postings here: an exact empty page
        STATS.inc("served")
        z = np.full(window, -np.inf, np.float32)
        return {"topk_key": z, "topk_idx": np.full(window, -1, np.int32),
                "topk_scores": z, "total": 0, "max_score": -np.inf,
                "total_rel": "eq"}

    B_pad = next_pow2(len(offs), floor=8)
    bstart = np.zeros(B_pad, np.int32)
    blen = np.zeros(B_pad, np.int32)
    bweight = np.zeros(B_pad, np.float32)
    bstart[: len(offs)] = offs.astype(np.int32)
    blen[: len(offs)] = lens
    bweight[: len(offs)] = bw
    bucket = ops.pick_bucket(kept_post)

    arrs = seg.device_arrays()
    post = arrs["postings"][lt.field]
    cost = _qc.current()
    if cost is not None:
        # actual moved bytes of the eager pass: doc i32 + u8/u16 impact
        # per gathered slot — the codec-v2 byte-volume claim, measured
        cost.note_actual(bucket * (4 + plane.bits // 8), kept_post,
                         Ccand, path="impact", segment=seg)
    with TRACER.span("impactpath.gather", blocks=int(len(offs)),
                     bucket=bucket), METRICS.timer("impactpath.gather"):
        prog = C.build_impact_program(B_pad, bucket, Ccand, plane.bits)
        vals, idx, total = jax.device_get(prog(
            post["doc_ids"], post["impacts"], arrs["live"], bstart, blen,
            bweight, np.float32(1.0 if pruned else msm)))
    vals = np.asarray(vals)
    idx = np.asarray(idx)
    nvalid = int((vals > -np.inf).sum())
    total = int(total)
    rel = "gte" if pruned else "eq"

    if nvalid == 0:
        if pruned:
            # matches may hide entirely in pruned blocks
            STATS.inc("escalated")
            _ins.note_escalation()
            return None
        STATS.inc("served")
        z = np.full(window, -np.inf, np.float32)
        return {"topk_key": z, "topk_idx": np.full(window, -1, np.int32),
                "topk_scores": z, "total": 0, "max_score": -np.inf,
                "total_rel": "eq"}

    cand = idx[:nvalid].astype(np.int64)
    exact, counts = _exact_scores(seg, lt.field, rows, exact_weights,
                                  k1q, b_eff, avgdlq, cand,
                                  dot=is_sparse)
    if exact_scale != np.float32(1.0):
        exact = (exact * exact_scale).astype(np.float32)
    pass_msm = counts >= msm
    exact_m = np.where(pass_msm, exact, -np.inf).astype(np.float32)
    n_pass = int(pass_msm.sum())
    # score ties break on the layout-invariant arrival rank (== doc id
    # on unreordered segments): the BP reorder parity contract
    tr = seg.tie_ranks()
    order = np.lexsort((cand if tr is None else tr[cand], -exact_m))
    theta = (float(exact_m[order[window - 1]]) if n_pass >= window
             else -np.inf)
    E = _error_bound(plane, weights, rows, k1q, b_eff, avgdlq,
                     drift=drift)

    # displacement bound for every non-candidate doc: seen-but-lost docs
    # (only exist when the kernel window filled) carry approx ≤ the C-th
    # approx value plus quant/drift error plus whatever pruning hid;
    # never-seen docs are bounded by the pruned remainder PLUS the same
    # error term (the sidecar prices blocks in the quantized domain —
    # the true f32 contribution can sit up to eps above it)
    bound = (rem + E) if pruned else -np.inf
    if nvalid == Ccand:
        bound = max(bound, float(vals[nvalid - 1]) + E + rem)
    if theta > -np.inf and bound < theta:
        STATS.inc("served")
        if pruned:
            STATS.inc("pruned_served")
        tot = total if not pruned or msm <= 1 else n_pass
        return _result(exact_m, cand, order, window, tot, rel)
    if not pruned and nvalid < Ccand:
        # the candidate set IS every matching doc: exact by construction
        # (window may be short — that's the true result set)
        STATS.inc("served")
        return _result(exact_m, cand, order, window, total, "eq")

    # ---- phase 2: widen to every doc any kept block mentions — unseen
    # docs are then bounded by the pruned remainder alone ----
    if pruned:
        if _fr.RECORDER.enabled and _fr.current():
            _fr.RECORDER.record(_fr.current(), "impactpath.rung",
                                rung="phase2_union", blocks=int(len(offs)))
        with TRACER.span("impactpath.phase2", postings=kept_post), \
                METRICS.timer("impactpath.phase2"):
            ids = [pb.doc_ids[int(o): int(o) + int(l)]
                   for o, l in zip(offs, lens)]
            union = np.unique(np.concatenate(ids)).astype(np.int64)
            if len(union) and seg.live_count != seg.ndocs:
                union = union[seg.live[union]]
            exact2, counts2 = _exact_scores(seg, lt.field, rows,
                                            exact_weights, k1q, b_eff,
                                            avgdlq, union,
                                            dot=is_sparse)
            if exact_scale != np.float32(1.0):
                exact2 = (exact2 * exact_scale).astype(np.float32)
            pass2 = counts2 >= msm
            exact2_m = np.where(pass2, exact2, -np.inf).astype(np.float32)
            n2 = int(pass2.sum())
            order2 = np.lexsort((union if tr is None else tr[union],
                                 -exact2_m))
            theta2 = (float(exact2_m[order2[window - 1]])
                      if n2 >= window else -np.inf)
            # + E: the remainder is a quantized-domain price; the true
            # exact contribution of a pruned posting can exceed it by
            # the per-term quant/drift epsilon
            if theta2 > -np.inf and rem + E < theta2:
                STATS.inc("served")
                STATS.inc("pruned_served")
                STATS.inc("phase2_served")
                return _result(exact2_m, union, order2, window, n2, "gte")

    STATS.inc("escalated")
    _ins.note_escalation()
    if _fr.RECORDER.enabled and _fr.current():
        _fr.RECORDER.record(_fr.current(), "impactpath.rung",
                            rung="dense_escalation")
    return None
