"""Suggesters: term, phrase, completion.

Reference `search/suggest/SuggestBuilder.java`,
`suggest/term/TermSuggester.java` (Lucene DirectSpellChecker),
`suggest/phrase/PhraseSuggester.java` (candidate generation + gram language
model + stupid-backoff/laplace smoothing),
`suggest/completion/CompletionSuggester.java` (FST prefix automaton).

TPU posture: suggestion is a tiny-term-dictionary problem, not a FLOPs
problem — the reference runs it JVM-host-side over Lucene's FST; we run it
Python-host-side over the segment term dictionaries (sorted vocab lists)
with an edit-distance band filter. The completion suggester keeps a
per-segment sorted (input, weight, doc) array built from `_source` — the
FST-lite analog, prefix lookup by bisect.
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, List, Optional, Tuple

from . import query_dsl as dsl


# ---------------------------------------------------------------------
# edit distance (banded, early-exit) — shared by term/phrase/completion
# ---------------------------------------------------------------------

def edit_distance_le(a: str, b: str, k: int) -> Optional[int]:
    """Damerau-lite Levenshtein distance if <= k else None (banded DP)."""
    la, lb = len(a), len(b)
    if abs(la - lb) > k:
        return None
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        lo = max(1, i - k)
        hi = min(lb, i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        best = k + 1
        for j in range(lo, hi + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (i > 1 and j > 1 and a[i - 1] == b[j - 2]
                    and a[i - 2] == b[j - 1]):
                cur[j] = min(cur[j], prev[j - 1])  # adjacent transposition-ish
            best = min(best, cur[j])
        if best > k:
            return None
        prev = cur
    return prev[lb] if prev[lb] <= k else None


# ---------------------------------------------------------------------
# shared term-dictionary access
# ---------------------------------------------------------------------

def _field_stats(segments, field: str):
    """(doc_freq fn, vocab union iterator helpers) over live segments."""
    def doc_freq(term: str) -> int:
        return sum(s.postings[field].doc_freq(term) for s in segments
                   if field in s.postings)
    return doc_freq


def _candidates(segments, field: str, token: str, max_edits: int,
                prefix_len: int, max_inspections: int = 1000
                ) -> List[Tuple[str, int, int]]:
    """-> [(term, distance, doc_freq)] within edit distance, sharing the
    required prefix (reference DirectSpellChecker.minPrefix)."""
    seen: Dict[str, int] = {}
    prefix = token[:prefix_len]
    for seg in segments:
        pb = seg.postings.get(field)
        if pb is None:
            continue
        vocab = pb.vocab
        if prefix:
            lo = bisect.bisect_left(vocab, prefix)
            hi = bisect.bisect_left(vocab, prefix + "￿")
        else:
            lo, hi = 0, len(vocab)
        for i in range(lo, min(hi, lo + max_inspections)):
            t = vocab[i]
            if t == token or t in seen:
                continue
            d = edit_distance_le(token, t, max_edits)
            if d is not None and d > 0:
                seen[t] = d
    doc_freq = _field_stats(segments, field)
    return [(t, d, doc_freq(t)) for t, d in seen.items()]


def _score(token: str, cand: str, distance: int) -> float:
    """DirectSpellChecker-style similarity in (0, 1)."""
    return 1.0 - distance / max(min(len(token), len(cand)), 1)


# ---------------------------------------------------------------------
# term suggester
# ---------------------------------------------------------------------

def term_suggest(segments, mappings, text: str, opts: dict) -> List[dict]:
    field = opts["field"]
    size = int(opts.get("size", 5))
    mode = opts.get("suggest_mode", "missing")
    max_edits = int(opts.get("max_edits", 2))
    prefix_len = int(opts.get("prefix_length", 1))
    min_len = int(opts.get("min_word_length", 4))
    sort = opts.get("sort", "score")
    doc_freq = _field_stats(segments, field)

    ft = mappings.resolve_field(field)
    analyzer = mappings.search_analyzer_for(ft) if ft is not None else None
    tokens = analyzer.terms(text) if analyzer else text.lower().split()

    out = []
    offset = 0
    for tok in tokens:
        pos = text.lower().find(tok, offset)
        if pos < 0:
            pos = offset
        entry = {"text": tok, "offset": pos, "length": len(tok),
                 "options": []}
        offset = pos + len(tok)
        tok_df = doc_freq(tok)
        need = (mode == "always" or (mode == "missing" and tok_df == 0)
                or mode == "popular")
        if need and len(tok) >= min_len:
            cands = _candidates(segments, field, tok, max_edits, prefix_len)
            opts_list = []
            for t, d, df in cands:
                if df <= 0:
                    continue
                if mode == "popular" and df <= tok_df:
                    continue
                opts_list.append({"text": t, "score": round(_score(tok, t, d), 6),
                                  "freq": df})
            if sort == "frequency":
                opts_list.sort(key=lambda o: (-o["freq"], -o["score"], o["text"]))
            else:
                opts_list.sort(key=lambda o: (-o["score"], -o["freq"], o["text"]))
            entry["options"] = opts_list[:size]
        out.append(entry)
    return out


# ---------------------------------------------------------------------
# phrase suggester
# ---------------------------------------------------------------------

def _collection_tf(segments, field: str, term: str) -> float:
    tot = 0.0
    for s in segments:
        pb = s.postings.get(field)
        if pb is None:
            continue
        r = pb.row(term)
        if r >= 0:
            a, b = pb.row_slice(r)
            tot += float(pb.tfs[a:b].sum())
    return tot


def _total_tf(segments, field: str) -> float:
    tot = 0.0
    for s in segments:
        st = s.text_stats.get(field)
        if st:
            tot += st.sum_dl
    return tot


def phrase_suggest(segments, mappings, text: str, opts: dict) -> List[dict]:
    """Candidate generation per token + beam over combinations scored by a
    stupid-backoff bigram LM. Bigram counts come from `collate`-style lookup
    of the shingled gram field when `field` carries shingles ("w1 w2" terms);
    otherwise the model backs off to unigrams only."""
    field = opts["field"]
    gram_field = opts.get("gram_field", field)
    size = int(opts.get("size", 5))
    max_errors = float(opts.get("max_errors", 1.0))
    confidence = float(opts.get("confidence", 1.0))
    rwel = float(opts.get("real_word_error_likelihood", 0.95))
    discount = 0.4   # stupid backoff
    hl = opts.get("highlight") or {}
    pre, post = hl.get("pre_tag", ""), hl.get("post_tag", "")

    ft = mappings.resolve_field(field)
    analyzer = mappings.search_analyzer_for(ft) if ft is not None else None
    tokens = analyzer.terms(text) if analyzer else text.lower().split()
    if not tokens:
        return [{"text": text, "offset": 0, "length": len(text),
                 "options": []}]

    total = max(_total_tf(segments, field), 1.0)
    vocab_n = max(sum(len(s.postings[field].vocab) for s in segments
                      if field in s.postings), 1)

    def uni_p(w: str) -> float:
        # laplace-smoothed unigram probability
        return (_collection_tf(segments, field, w) + 0.5) / (total + 0.5 * vocab_n)

    def bi_p(w1: str, w2: str) -> float:
        big = _collection_tf(segments, gram_field, f"{w1} {w2}")
        if big > 0:
            c1 = _collection_tf(segments, field, w1)
            if c1 > 0:
                return big / c1
        return discount * uni_p(w2)

    max_cand = 4
    per_token: List[List[Tuple[str, float]]] = []
    doc_freq = _field_stats(segments, field)
    for tok in tokens:
        cands = [(tok, 1.0 if doc_freq(tok) > 0 else 0.5)]
        for t, d, df in _candidates(segments, field, tok,
                                    int(opts.get("max_edits", 2)),
                                    int(opts.get("prefix_length", 1))):
            if df > 0:
                cands.append((t, _score(tok, t, d)))
        cands.sort(key=lambda c: -c[1])
        per_token.append(cands[:max_cand])

    def lm_score(seq: List[str]) -> float:
        p = uni_p(seq[0])
        score = p
        for i in range(1, len(seq)):
            score *= bi_p(seq[i - 1], seq[i])
        return score

    # beam over combinations, bounded errors
    max_changes = max(1, int(round(max_errors if max_errors >= 1
                                   else max_errors * len(tokens))))
    beams: List[Tuple[List[str], int, float]] = [([], 0, 1.0)]
    for ti, cands in enumerate(per_token):
        nxt = []
        for seq, changes, sim in beams:
            for ci, (cand, csim) in enumerate(cands):
                ch = changes + (1 if cand != tokens[ti] else 0)
                if ch > max_changes:
                    continue
                nxt.append((seq + [cand], ch,
                            sim * (csim if cand != tokens[ti] else rwel)))
        nxt.sort(key=lambda x: -x[2])
        beams = nxt[: 12]

    base_seq = tokens
    base = lm_score(base_seq) * (rwel ** len(tokens))
    options = []
    seen = set()
    for seq, changes, sim in beams:
        phrase = " ".join(seq)
        if phrase in seen:
            continue
        seen.add(phrase)
        sc = lm_score(seq) * sim
        if seq == base_seq:
            options.append({"text": phrase, "score": sc})
            continue
        if sc <= base * confidence:
            continue
        opt = {"text": phrase, "score": sc}
        if pre or post:
            opt["highlighted"] = " ".join(
                f"{pre}{w}{post}" if w != tokens[i] else w
                for i, w in enumerate(seq))
        options.append(opt)
    options.sort(key=lambda o: -o["score"])
    return [{"text": text, "offset": 0, "length": len(text),
             "options": options[:size]}]


# ---------------------------------------------------------------------
# completion suggester
# ---------------------------------------------------------------------

def _completion_entries(seg, field: str) -> List[Tuple[str, int, int]]:
    """Sorted (input_lower, weight, doc) built from _source — the FST-lite."""
    cache = seg.__dict__.setdefault("_completion_cache", {})
    if field in cache:
        return cache[field]
    entries: List[Tuple[str, int, int]] = []
    for doc in range(seg.ndocs):
        if not seg.live[doc]:
            continue
        src = seg.sources[doc]
        v = src.get(field) if isinstance(src, dict) else None
        if v is None:
            continue
        items = v if isinstance(v, list) else [v]
        for it in items:
            if isinstance(it, dict):
                inputs = it.get("input", [])
                inputs = inputs if isinstance(inputs, list) else [inputs]
                w = int(it.get("weight", 1))
            else:
                inputs, w = [str(it)], 1
            for inp in inputs:
                entries.append((str(inp).lower(), w, doc))
    entries.sort()
    cache[field] = entries
    return entries


def completion_suggest(segments, mappings, prefix: str, opts: dict,
                       seg_ids) -> List[dict]:
    field = opts["field"]
    size = int(opts.get("size", 5))
    skip_dup = bool(opts.get("skip_duplicates", False))
    fuzzy = opts.get("fuzzy")
    p = prefix.lower()
    collected = []
    for si, seg in enumerate(segments):
        entries = _completion_entries(seg, field)
        keys = [e[0] for e in entries]
        if fuzzy:
            fz = (2 if fuzzy is True else
                  int(fuzzy.get("fuzziness", 2) if str(fuzzy.get(
                      "fuzziness", 2)).isdigit() else 2))
            plen = int(fuzzy.get("prefix_length", 1)) if isinstance(
                fuzzy, dict) else 1
            anchor = p[:plen]
            lo = bisect.bisect_left(keys, anchor)
            hi = bisect.bisect_left(keys, anchor + "￿") if anchor \
                else len(keys)
            for i in range(lo, hi):
                inp, w, doc = entries[i]
                cand_prefix = inp[: len(p)]
                if edit_distance_le(p, cand_prefix, fz) is not None:
                    collected.append((inp, w, si, doc))
        else:
            lo = bisect.bisect_left(keys, p)
            hi = bisect.bisect_left(keys, p + "￿")
            for i in range(lo, hi):
                inp, w, doc = entries[i]
                collected.append((inp, w, si, doc))
    collected.sort(key=lambda e: (-e[1], e[0]))
    options = []
    seen_txt = set()
    for inp, w, si, doc in collected:
        if skip_dup and inp in seen_txt:
            continue
        seen_txt.add(inp)
        seg = segments[si]
        options.append({"text": inp, "_id": seg.ids[doc],
                        "_score": float(w),
                        "_source": seg.sources[doc]})
        if len(options) >= size:
            break
    return [{"text": prefix, "offset": 0, "length": len(prefix),
             "options": options}]


# ---------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------

def run_suggest(suggest_body: dict, segments, mappings) -> dict:
    """-> the response `suggest` section (reference shape: one entry list per
    named suggestion)."""
    out = {}
    global_text = suggest_body.get("text")
    for name, spec in suggest_body.items():
        if name == "text":
            continue
        if not isinstance(spec, dict):
            raise dsl.QueryParseError(f"invalid suggest section [{name}]")
        text = spec.get("text", global_text)
        if "term" in spec:
            if text is None:
                raise dsl.QueryParseError(f"suggest [{name}] requires [text]")
            out[name] = term_suggest(segments, mappings, str(text),
                                     spec["term"])
        elif "phrase" in spec:
            if text is None:
                raise dsl.QueryParseError(f"suggest [{name}] requires [text]")
            out[name] = phrase_suggest(segments, mappings, str(text),
                                       spec["phrase"])
        elif "completion" in spec:
            prefix = spec.get("prefix", text)
            if prefix is None:
                raise dsl.QueryParseError(
                    f"suggest [{name}] requires [prefix]")
            out[name] = completion_suggest(segments, mappings, str(prefix),
                                           spec["completion"], None)
        else:
            raise dsl.QueryParseError(
                f"suggest [{name}] needs one of [term|phrase|completion]")
    return out
